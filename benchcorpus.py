"""Per-query corpus benchmark: TPC-DS-shaped star queries + the mortgage
ETL run end-to-end through TpuSession (scan -> plan -> device kernels ->
collect) against the CPU engine on the same data — round-5 verdict item
3: the headline stops being a single fused microbench and gains a
per-query device-vs-CPU table (the reference's whole-query speedup
posture, docs/FAQ.md:105-109).

The star fact table is written as PARQUET WITH DECIMAL money columns and
a date column, so the device scan path (decimal FLBA decode, fused
multi-column program) is on the measured path — exactly the columns that
used to evict files from device decode.

Invoked by bench.py in its own subprocess (--corpus-only); emits one
marked JSON line with per-query seconds and speedups."""

from __future__ import annotations

import os
import time

import numpy as np

N_SALES = 1_000_000
N_DATES = 2_000
N_ITEMS = 2_000
N_STORES = 64
N_CUSTOMERS = 20_000


def _write_star(tmpdir: str):
    import decimal
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(7)
    price_raw = rng.integers(100, 25000, N_SALES)
    nulls = rng.random(N_SALES) < 0.02
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(0, N_DATES, N_SALES).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(0, N_ITEMS, N_SALES).astype(np.int64)),
        "ss_store_sk": pa.array(
            rng.integers(0, N_STORES, N_SALES).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(0, N_CUSTOMERS, N_SALES).astype(np.int64)),
        "ss_quantity": pa.array(
            rng.integers(1, 20, N_SALES).astype(np.int32)),
        "ss_sales_price": pa.array(
            [None if nulls[i] else
             decimal.Decimal(int(price_raw[i])).scaleb(-2)
             for i in range(N_SALES)], type=pa.decimal128(7, 2)),
    })
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(N_DATES, dtype=np.int64)),
        "d_year": pa.array((2019 + np.arange(N_DATES) // 365)
                           .astype(np.int32)),
        "d_moy": pa.array((np.arange(N_DATES) % 365 // 31 + 1)
                          .astype(np.int32)),
        "d_dow": pa.array((np.arange(N_DATES) % 7).astype(np.int32)),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(N_ITEMS, dtype=np.int64)),
        "i_brand": pa.array([f"brand{i % 37}" for i in range(N_ITEMS)]),
        "i_category": pa.array([f"cat{i % 11}" for i in range(N_ITEMS)]),
        "i_price": pa.array(rng.uniform(1, 200, N_ITEMS).round(2)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(N_STORES, dtype=np.int64)),
        "s_state": pa.array([f"ST{i % 5}" for i in range(N_STORES)]),
    })
    paths = {}
    total = 0
    for name, tbl in (("store_sales", store_sales), ("date_dim", date_dim),
                      ("item", item), ("store", store)):
        p = os.path.join(tmpdir, f"{name}.parquet")
        pq.write_table(tbl, p, compression="snappy")
        paths[name] = p
        total += os.path.getsize(p)
    return paths, total


def _queries(session, paths):
    from spark_rapids_tpu.expr import (Average, Count, RowNumber, Sum, col,
                                       lit)
    ss = session.read_parquet(paths["store_sales"])
    dd = session.read_parquet(paths["date_dim"])
    it = session.read_parquet(paths["item"])
    st = session.read_parquet(paths["store"])

    q3 = (ss.join(dd, condition=col("ss_sold_date_sk") == col("d_date_sk"),
                  how="inner")
          .filter(col("d_moy") == lit(11))
          .join(it, condition=col("ss_item_sk") == col("i_item_sk"),
                how="inner")
          .group_by("d_year", "i_brand")
          .agg(sum_agg=Sum(col("ss_sales_price"))))
    q7 = (ss.join(it, condition=col("ss_item_sk") == col("i_item_sk"),
                  how="inner")
          .join(st, condition=col("ss_store_sk") == col("s_store_sk"),
                how="inner")
          .filter(col("s_state") == lit("ST1"))
          .group_by("i_category")
          .agg(q=Average(col("ss_quantity")), n=Count(lit(1))))
    per_cust = (ss.group_by("ss_customer_sk")
                .agg(spend=Sum(col("ss_sales_price")),
                     qty=Sum(col("ss_quantity"))))
    q68 = per_cust.window(partition_by=[],
                          order_by=[(col("spend"), False, False)],
                          rnk=RowNumber())
    q96 = (ss.join(dd, condition=col("ss_sold_date_sk") == col("d_date_sk"),
                   how="inner")
           .filter((col("d_dow") == lit(6)) & (col("ss_quantity")
                                               > lit(10)))
           .join(st, condition=col("ss_store_sk") == col("s_store_sk"),
                 how="inner")
           .agg(cnt=Count(lit(1))))
    return {"q3_brand_report": q3, "q7_star_avg": q7,
            "q68_window_rank": q68, "q96_selective_count": q96}


def _mortgage_query(session):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from apps.mortgage import (aggregates_with_join, gen_acquisition,
                               gen_performance)
    rng = np.random.default_rng(42)
    perf, acq = gen_performance(rng), gen_acquisition(rng)
    return aggregates_with_join(session,
                                session.from_arrow(perf),
                                session.from_arrow(acq))


def run_corpus(tmpdir: str) -> dict:
    """Time each corpus query on the device engine vs the CPU engine.
    Returns {query: {device_s, cpu_s, speedup, rows}} + aggregates."""
    from spark_rapids_tpu.plugin import TpuSession
    paths, corpus_bytes = _write_star(tmpdir)
    session = TpuSession({"spark.rapids.sql.enabled": True,
                          "spark.rapids.sql.explain": "NONE"})
    session.initialize_device()
    queries = dict(_queries(session, paths))
    queries["mortgage_agg_join"] = _mortgage_query(session)

    out = {"corpus_bytes": corpus_bytes, "fact_rows": N_SALES,
           "queries": {}}
    speedups = []
    scan_best = None
    for name, q in queries.items():
        q.collect()  # compile + warm (cache persists across runs)
        dev = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            res = q.collect()
            dev = min(dev, time.perf_counter() - t0)
        cpu = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            res_cpu = q.collect_cpu()
            cpu = min(cpu, time.perf_counter() - t0)
        assert res.num_rows == res_cpu.num_rows, name
        sp = cpu / dev if dev > 0 else float("inf")
        speedups.append(sp)
        out["queries"][name] = {"device_s": round(dev, 4),
                                "cpu_s": round(cpu, 4),
                                "speedup": round(sp, 3),
                                "rows": res.num_rows}
        if name.startswith("q"):
            scan_best = dev if scan_best is None else min(scan_best, dev)
    out["geomean_speedup"] = round(
        float(np.exp(np.mean(np.log(np.maximum(speedups, 1e-9))))), 3)
    if scan_best:
        out["corpus_scan_gbps"] = round(
            corpus_bytes / scan_best / 1e9, 3)
    return out
