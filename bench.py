"""Benchmark: fused scan->filter->join->aggregate query step on one chip.

The BASELINE metric family is "GB/s/chip scan+hash-join" / "speedup vs CPU Spark"
(reference claims 3-7x, typical 4x — docs/FAQ.md:105-109). This runs the q5-ish
pipeline (BASELINE workload #1) as one XLA program on the real chip, validates it
against a numpy oracle, and reports speedup vs that oracle (a *vectorized-C* CPU
stand-in — far faster than row-based CPU Spark, so conservative).

TPU-native choices (measured on chip, see commit history):
  * join = dense-table gather (build dim table via scatter, probe via gather):
    3.4x faster than XLA's searchsorted lowering at 4M probes.
  * grouped agg = segment_sum; f64 (Spark DoubleType semantics) is the dominant
    cost on TPU (emulated f64 scatter-add) — the standing kernel-optimization
    target (Pallas segmented reduce).
  * timing: the axon tunnel has ~70ms/call RPC overhead and block_until_ready
    returns early, so the step is iterated K times INSIDE one program
    (lax.scan) and D2H forces completion; per-step = (total - noop) / K.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Hardening (round-1 failure mode): the axon TPU backend can fail at init
(UNAVAILABLE) or hang indefinitely in make_c_api_client. The parent process
therefore runs the measurement in a CHILD subprocess under a watchdog timeout,
retries on failure/timeout with backoff, and on final failure prints a single
parseable {"metric": ..., "error": ...} JSON line instead of a traceback —
one round must never lose its perf evidence to a transient backend error
(reference bar: fail fast + loud, Plugin.scala:365-389,436-459).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_FACT = 4_194_304
N_DIM = 65_536
N_GROUPS = 1_024
KEY_SPACE = 131_072
BYTES_PER_ROW = 8 + 4 + 8  # fact: key i64, grp i32, val f64
K_STEPS = 8


def make_data(seed: int = 0):
    rng = np.random.default_rng(seed)
    fact_key = rng.integers(0, KEY_SPACE, size=N_FACT).astype(np.int64)
    fact_grp = rng.integers(0, N_GROUPS, size=N_FACT).astype(np.int32)
    fact_val = rng.uniform(0.5, 1.5, size=N_FACT).astype(np.float64)
    dim_key = np.sort(rng.permutation(KEY_SPACE)[:N_DIM]).astype(np.int64)
    dim_w = rng.uniform(0.5, 1.5, size=N_DIM).astype(np.float64)
    return fact_key, fact_grp, fact_val, dim_key, dim_w


def tpu_many_steps():
    """One program running the query step K_STEPS times (amortizes tunnel RPC).

    The grouped aggregation runs through the Pallas MXU segmented-sum kernel
    (ops/pallas_segsum.py): XLA's f64 segment_sum lowers to an emulated-f64
    scatter-add measured at 0.300s/step for this shape; the Pallas kernel does
    the same reduction in 0.019s/step at ~1e-9 relative error (one-hot MXU
    matmuls on a hi/lo split, per-chunk f32 partials combined in f64)."""
    import jax
    import jax.numpy as jnp
    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.ops.pallas_segsum import segment_sum_f64

    @jax.jit
    def many(fact_key, fact_grp, fact_val, dim_key, dim_w):
        tw = jnp.zeros(KEY_SPACE, jnp.float64).at[dim_key].set(dim_w)
        tm = jnp.zeros(KEY_SPACE, bool).at[dim_key].set(True)

        def step(acc, _):
            keep = fact_val > 0.6
            w = tw[fact_key]
            matched = tm[fact_key] & keep
            contrib = jnp.where(matched, fact_val * w, 0.0)
            sums = segment_sum_f64(contrib, fact_grp, N_GROUPS)
            rows = jnp.sum(matched).astype(jnp.int64)
            return (acc[0] + sums, acc[1] + rows), None

        init = (jnp.zeros(N_GROUPS, jnp.float64), jnp.int64(0))
        (sums, rows), _ = jax.lax.scan(step, init, jnp.arange(K_STEPS))
        return sums / K_STEPS, rows // K_STEPS

    return many


def cpu_pipeline(fact_key, fact_grp, fact_val, dim_key, dim_w):
    keep = fact_val > 0.6
    ix = np.clip(np.searchsorted(dim_key, fact_key), 0, len(dim_key) - 1)
    matched = (dim_key[ix] == fact_key) & keep
    contrib = np.where(matched, fact_val * dim_w[ix], 0.0)
    sums = np.bincount(fact_grp, weights=contrib, minlength=N_GROUPS)
    return sums, int(matched.sum())


def _force(x):
    return np.asarray(x)


ATTEMPTS = 3
# First compile via the tunnel is ~20-40s and the measured section is seconds;
# a healthy run fits in ~2 min. A hung backend init eats the whole window, so
# keep it tight — 3 attempts must stay well under the driver's round budget.
ATTEMPT_TIMEOUT_S = 180
_CHILD_ENV = "SPARK_RAPIDS_TPU_BENCH_CHILD"
_MARK = "@BENCH_RESULT@"


_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")


def _enable_compilation_cache():
    """Persist compiled programs across processes/rounds: a warm bench run
    skips the ~20-40s tunnel compile, so a healthy attempt completes in
    seconds (round-2 verdict item 1a)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax: default is fine


def main():
    _enable_compilation_cache()
    import jax
    # test hook: SPARK_RAPIDS_TPU_BENCH_PLATFORM=cpu forces the platform
    # (the axon plugin overrides JAX_PLATFORMS, so env alone is not enough)
    plat = os.environ.get("SPARK_RAPIDS_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp

    data = make_data()
    dev_args = [jnp.asarray(a) for a in data]

    # tunnel RPC floor: noop program, D2H-forced
    noop = jax.jit(lambda x: x + 1)
    _force(noop(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(10):
        _force(noop(jnp.float32(0)))
    overhead = (time.perf_counter() - t0) / 10

    many = tpu_many_steps()
    _force(many(*dev_args)[0])  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        sums, rows = many(*dev_args)
        _force(sums)
        best = min(best, time.perf_counter() - t0)
    t_tpu = max((best - overhead) / K_STEPS, 1e-9)

    t_cpu = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_sums, cpu_rows = cpu_pipeline(*data)
        t_cpu = min(t_cpu, time.perf_counter() - t0)
    assert int(rows) == cpu_rows, (int(rows), cpu_rows)
    # K-step accumulate/divide reorders f64 additions; this is a sanity check,
    # exactness is the differential suite's job
    np.testing.assert_allclose(np.asarray(sums), cpu_sums, rtol=1e-6)

    speedup = t_cpu / t_tpu
    gbps = N_FACT * BYTES_PER_ROW / t_tpu / 1e9
    print(_MARK + json.dumps({
        "metric": "scan_join_agg_speedup_vs_cpu",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 4.0, 3),
        "detail": {"device": str(jax.devices()[0]),
                   "tpu_step_s": round(t_tpu, 5), "cpu_s": round(t_cpu, 5),
                   "scan_gbps": round(gbps, 3), "rows": N_FACT,
                   "rpc_overhead_s": round(overhead, 4)},
    }), flush=True)


PROBE_TIMEOUT_S = 35
PROBE_ATTEMPTS = 2


def probe_backend() -> "tuple[bool, str]":
    """~30s-bounded subprocess probe of the device backend BEFORE burning a
    full attempt window: a dead tunnel costs 2x35s, not 3x180s (round-2
    verdict item 1b). Returns (ok, detail)."""
    plat = os.environ.get("SPARK_RAPIDS_TPU_BENCH_PLATFORM")
    cfg = (f"jax.config.update('jax_platforms', {plat!r}); " if plat else "")
    code = f"import jax; {cfg}print(jax.devices()[0])"
    last = ""
    for i in range(1, PROBE_ATTEMPTS + 1):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=PROBE_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            last = (f"probe {i}: no backend response in {PROBE_TIMEOUT_S}s "
                    "(wedged tunnel)")
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            return True, proc.stdout.strip().splitlines()[-1]
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["<no output>"]
        last = f"probe {i}: rc={proc.returncode} {tail[0]}"
    return False, last


def supervise() -> int:
    """Probe the backend, then run main() in a child under a watchdog;
    retry; emit error JSON if all fail."""
    ok, detail = probe_backend()
    if not ok:
        print(json.dumps({
            "metric": "scan_join_agg_speedup_vs_cpu",
            "value": None,
            "unit": "x",
            "vs_baseline": None,
            "error": f"backend probe failed, skipping attempts: {detail}",
            "detail": {"probe": detail},
        }), flush=True)
        return 1
    errors = [f"probe ok: {detail}"]
    for attempt in range(1, ATTEMPTS + 1):
        env = dict(os.environ, **{_CHILD_ENV: "1"})
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=ATTEMPT_TIMEOUT_S,
                env=env)
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timeout after "
                          f"{ATTEMPT_TIMEOUT_S}s (backend init hang?)")
            continue
        for line in proc.stdout.splitlines():
            if line.startswith(_MARK):
                print(line[len(_MARK):], flush=True)
                return 0
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        errors.append(f"attempt {attempt}: rc={proc.returncode} "
                      + " | ".join(tail))
        if attempt < ATTEMPTS:
            time.sleep(5 * attempt)
    print(json.dumps({
        "metric": "scan_join_agg_speedup_vs_cpu",
        "value": None,
        "unit": "x",
        "vs_baseline": None,
        "error": f"all {ATTEMPTS} attempts failed",
        "detail": {"attempts": errors},
    }), flush=True)
    return 1


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV):
        main()
    else:
        sys.exit(supervise())
