"""Benchmark: fused scan->filter->join->aggregate query step on one chip.

The BASELINE metric family is "GB/s/chip scan+hash-join" / "speedup vs CPU Spark"
(reference claims 3-7x, typical 4x — docs/FAQ.md:105-109). This runs the q5-ish
pipeline (BASELINE workload #1) as one XLA program on the real chip, validates it
against a numpy oracle, and reports speedup vs that oracle (a *vectorized-C* CPU
stand-in — far faster than row-based CPU Spark, so conservative).

TPU-native choices (measured on chip, see commit history):
  * join = dense-table gather (build dim table via scatter, probe via gather):
    3.4x faster than XLA's searchsorted lowering at 4M probes.
  * grouped agg = segment_sum; f64 (Spark DoubleType semantics) is the dominant
    cost on TPU (emulated f64 scatter-add) — the standing kernel-optimization
    target (Pallas segmented reduce).
  * timing: the axon tunnel has ~70ms/call RPC overhead and block_until_ready
    returns early, so the step is iterated K times INSIDE one program
    (lax.scan) and D2H forces completion; per-step = (total - noop) / K.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} —
detail now carries achieved FLOP/s, MFU vs bf16 peak, pipeline GB/s, and a
device-parquet scan-decode GB/s companion metric (round-3 verdict item 1b).

Hardening (round-1 failure mode): the axon TPU backend can fail at init
(UNAVAILABLE) or hang indefinitely in make_c_api_client. The parent process
therefore runs the measurement in a CHILD subprocess under a watchdog timeout,
retries on failure/timeout with backoff, and on final failure prints a single
parseable {"metric": ..., "error": ...} JSON line instead of a traceback —
one round must never lose its perf evidence to a transient backend error
(reference bar: fail fast + loud, Plugin.scala:365-389,436-459).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_FACT = 4_194_304
N_DIM = 65_536
N_GROUPS = 1_024
KEY_SPACE = 131_072
BYTES_PER_ROW = 8 + 4 + 8  # fact: key i64, grp i32, val f64
K_STEPS = 8

# FLOP accounting (round-3 verdict item 1b: emit achieved FLOP/s + MFU).
#   * algorithmic: what the query semantically needs per fact row —
#     1 compare + 1 mul + 1 select + 1 add.
#   * executed: what actually runs on the MXU — the Pallas segmented sum
#     computes, per row, a [LANES]x[LANES,G] one-hot dot contribution
#     (G MACs = 2G flops) twice (hi/lo f64 split), so N*G*4.
ALGO_FLOPS_PER_STEP = 4 * N_FACT
MXU_FLOPS_PER_STEP = N_FACT * N_GROUPS * 4

# Peak bf16 FLOP/s per chip by jax device_kind substring (public specs:
# cloud.google.com/tpu/docs/system-architecture-tpu-vm). MFU is reported
# against bf16 peak — the standard convention — even though this pipeline
# runs f32/f64 work, so the number is conservative.
_PEAK_BF16_BY_KIND = [
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),        # v5e reports device_kind "TPU v5 lite" / "v5litepod"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    k = device_kind.lower()
    for sub, peak in _PEAK_BF16_BY_KIND:
        if sub in k:
            return peak
    return None


def make_data(seed: int = 0):
    rng = np.random.default_rng(seed)
    fact_key = rng.integers(0, KEY_SPACE, size=N_FACT).astype(np.int64)
    fact_grp = rng.integers(0, N_GROUPS, size=N_FACT).astype(np.int32)
    fact_val = rng.uniform(0.5, 1.5, size=N_FACT).astype(np.float64)
    dim_key = np.sort(rng.permutation(KEY_SPACE)[:N_DIM]).astype(np.int64)
    dim_w = rng.uniform(0.5, 1.5, size=N_DIM).astype(np.float64)
    return fact_key, fact_grp, fact_val, dim_key, dim_w


def tpu_many_steps():
    """One program running the query step K_STEPS times (amortizes tunnel RPC).

    The grouped aggregation runs through the Pallas MXU segmented-sum kernel
    (ops/pallas_segsum.py): XLA's f64 segment_sum lowers to an emulated-f64
    scatter-add measured at 0.300s/step for this shape; the Pallas kernel does
    the same reduction in 0.019s/step at ~1e-9 relative error (one-hot MXU
    matmuls on a hi/lo split, per-chunk f32 partials combined in f64)."""
    import jax
    import jax.numpy as jnp
    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.ops.pallas_segsum import segment_sum_f64

    @jax.jit
    def many(fact_key, fact_grp, fact_val, dim_key, dim_w):
        tw = jnp.zeros(KEY_SPACE, jnp.float64).at[dim_key].set(dim_w)
        tm = jnp.zeros(KEY_SPACE, bool).at[dim_key].set(True)

        def step(acc, _):
            keep = fact_val > 0.6
            w = tw[fact_key]
            matched = tm[fact_key] & keep
            contrib = jnp.where(matched, fact_val * w, 0.0)
            sums = segment_sum_f64(contrib, fact_grp, N_GROUPS)
            rows = jnp.sum(matched).astype(jnp.int64)
            return (acc[0] + sums, acc[1] + rows), None

        init = (jnp.zeros(N_GROUPS, jnp.float64), jnp.int64(0))
        (sums, rows), _ = jax.lax.scan(step, init, jnp.arange(K_STEPS))
        return sums / K_STEPS, rows // K_STEPS

    return many


def cpu_pipeline(fact_key, fact_grp, fact_val, dim_key, dim_w,
                 lo: int = 0, hi: int = None):
    fk = fact_key[lo:hi]
    keep = fact_val[lo:hi] > 0.6
    ix = np.clip(np.searchsorted(dim_key, fk), 0, len(dim_key) - 1)
    matched = (dim_key[ix] == fk) & keep
    contrib = np.where(matched, fact_val[lo:hi] * dim_w[ix], 0.0)
    sums = np.bincount(fact_grp[lo:hi], weights=contrib,
                       minlength=N_GROUPS)
    return sums, int(matched.sum())


# fork-inherited by oracle worker processes (copy-on-write, no pickling)
_ORACLE_DATA = None


def _oracle_shard(bounds):
    lo, hi = bounds
    return cpu_pipeline(*_ORACLE_DATA, lo=lo, hi=hi)


def cpu_oracle_parallel(data, workers: int):
    """Row-sharded CPU oracle across `workers` forked processes — the
    honest multi-core CPU baseline (round-4 verdict weak #3: the single-
    process oracle slows with machine load, swinging the headline 23.9 ->
    56.4). Returns (sums, rows, best wall seconds of 3 timed parallel
    runs); pool spin-up and a warm pass are excluded, per-map scatter/
    gather overhead is included (it is part of a real parallel oracle)."""
    import multiprocessing as mp
    global _ORACLE_DATA
    _ORACLE_DATA = data
    bounds = np.linspace(0, N_FACT, workers + 1).astype(int)
    shards = list(zip(bounds[:-1], bounds[1:]))
    ctx = mp.get_context("fork")
    with ctx.Pool(workers) as pool:
        parts = pool.map(_oracle_shard, shards)  # warm: faults, imports
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            parts = pool.map(_oracle_shard, shards)
            best = min(best, time.perf_counter() - t0)
    sums = np.sum([p[0] for p in parts], axis=0)
    rows = sum(p[1] for p in parts)
    return sums, rows, best


def _force(x):
    return np.asarray(x)


SCAN_ROWS = 2_097_152
SCAN_ROW_GROUP = SCAN_ROWS // 8   # 8 chunks: the multi-chunk fusion unit
SCAN_CHUNKS_PER_DISPATCH = 4


def scan_decode_bench(tmpdir: str):
    """Device parquet decode throughput (io/parquet_device.py) vs the
    HOST pyarrow decode of the SAME file, measured in the same process —
    round-4 verdict item 2 ("prove the device path beats the thing it
    replaced"). Two corpora: snappy (decompression-bound for any decoder
    — both paths pay it) and uncompressed (the decode paths themselves).
    Both device paths are measured: the serial per-row-group decode (the
    r05 unit, `_serial` keys) and the pipelined fused MULTI-CHUNK decode
    (packed single-transfer, N row groups per dispatch) that is the
    headline — with TaskMetrics dispatch accounting beside each so the
    dispatch amortization (dispatches-per-scan-batch, ISSUE-6 acceptance)
    is in the JSON, not inferred. GB/s are file-relative; raw decoded
    bytes ride along. May raise; the caller guards (main() prints the
    primary metric line first)."""
    import jax
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io.parquet_device import (
        device_decode_file, file_supported)
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    rng = np.random.default_rng(7)
    n = SCAN_ROWS
    t = pa.table({
        "k": pa.array(rng.integers(0, 1 << 40, n)),
        "v": pa.array(rng.uniform(0.0, 1.0, n)),
        "g": pa.array(rng.integers(0, 1024, n).astype(np.int32)),
    })
    raw_bytes = n * (8 + 8 + 4)
    session = TpuSession({"spark.rapids.sql.enabled": True,
                          "spark.rapids.sql.explain": "NONE"})
    session.initialize_device()
    out = {"scan_rows": n, "scan_row_groups": n // SCAN_ROW_GROUP,
           "scan_chunks_per_dispatch": SCAN_CHUNKS_PER_DISPATCH}

    for tag, comp in (("", "snappy"), ("_plain", "none")):
        path = os.path.join(tmpdir, f"scanbench{tag}.parquet")
        pq.write_table(t, path, compression=comp,
                       row_group_size=SCAN_ROW_GROUP)
        file_bytes = os.path.getsize(path)
        schema = session.read_parquet(path).plan.output

        def run(chunks):
            tm = TaskMetrics.get()
            tm.scan_dispatches = tm.scan_chunks = 0
            leaves = []
            batches = 0
            pf = file_supported(path, schema)
            for batch, _rows in device_decode_file(
                    pf, path, schema, chunks_per_dispatch=chunks):
                batches += 1
                for col in batch.columns:
                    leaves.append(col.data)
            jax.block_until_ready(leaves)
            return tm.scan_dispatches, tm.scan_chunks, batches

        def measure(chunks):
            # compile separated from execute: the first call pays
            # trace+compile (or a persistent-cache load on a warm
            # process); steady-state execute is measured warm. BENCH json
            # carries both so warm-path wins stay trackable per round.
            t0 = time.perf_counter()
            dispatches, chnks, batches = run(chunks)
            compile_s = time.perf_counter() - t0
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                run(chunks)
                best = min(best, time.perf_counter() - t0)
            return compile_s, best, dispatches, chnks, batches

        comp_m, best_m, disp_m, chnk_m, batch_m = \
            measure(SCAN_CHUNKS_PER_DISPATCH)
        comp_s, best_s, disp_s, chnk_s, batch_s = measure(1)
        host = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            pq.read_table(path)
            host = min(host, time.perf_counter() - t0)
        out.update({
            # headline: the pipelined fused multi-chunk path
            f"scan_compile_s{tag}": round(max(comp_m - best_m, 0.0), 5),
            f"scan_decode_gbps_raw{tag}": round(raw_bytes / best_m / 1e9,
                                                3),
            f"scan_decode_gbps_file{tag}":
                round(file_bytes / best_m / 1e9, 3),
            f"scan_decode_s{tag}": round(best_m, 5),
            f"dispatches_per_scan_batch{tag}":
                round(disp_m / max(batch_m, 1), 2),
            f"dispatches_per_chunk{tag}":
                round(disp_m / max(chnk_m, 1), 2),
            # the r05 serial per-row-group unit, same file, same process
            f"scan_decode_gbps_file_serial{tag}":
                round(file_bytes / best_s / 1e9, 3),
            f"scan_decode_s_serial{tag}": round(best_s, 5),
            f"dispatches_per_scan_batch_serial{tag}":
                round(disp_s / max(batch_s, 1), 2),
            f"dispatch_reduction_x{tag}":
                round((disp_s / max(chnk_s, 1))
                      / (disp_m / max(chnk_m, 1)), 2),
            # the thing the device path replaced
            f"host_pyarrow_gbps_file{tag}":
                round(file_bytes / host / 1e9, 3),
            f"host_pyarrow_s{tag}": round(host, 5),
            f"scan_vs_host{tag}": round(host / best_m, 3),
        })
    try:
        out.update(pipeline_query_bench(tmpdir))
    except Exception as e:  # must not sink the scan numbers
        out["pipeline_bench_error"] = f"{type(e).__name__}: {e}"
    try:
        out.update(scan_pushdown_bench(tmpdir))
    except Exception as e:  # must not sink the scan numbers
        out["pushdown_bench_error"] = f"{type(e).__name__}: {e}"
    return out


PIPE_DIM = 4096


def pipeline_query_bench(tmpdir: str) -> dict:
    """End-to-end pipeline-on vs pipeline-off on the scan+join bench
    (ISSUE-6 acceptance): the SAME engine query — parquet scan -> filter
    -> hash join -> grouped agg — runs with pipelined execution on and
    off, results must be bit-identical, and both wall times land in the
    JSON. The aggregation sums an INTEGER column and counts rows so the
    equality gate is exact: f64 sums regroup across the pipeline's larger
    merged batches (the documented variableFloatAgg grouping caveat) and
    would reduce the gate to approx."""
    import pyarrow as pa
    from spark_rapids_tpu.expr import Count, Sum, col
    from spark_rapids_tpu.plugin import TpuSession

    rng = np.random.default_rng(11)
    path = os.path.join(tmpdir, "pipebench.parquet")
    if not os.path.exists(path):
        import pyarrow.parquet as pq
        n = SCAN_ROWS // 2
        t = pa.table({
            "k": pa.array(rng.integers(0, PIPE_DIM, n)),
            "g": pa.array(rng.integers(0, 1024, n).astype(np.int32)),
            "v": pa.array(rng.uniform(0.0, 1.0, n)),
            "c": pa.array(rng.integers(0, 1 << 30, n)),
        })
        pq.write_table(t, path, row_group_size=SCAN_ROW_GROUP)
    dim = pa.table({
        "k": pa.array(np.arange(PIPE_DIM)),
        "w": pa.array(rng.integers(0, 1000, PIPE_DIM)),
    })

    def run(pipeline: bool):
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.pipeline.enabled": pipeline,
        })
        sess.initialize_device()
        q = (sess.read_parquet(path)
             .filter(col("v") > 0.25)
             .join(sess.from_arrow(dim), on="k")
             .group_by("g").agg(total=Sum(col("c") + col("w")),
                                cnt=Count(col("v"))))
        q.collect()  # warm (compiles)
        best = float("inf")
        res = None
        for _ in range(3):
            t0 = time.perf_counter()
            res = q.collect()
            best = min(best, time.perf_counter() - t0)
        return res.sort_by("g"), best

    res_off, t_off = run(False)
    res_on, t_on = run(True)
    return {
        "pipeline_on_s": round(t_on, 5),
        "pipeline_off_s": round(t_off, 5),
        "pipeline_speedup": round(t_off / t_on, 3),
        "pipeline_identical": bool(res_on.equals(res_off)),
    }


def scan_pushdown_bench(tmpdir: str, full: bool = False) -> dict:
    """Scan-pushdown sweep (ISSUE-12): the SAME engine query — parquet
    scan -> filter (-> aggregate) — with pushdown on vs off, across
    selectivity x predicate type, reporting file-relative GB/s, device
    ROW-DATA bytes materialised and rows pruned pre-materialisation (the
    machine-independent proxies), plus the aggregate-only shape that must
    materialise zero row data. Results are equality-gated per shape.
    Footer row-group pruning stays ON (it is part of the shipped path);
    the uniformly-shuffled string column defeats it, so `str_eq` isolates
    the in-dispatch dictionary-domain win while `int_*` shapes also bank
    clustered-predicate row-group skips — both appear in real scans.
    `full=False` keeps the sweep inside the --scan-only child budget."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.expr import Count, Max, Min, Sum, col
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    rng = np.random.default_rng(19)
    n = SCAN_ROWS // 2
    path = os.path.join(tmpdir, "pdbench.parquet")
    if not os.path.exists(path):
        t = pa.table({
            "k": pa.array(np.arange(n, dtype=np.int64)),
            "g": pa.array(rng.integers(0, 1024, n).astype(np.int32)),
            "s": pa.array([f"name{v:03d}" for v in
                           rng.integers(0, 100, n)]),
            "v": pa.array(rng.uniform(0.0, 1.0, n)),
        })
        pq.write_table(t, path, row_group_size=SCAN_ROW_GROUP)
    file_bytes = os.path.getsize(path)

    shapes = [
        ("int_sel1", lambda df: df.filter(col("k") < n // 100), None),
        ("str_eq", lambda df: df.filter(col("s") == "name007"), None),
        ("agg_only", lambda df: df.filter(col("k") < n // 20).agg(
            cnt=Count(), mn=Min(col("k")), mx=Max(col("g")),
            sm=Sum(col("k"))), "k"),
    ]
    if full:
        shapes[1:1] = [
            ("int_sel50", lambda df: df.filter(col("k") < n // 2), None),
            ("int_sel100", lambda df: df.filter(col("k") >= 0), None),
        ]

    out = {"pushdown_rows": n, "pushdown_file_bytes": file_bytes}

    def run(build, pushdown):
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.scan.pushdown.enabled": pushdown,
        })
        sess.initialize_device()
        q = build(sess.read_parquet(path))
        q.collect()  # warm (compiles)
        best, res = float("inf"), None
        for _ in range(3):
            TaskMetrics.reset()  # metrics report ONE run, not the sum
            t0 = time.perf_counter()
            res = q.collect()
            best = min(best, time.perf_counter() - t0)
        tm = TaskMetrics.get()
        return res, best, tm.scan_bytes_materialized, tm.scan_rows_pruned

    for name, build, sort_col in shapes:
        res_on, t_on, bytes_on, pruned_on = run(build, True)
        res_off, t_off, _, _ = run(build, False)
        a, b = res_on, res_off
        if sort_col is None and a.num_rows and "k" in a.schema.names:
            a = a.sort_by([("k", "ascending")])
            b = b.sort_by([("k", "ascending")])
        out.update({
            f"pushdown_{name}_gbps_on": round(file_bytes / t_on / 1e9, 3),
            f"pushdown_{name}_gbps_off": round(file_bytes / t_off / 1e9,
                                               3),
            f"pushdown_{name}_s_on": round(t_on, 5),
            f"pushdown_{name}_s_off": round(t_off, 5),
            f"pushdown_{name}_speedup": round(t_off / t_on, 3),
            f"pushdown_{name}_bytes_materialized": int(bytes_on),
            f"pushdown_{name}_rows_pruned": int(pruned_on),
            f"pushdown_{name}_identical": bool(a.equals(b)),
        })
    return out


def fusion_query_bench() -> dict:
    """Whole-stage fusion sweep (ISSUE-16): the SAME engine query with
    fusion on vs off across three chain shapes — filter->project,
    project->broadcast-probe->project, and an expression-heavy
    filter + stacked-projection chain — reporting wall, device-dispatch
    counts per run (the machine-independent win: one dispatch per fused
    stage per batch) and the per-shape bit-identical gate. The gates the
    matrix script enforces: >=2x fewer dispatches overall, wall no worse
    on any shape, faster on the expression-heavy shape."""
    import pyarrow as pa
    from spark_rapids_tpu.expr import col
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    rng = np.random.default_rng(23)
    n = SCAN_ROWS // 4
    fact = pa.table({
        "k": pa.array(rng.integers(0, 4096, n).astype(np.int64)),
        "a": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "b": pa.array(rng.integers(1, 100, n).astype(np.int64)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(4096, dtype=np.int64)),
        "w": pa.array(rng.integers(1, 9, 4096).astype(np.int64)),
    })

    def fp(df, _):  # filter -> project
        return df.filter(col("a") > 0).select(
            (col("a") * 2 + col("b")).alias("x"), col("k"))

    def join(df, sess):  # project -> broadcast probe -> project
        d = sess.from_arrow(dim)
        return df.select(col("k"), (col("a") + col("b")).alias("v")) \
            .join(d, on="k", how="inner") \
            .select((col("v") * col("w")).alias("x"), col("k"))

    def exprheavy(df, _):  # long chain: per-op dispatch overhead dominates
        q = df.filter(col("a") > -900)
        for i in range(1, 7):
            q = q.select(col("k"), (col("a") + i).alias("a"),
                         (col("b") * 2 - col("a")).alias("b"))
        return q.select((col("a") + col("b")).alias("x"), col("k"))

    def prep(build, fusion):
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.fusion.enabled": fusion,
        })
        sess.initialize_device()
        q = build(sess.from_arrow(fact), sess)
        q.collect()  # warm (compiles)
        return q

    def measure(q):
        TaskMetrics.reset()  # dispatches report ONE run, not the sum
        t0 = time.perf_counter()
        res = q.collect()
        return res, time.perf_counter() - t0, \
            TaskMetrics.get().device_dispatches

    def run(build):
        # interleave the on/off reps so clock-speed / cache drift within
        # the process cancels instead of biasing whichever ran first
        q_on, q_off = prep(build, True), prep(build, False)
        t_on = t_off = float("inf")
        for _ in range(5):
            res_on, t, d_on = measure(q_on)
            t_on = min(t_on, t)
            res_off, t, d_off = measure(q_off)
            t_off = min(t_off, t)
        return res_on, t_on, d_on, res_off, t_off, d_off

    out = {"fusion_rows": n}
    tot_on = tot_off = 0
    for name, build in [("fp", fp), ("join", join),
                        ("exprheavy", exprheavy)]:
        res_on, t_on, d_on, res_off, t_off, d_off = run(build)
        a = res_on.sort_by([("k", "ascending"), ("x", "ascending")])
        b = res_off.sort_by([("k", "ascending"), ("x", "ascending")])
        tot_on += d_on
        tot_off += d_off
        out.update({
            f"fusion_{name}_s_on": round(t_on, 5),
            f"fusion_{name}_s_off": round(t_off, 5),
            f"fusion_{name}_speedup": round(t_off / t_on, 3),
            f"fusion_{name}_dispatches_on": int(d_on),
            f"fusion_{name}_dispatches_off": int(d_off),
            f"fusion_{name}_identical": bool(a.equals(b)),
        })
    out["fusion_dispatch_reduction_x"] = round(tot_off / max(tot_on, 1), 3)
    return out


ATTEMPTS = 3
# First compile via the tunnel is ~20-40s per program and the measured
# sections are seconds; a healthy cold run (pipeline + scan-decode compiles)
# fits in ~3 min. A hung backend init eats the whole window, so keep it
# bounded — 3 attempts must stay well under the driver's round budget.
ATTEMPT_TIMEOUT_S = 300
_CHILD_ENV = "SPARK_RAPIDS_TPU_BENCH_CHILD"
_MARK = "@BENCH_RESULT@"


_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")


def _enable_compilation_cache():
    """Persist compiled programs across processes/rounds: a warm bench run
    skips the ~20-40s tunnel compile, so a healthy attempt completes in
    seconds (round-2 verdict item 1a)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax: default is fine


def _apply_platform_override():
    """Test hook: SPARK_RAPIDS_TPU_BENCH_PLATFORM=cpu forces the platform
    (the axon plugin overrides JAX_PLATFORMS, so env alone is not enough)."""
    plat = os.environ.get("SPARK_RAPIDS_TPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def main():
    t_start = time.perf_counter()
    _enable_compilation_cache()
    _apply_platform_override()
    import jax
    import jax.numpy as jnp

    data = make_data()
    dev_args = [jnp.asarray(a) for a in data]

    # tunnel RPC floor: noop program, D2H-forced
    noop = jax.jit(lambda x: x + 1)
    _force(noop(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(10):
        _force(noop(jnp.float32(0)))
    overhead = (time.perf_counter() - t0) / 10

    many = tpu_many_steps()
    t0 = time.perf_counter()
    _force(many(*dev_args)[0])  # compile (or persistent-cache load)
    t_compile_wall = time.perf_counter() - t0
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        sums, rows = many(*dev_args)
        _force(sums)
        best = min(best, time.perf_counter() - t0)
    t_tpu = max((best - overhead) / K_STEPS, 1e-9)

    t_cpu_1p = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_sums, cpu_rows = cpu_pipeline(*data)
        t_cpu_1p = min(t_cpu_1p, time.perf_counter() - t0)
    # headline oracle: multi-process (all cores), so `vs_baseline` stops
    # swinging with machine load starving one python process; the
    # single-process number rides along for cross-round continuity
    workers = min(os.cpu_count() or 1, 8)
    if workers > 1:
        try:
            par_sums, par_rows, t_cpu = cpu_oracle_parallel(data, workers)
        except OSError:  # fork-hostile environment: single-proc oracle
            workers, t_cpu = 1, t_cpu_1p
        else:
            # correctness of the parallel oracle must fail LOUDLY — only
            # environment errors above may downgrade to single-process
            assert par_rows == cpu_rows, (par_rows, cpu_rows)
            np.testing.assert_allclose(par_sums, cpu_sums, rtol=1e-9)
    else:
        t_cpu = t_cpu_1p
    assert int(rows) == cpu_rows, (int(rows), cpu_rows)
    # K-step accumulate/divide reorders f64 additions; this is a sanity check,
    # exactness is the differential suite's job
    np.testing.assert_allclose(np.asarray(sums), cpu_sums, rtol=1e-6)

    speedup = t_cpu / t_tpu
    gbps = N_FACT * BYTES_PER_ROW / t_tpu / 1e9
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev))
    peak = _peak_flops(kind)
    mxu_flops = MXU_FLOPS_PER_STEP / t_tpu
    # compile vs execute split: compile_s is the first-call wall minus one
    # steady-state execution — ~0 on a warm persistent cache, tens of
    # seconds cold over the tunnel — so BENCH rounds can track warm-path
    # wins separately from kernel-time regressions.
    try:  # per-attempt machine-load context (VERDICT weak #3: the
        loadavg = [round(x, 2) for x in os.getloadavg()]  # oracle swings
    except OSError:                                       # with load)
        loadavg = None
    detail = {"device": str(dev), "device_kind": kind,
              "tpu_step_s": round(t_tpu, 5), "cpu_s": round(t_cpu, 5),
              "cpu_s_singleproc": round(t_cpu_1p, 5),
              "cpu_oracle_workers": workers,
              "loadavg": loadavg,
              "compile_s": round(max(t_compile_wall - best, 0.0), 4),
              "execute_s": round(best, 5),
              "pipeline_gbps": round(gbps, 3), "rows": N_FACT,
              "rpc_overhead_s": round(overhead, 4),
              "executed_mxu_flops_per_s": round(mxu_flops, 1),
              "algo_flops_per_s": round(ALGO_FLOPS_PER_STEP / t_tpu, 1),
              "mfu_vs_bf16_peak": (round(mxu_flops / peak, 6)
                                   if peak else None),
              "peak_bf16_flops": peak}

    def emit(d):
        print(_MARK + json.dumps({
            "metric": "scan_join_agg_speedup_vs_cpu",
            "value": round(speedup, 3),
            "unit": "x",
            "vs_baseline": round(speedup / 4.0, 3),
            "detail": d,
        }), flush=True)

    # Primary metric FIRST: if the scan bench hangs and the watchdog kills
    # this child, the supervisor still salvages this line from partial
    # stdout. A successful scan bench re-emits with the extra fields; the
    # supervisor takes the LAST marked line.
    emit(detail)
    try:
        detail.update(_scan_bench_subprocess(t_start))
    except Exception as e:  # scan bench must not sink the primary metric
        detail["scan_decode_error"] = f"{type(e).__name__}: {e}"
    emit(detail)
    # scheduler scenario (ISSUE-7): appended to the BENCH detail when the
    # attempt budget allows; a failure/timeout records the error and keeps
    # every number already emitted
    try:
        detail["sched_bench"] = _sched_bench_subprocess(t_start)
    except Exception as e:
        detail["sched_bench_error"] = f"{type(e).__name__}: {e}"
    emit(detail)


SCAN_CHILD_TIMEOUT_S = 240


def _child_bench_subprocess(flag: str, t_attempt_start: float,
                            marker: str = _MARK,
                            keep_marker: bool = False) -> dict:
    """Run one bench scenario in a FRESH child process, its timeout
    clamped to the REMAINING attempt budget (minus margin for the final
    emit) so the attempt watchdog can never fire while the grandchild
    runs and orphan it. Returns the last `marker`-prefixed JSON line
    (`keep_marker` when the marker is part of the JSON itself)."""
    elapsed = time.perf_counter() - t_attempt_start
    budget = min(SCAN_CHILD_TIMEOUT_S, ATTEMPT_TIMEOUT_S - elapsed - 20)
    if budget <= 5:
        raise RuntimeError(f"no attempt budget left for the {flag} child")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), flag],
        capture_output=True, text=True, timeout=budget)
    for line in reversed((proc.stdout or "").splitlines()):
        if line.startswith(marker):
            return json.loads(line if keep_marker else line[len(marker):])
    raise RuntimeError(
        f"{flag} child rc={proc.returncode}: "
        f"{(proc.stderr or '')[-300:]}")


def _scan_bench_subprocess(t_attempt_start: float) -> dict:
    """Scan bench in its own process. After a large compiled program
    executes, the axon tunnel drops out of its fast dispatch path (eager
    per-op latency measured 0.04ms -> 3.7ms, H2D goes synchronous),
    which buries the scan measurement under ~8x inflated transfer time; a
    real scan runs in its own executor process, so a fresh child is the
    faithful measurement."""
    return _child_bench_subprocess("--scan-only", t_attempt_start)


def _sched_bench_subprocess(t_attempt_start: float) -> dict:
    """Sched scenario in a fresh process (same rationale as the scan
    child: engine state from the main measurement must not skew it).
    --sched prints bare JSON, so the marker is the opening brace."""
    return _child_bench_subprocess("--sched", t_attempt_start, marker="{",
                                   keep_marker=True)


def scan_only() -> None:
    _enable_compilation_cache()
    _apply_platform_override()
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        print(_MARK + json.dumps(scan_decode_bench(td)), flush=True)


PROFILE_ROWS = 32_768
PROFILE_DIM = 512
PROFILE_GROUPS = 16


def profile_query(log_dir: str, force_spill: bool = True) -> dict:
    """Run two representative engine queries with the profiler's JSONL
    event log enabled (ISSUE-4 flag: `--profile-query DIR`):

      1. scan -> filter -> shuffle repartition -> hash join -> ORDER BY a
         detail column — small batches force the out-of-core sort (runs
         parked spillable) and, with `force_spill`, a tight device budget
         makes parked runs spill to host for real;
      2. scan -> grouped aggregation.

    Together the emitted profile exercises every phase the report tool
    breaks down (op/sort/join/agg/spill/shuffle timers all nonzero) and
    gives the per-query comparison table two rows. Returns a summary
    dict; the caller prints it as one JSON line."""
    _apply_platform_override()
    import pyarrow as pa
    from spark_rapids_tpu.expr import Sum, col
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.spans import validate_record

    rng = np.random.default_rng(11)
    n = PROFILE_ROWS
    fact = pa.table({
        "k": pa.array(rng.integers(0, PROFILE_DIM, n)),
        "g": pa.array(rng.integers(0, PROFILE_GROUPS, n).astype(np.int32)),
        "v": pa.array(rng.uniform(0.0, 1.0, n)),
    })
    dim = pa.table({
        "k": pa.array(np.arange(PROFILE_DIM)),
        "w": pa.array(rng.uniform(0.0, 1.0, PROFILE_DIM)),
    })
    session = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.explain": "NONE",
        "spark.rapids.sql.metrics.level": "DEBUG",
        "spark.rapids.tpu.metrics.eventLog.dir": log_dir,
        # many small batches: the sort takes its out-of-core path (runs
        # parked spillable) and the exchange really partitions
        "spark.rapids.sql.batchSizeRows": 4096,
        "spark.rapids.sql.batchSizeBytes": 1 << 20,
    })
    session.initialize_device()
    if force_spill:
        # tight budget: parked sort runs / join builds exceed it, so the
        # park-time accounting (MemoryBudget.note_parked) spills older
        # runs to host — spillTime/readSpill are real measurements
        from spark_rapids_tpu.memory.budget import MemoryBudget
        MemoryBudget.initialize(1 << 20, session.conf)

    q1 = (session.from_arrow(fact)
          .filter(col("v") > 0.1)
          .repartition(4, "k")
          .join(session.from_arrow(dim), on="k")
          .sort("v"))
    out1 = q1.collect()
    prof1 = session.last_profile

    q2 = (session.from_arrow(fact)
          .group_by("g").agg(total=Sum(col("v"))))
    out2 = q2.collect()
    prof2 = session.last_profile

    timers: dict = {}
    bad = 0
    n_recs = 0
    spilled_ns = 0
    for prof in (prof1, prof2):
        if prof is None:
            continue
        recs = prof.to_records()
        n_recs += len(recs)
        bad += sum(1 for r in recs if validate_record(r))
        for r in recs:
            if r["type"] == "operator":
                for k, v in r["metrics"].items():
                    if k.lower().endswith("time") and v:
                        timers[k] = timers.get(k, 0) + v
        tm = prof.task_metrics
        spilled_ns += tm.get("spill_to_host_ns", 0) + \
            tm.get("spill_to_disk_ns", 0)
    return {
        "metric": "profile_query",
        "rows_out": out1.num_rows + out2.num_rows,
        "event_log_dir": log_dir,
        "records": n_recs,
        "invalid_records": bad,
        "wall_ms": round(sum((p.wall_ns if p else 0)
                             for p in (prof1, prof2)) / 1e6, 1),
        "spill_ms": round(spilled_ns / 1e6, 3),
        "nonzero_timers": sorted(timers),
        "task_metrics": {k: v for k, v in (prof2.task_metrics if prof2
                                           else {}).items() if v},
    }


SCHED_LOW_QUERIES = 8
SCHED_HIGH_QUERIES = 2
SCHED_ROWS = 200_000


def sched_bench() -> dict:
    """Overloaded mixed-priority workload (ISSUE-7 flag: `bench.py
    --sched`): N_low low-priority queries flood a concurrentGpuTasks=1
    engine, then N_high high-priority queries arrive late. The SAME
    workload runs twice — FIFO baseline (sched.enabled=false; queries
    still carry contexts so admission is per-query and waits are
    measurable) and scheduler-on (strict priority + fair share) — and the
    JSON reports per-mode admission-wait p50/p99 and the high-priority
    latency the scheduler exists to protect. Acceptance: sched-on
    high-pri p99 < FIFO high-pri p99 under overload."""
    _apply_platform_override()
    import pyarrow as pa
    from spark_rapids_tpu.expr import Count, Sum, col
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.sched import QueryContext
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    from spark_rapids_tpu.tools.profile_report import _percentile

    rng = np.random.default_rng(17)
    n = SCHED_ROWS
    t = pa.table({
        "k": pa.array(rng.integers(0, 4096, n)),
        "g": pa.array(rng.integers(0, 256, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
    })

    def percentile(vals, p):
        return _percentile(sorted(vals), p)

    def run_mode(sched_on: bool) -> dict:
        import threading
        import time as _t
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.concurrentGpuTasks": 1,
            "spark.rapids.tpu.sched.enabled": sched_on,
        })
        sess.initialize_device()
        TpuSemaphore.initialize(1, sess.conf)

        def make_plan():
            return (sess.from_arrow(t).filter(col("v") > 0.2)
                    .group_by("g").agg(total=Sum(col("v")),
                                       cnt=Count(col("k")))).plan

        # warm: compiles out of the measurement
        sess.execute_plan(make_plan(), sched_ctx=QueryContext())
        lat = {}
        wait = {}
        errs = []

        def worker(name, priority):
            try:
                ctx = QueryContext(priority=priority)
                t0 = _t.perf_counter()
                sess.execute_plan(make_plan(), sched_ctx=ctx)
                lat[name] = _t.perf_counter() - t0
                wait[name] = TaskMetrics.get().semaphore_wait_ns / 1e9
            except Exception as e:  # noqa: BLE001 — reported in JSON
                errs.append(f"{name}: {type(e).__name__}: {e}")

        low = [threading.Thread(target=worker, args=(f"low{i}", 0))
               for i in range(SCHED_LOW_QUERIES)]
        high = [threading.Thread(target=worker, args=(f"high{i}", 10))
                for i in range(SCHED_HIGH_QUERIES)]
        for th in low:
            th.start()
        _t.sleep(0.05)  # the overload is standing when high-pri arrives
        for th in high:
            th.start()
        for th in low + high:
            th.join(timeout=600)
        TpuSemaphore._instance = None
        waits = list(wait.values())
        high_lat = [lat[k] for k in lat if k.startswith("high")]
        return {
            "queries": len(lat),
            "errors": errs,
            "wait_p50_s": round(percentile(waits, 50), 4),
            "wait_p99_s": round(percentile(waits, 99), 4),
            "highpri_mean_s": round(float(np.mean(high_lat)), 4)
            if high_lat else None,
            "highpri_p99_s": round(percentile(high_lat, 99), 4)
            if high_lat else None,
        }

    fifo = run_mode(False)
    sched = run_mode(True)
    out = {
        "metric": "sched_bench",
        "low_queries": SCHED_LOW_QUERIES,
        "high_queries": SCHED_HIGH_QUERIES,
        "rows_per_query": SCHED_ROWS,
        "fifo": fifo,
        "sched": sched,
    }
    if fifo.get("highpri_p99_s") and sched.get("highpri_p99_s"):
        out["highpri_p99_speedup_x"] = round(
            fifo["highpri_p99_s"] / sched["highpri_p99_s"], 3)
    return out


RESCACHE_ROWS = 400_000
RESCACHE_REPEATS = 21  # 1 cold + 20 warm => 20/21 ≈ 0.95 hit rate


def rescache_bench() -> dict:
    """Repeated-dashboard-query workload (ISSUE-9 flag: `bench.py
    --rescache`): the SAME scan->filter->aggregate query over a parquet
    file runs RESCACHE_REPEATS times with the result cache on. Reports
    the whole-query hit rate, cold-vs-warm latency (a warm hit is a host
    reply — no decode, no kernels, no admission), the bit-identical gate
    across every repetition, and the no-admission-token assertion
    (scheduler enabled; warm runs must record sched_admissions == 0).
    Acceptance: hit rate > 0.9 and measured warm speedup with identical
    results."""
    _apply_platform_override()
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu import rescache
    from spark_rapids_tpu.expr import Count, Sum, col
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    rng = np.random.default_rng(23)
    n = RESCACHE_ROWS
    t = pa.table({
        "k": pa.array(rng.integers(0, 4096, n)),
        "g": pa.array(rng.integers(0, 256, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
    })
    tmp = tempfile.mkdtemp(prefix="srtpu_rescache_bench_")
    path = os.path.join(tmp, "fact.parquet")
    pq.write_table(t, path, row_group_size=65_536)

    sess = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.explain": "NONE",
        "spark.rapids.tpu.rescache.enabled": True,
        "spark.rapids.tpu.sched.enabled": True,
    })
    sess.initialize_device()
    TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)

    def q():
        return (sess.read_parquet(path).filter(col("v") > 0.25)
                .group_by("g").agg(total=Sum(col("v")),
                                   cnt=Count(col("k")))
                ).collect().sort_by("g")

    # one throwaway compile-warm pass on a DIFFERENT (uncached) shape so
    # the cold measurement is decode+execute, not XLA compilation
    (sess.from_arrow(t.slice(0, 8192)).filter(col("v") > 0.25)
     .group_by("g").agg(total=Sum(col("v")),
                        cnt=Count(col("k")))).collect()

    lat = []
    admissions = []
    hits = []
    reference = None
    identical = True
    for _ in range(RESCACHE_REPEATS):
        t0 = time.perf_counter()
        r = q()
        lat.append(time.perf_counter() - t0)
        tm = TaskMetrics.get()
        admissions.append(tm.sched_admissions)
        hits.append(tm.rescache_hits)
        if reference is None:
            reference = r
        elif not r.equals(reference):
            identical = False
    stats = rescache.stats() or {}
    cold_s = lat[0]
    warm = lat[1:]
    warm_mean = float(np.mean(warm)) if warm else None
    hit_runs = sum(1 for h in hits[1:] if h >= 1)
    hit_rate = hit_runs / max(len(lat) - 1, 1)
    warm_admissions = sum(admissions[1:])
    TpuSemaphore._instance = None
    out = {
        "metric": "rescache_bench",
        "rows": n,
        "repeats": RESCACHE_REPEATS,
        "cold_s": round(cold_s, 5),
        "warm_mean_s": round(warm_mean, 6) if warm_mean else None,
        "warm_p50_s": round(sorted(warm)[len(warm) // 2], 6)
        if warm else None,
        "speedup_warm_vs_cold_x": round(cold_s / warm_mean, 2)
        if warm_mean else None,
        "hit_rate": round(hit_rate, 4),
        "bit_identical": identical,
        "warm_admissions_total": warm_admissions,
        "cache_stats": {k: stats.get(k) for k in
                        ("entries", "bytes", "hits", "misses", "stores",
                         "evictions")},
        "ok": bool(identical and hit_rate > 0.9
                   and warm_admissions == 0),
    }
    return out


MULTICHIP_NDEV = 8
MULTICHIP_ROWS = 400_000
MULTICHIP_DIM = 4_096


def multichip_bench() -> dict:
    """Sharded mesh execution end-to-end (ISSUE-15 flag: `bench.py
    --multichip`): the SAME scan->filter->exchange->join->agg query over
    one parquet fact file runs three ways on the same data —

      * single : one device, no exchanges (the BASELINE engine path);
      * host   : explicit 8-way hash repartition of both join inputs
                 through the MULTITHREADED shuffle manager (the host TCP
                 data plane's serialized bytes);
      * mesh   : `spark.rapids.tpu.mesh.*` sharded execution — scans
                 sharded across the 8 chips, exchanges as ICI
                 collectives, partitions device-resident between stages.

    Reports per-stage wall (scan / scan+filter / full pipeline, warm of
    two runs), bytes moved over ICI vs the host shuffle, collective and
    shard counts, and the bit-identical gate across all three legs.
    Acceptance: identical results, MESH_EXCHANGES > 0 on the mesh leg,
    ZERO host-shuffle bytes on the mesh leg. Feeds the next TPU run
    alongside MULTICHIP_rNN."""
    _apply_platform_override()
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.exec import exchange as EX
    from spark_rapids_tpu.expr import Count, Max, Min, Sum, col
    from spark_rapids_tpu.plugin import TpuSession
    from spark_rapids_tpu.utils.metrics import TaskMetrics

    import jax
    ndev = MULTICHIP_NDEV
    if len(jax.devices()) < ndev:
        return {"metric": "multichip_bench", "ndev": ndev,
                "error": f"only {len(jax.devices())} devices present "
                         "(hint: SPARK_RAPIDS_TPU_BENCH_PLATFORM=cpu "
                         "forces the 8-virtual-device mesh)"}

    rng = np.random.default_rng(15)
    n = MULTICHIP_ROWS
    fact = pa.table({
        "id": pa.array(rng.integers(0, 50_000, n), type=pa.int64()),
        "val": pa.array(rng.uniform(-1, 1, n), type=pa.float64()),
        "small": pa.array(rng.integers(-100, 100, n).astype(np.int32)),
    })
    dim_keys = rng.permutation(50_000)[:MULTICHIP_DIM]
    dim = pa.table({
        "id": pa.array(dim_keys, type=pa.int64()),
        "tag": pa.array([f"t{int(k) % 31}" for k in dim_keys]),
    })
    tmp = tempfile.mkdtemp(prefix="srtpu_multichip_bench_")
    fact_path = os.path.join(tmp, "fact.parquet")
    dim_path = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fact_path, row_group_size=n // 16)
    pq.write_table(dim, dim_path)

    base_conf = {
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.explain": "NONE",
        "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
    }
    mesh_conf = dict(base_conf)
    mesh_conf.update({
        "spark.rapids.shuffle.mode": "ICI",
        "spark.rapids.tpu.mesh.shape": f"shuffle={ndev}",
        "spark.rapids.tpu.mesh.enabled": True,
    })

    def queries(sess, repartition):
        scan = sess.read_parquet(fact_path)
        filt = scan.filter(col("val") > -0.5)
        left, right = filt, sess.read_parquet(dim_path)
        if repartition:
            left = left.repartition(ndev, "id")
            right = right.repartition(ndev, "id")
        full = (left.join(right, on="id", how="inner")
                .group_by("tag").agg(n=Count(col("val")),
                                     s=Sum(col("small")),
                                     mx=Max(col("id")),
                                     mn=Min(col("small"))))
        return {"scan": scan, "scan_filter": filt, "full": full}

    def run_leg(conf, repartition=False):
        sess = TpuSession(dict(conf))
        qs = queries(sess, repartition)
        stages = {}
        for name, q in qs.items():
            walls = []
            for _ in range(2):  # second run is compile-warm
                t0 = time.perf_counter()
                result = q.collect()
                walls.append(time.perf_counter() - t0)
            stages[name + "_s"] = round(min(walls), 4)
            stages[name + "_cold_s"] = round(walls[0], 4)
        TaskMetrics.reset()
        before = EX.MESH_EXCHANGES
        result = qs["full"].collect().sort_by("tag")
        tm = TaskMetrics.get()
        return result, stages, {
            "mesh_exchanges": EX.MESH_EXCHANGES - before,
            "mesh_shards": tm.mesh_shards,
            "ici_bytes": tm.mesh_ici_bytes,
            "host_shuffle_bytes": tm.shuffle_bytes_written,
        }

    r_single, st_single, m_single = run_leg(base_conf)
    r_host, st_host, m_host = run_leg(base_conf, repartition=True)
    r_mesh, st_mesh, m_mesh = run_leg(mesh_conf)

    identical = r_single.equals(r_host) and r_single.equals(r_mesh)
    out = {
        "metric": "multichip_bench",
        "ndev": ndev,
        "rows": n,
        "single": st_single,
        "host_shuffle": {**st_host,
                         "shuffle_bytes": m_host["host_shuffle_bytes"]},
        "mesh": {**st_mesh, **m_mesh},
        "bytes_over_ici": m_mesh["ici_bytes"],
        "bytes_over_host_shuffle": m_host["host_shuffle_bytes"],
        "speedup_mesh_vs_single_x": round(
            st_single["full_s"] / st_mesh["full_s"], 3)
        if st_mesh["full_s"] else None,
        "bit_identical": bool(identical),
        "ok": bool(identical and m_mesh["mesh_exchanges"] > 0
                   and m_mesh["host_shuffle_bytes"] == 0
                   and m_mesh["mesh_shards"] >= ndev),
    }
    return out


STATS_ROWS = 300_000


def stats_bench() -> dict:
    """Runtime-statistics feedback bench (ISSUE-11 flag: `bench.py
    --stats`): a deliberately misestimate-prone join — the build side is
    an equality filter whose static selectivity heuristic (5%) is ~3000x
    off — runs cold (static estimates) then warm (history feedback).
    Reports the worst per-operator q-error before/after feedback, the
    plan-choice flip (shuffled join cold -> broadcast join warm, since
    the build side's OBSERVED size sits under the broadcast threshold),
    and the adaptive coalesce decision flipping from observed-bytes to
    history (picked before the stage runs). Acceptance: cold q-error
    >= 10, warm q-error <= 1.5, both flips happen, results identical."""
    _apply_platform_override()
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu import stats
    from spark_rapids_tpu.expr import Sum, col, lit
    from spark_rapids_tpu.plugin import TpuSession

    rng = np.random.default_rng(41)
    n = STATS_ROWS
    b = rng.integers(0, 10_000_000, n)
    b[:100] = 777  # the filter's ACTUAL survivors
    rng.shuffle(b)
    tmp = tempfile.mkdtemp(prefix="srtpu_stats_bench_")
    fpath = os.path.join(tmp, "fact.parquet")
    dpath = os.path.join(tmp, "dim.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 4096, n)),
        "v": pa.array(rng.uniform(size=n))}), fpath,
        row_group_size=65_536)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 4096, n)),
        "b": pa.array(b)}), dpath, row_group_size=65_536)

    sess = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.explain": "NONE",
        "spark.rapids.tpu.stats.enabled": True,
        "spark.rapids.tpu.stats.feedback.enabled": True,
        # between the ACTUAL filtered build side (~2KB) and the static
        # 5%-selectivity estimate (~250KB)
        "spark.rapids.sql.autoBroadcastJoinThreshold": 64 << 10,
    })

    def q():
        f = sess.read_parquet(fpath)
        d = sess.read_parquet(dpath).filter(col("b") == lit(777))
        return (f.join(d, on="k").group_by("k")
                .agg(s=Sum(col("v")))).collect().sort_by("k")

    def run():
        t0 = time.perf_counter()
        r = q()
        dt = time.perf_counter() - t0
        worst = sess.last_stats.worst()
        joins = [o["name"] for o in sess.last_stats.ops if "Join" in
                 o["name"]]
        return r, dt, worst, joins

    r_cold, t_cold, worst_cold, joins_cold = run()
    r_warm, t_warm, worst_warm, joins_warm = run()
    flip = "TpuShuffledHashJoinExec" in joins_cold and \
        "TpuBroadcastHashJoinExec" in joins_warm

    # adaptive coalesce: observed-bytes cold, history warm (decided
    # before the stage executes)
    sess2 = TpuSession({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.explain": "NONE",
        "spark.rapids.sql.adaptive.enabled": True,
        "spark.rapids.tpu.stats.enabled": True,
        "spark.rapids.tpu.stats.feedback.enabled": True,
    })
    t2 = pa.table({
        "k": pa.array(rng.integers(0, 512, 100_000)),
        "v": pa.array(rng.uniform(size=100_000))})
    aq = sess2.from_arrow(t2).repartition(32, "k") \
        .group_by("k").agg(s=Sum(col("v")))
    a1 = aq.collect().sort_by("k")
    co_cold = [e for e in sess2._adaptive_log
               if e["rule"] == "coalescePartitions"]
    a2 = aq.collect().sort_by("k")
    co_warm = [e for e in sess2._adaptive_log
               if e["rule"] == "coalescePartitions"]
    coalesce_flip = bool(
        co_cold and co_cold[0]["source"] == "observed"
        and co_warm and co_warm[0]["source"] == "history"
        and co_cold[0]["to"] == co_warm[0]["to"])

    hist = stats.stats() or {}
    out = {
        "metric": "stats_bench",
        "rows": n,
        "cold_s": round(t_cold, 4),
        "warm_s": round(t_warm, 4),
        "q_error_cold": round(float(worst_cold["q_error"]), 2)
        if worst_cold else None,
        "q_error_warm": round(float(worst_warm["q_error"]), 2)
        if worst_warm else None,
        "join_cold": joins_cold,
        "join_warm": joins_warm,
        "broadcast_flip": flip,
        "coalesce_cold": co_cold[0] if co_cold else None,
        "coalesce_warm": co_warm[0] if co_warm else None,
        "coalesce_flip": coalesce_flip,
        "bit_identical": bool(r_cold.equals(r_warm)
                              and a1.equals(a2)),
        "history": {k: hist.get(k) for k in
                    ("entries", "hits", "misses", "records")},
        "ok": bool(worst_cold and worst_warm
                   and worst_cold["q_error"] >= 10
                   and worst_warm["q_error"] <= 1.5
                   and flip and coalesce_flip
                   and r_cold.equals(r_warm) and a1.equals(a2)),
    }
    return out


FLEET_WORKERS = 3
FLEET_PLANS = 4          # distinct dashboard queries in the mix
FLEET_ROUNDS = 7         # repeats of the mix: 4 cold + 24 warm chances
FLEET_ROWS = 200_000


def fleet_bench() -> dict:
    """Fleet-gateway routing bench (ISSUE-10 flag: `bench.py --fleet`):
    a repeated mixed dashboard workload (FLEET_PLANS distinct queries x
    FLEET_ROUNDS) dispatched through a gateway over FLEET_WORKERS real
    `TpuDeviceService` processes, once with forced-random routing and
    once with cache-affinity routing. Workers run the result cache; XLA
    compiles are pre-warmed on every worker so the two modes differ only
    in PLACEMENT. Reports per-mode warm hit rate and p50/p99 latency —
    affinity should approach hit_rate 1.0 where random sits near 1/N.
    Workers are pinned to the CPU backend (N processes cannot share one
    TPU); the routing/caching effects measured here are
    placement-layer."""
    import tempfile
    import threading

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.fleet.gateway import FleetGateway
    from spark_rapids_tpu.service import TpuServiceClient
    from spark_rapids_tpu.tools.profile_report import _percentile

    repo = os.path.dirname(os.path.abspath(__file__))
    d = tempfile.mkdtemp(prefix="srtpu_fleet_bench_")
    rng = np.random.default_rng(13)
    t = pa.table({"k": pa.array(rng.integers(0, 4096, FLEET_ROWS)),
                  "v": pa.array(rng.uniform(size=FLEET_ROWS))})
    path = os.path.join(d, "fact.parquet")
    pq.write_table(t, path, row_group_size=65_536)
    paths = {"t": [path]}

    def attr(name, dt):
        return [{"class": "org.apache.spark.sql.catalyst.expressions."
                 "AttributeReference", "num-children": 0, "name": name,
                 "dataType": dt, "nullable": True, "metadata": {},
                 "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]

    def plan(thr):
        filt = {"class": "org.apache.spark.sql.execution.FilterExec",
                "num-children": 1,
                "condition": [
                    {"class": "org.apache.spark.sql.catalyst.expressions."
                     "GreaterThan", "num-children": 2}]
                + attr("v", "double")
                + [{"class": "org.apache.spark.sql.catalyst.expressions."
                    "Literal", "num-children": 0, "value": str(thr),
                    "dataType": "double"}]}
        scan = {"class": "org.apache.spark.sql.execution."
                "FileSourceScanExec", "num-children": 0,
                "relation": "HadoopFsRelation(parquet)",
                "output": [attr("k", "long"), attr("v", "double")],
                "tableIdentifier": "t"}
        return json.dumps([filt, scan])

    plans = [plan(0.1 + 0.17 * i) for i in range(FLEET_PLANS)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    socks = {f"w{i}": os.path.join(d, f"w{i}.sock")
             for i in range(FLEET_WORKERS)}
    procs = {n: subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.service.server",
         "--socket", s, "--platform", "cpu",
         "--conf", "spark.rapids.tpu.rescache.enabled=true"],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL) for n, s in socks.items()}
    try:
        for s in socks.values():
            TpuServiceClient(s, deadline_s=120.0).connect().close()
        # compile-warm EVERY plan on EVERY worker so random's extra XLA
        # compiles don't masquerade as routing cost
        for s in socks.values():
            with TpuServiceClient(s, deadline_s=300.0) as cli:
                for p in plans:
                    cli.run_plan(p, paths)

        def pool_hits(cli) -> int:
            stats = cli.cache_stats()
            return sum(w.get("hits", {}).get("query", 0)
                       for w in stats.values() if isinstance(w, dict))

        def pool_entries(cli) -> int:
            stats = cli.cache_stats()
            return sum(w.get("entries", 0)
                       for w in stats.values() if isinstance(w, dict))

        def run_mode(routing: str) -> dict:
            for s in socks.values():
                with TpuServiceClient(s, deadline_s=30.0) as cli:
                    cli.cache_invalidate()
            gw_sock = os.path.join(d, f"gw_{routing}.sock")
            gw = FleetGateway(
                list(socks.items()),
                {"spark.rapids.tpu.fleet.routing": routing,
                 "spark.rapids.tpu.fleet.probe.intervalMs": 500},
                gw_sock)
            th = threading.Thread(target=gw.serve_forever, daemon=True)
            th.start()
            lat = []
            reference = [None] * len(plans)
            identical = True
            with TpuServiceClient(gw_sock, deadline_s=300.0) as cli:
                hits0 = pool_hits(cli)   # lifetime counters: delta them
                hits_round2 = None
                for rnd_ix in range(FLEET_ROUNDS):
                    for i, p in enumerate(plans):
                        t0 = time.perf_counter()
                        r = cli.run_plan(p, paths)
                        lat.append(time.perf_counter() - t0)
                        if reference[i] is None:
                            reference[i] = r
                        elif not r.equals(reference[i]):
                            identical = False
                    if rnd_ix == 1:
                        hits_round2 = pool_hits(cli) - hits0
                hits = pool_hits(cli) - hits0
                entries = pool_entries(cli)
                cli.shutdown()
            th.join(timeout=10)
            warm_chances = len(plans) * (FLEET_ROUNDS - 1)
            lat_sorted = sorted(lat)
            return {
                "queries": len(lat),
                "warm_hit_rate": round(hits / warm_chances, 4),
                # round 2 isolates the 1/N story: under random routing a
                # repeat only hits when it lands on the one worker that
                # saw it; affinity pins it there by construction
                "round2_hit_rate": round((hits_round2 or 0) / len(plans),
                                         4),
                "p50_s": round(_percentile(lat_sorted, 50), 5),
                "p99_s": round(_percentile(lat_sorted, 99), 5),
                "bit_identical": identical,
                "cache_entries_pool": entries,
                "route_decisions": gw._fleet_stats()["route_decisions"],
            }

        rnd = run_mode("random")
        aff = run_mode("affinity")
    finally:
        for n, p in procs.items():
            try:
                with TpuServiceClient(socks[n], deadline_s=3.0) as cli:
                    cli.shutdown()
            except Exception:
                pass
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    out = {
        "metric": "fleet_bench",
        "workers": FLEET_WORKERS,
        "plans": FLEET_PLANS,
        "rounds": FLEET_ROUNDS,
        "rows": FLEET_ROWS,
        "random": rnd,
        "affinity": aff,
        "ok": bool(aff["bit_identical"] and rnd["bit_identical"]
                   and aff["warm_hit_rate"] > rnd["warm_hit_rate"]),
    }
    if rnd["p50_s"]:
        out["p50_speedup_affinity_vs_random_x"] = round(
            rnd["p50_s"] / max(aff["p50_s"], 1e-9), 2)
    return out


PROBE_TIMEOUT_S = 35
PROBE_ATTEMPTS = 2


def probe_backend() -> "tuple[bool, str]":
    """~30s-bounded subprocess probe of the device backend BEFORE burning a
    full attempt window: a dead tunnel costs 2x35s, not 3x300s (round-2
    verdict item 1b). Returns (ok, detail)."""
    plat = os.environ.get("SPARK_RAPIDS_TPU_BENCH_PLATFORM")
    cfg = (f"jax.config.update('jax_platforms', {plat!r}); " if plat else "")
    code = f"import jax; {cfg}print(jax.devices()[0])"
    last = ""
    for i in range(1, PROBE_ATTEMPTS + 1):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=PROBE_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            last = (f"probe {i}: no backend response in {PROBE_TIMEOUT_S}s "
                    "(wedged tunnel)")
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            return True, proc.stdout.strip().splitlines()[-1]
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["<no output>"]
        last = f"probe {i}: rc={proc.returncode} {tail[0]}"
    return False, last


def supervise() -> int:
    """Probe the backend, then run main() in a child under a watchdog;
    retry; emit error JSON if all fail."""
    ok, detail = probe_backend()
    if not ok:
        print(json.dumps({
            "metric": "scan_join_agg_speedup_vs_cpu",
            "value": None,
            "unit": "x",
            "vs_baseline": None,
            "error": f"backend probe failed, skipping attempts: {detail}",
            "detail": {"probe": detail},
        }), flush=True)
        return 1
    errors = [f"probe ok: {detail}"]

    def last_marked(stdout):
        lines = [ln for ln in (stdout or "").splitlines()
                 if ln.startswith(_MARK)]
        return lines[-1][len(_MARK):] if lines else None

    for attempt in range(1, ATTEMPTS + 1):
        env = dict(os.environ, **{_CHILD_ENV: "1"})
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=ATTEMPT_TIMEOUT_S,
                env=env)
        except subprocess.TimeoutExpired as te:
            # salvage the primary-metric line from partial stdout: main()
            # emits it before the scan bench, so a scan-bench hang still
            # yields the headline number
            out = te.stdout
            if isinstance(out, bytes):
                out = out.decode("utf-8", "replace")
            line = last_marked(out)
            if line:
                print(line, flush=True)
                return 0
            errors.append(f"attempt {attempt}: timeout after "
                          f"{ATTEMPT_TIMEOUT_S}s (backend init hang?)")
            continue
        line = last_marked(proc.stdout)
        if line:
            print(line, flush=True)
            return 0
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        errors.append(f"attempt {attempt}: rc={proc.returncode} "
                      + " | ".join(tail))
        if attempt < ATTEMPTS:
            time.sleep(5 * attempt)
    print(json.dumps({
        "metric": "scan_join_agg_speedup_vs_cpu",
        "value": None,
        "unit": "x",
        "vs_baseline": None,
        "error": f"all {ATTEMPTS} attempts failed",
        "detail": {"attempts": errors},
    }), flush=True)
    return 1


if __name__ == "__main__":
    if "--profile-query" in sys.argv:
        # bench flag (ISSUE-4): emit the JSONL profile event log for one
        # engine query into the given dir and print a one-line summary
        ix = sys.argv.index("--profile-query")
        if ix + 1 >= len(sys.argv):
            print("usage: bench.py --profile-query LOG_DIR [--no-spill]",
                  file=sys.stderr)
            sys.exit(2)
        _enable_compilation_cache()
        print(json.dumps(profile_query(
            sys.argv[ix + 1],
            force_spill="--no-spill" not in sys.argv)), flush=True)
    elif "--sched" in sys.argv:
        # bench flag (ISSUE-7): overloaded mixed-priority workload, FIFO
        # baseline vs scheduler, one JSON line (appended to BENCH detail)
        _enable_compilation_cache()
        print(json.dumps(sched_bench()), flush=True)
    elif "--fleet" in sys.argv:
        # bench flag (ISSUE-10): repeated mixed workload over a worker
        # pool — affinity vs forced-random routing: warm hit rate and
        # p50/p99 latency per mode; one JSON line
        print(json.dumps(fleet_bench()), flush=True)
    elif "--stats" in sys.argv:
        # bench flag (ISSUE-11): misestimate-prone join cold vs warm-
        # history — q-error before/after feedback, broadcast-vs-shuffle
        # and coalesce-count plan flips; one JSON line
        _enable_compilation_cache()
        print(json.dumps(stats_bench()), flush=True)
    elif "--multichip" in sys.argv:
        # bench flag (ISSUE-15): sharded mesh execution — single-device
        # vs host-shuffle vs ICI-collective legs on the same data, with
        # per-stage wall, bytes over ICI vs host shuffle, and the
        # bit-identical gate; one JSON line
        if os.environ.get("SPARK_RAPIDS_TPU_BENCH_PLATFORM") == "cpu":
            # must land before jax initializes a backend
            import re as _re
            _f = os.environ.get("XLA_FLAGS", "")
            _f = _re.sub(r"--xla_force_host_platform_device_count=\d+",
                         "", _f)
            os.environ["XLA_FLAGS"] = (
                _f + f" --xla_force_host_platform_device_count="
                     f"{MULTICHIP_NDEV}").strip()
            os.environ["JAX_PLATFORMS"] = "cpu"
        _enable_compilation_cache()
        print(json.dumps(multichip_bench()), flush=True)
    elif "--rescache" in sys.argv:
        # bench flag (ISSUE-9): repeated-query workload through the
        # result cache — hit rate, warm-vs-cold speedup, bit-identical
        # gate, zero-admission warm runs; one JSON line
        _enable_compilation_cache()
        print(json.dumps(rescache_bench()), flush=True)
    elif "--scan-pushdown" in sys.argv:
        # bench flag (ISSUE-12): full pushdown sweep (selectivity x
        # predicate type + aggregate-only), GB/s + bytes-materialised +
        # rows-pruned per shape; one JSON line
        _enable_compilation_cache()
        _apply_platform_override()
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            print(json.dumps(scan_pushdown_bench(td, full=True)),
                  flush=True)
    elif "--fusion" in sys.argv:
        # bench flag (ISSUE-16): whole-stage fusion sweep — the same
        # chains with fusion on vs off: wall, device-dispatch counts and
        # the overall dispatch-reduction factor, bit-identical gate per
        # shape; one JSON line
        _enable_compilation_cache()
        _apply_platform_override()
        print(json.dumps(fusion_query_bench()), flush=True)
    elif "--scan-only" in sys.argv:
        scan_only()
    elif os.environ.get(_CHILD_ENV):
        main()
    else:
        sys.exit(supervise())
