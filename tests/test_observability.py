"""Observability suite (ISSUE-4, marker `observability`): span tracer +
QueryProfile registry, metrics-level filtering, thread-safe MetricsSet,
canonical-metric wiring (no orphan constants), trace_range exception
regression, event-log JSONL schema round-trip, the offline report tool,
parked-batch spill accounting, and the end-to-end profiled query.

scripts/profile_matrix.sh runs these standalone plus the bench-driven
emit/validate/disabled-path checks."""

import json
import os
import re
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.utils import metrics as M
from spark_rapids_tpu.utils import spans
from spark_rapids_tpu.utils.metrics import MetricsSet, TaskMetrics
from spark_rapids_tpu.utils.spans import (QueryProfile, begin_profile,
                                          end_profile, span, validate_record,
                                          write_event_log)

pytestmark = pytest.mark.observability

SRC_ROOT = os.path.join(os.path.dirname(__file__), "..", "spark_rapids_tpu")


@pytest.fixture(autouse=True)
def _no_leaked_profile():
    """Every test must leave the module-global profile slot empty."""
    yield
    prof = spans.current_profile()
    if prof is not None:
        end_profile(prof)
    assert spans.current_profile() is None


# ---------------------------------------------------------------------------
# satellite: trace_range exception regression
# ---------------------------------------------------------------------------


class TestTraceRange:
    def test_metric_fed_when_region_raises(self):
        from spark_rapids_tpu.utils.tracing import trace_range
        m = M.Metric("t", M.ESSENTIAL, live=True)
        with pytest.raises(ValueError):
            with trace_range("failing", metric=m):
                time.sleep(0.005)
                raise ValueError("boom")
        # pre-fix the elapsed time was lost entirely on exception
        assert m.value >= 4_000_000  # >= 4ms in ns

    def test_metric_fed_on_success(self):
        from spark_rapids_tpu.utils.tracing import trace_range
        m = M.Metric("t", M.ESSENTIAL, live=True)
        with trace_range("ok", metric=m):
            time.sleep(0.002)
        assert m.value > 0


# ---------------------------------------------------------------------------
# satellite: no orphan canonical metric constants
# ---------------------------------------------------------------------------


class TestNoOrphanConstants:
    def _canonical_names(self):
        return [k for k, v in vars(M).items()
                if k.isupper() and isinstance(v, str)
                and k not in ("ESSENTIAL", "MODERATE", "DEBUG")]

    def test_every_constant_created_by_an_operator(self):
        """Each canonical name in utils/metrics.py must be CREATED somewhere
        in the engine (`.create(M.<NAME>...)`) — a declared-but-dead metric
        constant is an observability lie."""
        sources = []
        for root, _dirs, files in os.walk(SRC_ROOT):
            for f in files:
                if f.endswith(".py") and not f.endswith("metrics.py"):
                    with open(os.path.join(root, f)) as fh:
                        sources.append(fh.read())
        blob = "\n".join(sources)
        orphans = [name for name in self._canonical_names()
                   if not re.search(r"create\(\s*M\.%s\b" % name, blob)]
        assert not orphans, f"declared-but-dead metric constants: {orphans}"

    def test_constants_are_unique(self):
        names = self._canonical_names()
        values = [getattr(M, n) for n in names]
        assert len(set(values)) == len(values)


# ---------------------------------------------------------------------------
# satellite: MetricsSet thread safety + level filtering
# ---------------------------------------------------------------------------


class TestMetricsSet:
    def test_create_snapshot_concurrent(self):
        ms = MetricsSet("MODERATE")
        errors = []

        def worker(tid):
            try:
                for i in range(300):
                    m = ms.create(f"m{i % 20}", M.MODERATE)
                    m.add(1)
                    ms.snapshot()
                    _ = ms[f"m{(i + tid) % 20}"]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        snap = ms.snapshot()
        assert len(snap) == 20
        assert sum(snap.values()) == 8 * 300

    def test_create_same_name_returns_same_metric(self):
        ms = MetricsSet("MODERATE")
        assert ms.create("x") is ms.create("x")

    def test_level_filtering_live_and_noop(self):
        # ESSENTIAL session: only ESSENTIAL metrics are live
        ms = MetricsSet("ESSENTIAL")
        ess = ms.create("rows", M.ESSENTIAL)
        mod = ms.create("opTime", M.MODERATE)
        dbg = ms.create("peak", M.DEBUG)
        for m in (ess, mod, dbg):
            m.add(7)
            m.set_max(99)
        assert ess.live and ess.value == 99
        assert not mod.live and mod.value == 0  # dead metric is a no-op
        assert not dbg.live and dbg.value == 0
        assert set(ms.snapshot()) == {"rows"}

        # DEBUG session: everything is live
        ms2 = MetricsSet("DEBUG")
        assert ms2.create("a", M.ESSENTIAL).live
        assert ms2.create("b", M.MODERATE).live
        assert ms2.create("c", M.DEBUG).live

    def test_missing_metric_is_noop(self):
        ms = MetricsSet("MODERATE")
        ms["never-created"].add(5)  # must not raise
        assert ms.snapshot() == {}


# ---------------------------------------------------------------------------
# satellite: TaskMetrics.explain_string composition
# ---------------------------------------------------------------------------


class TestTaskMetricsExplain:
    def test_empty_when_clean(self):
        assert TaskMetrics().explain_string() == ""

    def test_all_parts_compose(self):
        tm = TaskMetrics()
        tm.retry_count = 2
        tm.split_retry_count = 1
        tm.retry_block_ns = 3_000_000
        tm.retry_backoff_ms = [2.0, 4.0]
        tm.shuffle_retry_count = 3
        tm.shuffle_bytes_written = 1000
        tm.shuffle_bytes_read = 900
        tm.shuffle_fetch_wait_ns = 2_000_000
        tm.compile_count = 4
        tm.compile_ns = 5_000_000
        s = tm.explain_string()
        assert s.startswith("TaskMetrics: ")
        assert "oomRetries=2" in s and "splitRetries=1" in s
        assert "backoffsMs=[2.0, 4.0]" in s
        assert "shuffleFetchRetries=3" in s
        assert "shuffleBytesWritten=1000" in s
        assert "shuffleBytesRead=900" in s
        assert "shuffleFetchWaitMs=2.0" in s
        assert "compiles=4" in s and "compileMs=5.0" in s
        # the four families are ';'-separated in declaration order
        assert s.count(";") == 3

    def test_thread_local_isolation(self):
        TaskMetrics.reset()
        TaskMetrics.get().retry_count = 5
        seen = []

        def other():
            seen.append(TaskMetrics.get().retry_count)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert seen == [0]
        TaskMetrics.reset()


# ---------------------------------------------------------------------------
# tentpole: span tracer + QueryProfile
# ---------------------------------------------------------------------------


class TestSpans:
    def test_disabled_path_returns_shared_noop(self):
        assert spans.current_profile() is None
        s1 = span("anything", kind="spill")
        s2 = span("else")
        assert s1 is spans.NOOP_SPAN and s2 is spans.NOOP_SPAN
        with s1 as s:
            s.inc(bytes=5)  # must be a no-op, not an error

    def test_nesting_via_thread_stack(self):
        prof = begin_profile("q")
        try:
            with span("outer", kind="phase") as outer:
                with span("inner", kind="spill", bytes=10) as inner:
                    time.sleep(0.001)
                assert inner.parent_id == outer.span_id
            assert outer.parent_id == QueryProfile.ROOT_SPAN_ID
        finally:
            end_profile(prof)
        prof.finish()
        named = {s.name: s for s in prof.spans}
        assert named["inner"].dur_ns > 0
        assert named["inner"].attrs["bytes"] == 10
        assert named["outer"].dur_ns >= named["inner"].dur_ns

    def test_worker_thread_spans_parent_to_root(self):
        prof = begin_profile("q")
        try:
            def worker():
                with span("w", kind="shuffle"):
                    pass
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        finally:
            end_profile(prof)
        prof.finish()
        w = [s for s in prof.spans if s.name == "w"]
        assert len(w) == 1 and w[0].parent_id == QueryProfile.ROOT_SPAN_ID

    def test_suppressed_thread_records_nothing(self):
        # the AOT warmup thread suppresses itself so overlapping background
        # compiles never pollute the active query's profile
        prof = begin_profile("q")
        try:
            def worker():
                spans.suppress_in_thread()
                with span("warmup-compile", kind="compile"):
                    pass
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        finally:
            end_profile(prof)
        prof.finish()
        assert prof.spans == []

    def test_span_exception_still_recorded(self):
        prof = begin_profile("q")
        try:
            with pytest.raises(RuntimeError):
                with span("failing", kind="compile"):
                    raise RuntimeError("x")
        finally:
            end_profile(prof)
        prof.finish()
        assert [s.name for s in prof.spans] == ["failing"]

    def test_finish_is_idempotent_and_snapshots_deltas(self):
        class FakeExec:
            def __init__(self, name):
                self._name = name
                self.metrics = MetricsSet("MODERATE")
                self.children = []

            @property
            def name(self):
                return self._name

            def _arg_string(self):
                return "[x]"

        parent, child = FakeExec("Parent"), FakeExec("Child")
        parent.children = [child]
        m = child.metrics.create("opTime", M.MODERATE)
        m.add(100)  # pre-query value: must NOT appear in the profile
        prof = QueryProfile("q")
        prof.attach_plan(parent)
        m.add(42)
        prof.finish()
        prof.finish()  # idempotent
        table = {t["name"]: t for t in prof.operator_table()}
        assert table["Child"]["values"]["opTime"] == 42
        assert table["Child"]["parent_id"] == table["Parent"]["op_id"]
        assert table["Parent"]["args"] == "[x]"
        assert "Child: opTime=" in prof.explain_profile().replace("[x]", "")


# ---------------------------------------------------------------------------
# tentpole: event-log JSONL schema round-trip
# ---------------------------------------------------------------------------


class TestEventLogRoundTrip:
    def _make_profile(self):
        prof = begin_profile("roundtrip")
        try:
            with span("spill:to_host", kind="spill", bytes=2048):
                pass
            with span("compile:exec.sort", kind="compile", op="exec.sort"):
                pass
        finally:
            end_profile(prof)
        tm = TaskMetrics()
        tm.retry_count = 1
        tm.retry_backoff_ms = [2.0]
        tm.shuffle_bytes_read = 77
        prof.finish(tm)
        return prof

    def test_records_validate_and_survive_json(self, tmp_path):
        prof = self._make_profile()
        path = write_event_log(prof, str(tmp_path))
        assert os.path.basename(path).startswith("events-")
        lines = open(path).read().splitlines()
        assert len(lines) == len(prof.to_records())
        for line in lines:
            rec = json.loads(line)
            assert validate_record(rec) == [], rec
        types = [json.loads(l)["type"] for l in lines]
        assert types.count("query") == 1
        assert types.count("span") == 3  # root + 2 phases
        qrec = json.loads(lines[0])
        assert qrec["v"] == spans.SCHEMA_VERSION
        assert qrec["task_metrics"]["shuffle_bytes_read"] == 77

    def test_append_only_across_queries(self, tmp_path):
        p1 = write_event_log(self._make_profile(), str(tmp_path))
        n1 = len(open(p1).read().splitlines())
        p2 = write_event_log(self._make_profile(), str(tmp_path))
        assert p1 == p2  # same per-process file, appended
        assert len(open(p2).read().splitlines()) == 2 * n1

    def test_validate_rejects_bad_records(self):
        assert validate_record({"v": 99, "type": "query"})
        assert validate_record({"v": 1, "type": "nope"})
        assert validate_record([1, 2, 3])
        errs = validate_record({"v": 1, "type": "span", "query_id": "a",
                               "span_id": "NOT_INT", "name": "n",
                               "kind": "martian", "start_ns": 0,
                               "dur_ns": 0, "attrs": {}})
        assert any("span_id" in e for e in errs)
        assert any("kind" in e for e in errs)


# ---------------------------------------------------------------------------
# tentpole: offline report tool on a synthetic log
# ---------------------------------------------------------------------------


def _synthetic_records(query_id, label, slow_op="TpuSortExec",
                       retries=False):
    tmetrics = {"retry_count": 3, "split_retry_count": 1,
                "retry_block_ns": 12_000_000,
                "retry_backoff_ms": [2.0, 4.0, 8.0],
                "shuffle_retry_count": 2} if retries else {}
    return [
        {"v": 1, "type": "query", "query_id": query_id, "label": label,
         "wall_ns": 50_000_000, "task_metrics": tmetrics,
         "n_operators": 2, "n_spans": 3},
        {"v": 1, "type": "operator", "query_id": query_id, "op_id": 0,
         "parent_id": None, "name": slow_op, "args": "",
         "metrics": {"sortTime": 30_000_000, "numOutputRows": 100,
                     "numOutputBatches": 2}},
        {"v": 1, "type": "operator", "query_id": query_id, "op_id": 1,
         "parent_id": 0, "name": "TpuScanExec", "args": "",
         "metrics": {"readTime": 1_000_000, "numOutputRows": 100,
                     "numOutputBatches": 2}},
        {"v": 1, "type": "span", "query_id": query_id, "span_id": 0,
         "parent_id": None, "name": label, "kind": "query",
         "start_ns": 0, "dur_ns": 50_000_000, "attrs": {}},
        {"v": 1, "type": "span", "query_id": query_id, "span_id": 1,
         "parent_id": 0, "name": "compile:exec.sort", "kind": "compile",
         "start_ns": 0, "dur_ns": 20_000_000, "attrs": {}},
        {"v": 1, "type": "span", "query_id": query_id, "span_id": 2,
         "parent_id": 0, "name": "spill:to_host", "kind": "spill",
         "start_ns": 0, "dur_ns": 5_000_000, "attrs": {"bytes": 4096}},
    ]


class TestReportTool:
    def _write(self, tmp_path, records, name="events-1.jsonl"):
        p = tmp_path / name
        with open(p, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return str(p)

    def test_report_on_synthetic_log(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.profile_report import main
        recs = _synthetic_records("q-1", "sortq", retries=True) + \
            _synthetic_records("q-2", "aggq", slow_op="TpuHashAggregateExec")
        self._write(tmp_path, recs)
        assert main([str(tmp_path), "--validate"]) == 0
        out = capsys.readouterr().out
        # top operators, slowest first
        assert out.index("TpuSortExec") < out.index("TpuScanExec")
        # breakdown has the compile/spill rows with the span totals
        assert "compile" in out and "20.0" in out
        assert "spill" in out and "5.0" in out and "4096" in out
        # retry storm surfaced with the backoff schedule
        assert "OOM retries=3" in out and "[2.0, 4.0, 8.0]" in out
        assert "shuffle fetch retries=2" in out
        # two queries -> comparison table
        assert "per-query comparison" in out
        assert "q-1" in out and "q-2" in out

    def test_validate_fails_on_corrupt_record(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.profile_report import main
        recs = _synthetic_records("q-1", "sortq")
        recs[1] = {"v": 1, "type": "operator"}  # missing required fields
        self._write(tmp_path, recs)
        assert main([str(tmp_path), "--validate"]) == 1
        assert "INVALID" in capsys.readouterr().err

    def test_torn_tail_line_tolerated_without_validate(self, tmp_path,
                                                       capsys):
        from spark_rapids_tpu.tools.profile_report import main
        p = self._write(tmp_path, _synthetic_records("q-1", "sortq"))
        with open(p, "a") as f:
            f.write('{"v": 1, "type": "span", "trunc')  # crash mid-append
        assert main([str(tmp_path)]) == 0
        assert "TpuSortExec" in capsys.readouterr().out

    def test_json_model_output(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.profile_report import main
        self._write(tmp_path, _synthetic_records("q-1", "sortq"))
        assert main([str(tmp_path), "--json"]) == 0
        model = json.loads(capsys.readouterr().out)
        assert model["queries"][0]["label"] == "sortq"
        assert model["queries"][0]["phases"]["spill"]["bytes"] == 4096


# ---------------------------------------------------------------------------
# engine wiring: parked-batch budget accounting + peak watermark
# ---------------------------------------------------------------------------


def _batch(n=2048):
    from spark_rapids_tpu.columnar import batch_from_arrow
    return batch_from_arrow(pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "b": pa.array(np.arange(n, dtype=np.float64)),
    }))


class TestParkedAccounting:
    def test_parking_over_budget_spills_older_runs(self):
        from spark_rapids_tpu.memory.budget import MemoryBudget
        from spark_rapids_tpu.memory.catalog import BufferCatalog
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        BufferCatalog._instance = BufferCatalog(host_limit=1 << 30)
        b = _batch()
        size = b.device_memory_size()
        MemoryBudget.initialize(int(size * 1.5))
        TaskMetrics.reset()
        try:
            first = SpillableColumnarBatch(b)
            assert not first.spilled
            second = SpillableColumnarBatch(_batch())
            # parking the second run overflowed the budget: the OLDER run
            # spilled to host (bounded device residency), quietly — no
            # RetryOOM, no fault-injection allocation consumed
            assert first.spilled
            assert not second.spilled
            assert TaskMetrics.get().spill_to_host_ns > 0
            # re-acquiring unspills and rebalances the accounting
            got = first.get_batch()
            assert int(got.row_count()) == 2048
            first.close()
            second.close()
            assert MemoryBudget.get().used == 0
        finally:
            MemoryBudget.initialize(1 << 62)
            BufferCatalog._instance = None

    def test_note_parked_tracks_peak(self):
        from spark_rapids_tpu.memory.budget import MemoryBudget
        MemoryBudget.initialize(1 << 40)
        mb = MemoryBudget.get()
        mb.note_parked(1000)
        mb.note_parked(500)
        assert mb.peak_used >= 1500
        mb.release(1500)
        mb.reset_peak()
        assert mb.peak_used == mb.used
        MemoryBudget.initialize(1 << 62)


# ---------------------------------------------------------------------------
# end-to-end: profiled engine query -> tree + event log; disabled -> nothing
# ---------------------------------------------------------------------------


class TestProfiledQuery:
    def _table(self, n=512):
        rng = np.random.default_rng(3)
        return pa.table({
            "k": pa.array(rng.integers(0, 16, n)),
            "v": pa.array(rng.uniform(0.0, 1.0, n)),
        })

    def test_profile_collected_and_event_log_written(self, tmp_path):
        from spark_rapids_tpu.expr import col
        from spark_rapids_tpu.plugin import TpuSession
        log_dir = str(tmp_path / "events")
        s = TpuSession({"spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.metrics.level": "DEBUG",
                        "spark.rapids.tpu.metrics.eventLog.dir": log_dir})
        out = s.from_arrow(self._table()).filter(col("v") > 0.5) \
            .sort("v").collect()
        assert out.num_rows > 0
        prof = s.last_profile
        assert prof is not None and prof.closed
        assert spans.current_profile() is None  # deactivated after the query
        text = s.explain_profile()
        assert "TpuSortExec" in text and "TpuFilterExec" in text
        assert "sortTime=" in text and "numOutputRows=" in text
        # the event log landed and every record validates
        files = [f for f in os.listdir(log_dir) if f.endswith(".jsonl")]
        assert len(files) == 1
        n_ops = n_queries = 0
        for line in open(os.path.join(log_dir, files[0])):
            rec = json.loads(line)
            assert validate_record(rec) == [], rec
            n_ops += rec["type"] == "operator"
            n_queries += rec["type"] == "query"
        assert n_queries == 1 and n_ops >= 3

    def test_in_memory_profile_without_event_log(self):
        from spark_rapids_tpu.expr import col
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.explain": "NONE",
                        "spark.rapids.tpu.metrics.profile.enabled": True})
        s.from_arrow(self._table()).filter(col("v") > 0.5).collect()
        assert s.last_profile is not None
        assert "TpuFilterExec" in s.explain_profile()

    def test_disabled_run_collects_nothing(self, tmp_path):
        from spark_rapids_tpu.expr import col
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.explain": "NONE"})
        out = s.from_arrow(self._table()).filter(col("v") > 0.5).collect()
        assert out.num_rows > 0
        assert s.last_profile is None
        assert s.explain_profile() == ""
        assert list(tmp_path.iterdir()) == []  # nothing written anywhere
