"""Runtime-statistics suite (marker `stats`; scripts/stats_matrix.sh runs
these standalone).

Covers: q-error math, the history store (LRU, merge, CRC-framed JSONL
persistence, corrupt-entry degrade-to-miss), golden stats fingerprints,
estimate-vs-actual collection with warm-history correction (the ≥10×
misestimate dropping to ~1), observed-selectivity reuse, the
feedback-off byte-identical-plan gate, adaptive coalesce-from-history
and skew pre-flag, the per-partition exchange skew histogram (stats +
telemetry), broadcast-vs-shuffle plan flips from history, cross-process
persistence round-trip, event-log stats records + profile_report
--stats, adaptive-decision surfacing, the misestimate incident, and the
off-path zero-state contract."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import stats, telemetry
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.stats.history import OpStats, StatsHistory, q_error
from spark_rapids_tpu.utils import spans

pytestmark = pytest.mark.stats

GOLDEN_PATH = os.path.join(os.path.dirname(__file__),
                           "golden_stats_fingerprints.json")


@pytest.fixture(autouse=True)
def _clean_stats():
    yield
    stats.shutdown()
    telemetry.shutdown()


def _session(**conf):
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.stats.enabled": True}
    base.update(conf)
    return TpuSession(base)


def _table(n=20_000, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 64, n)),
        "g": pa.array(rng.integers(0, 16, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
    })


# ---------------------------------------------------------------------------
# q-error math
# ---------------------------------------------------------------------------

class TestQError:
    def test_perfect(self):
        assert q_error(100, 100) == 1.0

    def test_symmetric(self):
        assert q_error(10, 1000) == q_error(1000, 10) == 100.0

    def test_floors_at_one_row(self):
        # both sides floor at 1 row: a 0-row actual against a 0.4-row
        # estimate is a perfect estimate, not a division by zero
        assert q_error(0.0, 0) == 1.0
        assert q_error(0.4, 0) == 1.0
        assert q_error(0, 50) == 50.0

    def test_at_least_one(self):
        assert q_error(3, 4) == 4 / 3


# ---------------------------------------------------------------------------
# history store
# ---------------------------------------------------------------------------

class TestHistory:
    def test_lru_eviction(self):
        h = StatsHistory(max_entries=3)
        for i in range(5):
            h.record(OpStats(digest=f"d{i}", op="x", rows=i))
        assert h.entry_count == 3
        assert h.lookup("d0") is None and h.lookup("d1") is None
        assert h.lookup("d4").rows == 4

    def test_lookup_moves_to_front(self):
        h = StatsHistory(max_entries=2)
        h.record(OpStats(digest="a", op="x", rows=1))
        h.record(OpStats(digest="b", op="x", rows=2))
        assert h.lookup("a") is not None      # refresh a
        h.record(OpStats(digest="c", op="x", rows=3))
        assert h.lookup("a") is not None      # b evicted, not a
        assert h.lookup("b") is None

    def test_merge_keeps_optional_facets(self):
        h = StatsHistory()
        h.record(OpStats(digest="d", op="x", rows=10,
                         part_bytes=[5, 100], selectivity=0.25))
        h.record(OpStats(digest="d", op="x", rows=12, bytes=640))
        e = h.lookup("d")
        assert e.rows == 12 and e.bytes == 640
        assert e.part_bytes == [5, 100] and e.selectivity == 0.25
        assert e.seen == 2

    def test_persistence_round_trip(self, tmp_path):
        h = StatsHistory(persist_dir=str(tmp_path))
        h.record(OpStats(digest="d1", op="scan", rows=123, bytes=456),
                 persistable=True)
        h.record(OpStats(digest="d2", op="filter", rows=7,
                         selectivity=0.01, part_bytes=[1, 2, 3]),
                 persistable=True)
        h2 = StatsHistory(persist_dir=str(tmp_path))
        assert h2.persist_loaded == 2
        assert h2.lookup("d1").rows == 123
        e2 = h2.lookup("d2")
        assert e2.selectivity == 0.01 and e2.part_bytes == [1, 2, 3]

    def test_non_persistable_stays_memory_only(self, tmp_path):
        h = StatsHistory(persist_dir=str(tmp_path))
        h.record(OpStats(digest="mem", op="scan", rows=9),
                 persistable=False)
        h.record(OpStats(digest="disk", op="scan", rows=8),
                 persistable=True)
        h2 = StatsHistory(persist_dir=str(tmp_path))
        assert h2.lookup("disk") is not None
        assert h2.lookup("mem") is None

    def test_corrupt_entries_degrade_to_miss(self, tmp_path):
        h = StatsHistory(persist_dir=str(tmp_path))
        h.record(OpStats(digest="good", op="scan", rows=5),
                 persistable=True)
        path = os.path.join(str(tmp_path), "stats_history.jsonl")
        with open(path) as f:
            good_line = f.read()
        with open(path, "w") as f:
            f.write("not a framed line at all\n")
            f.write("deadbeef {\"digest\": \"poisoned\", \"op\": \"x\", "
                    "\"rows\": 1e9}\n")       # CRC mismatch
            f.write(good_line)
            f.write("00000000 {broken json\n")
            f.write(good_line[: len(good_line) // 2])  # torn tail
        h2 = StatsHistory(persist_dir=str(tmp_path))
        assert h2.lookup("good").rows == 5
        assert h2.lookup("poisoned") is None
        assert h2.persist_skipped >= 3

    def test_steady_state_does_not_grow_file(self, tmp_path):
        h = StatsHistory(persist_dir=str(tmp_path))
        for _ in range(10):
            h.record(OpStats(digest="d", op="scan", rows=100),
                     persistable=True)
        path = os.path.join(str(tmp_path), "stats_history.jsonl")
        with open(path) as f:
            assert len(f.read().splitlines()) == 1


# ---------------------------------------------------------------------------
# fingerprints (stats namespace)
# ---------------------------------------------------------------------------

def _golden_plans(sess):
    """Range-rooted plans only: no in-memory identity, no file stat —
    stable across processes AND regenerations (same discipline as
    tests/golden_fingerprints.json for rescache)."""
    r = sess.range(1000)
    return {
        "range": r.plan,
        "filter": r.filter(col("id") % 7 == lit(3)).plan,
        "agg": r.select((col("id") % 10).alias("g"), col("id").alias("v"))
               .group_by("g").agg(total=Sum(col("v")),
                                  cnt=Count(col("v"))).plan,
        "repartition": r.repartition(4, "id").plan,
    }


class TestStatsFingerprints:
    def test_golden_stats_fingerprints(self):
        """Stats digests pinned — regenerate deliberately with
        SRTPU_REGEN_GOLDEN_STATS_FP=1 when the fingerprint recipe
        changes (a silent change orphans every persisted history; an
        ALIAS would feed one subtree's actuals to another's estimates)."""
        sess = _session()
        sess.initialize_device()
        digests = {}
        for name, plan in _golden_plans(sess).items():
            d, persistable = stats.make_digest(plan, sess.conf)
            assert d is not None and persistable, name
            digests[name] = d
        if os.environ.get("SRTPU_REGEN_GOLDEN_STATS_FP") or \
                not os.path.exists(GOLDEN_PATH):
            with open(GOLDEN_PATH, "w") as f:
                json.dump(digests, f, indent=2, sort_keys=True)
        with open(GOLDEN_PATH) as f:
            golden = json.load(f)
        assert digests == golden

    def test_namespace_separation_from_rescache(self):
        """A stats digest must never collide with a rescache digest for
        the same subtree — the namespaces hold different value kinds."""
        from spark_rapids_tpu.rescache.fingerprint import fingerprint
        sess = _session()
        sess.initialize_device()
        plan = _golden_plans(sess)["agg"]
        d, _ = stats.make_digest(plan, sess.conf)
        assert d != fingerprint(plan, sess.conf, extra="query|").digest

    def test_fail_closed_nondeterministic(self):
        from spark_rapids_tpu.expr.misc import SparkPartitionID
        sess = _session()
        sess.initialize_device()
        plan = sess.range(100).filter(SparkPartitionID() == lit(0)).plan
        d, _ = stats.make_digest(plan, sess.conf)
        assert d is None
        assert stats.selectivity_digest(plan) is None


# ---------------------------------------------------------------------------
# collection + feedback
# ---------------------------------------------------------------------------

class TestCollection:
    def test_misestimate_corrected_from_history(self):
        """The acceptance criterion: a repeated query whose static
        estimate is wrong by >=10x gets a corrected estimate from
        history — q-error drops to ~1 in explain_analyze."""
        sess = _session(**{"spark.rapids.tpu.stats.feedback.enabled": True})
        t = _table()
        # heuristic: agg over filter estimates rows/2/8; actual: 16 groups
        q = (sess.from_arrow(t).filter(col("v") > lit(0.9))
             .group_by("g").agg(total=Sum(col("v"))))
        q.collect()
        cold = sess.last_stats.worst()
        assert cold["q_error"] >= 10, cold
        q.collect()
        warm = sess.last_stats.worst()
        assert warm["q_error"] <= 1.5, warm
        text = sess.explain_analyze()
        assert "q_err" in text and "TpuHashAggregateExec" in text

    def test_observed_selectivity(self):
        sess = _session()
        t = _table()
        sess.from_arrow(t).filter(col("v") > lit(0.75)).collect()
        ops = {o["name"]: o for o in sess.last_stats.ops}
        sel = ops["TpuFilterExec"].get("selectivity")
        assert sel is not None and 0.2 < sel < 0.3

    def test_selectivity_reused_across_sources(self, tmp_path):
        """The (condition, child schema) selectivity key generalizes:
        the same predicate over a DIFFERENT file reuses the observed
        selectivity where whole-subtree row history must miss."""
        rng = np.random.default_rng(3)

        def write(path, n):
            pq.write_table(pa.table({
                "v": pa.array(np.where(rng.uniform(size=n) < 0.01,
                                       5.0, 0.0))}), path)
        p1 = str(tmp_path / "a.parquet")
        p2 = str(tmp_path / "b.parquet")
        write(p1, 20_000)
        write(p2, 20_000)
        sess = _session(**{"spark.rapids.tpu.stats.feedback.enabled": True})
        sess.read_parquet(p1).filter(col("v") > lit(1.0)).collect()
        from spark_rapids_tpu.plan.cbo import row_estimate
        plan2 = sess.read_parquet(p2).filter(col("v") > lit(1.0)).plan
        est = row_estimate(plan2, sess.conf)
        # static heuristic (no footer range hit): 0.5 * 20k = 10k;
        # observed selectivity ~0.01 predicts ~200
        assert est < 1000, est

    def test_feedback_off_estimates_unchanged(self):
        """Warm history with feedback OFF must not move a single
        estimate — the byte-identical-plan gate rides on this."""
        from spark_rapids_tpu.plan.cbo import row_estimate
        sess = _session()  # stats on, feedback off (default)
        t = _table()
        q = (sess.from_arrow(t).filter(col("v") > lit(0.9))
             .group_by("g").agg(total=Sum(col("v"))))
        static = row_estimate(q.plan, sess.conf)
        q.collect()  # history now warm
        assert row_estimate(q.plan, sess.conf) == static
        assert row_estimate(q.plan) == static

    def test_failed_query_records_nothing(self):
        sess = _session()
        sess.from_arrow(_table(n=2000)).collect()
        before = stats.stats()["records"]
        from spark_rapids_tpu import faults
        with faults.inject(faults.PREFETCH, "error", nth=1):
            with pytest.raises(Exception):
                sess.from_arrow(_table(n=2000, seed=9)) \
                    .group_by("g").agg(c=Count(col("v"))).collect()
        # the failed query's partial actuals must not have landed
        assert stats.stats()["records"] == before

    def test_incident_on_catastrophic_misestimate(self, tmp_path):
        sess = _session(**{
            "spark.rapids.tpu.telemetry.enabled": True,
            "spark.rapids.tpu.telemetry.flightRecorder.dir":
                str(tmp_path),
            "spark.rapids.tpu.stats.misestimate.incidentThreshold": 10.0})
        t = _table()
        (sess.from_arrow(t).filter(col("v") > lit(0.9))
         .group_by("g").agg(total=Sum(col("v")))).collect()
        reg = telemetry.registry()
        assert reg.get_value("tpu_incidents_total",
                             reason="misestimate") >= 1
        dumps = [p for p in os.listdir(str(tmp_path))
                 if p.endswith(".jsonl")]
        assert dumps, "misestimate incident should have dumped"


# ---------------------------------------------------------------------------
# off-path contract
# ---------------------------------------------------------------------------

class TestOffPath:
    def test_off_no_state_no_threads_same_plan(self):
        threads0 = threading.active_count()
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = _table(n=4000)
        q = sess.from_arrow(t).group_by("g").agg(c=Count(col("v")))
        explain_off = sess.explain_plan(q.plan)
        q.collect()
        assert not stats.is_enabled() and stats.get() is None
        assert stats.stats() is None
        assert sess.last_stats is None
        assert threading.active_count() <= threads0
        # same session shapes WITH stats on (feedback off): identical plan
        sess_on = _session()
        q_on = sess_on.from_arrow(t).group_by("g").agg(c=Count(col("v")))
        assert sess_on.explain_plan(q_on.plan) == explain_off

    def test_explain_analyze_requires_stats(self):
        sess = TpuSession({"spark.rapids.sql.enabled": True})
        with pytest.raises(ValueError, match="stats.enabled"):
            sess.explain_analyze(sess.range(10).plan)


# ---------------------------------------------------------------------------
# adaptive feedback
# ---------------------------------------------------------------------------

class TestAdaptiveFeedback:
    def test_coalesce_count_from_history_without_staging(self, rng):
        """Acceptance criterion: the warm run's coalesce count comes
        from HISTORY (decided before the stage ran) and equals what the
        observed bytes chose cold."""
        t = pa.table({"k": pa.array(rng.integers(0, 64, 4000)),
                      "v": pa.array(rng.uniform(size=4000))})
        sess = _session(**{
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.tpu.stats.feedback.enabled": True,
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                16 * 1024})
        q = sess.from_arrow(t).repartition(8, "k") \
            .group_by("k").agg(s=Sum(col("v")))
        r1 = q.collect().sort_by("k")
        log1 = [e for e in sess._adaptive_log
                if e["rule"] == "coalescePartitions"]
        r2 = q.collect().sort_by("k")
        log2 = [e for e in sess._adaptive_log
                if e["rule"] == "coalescePartitions"]
        assert log1 and log1[0]["source"] == "observed"
        assert log2 and log2[0]["source"] == "history"
        assert log1[0]["to"] == log2[0]["to"] < log1[0]["from"]
        assert r1.equals(r2)

    def test_skew_preflag_splits_below_row_threshold(self, rng):
        """History evidence waives the absolute row threshold: a hot
        partition the static detector ignores (below the threshold)
        splits on the warm run, bit-matching the CPU engine's rows."""
        n = 6000
        keys = np.concatenate([np.full(3 * n // 4, 7, np.int64),
                               rng.integers(1, 100, n - 3 * n // 4)])
        rng.shuffle(keys)
        probe = pa.table({"k": pa.array(keys),
                          "v": pa.array(rng.normal(size=n))})
        build = pa.table({"k": pa.array(np.arange(100)),
                          "w": pa.array(rng.uniform(size=100))})
        sess = _session(**{
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.tpu.stats.feedback.enabled": True,
            "spark.rapids.sql.adaptive.skewJoin."
            "skewedPartitionRowThreshold": 100_000,
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                16 * 1024,
            "spark.rapids.sql.autoBroadcastJoinThreshold": -1})
        lf = sess.from_arrow(probe).repartition(6, "k")
        rf = sess.from_arrow(build).repartition(6, "k")
        q = lf.join(rf, on="k").group_by("k") \
            .agg(s=Sum(col("v") * col("w")))
        r1 = q.collect().sort_by("k")
        assert not [e for e in sess._adaptive_log
                    if e["rule"] == "skewJoin"]
        r2 = q.collect().sort_by("k")
        pre = [e for e in sess._adaptive_log if e["rule"] == "skewPreflag"]
        splits = [e for e in sess._adaptive_log if e["rule"] == "skewJoin"]
        assert pre and splits and all(e["preflag"] for e in splits), \
            sess._adaptive_log
        assert r1.column("k").to_pylist() == r2.column("k").to_pylist()
        assert np.allclose(r1.column("s").to_numpy(),
                           r2.column("s").to_numpy())


# ---------------------------------------------------------------------------
# exchange skew histogram
# ---------------------------------------------------------------------------

class TestExchangeSkew:
    def test_partition_bytes_recorded_and_skew_flagged(self, rng):
        n = 4000
        keys = np.concatenate([np.zeros(3 * n // 4, np.int64),
                               rng.integers(1, 64, n // 4)])
        t = pa.table({"k": pa.array(keys),
                      "v": pa.array(rng.uniform(size=n))})
        sess = _session(**{"spark.rapids.tpu.telemetry.enabled": True})
        sess.from_arrow(t).repartition(4, "k").collect()
        ops = {o["name"]: o for o in sess.last_stats.ops}
        ex = ops["TpuShuffleExchangeExec"]
        pb = ex.get("part_bytes")
        assert pb and len(pb) == 4
        # one hot partition holds the bulk of the bytes
        assert max(pb) > 3 * sorted(pb)[len(pb) // 2]
        assert ex.get("skewed") is True
        assert stats.get().lookup(ex["digest"]).part_bytes == pb
        # telemetry satellite: the histogram family observed every
        # written partition and round-trips through the text format
        reg = telemetry.registry()
        from spark_rapids_tpu.telemetry import parse_prometheus
        parsed = parse_prometheus(reg.render())
        count = parsed["tpu_exchange_partition_bytes_count"][""]
        assert count >= len([b for b in pb if b > 0])
        assert reg.get_value("tpu_stats_skew_detections_total") >= 1

    def test_uniform_partitions_not_flagged(self, rng):
        t = pa.table({"k": pa.array(rng.integers(0, 64, 4000)),
                      "v": pa.array(rng.uniform(size=4000))})
        sess = _session()
        sess.from_arrow(t).repartition(4, "k").collect()
        ops = {o["name"]: o for o in sess.last_stats.ops}
        assert not ops["TpuShuffleExchangeExec"].get("skewed")


# ---------------------------------------------------------------------------
# plan-choice feedback (broadcast flip)
# ---------------------------------------------------------------------------

class TestPlanFlip:
    def test_broadcast_vs_shuffle_flips_on_history(self, tmp_path, rng):
        n = 50_000
        b = rng.integers(0, 1_000_000, n)
        b[:5] = 500  # exactly 5 rows survive the filter
        rng.shuffle(b)
        fpath = str(tmp_path / "fact.parquet")
        dpath = str(tmp_path / "dim.parquet")
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 1000, n)),
            "v": pa.array(rng.uniform(size=n))}), fpath)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 1000, n)),
            "b": pa.array(b)}), dpath)
        sess = _session(**{
            "spark.rapids.tpu.stats.feedback.enabled": True,
            # between actual build bytes (~90B) and the static estimate
            # (EqualTo => 5% of 50k rows)
            "spark.rapids.sql.autoBroadcastJoinThreshold": 4096})
        f = sess.read_parquet(fpath)
        d = sess.read_parquet(dpath).filter(col("b") == lit(500))
        q = f.join(d, on="k").group_by("k").agg(s=Sum(col("v")))
        r1 = q.collect().sort_by("k")
        ops1 = [o["name"] for o in sess.last_stats.ops]
        r2 = q.collect().sort_by("k")
        ops2 = [o["name"] for o in sess.last_stats.ops]
        assert "TpuShuffledHashJoinExec" in ops1, ops1
        assert "TpuBroadcastHashJoinExec" in ops2, ops2
        assert r1.equals(r2)


# ---------------------------------------------------------------------------
# cross-process persistence
# ---------------------------------------------------------------------------

_CHILD = r"""
import sys
import numpy as np
import pyarrow as pa
from spark_rapids_tpu.expr import Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession

path, hist_dir, phase = sys.argv[1], sys.argv[2], sys.argv[3]
sess = TpuSession({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.explain": "NONE",
    "spark.rapids.tpu.stats.enabled": True,
    "spark.rapids.tpu.stats.feedback.enabled": True,
    "spark.rapids.tpu.stats.history.dir": hist_dir})
q = (sess.read_parquet(path).filter(col("v") > lit(0.9))
     .group_by("g").agg(total=Sum(col("v"))))
q.collect()
print("WORST_QERR", sess.last_stats.worst()["q_error"])
"""


class TestCrossProcessPersistence:
    def test_restarted_worker_keeps_learned_cardinalities(self, tmp_path,
                                                          rng):
        """A fresh process with the same history dir answers the same
        query with history-corrected estimates — q-error ~1 on its very
        first run."""
        path = str(tmp_path / "t.parquet")
        hist = str(tmp_path / "hist")
        t = pa.table({
            "g": pa.array(rng.integers(0, 16, 20_000).astype(np.int32)),
            "v": pa.array(rng.uniform(size=20_000))})
        pq.write_table(t, path)

        def run():
            out = subprocess.run(
                [sys.executable, "-c", _CHILD, path, hist, "x"],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            assert out.returncode == 0, out.stderr
            line = [l for l in out.stdout.splitlines()
                    if l.startswith("WORST_QERR")][0]
            return float(line.split()[1])

        cold = run()
        warm = run()
        assert cold >= 10, cold
        assert warm <= 1.5, warm
        # the history file is CRC-framed JSONL with persistable entries
        hist_file = os.path.join(hist, "stats_history.jsonl")
        assert os.path.exists(hist_file)


# ---------------------------------------------------------------------------
# event log + report + explain_profile surfacing
# ---------------------------------------------------------------------------

class TestDiagnostics:
    def test_stats_records_validate_and_report(self, tmp_path):
        log_dir = str(tmp_path / "events")
        sess = _session(**{
            "spark.rapids.tpu.metrics.eventLog.dir": log_dir})
        t = _table()
        (sess.from_arrow(t).filter(col("v") > lit(0.9))
         .group_by("g").agg(total=Sum(col("v")))).collect()
        recs = []
        for name in os.listdir(log_dir):
            with open(os.path.join(log_dir, name)) as f:
                for line in f:
                    recs.append(json.loads(line))
        st = [r for r in recs if r.get("type") == "stats"]
        assert st, "stats records must land in the event log"
        for r in recs:
            assert spans.validate_record(r) == [], r
        from spark_rapids_tpu.tools.profile_report import (
            build_model, render_report, stats_summary)
        model = build_model(recs)
        summary = stats_summary(model)
        assert summary and summary["worst"][0]["q_error"] >= 10
        text = render_report(model, stats=True)
        assert "runtime statistics" in text and "q_error" in text

    def test_adaptive_decisions_in_profile_and_report(self, tmp_path, rng):
        log_dir = str(tmp_path / "events")
        t = pa.table({"k": pa.array(rng.integers(0, 64, 4000)),
                      "v": pa.array(rng.uniform(size=4000))})
        sess = _session(**{
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.tpu.metrics.eventLog.dir": log_dir,
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                16 * 1024})
        sess.from_arrow(t).repartition(8, "k") \
            .group_by("k").agg(s=Sum(col("v"))).collect()
        assert [e for e in sess._adaptive_log
                if e["rule"] == "coalescePartitions"]
        # explain_profile surfaces the decisions (satellite: they used
        # to live only on the session attribute)
        text = sess.explain_profile()
        assert "adaptive:" in text and "coalescePartitions" in text
        recs = []
        for name in os.listdir(log_dir):
            with open(os.path.join(log_dir, name)) as f:
                recs.extend(json.loads(l) for l in f)
        q_recs = [r for r in recs if r.get("type") == "query"
                  and r.get("adaptive")]
        assert q_recs, "query record must carry the adaptive log"
        from spark_rapids_tpu.tools.profile_report import (build_model,
                                                           render_report)
        out = render_report(build_model(recs))
        assert "adaptive decisions:" in out
        assert "coalescePartitions" in out

    def test_explain_analyze_executes_plan(self):
        sess = _session()
        t = _table(n=2000)
        q = sess.from_arrow(t).group_by("g").agg(c=Count(col("v")))
        text = sess.explain_analyze(q.plan)
        assert "RuntimeStats" in text and "actual" in text
