r"""Hive delimited-text scan tests (reference hive/rapids
GpuHiveTableScanExec: LazySimpleSerDe defaults - \x01 delimiters, \N nulls,
no header)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.explain": "NONE"})


SCHEMA = Schema(("id", "name", "score"), (T.LONG, T.STRING, T.DOUBLE))


def write_hive(path, rows, delim="\x01"):
    with open(path, "w") as f:
        for r in rows:
            f.write(delim.join(r"\N" if v is None else str(v)
                               for v in r) + "\n")


ROWS = [(1, "alice", 3.5), (2, None, 1.25), (3, "b\x02c", None),
        (4, "comma,quote\"x", 9.0)]


class TestHiveText:
    def test_roundtrip_default_serde(self, session, tmp_path):
        p = str(tmp_path / "t.txt")
        write_hive(p, ROWS)
        df = session.read_hive_text(p, schema=SCHEMA)
        got = df.collect().sort_by([("id", "ascending")]).to_pylist()
        assert got[0] == {"id": 1, "name": "alice", "score": 3.5}
        assert got[1]["name"] is None
        assert got[2]["score"] is None
        assert got[3]["name"] == 'comma,quote"x'  # no quoting in hive text
        cpu = df.collect_cpu().sort_by([("id", "ascending")]).to_pylist()
        assert got == cpu

    def test_custom_delimiter_and_query(self, session, tmp_path):
        p = str(tmp_path / "t.tsv")
        write_hive(p, ROWS, delim="\t")
        df = session.read_hive_text(p, schema=SCHEMA, sep="\t")
        out = (df.filter(col("id") > lit(1))
                 .agg(n=Count(lit(1)), s=Sum(col("score")))).collect()
        assert out.column("n").to_pylist() == [3]
        assert out.column("s").to_pylist() == [10.25]

    def test_schema_required(self, session, tmp_path):
        p = str(tmp_path / "t.txt")
        write_hive(p, ROWS)
        with pytest.raises(ValueError, match="schema"):
            session.read_hive_text(p)

    def test_nested_rejected(self, session, tmp_path):
        p = str(tmp_path / "t.txt")
        write_hive(p, ROWS)
        nested = Schema(("a",), (T.ArrayType(T.LONG),))
        with pytest.raises(ValueError, match="nested"):
            session.read_hive_text(p, schema=nested)

    def test_multifile(self, session, tmp_path):
        paths = []
        for i in range(3):
            p = str(tmp_path / f"part{i}.txt")
            write_hive(p, [(i * 10 + j, f"r{j}", float(j))
                           for j in range(5)])
            paths.append(p)
        df = session.read_hive_text(*paths, schema=SCHEMA)
        assert df.collect().num_rows == 15

    def test_disabled_by_conf(self, tmp_path):
        s = TpuSession({"spark.rapids.sql.format.hiveText.enabled": False,
                        "spark.rapids.sql.explain": "NONE"})
        p = str(tmp_path / "t.txt")
        write_hive(p, ROWS)
        with pytest.raises(ValueError, match="disabled"):
            s.read_hive_text(p, schema=SCHEMA)

    def test_malformed_cells_become_null(self, session, tmp_path):
        # LazySimpleSerDe: unparseable primitive cells -> NULL, not a crash
        p = str(tmp_path / "dirty.txt")
        with open(p, "w") as f:
            f.write("1\x01abc\x012.5\n")      # name col fine, others...
            f.write("oops\x01bob\x01xyz\n")   # bad long, bad double
            f.write("3\x01carol\x01\n")       # empty double cell
        df = session.read_hive_text(p, schema=SCHEMA)
        got = df.collect_cpu().to_pylist()
        assert got[0]["id"] == 1 and got[0]["score"] == 2.5
        assert got[1]["id"] is None and got[1]["score"] is None
        assert got[1]["name"] == "bob"
        assert got[2]["score"] is None

    def test_ragged_rows_pad_null(self, session, tmp_path):
        # LazySimpleSerDe: short rows pad missing trailing cols with NULL,
        # extra trailing fields are dropped
        p = str(tmp_path / "ragged.txt")
        with open(p, "w") as f:
            f.write("2\x01bob\n")                      # missing score
            f.write("3\x01carol\x011.5\x01extra\n")    # extra field
            f.write("4\n")                             # only id
        df = session.read_hive_text(p, schema=SCHEMA)
        got = df.collect_cpu().to_pylist()
        assert got[0] == {"id": 2, "name": "bob", "score": None}
        assert got[1] == {"id": 3, "name": "carol", "score": 1.5}
        assert got[2] == {"id": 4, "name": None, "score": None}

    def test_interior_empty_lines_are_rows(self, session, tmp_path):
        # LazySimpleSerDe emits a row for an empty line: first column is
        # the empty string (NULL after a numeric cast), the rest NULL.
        # Only the final empty chunk from a trailing newline is skipped.
        p = str(tmp_path / "blank.txt")
        with open(p, "w") as f:
            f.write("1\x01alice\x012.5\n")
            f.write("\n")
            f.write("2\x01bob\x011.0\n")   # trailing newline: no extra row
        df = session.read_hive_text(p, schema=SCHEMA)
        got = df.collect_cpu().to_pylist()
        assert len(got) == 3
        assert got[1] == {"id": None, "name": None, "score": None}
        str_schema = Schema(("a", "b"), (T.STRING, T.STRING))
        df2 = session.read_hive_text(p, schema=str_schema)
        got2 = df2.collect_cpu().to_pylist()
        assert got2[1] == {"a": "", "b": None}  # empty string, not NULL
