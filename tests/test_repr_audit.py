"""Repr-audit lint (ISSUE-10 satellite): kill the param-dropping-__repr__
bug class wholesale.

PRs 3-8 fixed 19+ expression classes one by one whose `__repr__` dropped
`__init__` params — each a latent compile-cache AND rescache-fingerprint
aliasing bug (two semantically different expressions rendering the same
string share one cached executable / one cached result = silently wrong
rows). This test introspects EVERY `Expression` (and `StaticExpr`)
subclass in the package and statically verifies each constructor param is
reflected in the repr surface, so the next expression with a forgotten
param fails CI instead of corrupting a dashboard three PRs later.

A param counts as covered when:
  * it is routed into `super().__init__(...)` — the parent renders it
    (parents are audited for their OWN params, so delegation chains
    bottom out at `Expression.__init__(children)`, which the base
    `__repr__` renders);
  * its name — or the `self.<attr>` it is assigned to — appears in the
    class's resolved repr surface (`__repr__` + `_arg_string` along the
    MRO);
  * it is explicitly allowlisted below, with a justification.
"""

import importlib
import inspect
import pkgutil
import re

import pytest

from spark_rapids_tpu.exec.base import StaticExpr
from spark_rapids_tpu.expr.base import Expression

pytestmark = pytest.mark.fleet  # rides the fleet matrix (ISSUE-10)

# The WHOLE package is walked (not just expr/) so stragglers defined
# beside their feature — delta zorder's InterleaveBits, pandas UDFs —
# are audited too, and so the collected set does not depend on which
# other test modules happened to import first in a full-suite run.

# (ClassName, param) pairs that genuinely do NOT belong in __repr__.
# Every entry needs a reason. Two legitimate reasons exist:
#   * schema-derived — the param is resolution metadata recomputed from
#     the input schema, which BOTH cache layers capture independently
#     (compile keys include avals; plan fingerprints render every node's
#     output schema), so repr omission cannot alias distinct programs;
#   * children-routed — __init__ funnels the param into the children
#     list through a local (so the static super()-delegation check can't
#     see it) and the base __repr__ renders children; the reconstruction
#     from children is unambiguous.
ALLOWLIST = {
    # schema-derived type/nullability metadata:
    ("AttributeReference", "dtype"), ("AttributeReference", "nullable"),
    ("BoundReference", "dtype"), ("BoundReference", "nullable"),
    ("NamedLambdaVariable", "dtype"), ("NamedLambdaVariable", "nullable"),
    # PandasUDF is deterministic=False AND in fingerprint._OPAQUE_EXPRS:
    # both caches fail closed on the whole subtree by design, and the
    # return type is schema-derived for the plan fingerprint
    ("PandasUDF", "return_type"),
    # children-routed via a local list (unambiguous reconstruction):
    ("CaseWhen", "branches"), ("CaseWhen", "else_expr"),
    # ArrayJoin validates delim/null_replacement as Literal children and
    # copies their .value; the literals render in children
    ("ArrayJoin", "child"), ("ArrayJoin", "delim"),
    ("ArrayJoin", "null_replacement"),
    ("AssertTrue", "condition"), ("AssertTrue", "message"),
    ("Sequence", "start"), ("Sequence", "stop"), ("Sequence", "step"),
    ("Overlay", "child"), ("Overlay", "replace"), ("Overlay", "pos"),
    ("Overlay", "length"),
    # higher-order fns: the lambda BODY (fn applied to the lambda vars)
    # becomes a child and renders; the callable itself is not identity
    # beyond its body, and with_index/has_finish fall out of the child
    # count
    ("ArrayTransform", "fn"),
    ("ArrayAggregate", "child"), ("ArrayAggregate", "zero"),
    ("ArrayAggregate", "merge"), ("ArrayAggregate", "finish"),
}


def _iter_expression_classes():
    import spark_rapids_tpu
    for info in pkgutil.walk_packages(spark_rapids_tpu.__path__,
                                      prefix="spark_rapids_tpu."):
        try:
            importlib.import_module(info.name)
        except Exception:
            # a module that cannot import in the test env (optional dep)
            # cannot contribute cached programs either
            pass

    seen = set()

    def walk(cls):
        for sub in cls.__subclasses__():
            if sub not in seen:
                seen.add(sub)
                yield sub
                yield from walk(sub)

    yield from walk(Expression)
    yield from walk(StaticExpr)
    # the fused-stage spec is kernel-key AND fingerprint material (its repr
    # is the whole fused program identity) — audit it like an expression
    from spark_rapids_tpu.plan.fusion import FusedStageSpec
    yield FusedStageSpec
    yield from walk(FusedStageSpec)


def _source_of(func) -> str:
    try:
        return inspect.getsource(func)
    except (OSError, TypeError):
        return ""


def _repr_surface(cls) -> str:
    """Source of the repr machinery this class actually resolves to:
    `__repr__` plus any `_arg_string` helper, walked up the MRO."""
    parts = []
    rfunc = cls.__repr__
    if rfunc is not object.__repr__:
        parts.append(_source_of(rfunc))
    arg_string = getattr(cls, "_arg_string", None)
    if arg_string is not None:
        parts.append(_source_of(arg_string))
    return "\n".join(parts)


def _own_init(cls):
    """The __init__ DEFINED on this class (None when inherited)."""
    return cls.__dict__.get("__init__")


_SUPER_RE = re.compile(
    r"(?:super\(\)|super\(\s*\w+\s*,\s*self\s*\)|[A-Za-z_][\w.]*)"
    r"\.__init__\s*\((?P<args>[^)]*(?:\([^)]*\)[^)]*)*)\)", re.S)


def _delegated_names(init_src: str) -> str:
    """Concatenated argument text of every *.__init__(...) call."""
    return "\n".join(m.group("args") for m in _SUPER_RE.finditer(init_src))


def _assigned_attrs(init_src: str, param: str) -> list:
    """Attribute names assigned (directly or via expression) from the
    param inside __init__: `self.X = ... param ...`."""
    out = []
    for m in re.finditer(r"self\.(\w+)\s*=\s*(.+)", init_src):
        if re.search(rf"\b{re.escape(param)}\b", m.group(2)):
            out.append(m.group(1))
    return out


def _audit(cls) -> list:
    init = _own_init(cls)
    if init is None:
        return []  # inherited ctor: params audited on the definer
    try:
        sig = inspect.signature(init)
    except (ValueError, TypeError):
        return []
    init_src = _source_of(init)
    surface = _repr_surface(cls)
    delegated = _delegated_names(init_src)
    problems = []
    for name, p in sig.parameters.items():
        if name == "self" or p.kind == p.VAR_KEYWORD:
            continue
        pname = name.lstrip("*")
        if (cls.__name__, pname) in ALLOWLIST:
            continue
        if re.search(rf"\b{re.escape(pname)}\b", delegated):
            continue  # parent renders it (parent audited separately)
        if re.search(rf"\b{re.escape(pname)}\b", surface):
            continue
        attrs = _assigned_attrs(init_src, pname)
        if any(re.search(rf"\b{re.escape(a)}\b", surface) for a in attrs):
            continue
        problems.append(
            f"{cls.__module__}.{cls.__name__}: __init__ param {pname!r} "
            f"is not reflected in __repr__/_arg_string (assigned attrs: "
            f"{attrs or 'none found'}) — a compile-cache/rescache "
            f"aliasing hazard; render it or allowlist with justification")
    return problems


def test_every_expression_param_is_repr_faithful():
    problems = []
    n = 0
    for cls in _iter_expression_classes():
        n += 1
        problems.extend(_audit(cls))
    assert n > 100, f"audit walked only {n} classes — collection broke?"
    assert not problems, (
        f"{len(problems)} param-dropping repr(s):\n" + "\n".join(problems))


def test_allowlist_entries_are_real():
    """Every allowlist entry must still name an existing class+param —
    stale entries would silently re-open the hole they documented."""
    classes = {c.__name__: c for c in _iter_expression_classes()}
    for clsname, param in ALLOWLIST:
        assert clsname in classes, f"allowlisted class {clsname} is gone"
        init = _own_init(classes[clsname])
        assert init is not None, f"{clsname} no longer defines __init__"
        assert param in inspect.signature(init).parameters, \
            f"{clsname}.{param} is no longer an __init__ param"
