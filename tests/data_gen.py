"""Composable random data generators — the differential harness's fuzzer
(reference `integration_tests/src/main/python/data_gen.py`: seeded composable
generators for every Spark type, the de-facto fuzzer of the project)."""

from __future__ import annotations

import string
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa


class DataGen:
    def __init__(self, arrow_type, nullable: bool = True,
                 null_frac: float = 0.1):
        self.arrow_type = arrow_type
        self.nullable = nullable
        self.null_frac = null_frac if nullable else 0.0

    def generate(self, rng: np.random.Generator, n: int) -> pa.Array:
        vals = self._values(rng, n)
        if self.null_frac > 0:
            mask = rng.random(n) < self.null_frac
            return pa.array(vals, type=self.arrow_type, mask=mask)
        return pa.array(vals, type=self.arrow_type)

    def _values(self, rng, n):
        raise NotImplementedError


class IntGen(DataGen):
    def __init__(self, bits: int = 64, lo=None, hi=None, **kw):
        t = {8: pa.int8(), 16: pa.int16(), 32: pa.int32(), 64: pa.int64()}[bits]
        super().__init__(t, **kw)
        info_lo = -(2 ** (bits - 1))
        info_hi = 2 ** (bits - 1) - 1
        self.lo = info_lo if lo is None else lo
        self.hi = info_hi if hi is None else hi
        self.bits = bits
        self.edge = [self.lo, self.hi, 0, -1, 1]

    def _values(self, rng, n):
        vals = rng.integers(self.lo, self.hi, n, dtype=np.int64,
                            endpoint=True)
        # sprinkle edge cases (reference gens include boundary values)
        for i in range(min(len(self.edge), n)):
            if rng.random() < 0.5:
                vals[rng.integers(0, n)] = self.edge[i]
        return vals.astype({8: np.int8, 16: np.int16, 32: np.int32,
                            64: np.int64}[self.bits])


class BooleanGen(DataGen):
    def __init__(self, **kw):
        super().__init__(pa.bool_(), **kw)

    def _values(self, rng, n):
        return rng.integers(0, 2, n).astype(bool)


class FloatGen(DataGen):
    def __init__(self, bits: int = 64, with_special: bool = True, **kw):
        super().__init__(pa.float32() if bits == 32 else pa.float64(), **kw)
        self.bits = bits
        self.with_special = with_special

    def _values(self, rng, n):
        vals = rng.normal(0, 1e6, n)
        if self.with_special and n >= 8:
            for v in (np.nan, np.inf, -np.inf, 0.0, -0.0):
                vals[rng.integers(0, n)] = v
        return vals.astype(np.float32 if self.bits == 32 else np.float64)


class StringGen(DataGen):
    def __init__(self, max_len: int = 20, charset: str = None,
                 with_unicode: bool = True, **kw):
        super().__init__(pa.string(), **kw)
        self.max_len = max_len
        self.charset = charset or (string.ascii_letters + string.digits + " ")
        self.with_unicode = with_unicode

    def _values(self, rng, n):
        out = []
        chars = list(self.charset)
        for _ in range(n):
            ln = int(rng.integers(0, self.max_len + 1))
            s = "".join(rng.choice(chars) for _ in range(ln))
            out.append(s)
        if self.with_unicode and n >= 4:
            out[int(rng.integers(0, n))] = "日本語テキスト"
            out[int(rng.integers(0, n))] = "🎉émoji"
            out[int(rng.integers(0, n))] = ""
        return out


class DateGen(DataGen):
    def __init__(self, **kw):
        super().__init__(pa.date32(), **kw)

    def _values(self, rng, n):
        return rng.integers(-25000, 25000, n).astype(np.int32)


class TimestampGen(DataGen):
    def __init__(self, **kw):
        super().__init__(pa.timestamp("us", tz="UTC"), **kw)

    def _values(self, rng, n):
        return rng.integers(-2**40, 2**44, n).astype(np.int64)


class DecimalGen(DataGen):
    def __init__(self, precision: int = 10, scale: int = 2, **kw):
        super().__init__(pa.decimal128(precision, scale), **kw)
        self.precision, self.scale = precision, scale

    def _values(self, rng, n):
        import decimal
        limit = 10 ** self.precision - 1
        unscaled = rng.integers(-limit, limit, n, endpoint=True)
        return [decimal.Decimal(int(u)).scaleb(-self.scale) for u in unscaled]


class ArrayGen(DataGen):
    """Random lists of a child generator's values (nested fuzzing)."""

    def __init__(self, child: DataGen, max_len: int = 6, **kw):
        super().__init__(pa.list_(child.arrow_type), **kw)
        self.child = child
        self.max_len = max_len

    def _values(self, rng, n):
        out = []
        for _ in range(n):
            m = int(rng.integers(0, self.max_len + 1))
            out.append(self.child.generate(rng, m).to_pylist())
        return out


class StructGen(DataGen):
    """Random structs from named child generators."""

    def __init__(self, fields: List[Tuple[str, DataGen]], **kw):
        super().__init__(pa.struct([(nm, g.arrow_type) for nm, g in fields]),
                         **kw)
        self.fields = fields

    def _values(self, rng, n):
        cols = {nm: g.generate(rng, n).to_pylist() for nm, g in self.fields}
        return [{nm: cols[nm][i] for nm, _ in self.fields} for i in range(n)]


def gen_table(rng: np.random.Generator, gens: List[Tuple[str, DataGen]],
              n: int = 1024) -> pa.Table:
    return pa.table({name: g.generate(rng, n) for name, g in gens})


# standard generator sets (reference's *_gens lists)
def basic_gens():
    return [("b", BooleanGen()), ("i8", IntGen(8)), ("i16", IntGen(16)),
            ("i32", IntGen(32)), ("i64", IntGen(64)), ("f32", FloatGen(32)),
            ("f64", FloatGen(64)), ("s", StringGen()), ("d", DateGen()),
            ("ts", TimestampGen())]
