"""Iceberg read tests (reference `sql-plugin/.../iceberg/`, iceberg spec
v1/v2). The table fixtures are hand-assembled per the spec — metadata.json +
avro manifest list + avro manifests (via the independent OCF encoder from
test_avro) + parquet data files — since no iceberg library is available."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.datasources.iceberg import (IcebergDeletesUnsupported,
                                                  IcebergError, IcebergTable)
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession

from test_avro import write_ocf

MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"]},
    ]}

MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "r102", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

ICEBERG_SCHEMA = {
    "type": "struct", "schema-id": 0, "fields": [
        {"id": 1, "name": "id", "required": True, "type": "long"},
        {"id": 2, "name": "v", "required": False, "type": "double"},
        {"id": 3, "name": "tag", "required": False, "type": "string"},
    ]}


class TableBuilder:
    """Assemble an iceberg table directory the way a writer would."""

    def __init__(self, root):
        self.root = str(root)
        self.data_dir = os.path.join(self.root, "data")
        self.meta_dir = os.path.join(self.root, "metadata")
        os.makedirs(self.data_dir)
        os.makedirs(self.meta_dir)
        self.snapshots = []
        self._file_no = 0

    def write_data_file(self, table: pa.Table) -> str:
        self._file_no += 1
        p = os.path.join(self.data_dir, f"f{self._file_no}.parquet")
        pq.write_table(table, p)
        return p

    def manifest(self, entries, name, content=0):
        """entries: list of (status, path) or (status, path, file_content)."""
        rows = []
        for e in entries:
            status, path = e[0], e[1]
            fc = e[2] if len(e) > 2 else 0
            rows.append({"status": status, "snapshot_id": None,
                         "data_file": {
                             "content": fc, "file_path": path,
                             "file_format": "PARQUET",
                             "record_count": 0, "file_size_in_bytes":
                                 os.path.getsize(path)}})
        p = os.path.join(self.meta_dir, f"{name}.avro")
        write_ocf(p, MANIFEST_SCHEMA, rows)
        return p

    def snapshot(self, manifests, snapshot_id, timestamp_ms,
                 manifest_contents=None):
        rows = []
        for i, m in enumerate(manifests):
            c = (manifest_contents or [0] * len(manifests))[i]
            rows.append({"manifest_path": m,
                         "manifest_length": os.path.getsize(m),
                         "partition_spec_id": 0, "content": c,
                         "added_snapshot_id": snapshot_id})
        mlist = os.path.join(self.meta_dir, f"snap-{snapshot_id}.avro")
        write_ocf(mlist, MANIFEST_LIST_SCHEMA, rows)
        self.snapshots.append({"snapshot-id": snapshot_id,
                               "timestamp-ms": timestamp_ms,
                               "manifest-list": mlist})
        return snapshot_id

    def commit(self, version=1, current=None):
        meta = {
            "format-version": 2,
            "table-uuid": "0000",
            "location": self.root,
            "schemas": [ICEBERG_SCHEMA],
            "current-schema-id": 0,
            "snapshots": self.snapshots,
            "current-snapshot-id":
                current if current is not None else
                (self.snapshots[-1]["snapshot-id"] if self.snapshots
                 else -1),
        }
        with open(os.path.join(self.meta_dir,
                               f"v{version}.metadata.json"), "w") as f:
            json.dump(meta, f)
        with open(os.path.join(self.meta_dir, "version-hint.text"),
                  "w") as f:
            f.write(str(version))


def sample(rng, n, tag):
    return pa.table({
        "id": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
        "v": pa.array(rng.normal(0, 1, n).round(3), type=pa.float64()),
        "tag": pa.array([tag] * n),
    })


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.explain": "NONE"})


class TestIcebergRead:
    def test_read_current_snapshot(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        t1, t2 = sample(rng, 100, "a"), sample(rng, 150, "b")
        m = b.manifest([(1, b.write_data_file(t1)),
                        (1, b.write_data_file(t2))], "m1")
        b.snapshot([m], snapshot_id=10, timestamp_ms=1000)
        b.commit()
        df = session.read_iceberg(str(tmp_path / "t"))
        got = df.collect()
        want = pa.concat_tables([t1, t2])
        assert got.num_rows == want.num_rows
        assert sorted(got.column("id").to_pylist()) == \
            sorted(want.column("id").to_pylist())
        cpu = df.collect_cpu()
        assert sorted(cpu.column("id").to_pylist()) == \
            sorted(want.column("id").to_pylist())

    def test_query_over_iceberg(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        t1 = sample(rng, 300, "a")
        m = b.manifest([(1, b.write_data_file(t1))], "m1")
        b.snapshot([m], 10, 1000)
        b.commit()
        df = session.read_iceberg(str(tmp_path / "t"))
        out = (df.filter(col("id") < lit(500))
                 .group_by("tag").agg(c=Count(lit(1)))).collect()
        want = sum(1 for x in t1.column("id").to_pylist() if x < 500)
        assert out.column("c").to_pylist() == [want]

    def test_time_travel(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        t1, t2 = sample(rng, 80, "a"), sample(rng, 90, "b")
        f1 = b.write_data_file(t1)
        m1 = b.manifest([(1, f1)], "m1")
        b.snapshot([m1], 10, 1000)
        # snapshot 2: f1 removed (status=2), f2 added
        f2 = b.write_data_file(t2)
        m2 = b.manifest([(2, f1), (1, f2)], "m2")
        b.snapshot([m2], 20, 2000)
        b.commit()
        tbl = IcebergTable(session, str(tmp_path / "t"))
        # current = snapshot 20 -> only f2
        assert tbl.data_files() == [f2]
        assert tbl.data_files(snapshot_id=10) == [f1]
        assert tbl.data_files(as_of_timestamp_ms=1500) == [f1]
        df_old = tbl.to_df(snapshot_id=10)
        assert df_old.collect().num_rows == 80
        df_new = tbl.to_df()
        assert df_new.collect().num_rows == 90

    def test_delete_manifest_rejected(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        t1 = sample(rng, 50, "a")
        m1 = b.manifest([(1, b.write_data_file(t1))], "m1")
        md = b.manifest([(1, b.write_data_file(t1))], "mdel")
        b.snapshot([m1, md], 10, 1000, manifest_contents=[0, 1])
        b.commit()
        with pytest.raises(IcebergDeletesUnsupported):
            IcebergTable(session, str(tmp_path / "t")).data_files()

    def test_delete_data_file_rejected(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        t1 = sample(rng, 50, "a")
        f1 = b.write_data_file(t1)
        m1 = b.manifest([(1, f1), (1, f1, 2)], "m1")  # equality-delete file
        b.snapshot([m1], 10, 1000)
        b.commit()
        with pytest.raises(IcebergDeletesUnsupported):
            IcebergTable(session, str(tmp_path / "t")).data_files()

    def test_column_pruning(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        m = b.manifest([(1, b.write_data_file(sample(rng, 40, "a")))], "m1")
        b.snapshot([m], 10, 1000)
        b.commit()
        df = session.read_iceberg(str(tmp_path / "t"), columns=["id", "tag"])
        got = df.collect()
        assert got.schema.names == ["id", "tag"]
        assert got.num_rows == 40

    def test_empty_table(self, session, tmp_path):
        b = TableBuilder(tmp_path / "t")
        b.commit()  # no snapshots
        df = session.read_iceberg(str(tmp_path / "t"))
        out = df.collect()
        assert out.num_rows == 0
        assert out.schema.names == ["id", "v", "tag"]

    def test_schema_from_metadata(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        b.commit()
        tbl = IcebergTable(session, str(tmp_path / "t"))
        from spark_rapids_tpu import types as T
        assert tbl.schema.names == ("id", "v", "tag")
        assert isinstance(tbl.schema.types[0], T.LongType)
        assert isinstance(tbl.schema.types[2], T.StringType)

    def test_disabled_by_conf(self, rng, tmp_path):
        s = TpuSession({"spark.rapids.sql.format.iceberg.enabled": False,
                        "spark.rapids.sql.explain": "NONE"})
        b = TableBuilder(tmp_path / "t")
        b.commit()
        with pytest.raises(ValueError, match="iceberg"):
            s.read_iceberg(str(tmp_path / "t"))

    def test_missing_snapshot_raises(self, session, rng, tmp_path):
        b = TableBuilder(tmp_path / "t")
        m = b.manifest([(1, b.write_data_file(sample(rng, 10, "a")))], "m1")
        b.snapshot([m], 10, 1000)
        b.commit()
        tbl = IcebergTable(session, str(tmp_path / "t"))
        with pytest.raises(IcebergError, match="snapshot 99"):
            tbl.data_files(snapshot_id=99)

    def test_schema_evolution_rejected(self, session, rng, tmp_path):
        # a data file written under an older schema (renamed column) must be
        # rejected loudly, not silently mis-resolved by name
        b = TableBuilder(tmp_path / "t")
        old = pa.table({
            "id": pa.array(rng.integers(0, 100, 20), type=pa.int64()),
            "v_old": pa.array(rng.normal(0, 1, 20), type=pa.float64()),
            "tag": pa.array(["x"] * 20),
        })
        m = b.manifest([(1, b.write_data_file(old))], "m1")
        b.snapshot([m], 10, 1000)
        b.commit()
        with pytest.raises(IcebergError, match="schema-evolved"):
            session.read_iceberg(str(tmp_path / "t"))

    def test_not_a_table_raises_iceberg_error(self, session, tmp_path):
        with pytest.raises(IcebergError, match="not an iceberg table"):
            IcebergTable(session, str(tmp_path / "nope"))
