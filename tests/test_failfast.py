"""Engine-wide fail-fast on a wedged device backend (reference
`Plugin.scala:436-459`: inspect executor startup failure, log diagnostics,
exit fast). The axon TPU runtime has been observed to HANG (not raise)
inside client init; a planned query must raise a typed error within the
configured deadline instead of blocking forever."""

import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.errors import DeviceStartupError
from spark_rapids_tpu.expr import col, lit
from spark_rapids_tpu.memory import device_manager as dm
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture
def fresh_device_manager():
    dm.DeviceManager.shutdown()
    yield
    dm.DeviceManager.shutdown()


def _session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE",
                       "spark.rapids.tpu.device.startupTimeoutSec": 1.0})


def _df(session):
    t = pa.table({"a": pa.array(range(10), type=pa.int64())})
    return session.from_arrow(t).filter(col("a") > lit(3))


class TestFailFast:
    def test_hanging_backend_raises_within_deadline(
            self, monkeypatch, fresh_device_manager):
        monkeypatch.setattr(dm, "_backend_touch",
                            lambda: time.sleep(3600))
        t0 = time.monotonic()
        with pytest.raises(DeviceStartupError, match="did not respond"):
            _df(_session()).collect()
        elapsed = time.monotonic() - t0
        assert elapsed < 10, f"fail-fast took {elapsed:.1f}s"

    def test_error_backend_raises_typed(self, monkeypatch,
                                        fresh_device_manager):
        def boom():
            raise RuntimeError("UNAVAILABLE: tunnel reset")
        monkeypatch.setattr(dm, "_backend_touch", boom)
        with pytest.raises(DeviceStartupError, match="UNAVAILABLE") as ei:
            _df(_session()).collect()
        assert "cause" in ei.value.diagnostics

    def test_second_query_fails_immediately(self, monkeypatch,
                                            fresh_device_manager):
        # the fatal startup error is remembered: later queries must not
        # re-arm a fresh deadline against the same wedged runtime
        monkeypatch.setattr(dm, "_backend_touch",
                            lambda: time.sleep(3600))
        s = _session()
        with pytest.raises(DeviceStartupError):
            _df(s).collect()
        t0 = time.monotonic()
        with pytest.raises(DeviceStartupError):
            _df(s).collect()
        assert time.monotonic() - t0 < 0.5

    def test_cpu_engine_unaffected(self, monkeypatch,
                                   fresh_device_manager):
        monkeypatch.setattr(dm, "_backend_touch",
                            lambda: time.sleep(3600))
        out = _df(_session()).collect_cpu()
        assert out.column("a").to_pylist() == [4, 5, 6, 7, 8, 9]

    def test_disabled_guard_passes_through(self, fresh_device_manager):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.tpu.device.startupTimeoutSec": -1.0})
        out = _df(s).collect()
        assert out.column("a").to_pylist() == [4, 5, 6, 7, 8, 9]
