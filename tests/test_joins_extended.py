"""Extended join forms: condition (non-equi) joins, cartesian / nested loop,
and existence joins — differential CPU-vs-TPU (reference:
GpuBroadcastNestedLoopJoinExecBase.scala, GpuCartesianProductExec.scala,
condition handling in GpuHashJoin.scala, ExistenceJoin)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import col, lit
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def left_table(rng, n=400):
    nulls = rng.random(n) < 0.1
    return pa.table({
        "k": pa.array(np.where(nulls, 0, rng.integers(0, 25, n)),
                      type=pa.int64(), mask=nulls),
        "a": pa.array(rng.integers(-50, 50, n), type=pa.int32()),
        "x": pa.array(rng.normal(0, 10, n).round(3), type=pa.float64()),
    })


def right_table(rng, n=300):
    nulls = rng.random(n) < 0.1
    return pa.table({
        "k": pa.array(np.where(nulls, 0, rng.integers(0, 25, n)),
                      type=pa.int64(), mask=nulls),
        "b": pa.array(rng.integers(-50, 50, n), type=pa.int32()),
        "y": pa.array(rng.normal(0, 10, n).round(3), type=pa.float64()),
    })


ALL_TYPES = ["inner", "left", "right", "full", "semi", "anti", "existence"]


def _sort_cols(how):
    if how in ("semi", "anti"):
        return ["k", "a", "x"]
    if how == "existence":
        return ["k", "a", "x", "exists"]
    return ["k", "a", "x", "b", "y"]


class TestConditionHashJoin:
    @pytest.mark.parametrize("how", ALL_TYPES)
    def test_equi_with_condition(self, session, rng, how):
        left = session.from_arrow(left_table(rng))
        right = session.from_arrow(right_table(rng))
        q = left.join(right, on="k", how=how, condition=col("a") > col("b"))
        assert_same(q, sort_by=_sort_cols(how))

    def test_condition_null_is_no_match(self, session):
        # condition evaluating to NULL must behave as false
        lt = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                       "a": pa.array([None, 5], type=pa.int32())})
        rt = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                       "b": pa.array([0, None], type=pa.int32())})
        left, right = session.from_arrow(lt), session.from_arrow(rt)
        q = left.join(right, on="k", how="left", condition=col("a") > col("b"))
        assert_same(q, sort_by=["k"])


class TestExistenceHashJoin:
    def test_existence_basic(self, session, rng):
        left = session.from_arrow(left_table(rng, n=250))
        right = session.from_arrow(right_table(rng, n=150))
        q = left.join(right, on="k", how="existence")
        assert_same(q, sort_by=["k", "a", "x", "exists"])

    def test_existence_empty_build(self, session, rng):
        left = session.from_arrow(left_table(rng, n=50))
        right = session.from_arrow(right_table(rng, n=150)) \
            .filter(col("b") > lit(10**6))
        q = left.join(right, on="k", how="existence")
        assert_same(q, sort_by=["k", "a", "x", "exists"])


class TestNestedLoopJoin:
    def test_cross_join(self, session, rng):
        left = session.from_arrow(left_table(rng, n=60))
        right = session.from_arrow(right_table(rng, n=45))
        q = left.cross_join(right)
        assert_same(q, sort_by=["k", "a", "x", "b", "y"])

    @pytest.mark.parametrize("how", ALL_TYPES)
    def test_pure_condition_join(self, session, rng, how):
        left = session.from_arrow(left_table(rng, n=80))
        right = session.from_arrow(right_table(rng, n=70))
        q = left.join(right, how=how, condition=col("a") == col("b"))
        assert_same(q, sort_by=_sort_cols(how))

    def test_non_equi_range_condition(self, session, rng):
        left = session.from_arrow(left_table(rng, n=90))
        right = session.from_arrow(right_table(rng, n=60))
        q = left.join(right, how="inner",
                      condition=(col("a") > col("b")) &
                                (col("x") < col("y")))
        assert_same(q, sort_by=["k", "a", "x", "b", "y"])

    def test_empty_sides(self, session, rng):
        left = session.from_arrow(left_table(rng, n=40))
        empty = session.from_arrow(right_table(rng, n=30)) \
            .filter(col("b") > lit(10**6))
        for how in ("inner", "left", "semi", "anti", "full"):
            q = left.join(empty, how=how, condition=col("a") > col("b"))
            assert_same(q, sort_by=_sort_cols(how))

    def test_streams_probe_batches(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.batchSizeRows": 64})
        left = sess.from_arrow(left_table(rng, n=300))
        right = sess.from_arrow(right_table(rng, n=40))
        q = left.join(right, how="full", condition=col("a") > col("b"))
        assert_same(q, sort_by=["k", "a", "x", "b", "y"])
