"""Live query introspection (ISSUE-13): in-flight registry, progress/ETA
from the statistics history, slow-query watchdog, and the surfaces —
/queries over HTTP, the `queries` service op, the fleet-gateway fan-out,
and the tpu_top console — plus the satellite tools (profile_report scan-
pushdown section, bench_compare).

Off-path contract is tested here and CI-gated by
scripts/liveview_matrix.sh: live.enabled=false spawns zero threads,
creates zero state, and keeps results byte-identical."""

import importlib.util
import json
import os
import signal
import socket as socketmod
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import faults, live, stats, telemetry
from spark_rapids_tpu.errors import QueryCancelledError
from spark_rapids_tpu.expr import Sum, col
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.service import TpuServiceClient
from spark_rapids_tpu.service.protocol import recv_msg, send_msg
from spark_rapids_tpu.utils.spans import validate_record

pytestmark = pytest.mark.live

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _live_teardown():
    """Every test leaves live/telemetry/stats OFF so suites sharing this
    process keep their zero-thread assumptions (the configure calls are
    enable-only)."""
    yield
    live.shutdown()
    telemetry.shutdown()
    stats.shutdown()
    assert not live.is_enabled() and live.get() is None


def _table(n=40_000, seed=7, groups=32):
    rng = np.random.default_rng(seed)
    return pa.table({
        "g": pa.array(rng.integers(0, groups, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n))})


def _run(sess, t):
    return (sess.from_arrow(t).filter(col("v") > 0.25)
            .group_by("g").agg(total=Sum(col("v")))).collect()


def _conf(**extra):
    base = {"spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.live.enabled": True,
            # several batches per query so the pull hook fires often
            "spark.rapids.sql.batchSizeRows": 8192}
    base.update(extra)
    return base


def _slow_fault(times=60, delay_s=0.02):
    """A deterministic mid-query slowdown: every tracked device
    allocation sleeps, spreading wall time across the whole pull chain
    so pollers observe intermediate states."""
    return faults.inject(faults.ALLOC, "delay", nth=0, times=times,
                         delay_s=delay_s)


# ---------------------------------------------------------------------------
class TestOffPath:
    def test_off_by_default_zero_state(self):
        threads0 = threading.active_count()
        sess = TpuSession({"spark.rapids.sql.explain": "NONE"})
        _run(sess, _table())
        assert not live.is_enabled()
        assert live.get() is None and live.watchdog() is None
        assert threading.active_count() <= threads0
        snap = live.snapshot()
        assert snap == {"enabled": False, "pid": os.getpid(),
                        "queries": [], "recent": []}
        # the hot hook is a no-op without state
        live.note_pull(object())
        assert live.current_entry() is None

    def test_on_off_results_identical(self):
        t = _table()
        # sessions identical except the live switch: same batch sizing,
        # so float-sum grouping matches and equality is byte-exact
        off_conf = _conf()
        off_conf["spark.rapids.tpu.live.enabled"] = False
        off = _run(TpuSession(off_conf), t)
        on = _run(TpuSession(_conf()), t)
        assert on.sort_by("g").equals(off.sort_by("g"))


# ---------------------------------------------------------------------------
class TestRegistryLifecycle:
    def test_recent_entry_fields(self):
        sess = TpuSession(_conf())
        _run(sess, _table())
        snap = live.snapshot()
        assert snap["enabled"] and snap["queries"] == []
        rec = snap["recent"][-1]
        assert rec["status"] == "ok"
        assert rec["rows"] > 0 and rec["pulls"] > 0
        assert rec["operator"]  # the last pulled operator is stamped
        names = [o["name"] for o in rec["ops"]]
        assert "TpuHashAggregateExec" in names
        assert any(o["rows"] > 0 for o in rec["ops"])
        assert rec["tenant"] == "default" and rec["trace_id"]
        # no stats history => rows-only mode, fail-closed
        assert rec["progress"] is None and rec["eta_s"] is None
        json.dumps(snap)  # the wire shape must be JSON-clean

    def test_inflight_mid_query_monotonic_progress(self):
        sess = TpuSession(_conf(**{"spark.rapids.tpu.stats.enabled": True}))
        t = _table()
        _run(sess, t)  # populate history (rows + wall per fingerprint)
        seen, progress_seq = [], []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                for q in live.snapshot()["queries"]:
                    seen.append(q["query_id"])
                    if q["progress"] is not None:
                        progress_seq.append(q["progress"])
                time.sleep(0.005)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        with _slow_fault():
            _run(sess, t)
        stop.set()
        poller.join(timeout=5)
        assert seen, "query never appeared in the in-flight registry"
        assert progress_seq, "no progress fractions observed mid-query"
        assert progress_seq == sorted(progress_seq), \
            f"progress went backwards: {progress_seq}"
        assert all(0.0 <= p <= 1.0 for p in progress_seq)
        assert live.snapshot()["queries"] == []  # cleared on finish

    def test_recent_ring_bounded(self):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.live.recentQueries": 3}))
        t = _table(n=4000)
        for _ in range(5):
            _run(sess, t)
        assert len(live.snapshot()["recent"]) == 3


# ---------------------------------------------------------------------------
class TestProgressEta:
    def test_progress_eta_with_history(self):
        sess = TpuSession(_conf(**{"spark.rapids.tpu.stats.enabled": True}))
        t = _table()
        _run(sess, t)
        first = live.snapshot()["recent"][-1]
        assert first["progress"] is None and first["eta_s"] is None
        _run(sess, t)
        rec = live.snapshot()["recent"][-1]
        assert rec["expected_wall_s"] and rec["expected_wall_s"] > 0
        assert rec["progress"] == pytest.approx(1.0)
        assert rec["eta_s"] == pytest.approx(0.0)
        # per-op expectations resolved from the fingerprint history
        assert any("expected_rows" in o and o.get("fraction") == 1.0
                   for o in rec["ops"])

    def test_wall_recorded_into_history(self):
        sess = TpuSession(_conf(**{"spark.rapids.tpu.stats.enabled": True}))
        _run(sess, _table())
        hist = stats.get()
        assert hist is not None
        walls = [e.wall_s for e in hist._entries.values() if e.wall_s > 0]
        assert walls, "no wall_s landed in the stats history"

    def test_deadline_fields_in_snapshot(self):
        from spark_rapids_tpu.sched import QueryContext, activate
        live.configure(_conf_obj())
        reg = live.get()
        ctx = QueryContext(tenant="t9", priority=2, deadline_s=30.0,
                           query_id="dl-q")
        with activate(ctx):
            entry = reg.begin(_dummy_exec(), None, "dl")
        snap = entry.snapshot()
        assert snap["query_id"] == "dl-q" and snap["tenant"] == "t9"
        assert snap["priority"] == 2
        assert snap["deadline_s"] == 30.0
        assert 0 < snap["remaining_s"] <= 30.0
        reg.end(entry, "ok")


def _conf_obj(**extra):
    from spark_rapids_tpu.config import TpuConf
    return TpuConf(_conf(**extra))


def _dummy_exec():
    from spark_rapids_tpu.exec.base import TpuExec
    return TpuExec([])


# ---------------------------------------------------------------------------
class TestWatchdog:
    def test_no_false_positive_without_history(self):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.live.slowFactor": 0.001,
            "spark.rapids.tpu.live.watchdog.intervalMs": 20}))
        with _slow_fault(times=20):
            _run(sess, _table())  # slow AND first-ever: no history
        rec = live.snapshot()["recent"][-1]
        assert rec["slow"] is False
        assert live.watchdog().flags == 0

    def test_slow_query_incident_with_live_snapshot(self, tmp_path):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.stats.enabled": True,
            "spark.rapids.tpu.telemetry.enabled": True,
            "spark.rapids.tpu.telemetry.flightRecorder.dir": str(tmp_path),
            "spark.rapids.tpu.live.slowFactor": 0.2,
            "spark.rapids.tpu.live.watchdog.intervalMs": 20}))
        t = _table()
        _run(sess, t)  # cold run: compile warmup (its wall is inflated)
        _run(sess, t)  # history run at WARM wall (latest record wins) —
        # the injected delay below then dominates slowFactor x history
        # regardless of whether this test runs standalone or mid-suite
        with _slow_fault(times=100, delay_s=0.03):
            _run(sess, t)
        rec = live.snapshot()["recent"][-1]
        assert rec["slow"] is True and "historical wall" in rec["slow_reason"]
        dumps = [f for f in os.listdir(tmp_path) if "slow_query" in f]
        assert dumps, f"no slow_query incident in {os.listdir(tmp_path)}"
        recs = [json.loads(line)
                for line in open(tmp_path / dumps[0])]
        bad = [validate_record(r) for r in recs if validate_record(r)]
        assert not bad, bad[:2]
        header = recs[0]
        assert header["reason"] == "slow_query"
        assert header["trace_id"] == rec["trace_id"]
        lv = header["attrs"]["live"]
        assert lv["query_id"] == rec["query_id"]
        assert lv["ops"], "no live operator snapshot in the incident"
        # exactly one incident per query
        assert live.watchdog().flags == 1

    def test_watchdog_cancel(self):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.stats.enabled": True,
            "spark.rapids.tpu.sched.tenant": "wd",  # activates a context
            "spark.rapids.tpu.live.slowFactor": 0.1,
            "spark.rapids.tpu.live.watchdog.intervalMs": 20,
            "spark.rapids.tpu.live.watchdog.cancel": True}))
        t = _table()
        _run(sess, t)  # compile warmup
        _run(sess, t)  # history at warm wall
        with _slow_fault(times=400, delay_s=0.05):
            with pytest.raises(QueryCancelledError) as ei:
                _run(sess, t)
        assert "watchdog" in str(ei.value)
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        TpuSemaphore._instance = None  # scheduled-query permit hygiene

    def test_deadline_approaching_flag(self):
        from spark_rapids_tpu.live.watchdog import Watchdog
        from spark_rapids_tpu.sched import QueryContext, activate
        live.configure(_conf_obj())
        reg = live.get()
        ctx = QueryContext(deadline_s=0.3)
        with activate(ctx):
            entry = reg.begin(_dummy_exec(), None, "dl")
        wd = Watchdog(reg, interval_s=999, slow_factor=3.0)
        assert wd.scan() == 0  # plenty of budget left: not flagged
        time.sleep(0.28)       # inside the last 10% of the deadline
        assert wd.scan() == 1
        assert entry.slow and "deadline" in entry.slow_reason
        assert wd.scan() == 0  # idempotent: one flag per query
        reg.end(entry, "deadline")


# ---------------------------------------------------------------------------
class TestSurfaces:
    def test_http_queries_endpoint_mid_query(self):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.stats.enabled": True,
            "spark.rapids.tpu.telemetry.enabled": True,
            "spark.rapids.tpu.telemetry.http.port": 0}))
        t = _table()
        _run(sess, t)
        port = telemetry.http_server().port

        def get():
            return json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/queries", timeout=5).read())

        seen = []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                seen.extend(q["query_id"] for q in get()["queries"])
                time.sleep(0.01)

        th = threading.Thread(target=poll, daemon=True)
        th.start()
        with _slow_fault():
            _run(sess, t)
        stop.set()
        th.join(timeout=5)
        assert seen, "in-flight query never visible on /queries"
        snap = get()
        assert snap["enabled"] and snap["queries"] == []
        assert snap["recent"]

    def test_telemetry_live_families(self):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.stats.enabled": True,
            "spark.rapids.tpu.telemetry.enabled": True}))
        t = _table()
        _run(sess, t)
        from spark_rapids_tpu.telemetry import parse_prometheus
        found = {"queries": 0, "progress": 0}
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                parsed = parse_prometheus(telemetry.render_prometheus())
                found["queries"] = max(
                    found["queries"],
                    sum(parsed.get("tpu_live_queries", {}).values()))
                found["progress"] = max(
                    found["progress"],
                    len(parsed.get("tpu_live_query_progress", {})))
                time.sleep(0.01)

        th = threading.Thread(target=poll, daemon=True)
        th.start()
        with _slow_fault():
            _run(sess, t)
        stop.set()
        th.join(timeout=5)
        assert found["queries"] >= 1, "tpu_live_queries never sampled >0"
        assert found["progress"] >= 1, \
            "tpu_live_query_progress never carried a series"

    def test_service_queries_op_and_surface_agreement(self, tmp_path):
        """The acceptance shape: during one running query, the HTTP
        endpoint, the service op, and the in-process registry all report
        the same query id (the gateway fan-out is TestGatewayFanout's
        job; the real-subprocess version is liveview_matrix.sh's)."""
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from spark_rapids_tpu.service.server import TpuDeviceService
        import pyarrow.parquet as pq
        rng = np.random.default_rng(5)
        big = pa.table({
            "k": pa.array(rng.integers(0, 50, 60_000).astype(np.int64)),
            "v": pa.array(rng.normal(0.1, 1.0, 60_000))})
        path = str(tmp_path / "t.parquet")
        pq.write_table(big, path)

        def attr(name, dt):
            return [{"class": "org.apache.spark.sql.catalyst.expressions."
                     "AttributeReference", "num-children": 0, "name": name,
                     "dataType": dt, "nullable": True, "metadata": {},
                     "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]

        plan = json.dumps([{
            "class": "org.apache.spark.sql.execution.FileSourceScanExec",
            "num-children": 0, "relation": "HadoopFsRelation(parquet)",
            "output": [attr("k", "long"), attr("v", "double")],
            "tableIdentifier": "t"}])
        svc = TpuDeviceService(
            _conf(**{"spark.rapids.sql.enabled": True,
                     "spark.rapids.tpu.telemetry.enabled": True,
                     "spark.rapids.tpu.telemetry.http.port": 0,
                     "spark.rapids.sql.batchSizeRows": 4096}),
            str(tmp_path / "svc.sock"))
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        try:
            for _ in range(400):
                if svc._listener is not None:
                    break
                time.sleep(0.01)
            port = telemetry.http_server().port
            qid = "agree-q1"
            done = threading.Event()

            def submit():
                with TpuServiceClient(str(tmp_path / "svc.sock"),
                                      deadline_s=120.0) as cli:
                    cli.run_plan(plan, paths={"t": [path]}, query_id=qid)
                done.set()

            sub = threading.Thread(target=submit, daemon=True)
            hits = {"http": False, "op": False, "reg": False}
            with TpuServiceClient(str(tmp_path / "svc.sock"),
                                  deadline_s=30.0) as poll_cli:
                with _slow_fault(times=200, delay_s=0.05):
                    sub.start()
                    deadline = time.monotonic() + 60
                    while not done.is_set() and \
                            time.monotonic() < deadline and \
                            not all(hits.values()):
                        body = json.loads(urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/queries",
                            timeout=5).read())
                        if any(q["query_id"] == qid
                               for q in body["queries"]):
                            hits["http"] = True
                        lv = poll_cli.queries()
                        if any(q["query_id"] == qid
                               for q in lv["queries"]):
                            hits["op"] = True
                        if any(e.query_id == qid
                               for e in live.get().inflight()):
                            hits["reg"] = True
                        time.sleep(0.02)
                    sub.join(timeout=90)
            assert all(hits.values()), f"surfaces disagreed: {hits}"
            with TpuServiceClient(str(tmp_path / "svc.sock"),
                                  deadline_s=10.0) as cli:
                lv = cli.queries()
                assert lv["enabled"]
                assert any(r["query_id"] == qid for r in lv["recent"])
        finally:
            svc._stop.set()
            th.join(timeout=10)
            TpuSemaphore._instance = None


# ---------------------------------------------------------------------------
class _FakeLiveWorker(threading.Thread):
    """Thread server answering ping + queries with a canned live view."""

    def __init__(self, sock_path, name="fw"):
        super().__init__(daemon=True)
        self.sock_path = sock_path
        self.worker_name = name
        self.srv = socketmod.socket(socketmod.AF_UNIX,
                                    socketmod.SOCK_STREAM)
        self.srv.bind(sock_path)
        self.srv.listen(16)
        self.srv.settimeout(0.2)
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.srv.accept()
            except socketmod.timeout:
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()
        self.srv.close()

    def _serve(self, conn):
        try:
            while True:
                header, _ = recv_msg(conn)
                if header.get("op") == "ping":
                    send_msg(conn, {"ok": True, "device": "fake"})
                elif header.get("op") == "queries":
                    send_msg(conn, {"ok": True, "live": {
                        "enabled": True, "pid": 1,
                        "queries": [{
                            "query_id": f"q-{self.worker_name}",
                            "label": "fake", "tenant": "default",
                            "status": "running", "started_ts": 1.0,
                            "elapsed_s": 0.5, "operator": "TpuFilterExec",
                            "rows": 10, "progress": 0.5, "eta_s": 0.5,
                            "slow": False, "ops": []}],
                        "recent": []}})
                else:
                    send_msg(conn, {"ok": False, "error": "nope"})
        except Exception:
            pass

    def close(self):
        self._stop.set()


class TestGatewayFanout:
    def _gateway(self, tmp_path, specs):
        from spark_rapids_tpu.fleet.gateway import FleetGateway
        gw_sock = str(tmp_path / "gw.sock")
        gw = FleetGateway(
            specs,
            {"spark.rapids.tpu.fleet.probe.intervalMs": 60_000,
             "spark.rapids.tpu.fleet.probe.timeoutSec": 1.0,
             "spark.rapids.tpu.fleet.dispatch.timeoutSec": 5.0},
            gw_sock)
        th = threading.Thread(target=gw.serve_forever, daemon=True)
        th.start()
        cli = TpuServiceClient(gw_sock, deadline_s=15.0).connect()
        return gw, th, cli

    def test_fanout_partial_annotated_never_error(self, tmp_path):
        ok = _FakeLiveWorker(str(tmp_path / "ok.sock"), name="ok")
        ok.start()
        drain = _FakeLiveWorker(str(tmp_path / "dr.sock"), name="dr")
        drain.start()
        specs = [("w_ok", ok.sock_path), ("w_drain", drain.sock_path),
                 ("w_dead", str(tmp_path / "nope.sock")),
                 ("w_open", str(tmp_path / "nope2.sock"))]
        gw, th, cli = self._gateway(tmp_path, specs)
        try:
            gw.registry.drain("w_drain")
            for _ in range(3):  # trip w_open's breaker
                gw.registry.note_failure("w_open", "boom")
            lv = cli.queries()
            assert lv["enabled"] and lv["role"] == "gateway"
            w = lv["workers"]
            # healthy worker: its query rides in, annotated
            assert w["w_ok"]["enabled"] and w["w_ok"]["queries"] == 1
            ids = {(q["query_id"], q["worker"]) for q in lv["queries"]}
            assert ("q-ok", "w_ok") in ids
            # draining worker still polled (its in-flight view matters)
            assert w["w_drain"]["draining"] is True
            assert ("q-dr", "w_drain") in ids
            # dead worker: error slot with breaker state, not an error
            assert "error" in w["w_dead"]
            assert "breaker" in w["w_dead"]
            # breaker-OPEN worker skipped without touching its socket
            assert w["w_open"] == {"breaker": "open", "draining": False,
                                   "outstanding": 0,
                                   "skipped": "breaker_open"}
        finally:
            cli.close()
            gw._stop.set()
            th.join(timeout=5)
            ok.close()
            drain.close()

    def test_fanout_all_dead_is_still_ok(self, tmp_path):
        specs = [("a", str(tmp_path / "a.sock")),
                 ("b", str(tmp_path / "b.sock"))]
        gw, th, cli = self._gateway(tmp_path, specs)
        try:
            lv = cli.queries()   # must not raise
            assert lv["queries"] == []
            assert set(lv["workers"]) == {"a", "b"}
            for slot in lv["workers"].values():
                assert "error" in slot or "skipped" in slot
        finally:
            cli.close()
            gw._stop.set()
            th.join(timeout=5)


# ---------------------------------------------------------------------------
class TestDebugSignal:
    def test_sigusr2_dumps_schema_valid_incident(self, tmp_path):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.live.debugSignal": True,
            "spark.rapids.tpu.telemetry.enabled": True,
            "spark.rapids.tpu.telemetry.flightRecorder.dir":
                str(tmp_path)}))
        _run(sess, _table(n=4000))
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [f for f in os.listdir(tmp_path)
                     if "debug_signal" in f]
            time.sleep(0.02)
        assert dumps, f"no debug_signal dump in {os.listdir(tmp_path)}"
        recs = [json.loads(line) for line in open(tmp_path / dumps[0])]
        bad = [validate_record(r) for r in recs if validate_record(r)]
        assert not bad, bad[:2]
        assert recs[0]["reason"] == "debug_signal"
        lv = recs[0]["attrs"]["live"]
        assert lv["enabled"] and lv["recent"], \
            "live registry missing from the dump"
        # the ring events rode along (telemetry was on)
        assert any(r["type"] == "event" for r in recs)

    def test_debug_dump_standalone_without_telemetry(self, tmp_path):
        sess = TpuSession(_conf(**{
            "spark.rapids.tpu.metrics.eventLog.dir": str(tmp_path)}))
        # force live configuration without telemetry
        sess.initialize_device()
        path = live.debug_dump()
        assert path and os.path.exists(path)
        rec = json.loads(open(path).readline())
        assert not validate_record(rec), validate_record(rec)
        assert rec["type"] == "incident" and rec["n_events"] == 0
        assert rec["attrs"]["live"]["enabled"] is True


# ---------------------------------------------------------------------------
class TestSatelliteTools:
    @staticmethod
    def _query_record(qid="1-1", **tm):
        base_tm = {"scan_rows_pruned": 0, "scan_rowgroups_pruned": 0,
                   "scan_bytes_materialized": 0}
        base_tm.update(tm)
        return {"v": 2, "type": "query", "query_id": qid,
                "trace_id": "t" * 16, "label": "q", "status": "ok",
                "ts": 1.0, "wall_ns": 5_000_000, "task_metrics": base_tm,
                "n_operators": 1, "n_spans": 1, "adaptive": []}

    def test_profile_report_scan_pushdown_section(self, tmp_path, capsys):
        from spark_rapids_tpu.tools import profile_report as pr
        recs = [self._query_record(scan_rows_pruned=1900,
                                   scan_rowgroups_pruned=3,
                                   scan_bytes_materialized=4096)]
        model = pr.build_model(recs)
        text = pr.render_report(model)
        assert "scan pushdown:" in text
        assert "rowsPruned=1900" in text
        assert "rowGroupsPruned=3" in text
        assert "bytesMaterialized=4096B" in text
        assert "=== scan pushdown ===" in text
        pd = pr.pushdown_summary(model)
        assert pd == {"queries": 1, "rows_pruned": 1900,
                      "rowgroups_pruned": 3, "bytes_materialized": 4096}
        # --json carries the section too
        log = tmp_path / "events-1.jsonl"
        log.write_text(json.dumps(recs[0]) + "\n")
        assert pr.main([str(log), "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["pushdown"]["rows_pruned"] == 1900
        # a pushdown-free log renders no section
        assert pr.pushdown_summary(
            pr.build_model([self._query_record()])) == {}

    @staticmethod
    def _bench_compare():
        spec = importlib.util.spec_from_file_location(
            "bench_compare", os.path.join(REPO, "scripts",
                                          "bench_compare.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_bench_compare_diff_and_gate(self, tmp_path, capsys):
        bc = self._bench_compare()
        base = {"metric": "scan_join_agg_speedup_vs_cpu", "value": 2.0,
                "unit": "x", "vs_baseline": 1.0,
                "detail": {"pipeline_gbps": 3.0, "scan_decode_gbps_raw":
                           0.3, "scan_dispatches": 48, "rows": 1000}}
        new = {"metric": "scan_join_agg_speedup_vs_cpu", "value": 4.0,
               "unit": "x", "vs_baseline": 2.0,
               "detail": {"pipeline_gbps": 6.0, "scan_decode_gbps_raw":
                          0.6, "scan_dispatches": 4, "rows": 1000}}
        pb = tmp_path / "BENCH_a.json"
        pn = tmp_path / "BENCH_b.json"
        pb.write_text(json.dumps(base))
        # the driver-wrapper shape must load too
        pn.write_text(json.dumps({"n": 1, "parsed": new}))
        assert bc.main([str(pb), str(pn)]) == 0
        out = capsys.readouterr().out
        assert "2.000" in out and "pipeline_gbps" in out \
            and "scan_dispatches" in out
        assert bc.main([str(pb), str(pn), "--fail-below", "1.5"]) == 0
        assert bc.main([str(pb), str(pn), "--fail-below", "3.0"]) == 2
        # an errored run (null headline) always fails the gate
        pe = tmp_path / "BENCH_err.json"
        pe.write_text(json.dumps({"metric": "m", "value": None,
                                  "error": "wedged tunnel",
                                  "detail": {}}))
        assert bc.main([str(pb), str(pe), "--fail-below", "0.1"]) == 2
        # json model shape (drain the earlier renders first)
        capsys.readouterr()
        assert bc.main([str(pb), str(pn), "--json"]) == 0
        model = json.loads(capsys.readouterr().out)
        assert model["headline"][0]["speedup_vs_base"] == \
            pytest.approx(2.0)

    def test_tpu_top_render_units(self):
        from spark_rapids_tpu.tools import tpu_top
        assert tpu_top.progress_bar(None).endswith("?%")
        bar = tpu_top.progress_bar(0.5, width=10)
        assert bar.count("#") == 5 and "50%" in bar
        assert "100%" in tpu_top.progress_bar(1.5)  # clamped
        # a gateway-role snapshot renders annotated worker rows
        snaps = [{"name": "gw", "socket": "/s", "ok": True, "live": {
            "enabled": True, "role": "gateway",
            "workers": {"w0": {"breaker": "open", "draining": False,
                               "outstanding": 0,
                               "skipped": "breaker_open"},
                        "w1": {"breaker": "closed", "draining": True,
                               "outstanding": 2, "enabled": True,
                               "queries": 1}},
            "queries": [{"query_id": "q1", "worker": "w1",
                         "tenant": "a", "status": "running",
                         "operator": "TpuSortExec", "rows": 5,
                         "progress": 0.25, "eta_s": 1.5,
                         "elapsed_s": 0.5, "started_ts": 1.0,
                         "slow": True}],
            "recent": []}}]
        frame = tpu_top.render(snaps)
        assert "gw/w0" in frame and "skipped" in frame
        assert "gw/w1" in frame and "yes" in frame  # draining column
        assert "q1" in frame and "SLOW" in frame and "TpuSortExec" in frame
        assert "25%" in frame

    def test_tpu_top_once_against_service(self, tmp_path, capsys):
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from spark_rapids_tpu.service.server import TpuDeviceService
        from spark_rapids_tpu.tools import tpu_top
        svc = TpuDeviceService(
            _conf(**{"spark.rapids.sql.enabled": True,
                     "spark.rapids.tpu.telemetry.enabled": True}),
            str(tmp_path / "svc.sock"))
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        try:
            for _ in range(400):
                if svc._listener is not None:
                    break
                time.sleep(0.01)
            _run(svc.session, _table(n=4000))
            assert tpu_top.main(
                ["--once", "--plain", f"svc={tmp_path / 'svc.sock'}"]) == 0
            out = capsys.readouterr().out
            assert "svc" in out and "in-flight queries" in out
            assert "recent:" in out  # the finished query shows up
            # a down endpoint degrades to a row, not a crash
            assert tpu_top.main(
                ["--once", "--plain", f"gone={tmp_path / 'no.sock'}"]) == 0
            assert "down" in capsys.readouterr().out
        finally:
            svc._stop.set()
            th.join(timeout=10)
            TpuSemaphore._instance = None
