"""Query-scheduler suite (marker `sched`; scripts/sched_matrix.sh runs it
standalone).

Covers the ISSUE-7 acceptance surface: mixed-priority queries racing on
`concurrentGpuTasks=1` with golden CPU-engine equality per query, strict
priority ordering under contention (no inversion), cooperative
cancellation mid-scan/mid-shuffle reclaiming the admission token with no
leaked threads or catalog handles, load shedding with the typed
`QueryRejectedError` before any device touch, the `sched.admit` fault
point degrading typed, deadline-aware retry/fetch backoff, per-tenant
memory sub-quotas, the service admission queue's dead-waiter removal, and
the scheduler-off FIFO equivalence gate."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.errors import (DeadlineExceededError,
                                     QueryCancelledError,
                                     QueryRejectedError, RetryOOM,
                                     SplitAndRetryOOM)
from spark_rapids_tpu.expr import Count, Sum, col
from spark_rapids_tpu.memory.budget import MemoryBudget
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.sched import (ABANDONED, AdmissionQueue, CancelToken,
                                    QueryContext, activate, checkpoint,
                                    parse_tenant_map)

pytestmark = pytest.mark.sched


def make_table(seed=7, n=20_000):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 64, n)),
        "g": pa.array(rng.integers(0, 16, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
    })


def sched_session(**extra):
    conf = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.concurrentGpuTasks": 1,
            "spark.rapids.tpu.sched.enabled": True}
    conf.update(extra)
    sess = TpuSession(conf)
    sess.initialize_device()
    # DeviceManager.initialize is once-per-process: re-arm the semaphore
    # for THIS conf (permits + sched policy signature)
    TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)
    return sess


@pytest.fixture
def restore_semaphore():
    """Every test here re-initializes the process semaphore; hand the next
    suite a fresh default instance (and assert we leaked no holders)."""
    yield
    sem = TpuSemaphore._instance
    if sem is not None and sem.scheduler is not None:
        assert sem.scheduler.queue.holders == 0, \
            "test left admission tokens held"
    TpuSemaphore._instance = None


def agg_query(sess, t):
    return (sess.from_arrow(t).filter(col("v") > 0.2)
            .group_by("g").agg(total=Sum(col("v")), cnt=Count(col("k"))))


class TestAdmissionQueueUnit:
    def test_fifo_when_unweighted(self):
        q = AdmissionQueue(1)
        assert q.acquire() == 1
        orders = []
        ths = []
        for i in range(4):
            th = threading.Thread(
                target=lambda i=i: orders.append((q.acquire(), i)))
            th.start()
            time.sleep(0.05)  # deterministic arrival order
            ths.append(th)
        for _ in range(4):
            q.release()
        for th in ths:
            th.join(timeout=5)
        q.release()
        assert [i for _, i in sorted(orders)] == [0, 1, 2, 3]

    def test_priority_beats_arrival(self):
        q = AdmissionQueue(1)
        q.acquire()
        got = []

        def worker(name, prio):
            got.append((q.acquire(priority=prio), name))
            q.release()

        lo = threading.Thread(target=worker, args=("low", 0))
        lo.start()
        time.sleep(0.05)
        hi = threading.Thread(target=worker, args=("high", 10))
        hi.start()
        time.sleep(0.05)
        q.release()  # high must go first despite arriving second
        lo.join(timeout=5)
        hi.join(timeout=5)
        assert sorted(got)[0][1] == "high"

    def test_weighted_fair_share(self):
        q = AdmissionQueue(1, weights={"a": 3.0, "b": 1.0})
        q.acquire()
        grants = []

        def worker(tenant):
            q.acquire(tenant=tenant)
            grants.append(tenant)
            q.release()

        ths = [threading.Thread(target=worker, args=(t,))
               for t in ["a"] * 9 + ["b"] * 9]
        for th in ths:
            th.start()
        time.sleep(0.2)
        q.release()
        for th in ths:
            th.join(timeout=10)
        # 3:1 stride => among the first 8 grants, 'a' gets ~6
        assert grants[:8].count("a") >= 5, grants

    def test_depth_shed(self):
        q = AdmissionQueue(1, max_depth=1)
        q.acquire()
        th = threading.Thread(target=q.acquire)
        th.start()
        time.sleep(0.05)
        with pytest.raises(QueryRejectedError) as ei:
            q.acquire()
        assert ei.value.depth == 1
        q.release()
        th.join(timeout=5)
        q.release()

    def test_wait_shed(self):
        q = AdmissionQueue(1, max_wait_s=0.1)
        q.acquire()
        t0 = time.monotonic()
        with pytest.raises(QueryRejectedError):
            q.acquire()
        assert 0.05 < time.monotonic() - t0 < 5.0
        q.release()

    def test_deadline_while_queued(self):
        q = AdmissionQueue(1)
        q.acquire()
        with pytest.raises(DeadlineExceededError):
            q.acquire(token=CancelToken(deadline_s=0.1))
        q.release()

    def test_cancel_wakes_parked_waiter(self):
        q = AdmissionQueue(1)
        q.acquire()
        tok = CancelToken()
        res = {}

        def park():
            try:
                q.acquire(token=tok)
            except QueryCancelledError:
                res["t"] = time.monotonic()

        th = threading.Thread(target=park)
        th.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        tok.cancel("test")
        th.join(timeout=5)
        assert res["t"] - t0 < 1.0, "cancel did not wake the waiter"
        # the abandoned waiter must not have consumed the token
        q.release()
        assert q.acquire(timeout=1.0) is not None
        q.release()

    def test_dead_waiter_removed_not_granted(self):
        """The release-on-disconnect satellite: a queued waiter whose
        liveness probe goes false is REMOVED; the token goes to the next
        live waiter, never to the dead one."""
        q = AdmissionQueue(1)
        q.acquire()
        alive = {"dead_client": True}
        res = {}

        def dead():
            res["dead"] = q.acquire(alive=lambda: alive["dead_client"])

        def live():
            res["live"] = q.acquire()
            q.release()

        td = threading.Thread(target=dead)
        td.start()
        time.sleep(0.05)
        tl = threading.Thread(target=live)
        tl.start()
        time.sleep(0.05)
        alive["dead_client"] = False  # client dies while parked FIRST in line
        td.join(timeout=5)
        assert res["dead"] is ABANDONED
        assert q.depth() == 1  # only the live waiter remains
        q.release()
        tl.join(timeout=5)
        assert "live" in res

    def test_fault_point_degrades_typed(self):
        q = AdmissionQueue(2)
        with faults.inject(faults.SCHED_ADMIT, "error", nth=1,
                           error=ConnectionResetError) as rule:
            with pytest.raises(QueryRejectedError):
                q.acquire()
            assert rule.fired == 1
        assert q.acquire() is not None  # next admit is clean
        q.release()
        assert q.holders == 0

    def test_idle_tenant_banks_no_credit(self):
        """A tenant that idles while another advances its pass must NOT
        rejoin with banked fair-share credit: the floor tracks queued
        tenants (or the max pass when nothing queues), not every tenant
        ever seen."""
        q = AdmissionQueue(1, weights={"a": 1.0, "b": 1.0})
        # b runs once early, then idles
        assert q.acquire(tenant="b") is not None
        q.release()
        # a runs many solo queries, advancing its pass far past b's
        for _ in range(20):
            q.acquire(tenant="a")
            q.release()
        # contention: one of each queued behind a held token — b must not
        # sweep ahead on its stale low pass beyond one fair turn
        q.acquire(tenant="hold")
        grants = []

        def worker(tenant):
            q.acquire(tenant=tenant)
            grants.append(tenant)
            q.release()

        ths = [threading.Thread(target=worker, args=(t,))
               for t in ["b", "a"] * 4]
        for th in ths:
            th.start()
        time.sleep(0.2)
        q.release()
        for th in ths:
            th.join(timeout=10)
        # equal weights from a level floor => near-alternation, not a
        # b-burst: within any prefix b leads a by at most ~1 grant
        for i in range(1, len(grants) + 1):
            lead = grants[:i].count("b") - grants[:i].count("a")
            assert lead <= 2, f"idle tenant swept ahead: {grants}"

    def test_parse_tenant_map(self):
        assert parse_tenant_map("a=4, b=1.5") == {"a": 4.0, "b": 1.5}
        assert parse_tenant_map("") == {}
        with pytest.raises(ValueError):
            parse_tenant_map("justakey")


class TestEngineScheduling:
    def test_mixed_priority_race_golden(self, restore_semaphore):
        """N mixed-priority queries race on concurrentGpuTasks=1; every
        result must equal the CPU engine's for the same plan."""
        sess = sched_session()
        tables = [make_table(seed=100 + i, n=8_000) for i in range(6)]
        expected = [agg_query(sess, t).plan for t in tables]
        golden = [sess.execute_plan(p, use_device=False).sort_by("g")
                  for p in expected]
        results = [None] * len(tables)
        errors = []

        def run(i):
            try:
                ctx = QueryContext(tenant=f"t{i % 2}",
                                   priority=(10 if i % 3 == 0 else 0))
                plan = agg_query(sess, tables[i]).plan
                results[i] = sess.execute_plan(
                    plan, sched_ctx=ctx).sort_by("g")
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((i, e))

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(len(tables))]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120)
        assert not errors, errors
        for i, (res, gold) in enumerate(zip(results, golden)):
            assert res is not None, f"query {i} produced nothing"
            assert res.equals(gold), f"query {i} diverged from CPU engine"
        assert TpuSemaphore.get().scheduler.queue.holders == 0

    def test_no_priority_inversion(self, restore_semaphore):
        """With the single token held, a high-priority query submitted
        AFTER a queued low-priority one is admitted first."""
        sess = sched_session()
        sched = TpuSemaphore.get().scheduler
        t = make_table(n=4_000)
        plan_lo = agg_query(sess, t).plan
        plan_hi = agg_query(sess, t).plan
        sched.queue.acquire()  # hold the only token
        admitted = []
        orig_admit = sched.admit

        def spy_admit():
            order = orig_admit()
            admitted.append(threading.current_thread().name)
            return order

        sched.admit = spy_admit
        try:
            lo = threading.Thread(
                name="lowpri", target=lambda: sess.execute_plan(
                    plan_lo, sched_ctx=QueryContext(priority=0)))
            lo.start()
            # low-pri must be PARKED in the queue before high-pri arrives
            for _ in range(200):
                if sched.queue.depth() >= 1:
                    break
                time.sleep(0.01)
            assert sched.queue.depth() >= 1, "low-pri never queued"
            hi = threading.Thread(
                name="highpri", target=lambda: sess.execute_plan(
                    plan_hi, sched_ctx=QueryContext(priority=10)))
            hi.start()
            for _ in range(200):
                if sched.queue.depth() >= 2:
                    break
                time.sleep(0.01)
            assert sched.queue.depth() >= 2, "high-pri never queued"
            sched.queue.release()  # free the held token: who gets it?
            lo.join(timeout=60)
            hi.join(timeout=60)
        finally:
            sched.admit = orig_admit
        assert admitted[0] == "highpri", admitted

    def test_shed_query_rejects_before_device(self, restore_semaphore):
        sess = sched_session(**{"spark.rapids.tpu.sched.maxQueueDepth": 1})
        sched = TpuSemaphore.get().scheduler
        sched.queue.acquire()          # token busy
        parked = threading.Thread(target=sched.queue.acquire)
        parked.start()                 # queue at max depth
        time.sleep(0.05)
        cat0 = BufferCatalog.get().live_count
        t = make_table(n=4_000)
        plan = agg_query(sess, t).plan
        with pytest.raises(QueryRejectedError):
            sess.execute_plan(plan, sched_ctx=QueryContext())
        # shed before admission: nothing parked on device, token not taken
        assert BufferCatalog.get().live_count == cat0
        sched.queue.release()
        parked.join(timeout=5)
        sched.queue.release()
        assert sched.queue.holders == 0

    def test_cancel_mid_scan_reclaims_everything(self, restore_semaphore,
                                                 tmp_path):
        """Cancel a parquet-scan query mid-stream (pipeline prefetch on):
        typed error, admission token returned, no leaked prefetch
        threads, no leaked catalog handles."""
        import pyarrow.parquet as pq
        sess = sched_session(**{
            "spark.rapids.sql.batchSizeRows": 1024,
            "spark.rapids.tpu.pipeline.enabled": True})
        path = str(tmp_path / "scan.parquet")
        pq.write_table(make_table(n=40_000), path, row_group_size=1024)
        cat0 = BufferCatalog.get().live_count
        threads0 = threading.active_count()
        ctx = QueryContext(tenant="a")
        plan = (sess.read_parquet(path).filter(col("v") > 0.1)
                .group_by("g").agg(total=Sum(col("v")))).plan

        def killer():
            time.sleep(0.05)
            ctx.token.cancel("mid-scan kill")

        th = threading.Thread(target=killer)
        th.start()
        try:
            sess.execute_plan(plan, sched_ctx=ctx)
        except QueryCancelledError:
            pass  # fast machines may finish first; both are legal
        th.join()
        # the admission token must be back regardless of outcome
        assert TpuSemaphore.get().scheduler.queue.holders == 0
        # prefetch producers joined: thread count returns to baseline
        for _ in range(100):
            if threading.active_count() <= threads0:
                break
            time.sleep(0.02)
        assert threading.active_count() <= threads0, \
            "leaked prefetch thread(s)"
        assert BufferCatalog.get().live_count == cat0, "leaked catalog handles"

    def test_cancel_mid_shuffle_backoff(self, restore_semaphore):
        """A fetch stuck in retry backoff observes the cancel (typed
        error) instead of sleeping out its schedule."""
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager.get()
        ctx = QueryContext()
        ctx.token.cancel("shuffle kill")
        with activate(ctx):
            t0 = time.monotonic()
            with pytest.raises(QueryCancelledError):
                # unknown peer => transport error => retry backoff path
                mgr._fetch_peer_with_retry(999, 0, "no-such-peer")
            assert time.monotonic() - t0 < 2.0

    def test_deadline_bounds_fetch_retries(self, restore_semaphore):
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager.get()
        ctx = QueryContext(deadline_s=0.02)
        with activate(ctx):
            with pytest.raises(DeadlineExceededError):
                mgr._fetch_peer_with_retry(999, 0, "no-such-peer")

    def test_fault_sched_admit_engine(self, restore_semaphore):
        sess = sched_session()
        t = make_table(n=4_000)
        plan = agg_query(sess, t).plan
        with faults.inject(faults.SCHED_ADMIT, "error", nth=1) as rule:
            with pytest.raises(QueryRejectedError):
                sess.execute_plan(plan, sched_ctx=QueryContext())
            assert rule.fired == 1
        assert TpuSemaphore.get().scheduler.queue.holders == 0
        # next query admits cleanly
        out = sess.execute_plan(plan, sched_ctx=QueryContext())
        assert out.num_rows > 0


class TestDeadlineBackoff:
    def test_with_retry_fails_fast_past_deadline(self):
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        calls = []

        def always_oom(_):
            calls.append(1)
            raise RetryOOM("pressure")

        ctx = QueryContext(deadline_s=0.005)
        with activate(ctx):
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                with_retry_no_split(object(), always_oom)
            # fail fast: no 8-attempt 250ms-capped backoff ladder
            assert time.monotonic() - t0 < 1.0
        assert len(calls) <= 4

    def test_backoff_clamps_to_remaining(self):
        from spark_rapids_tpu.memory.retry import deadline_backoff
        ctx = QueryContext(deadline_s=10.0)
        with activate(ctx):
            assert deadline_backoff(0.001) == 0.001
        with activate(QueryContext(deadline_s=0.001)):
            time.sleep(0.002)
            with pytest.raises(DeadlineExceededError):
                deadline_backoff(0.25)

    def test_no_context_no_change(self):
        from spark_rapids_tpu.memory.retry import deadline_backoff
        assert deadline_backoff(0.25) == 0.25


class TestTenantQuotas:
    def test_over_quota_tenant_splits_not_neighbour(self):
        """An over-quota reserve raises SplitAndRetryOOM WITHOUT spilling:
        spilling frees neighbours' buffers by global priority while the
        offender's pinned ledger would not move — the futile-eviction
        storm the review of this PR caught."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        conf = TpuSession({"spark.rapids.tpu.sched.tenant.quotas":
                           "small=0.001,big=0.9"}).conf
        budget = MemoryBudget(1 << 30, conf)
        MemoryBudget._instance, saved = budget, MemoryBudget._instance
        try:
            with activate(QueryContext(tenant="big")):
                neighbour = SpillableColumnarBatch(batch_from_arrow(
                    pa.table({"a": pa.array(
                        np.arange(1024, dtype=np.int64))})))
            quota = budget.tenant_quotas["small"]
            with activate(QueryContext(tenant="small")):
                with pytest.raises(SplitAndRetryOOM):
                    budget.reserve(quota + 1)  # over quota, global fine
            assert not neighbour.spilled, \
                "over-quota tenant evicted a neighbour's buffer"
            with activate(QueryContext(tenant="big")):
                neighbour.close()
            assert budget.tenant_used.get("small", 0) == 0
            assert budget.tenant_used.get("big", 0) == 0
        finally:
            MemoryBudget._instance = saved

    def test_spill_does_not_reattribute_tenant_charge(self):
        """Regression: a tier transition (spill/unspill) on a thread with
        SOME tenant's context active must move the GLOBAL ledger only —
        the parked buffer's tenant charge is pinned park→close, so a
        neighbour's eviction can neither credit the evictor nor
        double-charge the owner on unspill."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
        conf = TpuSession({"spark.rapids.tpu.sched.tenant.quotas":
                           "owner=0.5,evictor=0.5"}).conf
        budget = MemoryBudget(1 << 30, conf)
        MemoryBudget._instance, saved = budget, MemoryBudget._instance
        try:
            with activate(QueryContext(tenant="owner")):
                sp = SpillableColumnarBatch(batch_from_arrow(pa.table(
                    {"a": pa.array(np.arange(1024, dtype=np.int64))})))
            owner0 = budget.tenant_used.get("owner", 0)
            assert owner0 >= sp.size_bytes
            # spill + unspill under the EVICTOR's context
            with activate(QueryContext(tenant="evictor")):
                BufferCatalog.get().synchronous_spill(sp.size_bytes)
                assert sp.spilled
                assert budget.tenant_used.get("evictor", 0) == 0, \
                    "evictor was credited for the owner's buffer"
                assert budget.tenant_used.get("owner", 0) == owner0, \
                    "owner's pinned charge moved on spill"
                sp.get_batch(acquire_semaphore=False)  # unspill
                assert budget.tenant_used.get("owner", 0) == owner0, \
                    "owner double-charged on unspill"
                sp.close()
            assert budget.tenant_used.get("owner", 0) == 0, \
                "close did not credit the pinned owner charge"
        finally:
            MemoryBudget._instance = saved

    def test_unquotad_tenant_sees_global_only(self):
        conf = TpuSession({"spark.rapids.tpu.sched.tenant.quotas":
                           "small=0.1"}).conf
        budget = MemoryBudget(1000, conf)
        MemoryBudget._instance, saved = budget, MemoryBudget._instance
        try:
            with activate(QueryContext(tenant="other")):
                budget.reserve(900)  # no sub-quota for 'other'
                budget.release(900)
        finally:
            MemoryBudget._instance = saved


class TestSchedulerOffFifo:
    def test_off_has_no_scheduler_state(self, restore_semaphore):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)
        assert TpuSemaphore.get().scheduler is None
        t = make_table(n=4_000)
        out = agg_query(sess, t).collect()
        assert out.num_rows > 0

    def test_off_server_admission_is_fifo(self):
        """The service _Admission with sched disabled grants in strict
        arrival order and ignores priorities in the header path."""
        from spark_rapids_tpu.service.server import _Admission
        conf = TpuSession({}).conf
        adm = _Admission(1, conf)
        assert not adm.sched_enabled
        assert adm.acquire() == 1
        got = []
        ths = []
        for i, prio in enumerate([0, 10, 99]):
            th = threading.Thread(
                target=lambda i=i, p=prio: got.append(
                    (adm.acquire(priority=p), i)))
            th.start()
            time.sleep(0.05)
            ths.append(th)
        for _ in range(3):
            adm.release_one()
        for th in ths:
            th.join(timeout=5)
        adm.release_one()
        # arrival order wins even though later arrivals claimed higher
        # priority: the disabled door strips policy inputs
        assert [i for _, i in sorted(got)] == [0, 1, 2]

    def test_fifo_door_honors_token(self, restore_semaphore):
        """sched.enabled=false + a context with a deadline/cancel: a
        query parked at the plain FIFO semaphore must still unwind typed
        instead of blocking until a permit frees."""
        sem = TpuSemaphore(1)  # no conf: the FIFO door
        TpuSemaphore._instance = sem
        holder = threading.Thread(target=sem.acquire_if_necessary)
        holder.start()
        holder.join(timeout=5)  # holder thread keeps the only permit
        with activate(QueryContext(deadline_s=0.15)):
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                sem.acquire_if_necessary()
            assert time.monotonic() - t0 < 2.0
        tok = CancelToken()
        with activate(QueryContext(token=tok)):
            res = {}

            def park():
                try:
                    sem.acquire_if_necessary()
                except QueryCancelledError:
                    res["cancelled"] = True

            # the context is thread-local: adopt it on the parked thread
            from spark_rapids_tpu.sched import adopt, current
            ctx = current()

            def park_with_ctx():
                adopt(ctx)
                park()

            th = threading.Thread(target=park_with_ctx)
            th.start()
            time.sleep(0.1)
            tok.cancel("fifo-door test")
            th.join(timeout=5)
            assert res.get("cancelled"), "cancel did not unwind FIFO wait"

    def test_on_off_results_identical(self, restore_semaphore):
        t = make_table(n=8_000)
        sess_off = TpuSession({"spark.rapids.sql.enabled": True,
                               "spark.rapids.sql.explain": "NONE"})
        TpuSemaphore.initialize(sess_off.conf.concurrent_tpu_tasks,
                                sess_off.conf)
        off = agg_query(sess_off, t).collect().sort_by("g")
        sess_on = sched_session()
        on = agg_query(sess_on, t).collect().sort_by("g")
        assert on.equals(off)


class TestServiceCancelOp:
    @pytest.fixture
    def service(self, tmp_path):
        """In-process device service on a tmp socket (subprocess startup
        is test_service.py's job; the protocol seams are the target here).
        Scheduler ON with one token so tests can park a run_plan
        deterministically by holding the token."""
        from spark_rapids_tpu.service.server import TpuDeviceService
        svc = TpuDeviceService({"spark.rapids.sql.enabled": True,
                                "spark.rapids.sql.concurrentGpuTasks": 1,
                                "spark.rapids.tpu.sched.enabled": True},
                               str(tmp_path / "svc.sock"))
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        for _ in range(200):
            if svc._listener is not None:
                break
            time.sleep(0.01)
        # DeviceManager init is once-per-process: arm the semaphore for
        # the service conf (1 token, scheduler on)
        TpuSemaphore.initialize(1, svc.session.conf)
        yield svc
        svc._stop.set()
        th.join(timeout=10)
        TpuSemaphore._instance = None

    @staticmethod
    def _plan_json(tmp_path):
        """Minimal FileSourceScanExec plan + its parquet file."""
        import json
        import pyarrow.parquet as pq
        rng = np.random.default_rng(5)
        t = pa.table({
            "k": pa.array(rng.integers(0, 50, 2_000).astype(np.int64)),
            "v": pa.array(rng.normal(0.1, 1.0, 2_000))})
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path)

        def attr(name, dt):
            return [{"class": "org.apache.spark.sql.catalyst.expressions."
                     "AttributeReference", "num-children": 0, "name": name,
                     "dataType": dt, "nullable": True, "metadata": {},
                     "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]

        scan = {"class": "org.apache.spark.sql.execution."
                "FileSourceScanExec", "num-children": 0,
                "relation": "HadoopFsRelation(parquet)",
                "output": [attr("k", "long"), attr("v", "double")],
                "tableIdentifier": "t"}
        return json.dumps([scan]), {"t": [path]}

    def test_cancel_inflight_run_plan(self, service, tmp_path):
        from spark_rapids_tpu.service import TpuServiceClient
        plan, paths = self._plan_json(tmp_path)
        sock = service.socket_path
        # hold the one admission token: the run_plan parks in the
        # ADMISSION QUEUE (not a scheduler-blind lock), where the cancel
        # must reach it
        sched = TpuSemaphore.get().scheduler
        sched.queue.acquire()
        res = {}

        def submit():
            with TpuServiceClient(sock, deadline_s=60) as cli:
                try:
                    res["out"] = cli.run_plan(plan, paths=paths,
                                              query_id="q-kill")
                except Exception as e:  # noqa: BLE001 — asserted below
                    res["err"] = e

        th = threading.Thread(target=submit)
        th.start()
        # the query must be REGISTERED (parked in admission) before cancel
        for _ in range(300):
            if "q-kill" in service._queries and sched.queue.depth() >= 1:
                break
            time.sleep(0.01)
        assert "q-kill" in service._queries, "run_plan never registered"
        with TpuServiceClient(sock, deadline_s=60) as cli2:
            ack = cli2.cancel("q-kill", reason="test")
            assert ack["killed"]
        th.join(timeout=60)
        sched.queue.release()
        assert isinstance(res.get("err"), QueryCancelledError), res
        # the registry must not leak the cancelled query (the reply is
        # sent a beat before the handler's finally pops it)
        for _ in range(200):
            if "q-kill" not in service._queries:
                break
            time.sleep(0.01)
        assert "q-kill" not in service._queries

    def test_cancel_unknown_query(self, service):
        from spark_rapids_tpu.service import TpuServiceClient
        with TpuServiceClient(service.socket_path, deadline_s=60) as cli:
            with pytest.raises(KeyError):
                cli.cancel("nope")

    def test_deprioritize_inflight(self, service, tmp_path):
        from spark_rapids_tpu.service import TpuServiceClient
        plan, paths = self._plan_json(tmp_path)
        sock = service.socket_path
        sched = TpuSemaphore.get().scheduler
        sched.queue.acquire()  # park the run_plan in admission
        res = {}

        def submit():
            with TpuServiceClient(sock, deadline_s=60) as cli:
                res["out"] = cli.run_plan(plan, paths=paths,
                                          query_id="q-deprio", priority=10)

        th = threading.Thread(target=submit)
        th.start()
        for _ in range(300):
            if "q-deprio" in service._queries:
                break
            time.sleep(0.01)
        with TpuServiceClient(sock, deadline_s=60) as cli2:
            ack = cli2.cancel("q-deprio", priority=-5)
            assert not ack["killed"] and ack["priority"] == -5
        assert service._queries["q-deprio"].priority == -5
        sched.queue.release()
        th.join(timeout=60)
        assert "out" in res and res["out"].num_rows == 2_000


class TestProfileAndMetrics:
    def test_cancelled_query_profile_status(self, restore_semaphore,
                                            tmp_path):
        sess = sched_session(**{
            "spark.rapids.tpu.metrics.eventLog.dir": str(tmp_path)})
        t = make_table(n=8_000)
        ctx = QueryContext()
        ctx.token.cancel("pre-cancelled")
        with pytest.raises(QueryCancelledError):
            sess.execute_plan(agg_query(sess, t).plan, sched_ctx=ctx)
        prof = sess.last_profile
        assert prof is not None and prof.status == "cancelled"
        recs = prof.to_records()
        qrec = [r for r in recs if r["type"] == "query"][0]
        assert qrec["status"] == "cancelled"
        assert "sched_queue_wait_ns" in qrec["task_metrics"]

    def test_sched_counters_and_report_section(self, restore_semaphore,
                                               tmp_path):
        from spark_rapids_tpu.tools.profile_report import (build_model,
                                                           load_records,
                                                           render_report,
                                                           sched_summary)
        log_dir = str(tmp_path / "events")
        sess = sched_session(**{
            "spark.rapids.tpu.metrics.eventLog.dir": log_dir})
        t = make_table(n=8_000)
        out = sess.execute_plan(agg_query(sess, t).plan,
                                sched_ctx=QueryContext(tenant="rpt"))
        assert out.num_rows > 0
        tm = sess.last_profile.task_metrics
        assert tm.get("sched_admissions", 0) >= 1
        records, problems = load_records([log_dir], validate=True)
        assert not problems
        model = build_model(records)
        summary = sched_summary(model)
        assert summary and summary["admissions"] >= 1
        report = render_report(model)
        assert "=== scheduler ===" in report

    def test_explain_string_has_sched_line(self, restore_semaphore):
        from spark_rapids_tpu.utils.metrics import TaskMetrics
        tm = TaskMetrics()
        tm.sched_admissions = 2
        tm.sched_rejected = 1
        s = tm.explain_string()
        assert "schedAdmissions=2" in s and "schedRejected=1" in s
