"""Delta-style table: log replay, time travel, DELETE/UPDATE/MERGE through
the device engine — differential against handwritten oracles (reference
delta-lake GpuMergeIntoCommand/GpuUpdateCommand/GpuDeleteCommand;
BASELINE workload #4)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.datasources.delta import DeltaTable, src
from spark_rapids_tpu.datasources.delta.table import (
    DeltaConcurrentModification, DeltaMultipleMatches)
from spark_rapids_tpu.expr import Add, col, lit
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def base_table(rng, n=200):
    return pa.table({
        "id": pa.array(np.arange(n), type=pa.int64()),
        "v": pa.array(rng.integers(-100, 100, n), type=pa.int64()),
        "w": pa.array(rng.normal(0, 10, n).round(3), type=pa.float64()),
    })


def sort_py(t, key="id"):
    return t.sort_by([(key, "ascending")]).to_pylist()


class TestDeltaLog:
    def test_create_read_version(self, session, rng, tmp_path):
        t = base_table(rng)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        assert dt.version == 0
        assert sort_py(dt.read()) == sort_py(t)

    def test_time_travel_and_history(self, session, rng, tmp_path):
        t = base_table(rng, n=50)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        dt.delete(col("id") < lit(10))
        assert dt.version == 1
        assert dt.read(version=0).num_rows == 50
        assert dt.read().num_rows == 40
        hist = dt.history()
        assert hist[-1]["operation"] == "DELETE"

    def test_concurrent_commit_conflict(self, session, rng, tmp_path):
        dt = DeltaTable.create(session, tmp_path / "t", base_table(rng, 20))
        from spark_rapids_tpu.datasources.delta.table import _write_commit
        _write_commit(dt.log_dir, 1, [{"commitInfo": {"operation": "X"}}])
        with pytest.raises(DeltaConcurrentModification):
            _write_commit(dt.log_dir, 1, [{"commitInfo": {"operation": "Y"}}])


class TestCheckpoints:
    def test_periodic_checkpoint_written_and_replayed(self, session, rng,
                                                      tmp_path):
        import os
        t = base_table(rng, n=40)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        for i in range(12):  # default interval 10 -> checkpoint at v10
            dt.delete(col("id") == lit(i))
        log = os.path.join(str(tmp_path / "t"), "_delta_log")
        assert "0000000010.checkpoint.parquet" in os.listdir(log)
        assert "_last_checkpoint" in os.listdir(log)
        import json
        with open(os.path.join(log, "_last_checkpoint")) as f:
            assert json.load(f)["version"] == 10
        # replay through the checkpoint matches a full-JSON replay
        expected = sorted(r["id"] for r in t.to_pylist() if r["id"] >= 12)
        assert sorted(r["id"] for r in dt.read().to_pylist()) == expected
        # seed actually comes from the checkpoint (drop early JSONs)
        for v in range(0, 10):
            os.remove(os.path.join(log, f"{v:010d}.json"))
        dt2 = DeltaTable(session, tmp_path / "t")
        assert sorted(r["id"] for r in dt2.read().to_pylist()) == expected

    def test_time_travel_before_checkpoint_uses_json_replay(
            self, session, rng, tmp_path):
        t = base_table(rng, n=30)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        for i in range(11):
            dt.delete(col("id") == lit(i))
        # v3 predates the v10 checkpoint: replay must not seed from it
        got = sorted(r["id"] for r in dt.read(version=3).to_pylist())
        assert got == sorted(r["id"] for r in t.to_pylist()
                             if r["id"] >= 3)

    def test_corrupt_pointer_degrades_gracefully(self, session, rng,
                                                 tmp_path):
        import os
        t = base_table(rng, n=20)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        for i in range(10):
            dt.delete(col("id") == lit(i))
        log = os.path.join(str(tmp_path / "t"), "_delta_log")
        with open(os.path.join(log, "_last_checkpoint"), "w") as f:
            f.write("not json{")
        expected = sorted(r["id"] for r in t.to_pylist() if r["id"] >= 10)
        assert sorted(r["id"] for r in dt.read().to_pylist()) == expected

    def test_explicit_checkpoint_and_interval_conf(self, rng, tmp_path):
        import os
        session = TpuSession({"spark.rapids.sql.enabled": True,
                              "spark.rapids.sql.explain": "NONE",
                              "spark.rapids.delta.checkpointInterval": 3})
        t = base_table(rng, n=20)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        for i in range(4):
            dt.delete(col("id") == lit(i))
        log = os.path.join(str(tmp_path / "t"), "_delta_log")
        assert "0000000003.checkpoint.parquet" in os.listdir(log)
        fp = dt.checkpoint()  # explicit snapshot of the newest version
        assert fp.endswith("0000000004.checkpoint.parquet")


class TestDeleteUpdate:
    def test_delete(self, session, rng, tmp_path):
        t = base_table(rng)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        n = dt.delete(col("v") > lit(0))
        expect = [r for r in t.to_pylist() if not (r["v"] > 0)]
        got = dt.read().to_pylist()
        assert sorted(r["id"] for r in got) == sorted(r["id"] for r in expect)
        assert n == t.num_rows - len(expect)

    def test_update_with_condition(self, session, rng, tmp_path):
        t = base_table(rng)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        n = dt.update({"v": Add(col("v"), lit(1000))},
                      condition=col("id") < lit(50))
        assert n == 50
        got = {r["id"]: r["v"] for r in dt.read().to_pylist()}
        for r in t.to_pylist():
            expect = r["v"] + 1000 if r["id"] < 50 else r["v"]
            assert got[r["id"]] == expect

    def test_update_all_rows(self, session, rng, tmp_path):
        t = base_table(rng, 30)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        dt.update({"v": lit(7, None)})
        assert all(r["v"] == 7 for r in dt.read().to_pylist())


class TestMerge:
    def _setup(self, session, rng, tmp_path, n=120):
        t = base_table(rng, n)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        # source: half updates to existing ids, half new ids
        ids = np.concatenate([rng.choice(n, n // 4, replace=False),
                              np.arange(n, n + n // 4)])
        srct = pa.table({
            "id": pa.array(ids, type=pa.int64()),
            "nv": pa.array(rng.integers(500, 600, len(ids)),
                           type=pa.int64()),
        })
        return t, dt, srct

    def test_merge_update_and_insert(self, session, rng, tmp_path):
        t, dt, srct = self._setup(session, rng, tmp_path)
        stats = dt.merge(
            srct, on=col("id") == src("id"),
            when_matched_update={"v": src("nv")},
            when_not_matched_insert={"id": src("id"), "v": src("nv"),
                                     "w": lit(0.0)})
        # oracle
        tgt = {r["id"]: dict(r) for r in t.to_pylist()}
        upd = ins = 0
        for r in srct.to_pylist():
            if r["id"] in tgt:
                tgt[r["id"]]["v"] = r["nv"]
                upd += 1
            else:
                tgt[r["id"]] = {"id": r["id"], "v": r["nv"], "w": 0.0}
                ins += 1
        assert stats["updated"] == upd and stats["inserted"] == ins
        got = sort_py(dt.read())
        expect = sorted(tgt.values(), key=lambda r: r["id"])
        assert got == expect

    def test_merge_delete_matched(self, session, rng, tmp_path):
        t, dt, srct = self._setup(session, rng, tmp_path)
        stats = dt.merge(srct, on=col("id") == src("id"),
                         when_matched_delete=True)
        match_ids = {r["id"] for r in srct.to_pylist()}
        expect = [r for r in t.to_pylist() if r["id"] not in match_ids]
        assert dt.read().num_rows == len(expect)
        assert stats["deleted"] == t.num_rows - len(expect)

    def test_merge_insert_only(self, session, rng, tmp_path):
        t, dt, srct = self._setup(session, rng, tmp_path)
        stats = dt.merge(
            srct, on=col("id") == src("id"),
            when_not_matched_insert={"id": src("id"), "v": src("nv"),
                                     "w": lit(1.5)})
        new_ids = {r["id"] for r in srct.to_pylist()} - \
            {r["id"] for r in t.to_pylist()}
        assert stats["inserted"] == len(new_ids)
        assert dt.read().num_rows == t.num_rows + len(new_ids)
        # unmatched target rows untouched
        got = {r["id"]: r["v"] for r in dt.read().to_pylist()}
        for r in t.to_pylist():
            assert got[r["id"]] == r["v"]

    def test_merge_multiple_matches_raises(self, session, rng, tmp_path):
        t = base_table(rng, 20)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        dup = pa.table({"id": pa.array([3, 3], type=pa.int64()),
                        "nv": pa.array([1, 2], type=pa.int64())})
        with pytest.raises(DeltaMultipleMatches):
            dt.merge(dup, on=col("id") == src("id"),
                     when_matched_update={"v": src("nv")})

    def test_merge_non_equi_condition(self, session, rng, tmp_path):
        t = base_table(rng, 60)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        srct = pa.table({"lo": pa.array([10], type=pa.int64()),
                         "hi": pa.array([20], type=pa.int64()),
                         "nv": pa.array([999], type=pa.int64())})
        dt.merge(srct,
                 on=(col("id") >= src("lo")) & (col("id") < src("hi")),
                 when_matched_update={"v": src("nv")})
        got = {r["id"]: r["v"] for r in dt.read().to_pylist()}
        for r in t.to_pylist():
            expect = 999 if 10 <= r["id"] < 20 else r["v"]
            assert got[r["id"]] == expect


class TestDmlSemantics:
    """Regression tests for SQL-exact DML corner cases."""

    def test_delete_null_condition_keeps_row(self, session, tmp_path):
        # DELETE only removes rows where the condition is TRUE; NULL keeps
        t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                      "v": pa.array([5, None, -5], type=pa.int64())})
        dt = DeltaTable.create(session, tmp_path / "t", t)
        deleted = dt.delete(col("v") > lit(0))
        assert deleted == 1
        assert sort_py(dt.read()) == [
            {"id": 2, "v": None}, {"id": 3, "v": -5}]

    def test_update_null_condition_keeps_value(self, session, tmp_path):
        t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                      "v": pa.array([5, None, -5], type=pa.int64())})
        dt = DeltaTable.create(session, tmp_path / "t", t)
        updated = dt.update({"id": lit(0)}, condition=col("v") > lit(0))
        assert updated == 1
        got = {r["v"]: r["id"] for r in dt.read().to_pylist()}
        assert got[5] == 0 and got[None] == 2 and got[-5] == 3

    def test_update_unknown_column_raises(self, session, rng, tmp_path):
        dt = DeltaTable.create(session, tmp_path / "t", base_table(rng, 10))
        before_version = dt.version
        with pytest.raises(KeyError, match="bogus"):
            dt.update({"bogus": lit(9)})
        assert dt.version == before_version  # no no-op commit

    def test_insert_only_merge_no_duplicates(self, session, tmp_path):
        # multiple source matches are LEGAL with no matched clause, and the
        # matched target row must appear exactly once afterwards
        t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                      "v": pa.array([10, 20, 30], type=pa.int64())})
        dt = DeltaTable.create(session, tmp_path / "t", t)
        srct = pa.table({"id": pa.array([3, 3, 4], type=pa.int64()),
                         "v": pa.array([99, 98, 40], type=pa.int64())})
        stats = dt.merge(srct, on=col("id") == src("id"),
                         when_not_matched_insert={"id": src("id"),
                                                  "v": src("v")})
        # both id=4 source rows? no - only id=4 is unmatched, inserted once
        assert stats["inserted"] == 1
        got = sort_py(dt.read())
        assert got == [{"id": 1, "v": 10}, {"id": 2, "v": 20},
                       {"id": 3, "v": 30}, {"id": 4, "v": 40}]

    def test_merge_empty_source_noop(self, session, rng, tmp_path):
        t = base_table(rng, 20)
        dt = DeltaTable.create(session, tmp_path / "t", t)
        empty = t.slice(0, 0).rename_columns(["id", "v", "w"])
        stats = dt.merge(empty, on=col("id") == src("id"),
                         when_matched_update={"v": src("v")},
                         when_not_matched_insert={"id": src("id"),
                                                  "v": src("v"),
                                                  "w": src("w")})
        assert stats == {"updated": 0, "deleted": 0, "inserted": 0}
        assert sort_py(dt.read()) == sort_py(t)

    def test_read_nonexistent_version_raises(self, session, rng, tmp_path):
        dt = DeltaTable.create(session, tmp_path / "t", base_table(rng, 10))
        dt.delete(col("id") < lit(5))  # version 1
        with pytest.raises(ValueError, match="version 99"):
            dt.read(version=99)
        with pytest.raises(ValueError, match="version -5"):
            dt.read(version=-5)


class TestZOrder:
    def test_optimize_zorder_clusters_and_preserves_rows(self, tmp_path):
        """OPTIMIZE ZORDER BY (ZOrderRules analog): rows re-cluster by the
        morton key of the given columns; content is preserved exactly and
        the z columns become range-clustered (tighter footer min/max)."""
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.datasources.delta.table import DeltaTable
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        rng = np.random.default_rng(31)
        n = 2000
        t = pa.table({
            "x": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
            "y": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
            "v": pa.array(rng.normal(size=n))})
        path = str(tmp_path / "ztab")
        dt = DeltaTable.create(s, path, t)
        out = dt.optimize_zorder(["x", "y"])
        assert out["rows"] == n
        back = dt.read()
        keys = [("x", "ascending"), ("y", "ascending"), ("v", "ascending")]
        assert back.sort_by(keys).equals(t.sort_by(keys))  # content intact
        # clustering: mean adjacent |dx|+|dy| must beat the random order
        xs = np.asarray(back.column("x").to_pylist(), np.int64)
        ys = np.asarray(back.column("y").to_pylist(), np.int64)
        d_sorted = (np.abs(np.diff(xs)) + np.abs(np.diff(ys))).mean()
        x0 = np.asarray(t.column("x").to_pylist(), np.int64)
        y0 = np.asarray(t.column("y").to_pylist(), np.int64)
        d_orig = (np.abs(np.diff(x0)) + np.abs(np.diff(y0))).mean()
        assert d_sorted < d_orig / 4, (d_sorted, d_orig)
        assert dt.history()[0]["operation"] == "OPTIMIZE"

    def test_interleave_bits_expression(self):
        import numpy as np
        import jax.numpy as jnp
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.datasources.delta.zorder import InterleaveBits
        from spark_rapids_tpu.expr.base import BoundReference, EvalContext, Vec
        a = Vec(T.LONG, jnp.asarray(np.array([0, 3, 1, 2], np.int64)),
                jnp.ones(4, bool))
        b = Vec(T.LONG, jnp.asarray(np.array([0, 3, 2, 1], np.int64)),
                jnp.ones(4, bool))
        e = InterleaveBits([BoundReference(0, T.LONG),
                            BoundReference(1, T.LONG)], bits=2)
        ctx = EvalContext(jnp, row_mask=jnp.ones(4, bool))
        z = e.eval(ctx, [a, b])
        zs = [int(v) for v in np.asarray(z.data)]
        # identical input orderings -> diagonal morton keys ascend together
        order = np.argsort(zs)
        assert list(np.asarray(a.data)[order][:1]) == [0]
        assert len(set(zs)) == 4

    def test_hilbert_curve_unit_steps_and_optimize(self, tmp_path):
        """HilbertLongIndex (GpuHilbertLongIndex analog): exact Skilling
        transform — over a full grid, successive curve positions are unit
        steps in exactly one coordinate (the property morton lacks), and
        OPTIMIZE accepts curve='hilbert'."""
        import numpy as np
        import jax.numpy as jnp
        import pyarrow as pa
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.datasources.delta.table import DeltaTable
        from spark_rapids_tpu.datasources.delta.zorder import \
            HilbertLongIndex
        from spark_rapids_tpu.expr.base import (BoundReference, EvalContext,
                                                Vec)
        from spark_rapids_tpu.plugin import TpuSession

        class RawHilbert(HilbertLongIndex):
            def _rank(self, xp, v, mask, n):
                return v.data.astype(np.int64)

        b = 3
        g = np.arange(1 << b)
        coords = np.stack(np.meshgrid(g, g, indexing="ij"),
                          axis=-1).reshape(-1, 2)
        n = coords.shape[0]
        vecs = [Vec(T.LONG, jnp.asarray(coords[:, i].astype(np.int64)),
                    jnp.ones(n, bool)) for i in range(2)]
        e = RawHilbert([BoundReference(i, T.LONG) for i in range(2)],
                       bits=b)
        z = np.asarray(e.eval(EvalContext(jnp, row_mask=jnp.ones(n, bool)),
                              vecs).data)
        assert len(set(z.tolist())) == n  # bijection over the grid
        pts = coords[np.argsort(z)]
        steps = np.abs(np.diff(pts, axis=0)).sum(axis=1)
        assert (steps == 1).all()  # the Hilbert property

        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        rng = np.random.default_rng(7)
        t = pa.table({"x": pa.array(rng.integers(0, 99, 500)
                                    .astype(np.int64)),
                      "y": pa.array(rng.integers(0, 99, 500)
                                    .astype(np.int64))})
        dt = DeltaTable.create(s, str(tmp_path / "h"), t)
        out = dt.optimize_zorder(["x", "y"], curve="hilbert")
        assert out["curve"] == "hilbert" and out["rows"] == 500
        keys = [("x", "ascending"), ("y", "ascending")]
        assert dt.read().sort_by(keys).equals(t.sort_by(keys))

    def test_zorder_rejects_empty_and_bad_args(self, tmp_path):
        import pyarrow as pa
        import pytest as _pt
        from spark_rapids_tpu.datasources.delta.table import DeltaTable
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.explain": "NONE"})
        t = pa.table({"x": pa.array(range(10), type=pa.int64())})
        dt = DeltaTable.create(s, str(tmp_path / "e"), t)
        with _pt.raises(ValueError, match="at least one column"):
            dt.optimize_zorder([])
        with _pt.raises(ValueError, match="unknown clustering curve"):
            dt.optimize_zorder(["x"], curve="peano")
        # bits floor: degenerate bits never crash, table survives intact
        dt.optimize_zorder(["x"], bits=0, curve="hilbert")
        assert dt.read().num_rows == 10
