"""Device ORC decode (io/orc_device.py): RLEv2 + present streams +
strings decoded on device, differential against pyarrow's independent ORC
reader on generated files (reference `GpuOrcScan.scala:826,1081` — raw
stripe streams decoded on the accelerator, per-stripe fallback).

The INVERTED fallback tests assert default pyarrow-written ORC actually
takes the device path — the host path is the exception, not the rule."""

import numpy as np
import pyarrow as pa
import pytest
from pyarrow import orc

from spark_rapids_tpu.columnar.batch import Schema, batch_to_arrow
from spark_rapids_tpu.io.orc_device import (DeviceDecodeUnsupported,
                                            device_decode_file,
                                            file_supported)
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def mixed_table(rng, n=5000, nulls=True):
    def mk(vals):
        if not nulls:
            return pa.array(vals)
        return pa.array(vals, mask=rng.random(n) < 0.2)
    return pa.table({
        "i16": mk(rng.integers(-300, 300, n).astype(np.int16)),
        "i32": mk(rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "l": mk(rng.integers(-2**62, 2**62, n)),
        "seq": pa.array(np.arange(n, dtype=np.int64) * 3 + 7),  # DELTA
        "rep": pa.array(np.full(n, 42, np.int64)),    # SHORT_REPEAT
        "outlier": pa.array(np.where(rng.random(n) < 0.01, 2**40,
                                     rng.integers(0, 100, n))
                            .astype(np.int64)),       # PATCHED_BASE
        "f": mk(rng.normal(0, 1e3, n).astype(np.float32)),
        "d": mk(rng.normal(0, 1e6, n)),
        "b": mk(rng.integers(0, 2, n).astype(bool)),
        "s": mk(np.array([f"orc_{i % 997}_{'x' * (i % 11)}"
                          for i in range(n)], dtype=object)),
    })


def write_orc(tmp_path, t, name="t.orc", **kw):
    path = str(tmp_path / name)
    orc.write_table(t, path, **kw)
    return path


def assert_device_matches(path, expected: pa.Table, columns=None):
    """Decode through the DEVICE path only and diff against the
    INDEPENDENT pyarrow values (no engine code computed `expected`)."""
    f = orc.ORCFile(path)
    schema = Schema.from_arrow(f.schema)
    if columns:
        idx = [schema.names.index(c) for c in columns]
        schema = Schema(tuple(schema.names[i] for i in idx),
                        tuple(schema.types[i] for i in idx))
    info = file_supported(path, schema)
    total = 0
    for batch, nrows in device_decode_file(info, path, schema):
        at = batch_to_arrow(batch)
        exp = expected.slice(total, nrows)
        total += nrows
        for name in schema.names:
            got = at.column(name).to_pylist()[:nrows]
            want = exp.column(name).to_pylist()
            assert got == want, f"column {name} diverged"
    assert total == expected.num_rows
    return info


class TestDeviceOrcDecode:
    @pytest.mark.parametrize("compression",
                             ["uncompressed", "zlib", "snappy"])
    def test_mixed_roundtrip(self, session, rng, tmp_path, compression):
        t = mixed_table(rng)
        path = write_orc(tmp_path, t, compression=compression)
        assert_device_matches(path, orc.read_table(path))

    def test_default_pyarrow_file_takes_device_path(self, rng, tmp_path):
        """INVERTED fallback: a plain orc.write_table file must be
        device-decodable — file_supported must NOT raise."""
        path = write_orc(tmp_path, mixed_table(rng))
        f = orc.ORCFile(path)
        info = file_supported(path, Schema.from_arrow(f.schema))
        assert len(info.stripes) == 1

    def test_multi_stripe(self, rng, tmp_path):
        t = mixed_table(rng, n=30000)
        path = write_orc(tmp_path, t, stripe_size=65536, batch_size=1024)
        info = assert_device_matches(path, orc.read_table(path))
        assert len(info.stripes) > 1

    def test_dictionary_strings(self, rng, tmp_path):
        n = 8000
        t = pa.table({"s": pa.array(
            [f"tag_{i % 37}" for i in range(n)],
            ).cast(pa.string())})
        path = write_orc(tmp_path, t,
                         dictionary_key_size_threshold=1.0)
        assert_device_matches(path, orc.read_table(path))

    def test_dates(self, rng, tmp_path):
        n = 4000
        days = rng.integers(-3000, 20000, n).astype("datetime64[D]")
        t = pa.table({"dt": pa.array(days)})
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path))

    def test_column_pruning(self, rng, tmp_path):
        t = mixed_table(rng)
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path).select(
            ["l", "s"]), columns=["l", "s"])

    def test_empty_strings_and_all_null_column(self, rng, tmp_path):
        n = 2000
        t = pa.table({
            "e": pa.array(["" if i % 3 else f"v{i}" for i in range(n)]),
            "an": pa.array([None] * n, pa.int64()),
        })
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path))

    def test_zstd_falls_back_cleanly(self, session, rng, tmp_path):
        """zstd raw blocks don't self-describe a size pyarrow accepts:
        the footer gate must reject (host path), never crash."""
        t = mixed_table(rng, n=1000)
        path = write_orc(tmp_path, t, compression="zstd")
        f = orc.ORCFile(path)
        with pytest.raises(DeviceDecodeUnsupported):
            file_supported(path, Schema.from_arrow(f.schema))
        got = session.read_orc(path).collect()
        assert got.num_rows == 1000

    def test_malformed_delta_run_raises_decode_unsupported(self):
        """A corrupt DELTA header (1 value but literal deltas) must raise
        DeviceDecodeUnsupported — the per-stripe fallback net — not
        IndexError."""
        from spark_rapids_tpu.io.orc_device import _rlev2_runs
        with pytest.raises(DeviceDecodeUnsupported):
            _rlev2_runs(bytes([0xC4, 0x00, 0x02, 0x02, 0xFF]), 1, True)

    def test_timestamp_falls_back_cleanly(self, session, rng, tmp_path):
        """Timestamps use a SECONDARY stream — not device-decoded yet;
        the scan must still answer correctly via the host path."""
        n = 1000
        t = pa.table({
            "ts": pa.array(rng.integers(0, 2**40, n),
                           pa.timestamp("us", tz="UTC")),
            "v": pa.array(rng.normal(size=n))})
        path = write_orc(tmp_path, t)
        f = orc.ORCFile(path)
        with pytest.raises(DeviceDecodeUnsupported):
            file_supported(path, Schema.from_arrow(f.schema))
        got = session.read_orc(path).collect()
        assert got.num_rows == n
        assert got.column("ts").to_pylist() == \
            orc.read_table(path).column("ts").to_pylist()

    def test_query_over_device_decoded_scan(self, session, rng, tmp_path):
        """End to end: the planner's ORC scan feeds the device engine and
        answers match an independent numpy oracle."""
        n = 20000
        k = rng.integers(0, 50, n).astype(np.int64)
        v = rng.normal(size=n)
        t = pa.table({"k": pa.array(k), "v": pa.array(v)})
        path = write_orc(tmp_path, t)
        from spark_rapids_tpu.expr import Sum, col
        df = session.read_orc(path)
        got = df.filter(df["v"] > 0).group_by("k").agg(
            total=Sum(col("v"))).collect()
        import collections
        sums = collections.defaultdict(float)
        for kk, vv in zip(k, v):
            if vv > 0:
                sums[int(kk)] += vv
        rows = {r["k"]: r for r in got.to_pylist()}
        assert set(rows) == set(sums)
        for kk in sums:
            assert abs(rows[kk]["total"] - sums[kk]) <= 1e-9 * max(
                1.0, abs(sums[kk]))
