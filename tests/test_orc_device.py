"""Device ORC decode (io/orc_device.py): RLEv2 + present streams +
strings decoded on device, differential against pyarrow's independent ORC
reader on generated files (reference `GpuOrcScan.scala:826,1081` — raw
stripe streams decoded on the accelerator, per-stripe fallback).

The INVERTED fallback tests assert default pyarrow-written ORC actually
takes the device path — the host path is the exception, not the rule."""

import numpy as np
import pyarrow as pa
import pytest
from pyarrow import orc

from spark_rapids_tpu.columnar.batch import Schema, batch_to_arrow
from spark_rapids_tpu.io.orc_device import (DeviceDecodeUnsupported,
                                            device_decode_file,
                                            file_supported)
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def mixed_table(rng, n=5000, nulls=True):
    def mk(vals):
        if not nulls:
            return pa.array(vals)
        return pa.array(vals, mask=rng.random(n) < 0.2)
    return pa.table({
        "i16": mk(rng.integers(-300, 300, n).astype(np.int16)),
        "i32": mk(rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "l": mk(rng.integers(-2**62, 2**62, n)),
        "seq": pa.array(np.arange(n, dtype=np.int64) * 3 + 7),  # DELTA
        "rep": pa.array(np.full(n, 42, np.int64)),    # SHORT_REPEAT
        "outlier": pa.array(np.where(rng.random(n) < 0.01, 2**40,
                                     rng.integers(0, 100, n))
                            .astype(np.int64)),       # PATCHED_BASE
        "f": mk(rng.normal(0, 1e3, n).astype(np.float32)),
        "d": mk(rng.normal(0, 1e6, n)),
        "b": mk(rng.integers(0, 2, n).astype(bool)),
        "s": mk(np.array([f"orc_{i % 997}_{'x' * (i % 11)}"
                          for i in range(n)], dtype=object)),
    })


def write_orc(tmp_path, t, name="t.orc", **kw):
    path = str(tmp_path / name)
    orc.write_table(t, path, **kw)
    return path


def assert_device_matches(path, expected: pa.Table, columns=None):
    """Decode through the DEVICE path only and diff against the
    INDEPENDENT pyarrow values (no engine code computed `expected`)."""
    f = orc.ORCFile(path)
    schema = Schema.from_arrow(f.schema)
    if columns:
        idx = [schema.names.index(c) for c in columns]
        schema = Schema(tuple(schema.names[i] for i in idx),
                        tuple(schema.types[i] for i in idx))
    info = file_supported(path, schema)
    total = 0
    for batch, nrows in device_decode_file(info, path, schema):
        at = batch_to_arrow(batch)
        exp = expected.slice(total, nrows)
        total += nrows
        for name in schema.names:
            got = at.column(name).to_pylist()[:nrows]
            want = exp.column(name).to_pylist()
            assert got == want, f"column {name} diverged"
    assert total == expected.num_rows
    return info


class TestDeviceOrcDecode:
    @pytest.mark.parametrize("compression",
                             ["uncompressed", "zlib", "snappy"])
    def test_mixed_roundtrip(self, session, rng, tmp_path, compression):
        t = mixed_table(rng)
        path = write_orc(tmp_path, t, compression=compression)
        assert_device_matches(path, orc.read_table(path))

    def test_default_pyarrow_file_takes_device_path(self, rng, tmp_path):
        """INVERTED fallback: a plain orc.write_table file must be
        device-decodable — file_supported must NOT raise."""
        path = write_orc(tmp_path, mixed_table(rng))
        f = orc.ORCFile(path)
        info = file_supported(path, Schema.from_arrow(f.schema))
        assert len(info.stripes) == 1

    def test_multi_stripe(self, rng, tmp_path):
        t = mixed_table(rng, n=30000)
        path = write_orc(tmp_path, t, stripe_size=65536, batch_size=1024)
        info = assert_device_matches(path, orc.read_table(path))
        assert len(info.stripes) > 1

    def test_dictionary_strings(self, rng, tmp_path):
        n = 8000
        t = pa.table({"s": pa.array(
            [f"tag_{i % 37}" for i in range(n)],
            ).cast(pa.string())})
        path = write_orc(tmp_path, t,
                         dictionary_key_size_threshold=1.0)
        assert_device_matches(path, orc.read_table(path))

    def test_dates(self, rng, tmp_path):
        n = 4000
        days = rng.integers(-3000, 20000, n).astype("datetime64[D]")
        t = pa.table({"dt": pa.array(days)})
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path))

    def test_column_pruning(self, rng, tmp_path):
        t = mixed_table(rng)
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path).select(
            ["l", "s"]), columns=["l", "s"])

    def test_empty_strings_and_all_null_column(self, rng, tmp_path):
        n = 2000
        t = pa.table({
            "e": pa.array(["" if i % 3 else f"v{i}" for i in range(n)]),
            "an": pa.array([None] * n, pa.int64()),
        })
        path = write_orc(tmp_path, t)
        assert_device_matches(path, orc.read_table(path))

    def test_zstd_falls_back_cleanly(self, session, rng, tmp_path):
        """zstd raw blocks don't self-describe a size pyarrow accepts:
        the footer gate must reject (host path), never crash."""
        t = mixed_table(rng, n=1000)
        path = write_orc(tmp_path, t, compression="zstd")
        f = orc.ORCFile(path)
        with pytest.raises(DeviceDecodeUnsupported):
            file_supported(path, Schema.from_arrow(f.schema))
        got = session.read_orc(path).collect()
        assert got.num_rows == 1000

    def test_malformed_delta_run_raises_decode_unsupported(self):
        """A corrupt DELTA header (1 value but literal deltas) must raise
        DeviceDecodeUnsupported — the per-stripe fallback net — not
        IndexError."""
        from spark_rapids_tpu.io.orc_device import _rlev2_runs
        with pytest.raises(DeviceDecodeUnsupported):
            _rlev2_runs(bytes([0xC4, 0x00, 0x02, 0x02, 0xFF]), 1, True)

    def test_timestamps_take_device_path(self, session, rng, tmp_path):
        """INVERTED (was a fallback test): DATA seconds + SECONDARY nanos
        streams now decode on device, including pre-1970 values where the
        C++ writer stores negative nanos remainders."""
        n = 3000
        micros = np.concatenate([
            rng.integers(-4 * 10**15, 4 * 10**15, n - 4),
            np.array([0, -1, -999_995, 1_420_070_399_999_999])])
        t = pa.table({
            "ts": pa.array(micros, pa.timestamp("us", tz="UTC")),
            "v": pa.array(rng.normal(size=n))})
        path = write_orc(tmp_path, t)
        f = orc.ORCFile(path)
        schema = Schema.from_arrow(pa.schema(
            [("ts", pa.timestamp("us", tz="UTC")), ("v", pa.float64())]))
        file_supported(path, schema)  # no raise: fully device-decodable
        expected = orc.read_table(path).cast(pa.schema(
            [("ts", pa.timestamp("us", tz="UTC")), ("v", pa.float64())]))
        assert_device_matches(path, expected, columns=["ts", "v"])

    def test_decimal64_takes_device_path(self, rng, tmp_path):
        """decimal(p<=18): zigzag-varint mantissas decode on device via
        the segment-sum kernel; values diff against pyarrow exactly."""
        import decimal
        n = 4000
        mask = rng.random(n) < 0.15
        vals = [None if mask[i] else
                decimal.Decimal(int(rng.integers(-10**14, 10**14)))
                .scaleb(-2) for i in range(n)]
        t = pa.table({"d": pa.array(vals, type=pa.decimal128(16, 2)),
                      "k": pa.array(np.arange(n, dtype=np.int64))})
        path = write_orc(tmp_path, t)
        expected = orc.read_table(path)
        assert_device_matches(path, expected)

    def test_decimal128_column_falls_back_siblings_on_device(
            self, session, rng, tmp_path):
        """Per-column fallback: a decimal(30,8) column host-decodes while
        its siblings still ride the device path, and the merged batch
        matches pyarrow."""
        import decimal
        from spark_rapids_tpu.io.orc_device import columns_supported
        n = 2000
        wide = [decimal.Decimal(int(rng.integers(-10**18, 10**18)))
                .scaleb(-8) * 10**9 for i in range(n)]
        t = pa.table({
            "wide": pa.array(wide, type=pa.decimal128(30, 8)),
            "l": pa.array(rng.integers(-10**12, 10**12, n)),
            "s": pa.array([f"r{i % 37}" for i in range(n)])})
        path = write_orc(tmp_path, t)
        schema = Schema.from_arrow(orc.ORCFile(path).schema)
        info, bad = columns_supported(path, schema)
        assert set(bad) == {"wide"}
        got = session.read_orc(path).collect()
        exact = orc.read_table(path)
        for c in t.schema.names:
            assert got.column(c).to_pylist() == \
                exact.column(c).to_pylist(), c

    def test_query_over_device_decoded_scan(self, session, rng, tmp_path):
        """End to end: the planner's ORC scan feeds the device engine and
        answers match an independent numpy oracle."""
        n = 20000
        k = rng.integers(0, 50, n).astype(np.int64)
        v = rng.normal(size=n)
        t = pa.table({"k": pa.array(k), "v": pa.array(v)})
        path = write_orc(tmp_path, t)
        from spark_rapids_tpu.expr import Sum, col
        df = session.read_orc(path)
        got = df.filter(df["v"] > 0).group_by("k").agg(
            total=Sum(col("v"))).collect()
        import collections
        sums = collections.defaultdict(float)
        for kk, vv in zip(k, v):
            if vv > 0:
                sums[int(kk)] += vv
        rows = {r["k"]: r for r in got.to_pylist()}
        assert set(rows) == set(sums)
        for kk in sums:
            assert abs(rows[kk]["total"] - sums[kk]) <= 1e-9 * max(
                1.0, abs(sums[kk]))
