"""TPC-DS-shaped multi-operator query corpus (BASELINE workload #2's shape
at test scale): a star schema — store_sales fact with date/item/store/
customer dims — and report-style queries mirroring the classic q3/q7/q42/
q55/q68/q96 patterns, each run differentially on both engines."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (Average, CaseWhen, Count, If, Max, Min,
                                   Sum, col, lit)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same

N_DATES = 365
N_ITEMS = 60
N_STORES = 8
N_CUSTOMERS = 150
N_SALES = 4000


@pytest.fixture(scope="module")
def session():
    # AQE + CBO on: the corpus is the newest planning code's end-to-end
    # coverage (round-2 verdict weak item #6)
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE",
                       "spark.rapids.sql.adaptive.enabled": True,
                       "spark.rapids.sql.optimizer.enabled": True})


@pytest.fixture(scope="module")
def star(session):
    rng = np.random.default_rng(7)
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(N_DATES, dtype=np.int64)),
        "d_year": pa.array((2020 + np.arange(N_DATES) // 365)
                           .astype(np.int32)),
        "d_moy": pa.array((np.arange(N_DATES) % 365 // 31 + 1)
                          .astype(np.int32)),
        "d_dow": pa.array((np.arange(N_DATES) % 7).astype(np.int32)),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(N_ITEMS, dtype=np.int64)),
        "i_brand": pa.array([f"brand{i % 9}" for i in range(N_ITEMS)]),
        "i_category": pa.array([f"cat{i % 5}" for i in range(N_ITEMS)]),
        "i_price": pa.array(rng.uniform(1, 200, N_ITEMS).round(2)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(N_STORES, dtype=np.int64)),
        "s_state": pa.array([f"ST{i % 3}" for i in range(N_STORES)]),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(N_CUSTOMERS, dtype=np.int64)),
        "c_band": pa.array((np.arange(N_CUSTOMERS) % 10).astype(np.int32)),
    })
    nulls = rng.random(N_SALES) < 0.03
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(0, N_DATES, N_SALES).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(0, N_ITEMS, N_SALES).astype(np.int64)),
        "ss_store_sk": pa.array(
            rng.integers(0, N_STORES, N_SALES).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(0, N_CUSTOMERS, N_SALES).astype(np.int64)),
        "ss_quantity": pa.array(
            rng.integers(1, 20, N_SALES).astype(np.int32)),
        "ss_sales_price": pa.array(
            np.where(nulls, 0.0, rng.uniform(1, 250, N_SALES).round(2)),
            mask=nulls),
    })
    return {k: session.from_arrow(v, label=k) for k, v in {
        "date_dim": date_dim, "item": item, "store": store,
        "customer": customer, "store_sales": store_sales}.items()}


class TestTpcdsShapes:
    def test_q3_shape(self, session, star):
        """Brand report over a date-filtered fact (q3/q42/q52/q55 family)."""
        q = (star["store_sales"]
             .join(star["date_dim"],
                   condition=col("ss_sold_date_sk") == col("d_date_sk"),
                   how="inner")
             .filter(col("d_moy") == lit(11))
             .join(star["item"],
                   condition=col("ss_item_sk") == col("i_item_sk"),
                   how="inner")
             .group_by("d_year", "i_brand")
             .agg(sum_agg=Sum(col("ss_sales_price"))))
        assert_same(q, sort_by=["d_year", "i_brand"], approx_cols=("sum_agg",))

    def test_q7_shape(self, session, star):
        """Multi-dim star join with per-category averages (q7 family)."""
        q = (star["store_sales"]
             .join(star["item"],
                   condition=col("ss_item_sk") == col("i_item_sk"),
                   how="inner")
             .join(star["store"],
                   condition=col("ss_store_sk") == col("s_store_sk"),
                   how="inner")
             .filter(col("s_state") == lit("ST1"))
             .group_by("i_category")
             .agg(q=Average(col("ss_quantity")),
                  p=Average(col("ss_sales_price")),
                  n=Count(lit(1))))
        assert_same(q, sort_by=["i_category"], approx_cols=("q", "p"))

    def test_q68_shape(self, session, star):
        """Customer-level rollup with a post-join window rank (q68-ish)."""
        from spark_rapids_tpu.expr import RowNumber
        per_cust = (star["store_sales"]
                    .join(star["customer"],
                          condition=col("ss_customer_sk")
                          == col("c_customer_sk"), how="inner")
                    .group_by("c_customer_sk", "c_band")
                    .agg(spend=Sum(col("ss_sales_price")),
                         qty=Sum(col("ss_quantity"))))
        q = per_cust.window(partition_by=["c_band"],
                            order_by=[(col("spend"), False, False)],
                            rnk=RowNumber())
        out = assert_same(q, sort_by=["c_band", "c_customer_sk"],
                          approx_cols=("spend",))
        assert out.num_rows > 0

    def test_q96_shape(self, session, star):
        """Selective count over a chain of joins (q96 family)."""
        q = (star["store_sales"]
             .join(star["date_dim"],
                   condition=col("ss_sold_date_sk") == col("d_date_sk"),
                   how="inner")
             .filter((col("d_dow") == lit(6)) & (col("ss_quantity")
                                                 > lit(10)))
             .join(star["store"],
                   condition=col("ss_store_sk") == col("s_store_sk"),
                   how="inner")
             .agg(cnt=Count(lit(1))))
        assert_same(q)

    def test_q19_shape_semi_anti(self, session, star):
        """Semi/anti forms over the star (exists / not-exists rewrites)."""
        nov_dates = star["date_dim"].filter(col("d_moy") == lit(11))
        sold_nov = star["store_sales"].join(
            nov_dates, condition=col("ss_sold_date_sk") == col("d_date_sk"),
            how="semi")
        q = (sold_nov.group_by("ss_store_sk")
             .agg(n=Count(lit(1)), s=Sum(col("ss_sales_price"))))
        assert_same(q, sort_by=["ss_store_sk"], approx_cols=("s",))
        never_nov = star["item"].join(
            star["store_sales"].join(
                nov_dates,
                condition=col("ss_sold_date_sk") == col("d_date_sk"),
                how="semi"),
            condition=col("i_item_sk") == col("ss_item_sk"), how="anti")
        q2 = never_nov.agg(n=Count(lit(1)))
        assert_same(q2)

    def test_q36_shape_case_rollup(self, session, star):
        """Margin classification with CASE buckets (q36-ish rollup)."""
        q = (star["store_sales"]
             .join(star["item"],
                   condition=col("ss_item_sk") == col("i_item_sk"),
                   how="inner")
             .select("i_category", "ss_quantity",
                     margin=col("ss_sales_price") - col("i_price"),
                     bucket=CaseWhen(
                         [(col("ss_sales_price") > lit(200), lit("lux")),
                          (col("ss_sales_price") > lit(50), lit("mid"))],
                         lit("base")))
             .group_by("i_category", "bucket")
             .agg(m=Average(col("margin")), n=Count(lit(1)),
                  hi=Max(col("margin")), lo=Min(col("margin"))))
        assert_same(q, sort_by=["i_category", "bucket"],
                    approx_cols=("m", "hi", "lo"))

    def test_q65_shape_join_of_aggregates(self, session, star):
        """Join of two aggregate subqueries (q65 family)."""
        per_store_item = (star["store_sales"]
                          .group_by("ss_store_sk", "ss_item_sk")
                          .agg(rev=Sum(col("ss_sales_price"))))
        per_store = (per_store_item.group_by("ss_store_sk")
                     .agg(avg_rev=Average(col("rev"))))
        q = (per_store_item
             .join(per_store, on="ss_store_sk", how="inner")
             .filter(col("rev") > col("avg_rev"))
             .agg(n=Count(lit(1)), tot=Sum(col("rev"))))
        assert_same(q, approx_cols=("tot",))
