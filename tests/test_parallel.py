"""Distributed exchange tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8). Mirrors the reference's in-process shuffle
tests (`tests/.../shuffle/RapidsShuffleTestHelper.scala` mocked-transport suites):
the collective path is exercised end-to-end without hardware, with numpy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import Vec
from spark_rapids_tpu.expr.hashing import hash_vecs
from spark_rapids_tpu.parallel import (HashPartitioning, RangePartitioning,
                                       RoundRobinPartitioning,
                                       SinglePartitioning, make_mesh)
from spark_rapids_tpu.parallel.collective import (all_to_all_exchange,
                                                  broadcast_all_gather,
                                                  bucketize_by_partition,
                                                  build_exchange_fn,
                                                  compact_received)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


NDEV = 8


def _vec_i64(vals, valid=None):
    v = np.asarray(vals, np.int64)
    m = np.ones(len(v), bool) if valid is None else np.asarray(valid, bool)
    return Vec(T.LongType(), v, m)


# ---------------------------------------------------------------- partitioners

def test_hash_partitioning_matches_spark_pmod(rng):
    vals = rng.integers(-1000, 1000, size=64)
    vecs = [_vec_i64(vals)]
    hp = HashPartitioning((0,), 8)
    pid = np.asarray(hp.partition_ids(np, vecs, np.ones(64, bool)))
    h = hash_vecs(np, vecs, np.uint32(42)).astype(np.int32)
    expect = ((h % 8) + 8) % 8
    np.testing.assert_array_equal(pid, expect)
    assert pid.min() >= 0 and pid.max() < 8


def test_round_robin_and_single():
    mask = np.ones(10, bool)
    rr = RoundRobinPartitioning(3, start=1)
    np.testing.assert_array_equal(
        np.asarray(rr.partition_ids(np, [], mask)),
        (1 + np.arange(10)) % 3)
    sp = SinglePartitioning()
    assert np.all(np.asarray(sp.partition_ids(np, [], mask)) == 0)


def test_range_partitioning_bounds_and_nulls():
    v = _vec_i64([5, 15, 25, 0, 99], valid=[1, 1, 1, 1, 0])
    rp = RangePartitioning(0, np.array([10, 20], np.int64))
    pid = np.asarray(rp.partition_ids(np, [v], np.ones(5, bool)))
    np.testing.assert_array_equal(pid[:4], [0, 1, 2, 0])
    assert pid[4] == 0  # null -> nulls_first
    rp2 = RangePartitioning(0, np.array([10, 20], np.int64),
                            nulls_first=False)
    assert np.asarray(rp2.partition_ids(np, [v], np.ones(5, bool)))[4] == 2


# ------------------------------------------------------------ local bucketing

def test_bucketize_then_compact_roundtrip(rng):
    cap = 128
    n = 100
    data = rng.integers(0, 10_000, size=cap)
    pid_np = rng.integers(0, 4, size=cap).astype(np.int32)
    pid_np[n:] = -1
    slotted, counts, overflowed = bucketize_by_partition(
        [jnp.asarray(data)], jnp.asarray(pid_np), 4, cap)
    assert not bool(overflowed)
    counts = np.asarray(counts)
    for d in range(4):
        want = np.sort(data[:n][pid_np[:n] == d])
        got = np.sort(np.asarray(slotted[0][d, :counts[d]]))
        np.testing.assert_array_equal(got, want)
    # compact back
    leaves, total = compact_received([s for s in slotted], jnp.asarray(counts))
    assert int(total) == n
    np.testing.assert_array_equal(np.sort(np.asarray(leaves[0])[:n]),
                                  np.sort(data[:n]))


def test_repartition_expression_key(rng):
    from spark_rapids_tpu.expr import col, lit
    sess = _session()
    t = _arrow_table(rng)
    df = sess.from_arrow(t).repartition(3, col("id") % lit(np.int64(5)))
    out = df.collect()
    assert out.num_rows == 500
    assert out.schema.names == ["id", "val"]  # temp key column projected away


def test_range_partition_string_falls_back(rng):
    import pyarrow as pa
    sess = _session()
    t = pa.table({"name": pa.array(["a", "bb", "ccc", "d"] * 25)})
    out = sess.from_arrow(t).repartition_by_range(2, "name").collect()
    assert out.num_rows == 100


# ---------------------------------------------------------------- collectives

def _global_sharded(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("shuffle")))


def test_all_to_all_exchange_8dev(rng):
    mesh = make_mesh(NDEV)
    cap = 64  # per-device rows
    total_rows = NDEV * cap
    data = rng.integers(0, 1 << 30, size=total_rows).astype(np.int64)
    key = rng.integers(-500, 500, size=total_rows).astype(np.int64)
    # partition ids by spark hash of the key column
    hp = HashPartitioning((0,), NDEV)
    pid = np.asarray(hp.partition_ids(
        np, [_vec_i64(key)], np.ones(total_rows, bool))).astype(np.int32)

    fn = build_exchange_fn(mesh, NDEV)
    leaves, counts, overflowed = fn(
        [_global_sharded(mesh, jnp.asarray(data)),
         _global_sharded(mesh, jnp.asarray(key))],
        _global_sharded(mesh, jnp.asarray(pid)))
    assert not bool(overflowed)
    counts = np.asarray(counts)
    assert counts.sum() == total_rows
    out_data = np.asarray(leaves[0]).reshape(NDEV, -1)
    out_key = np.asarray(leaves[1]).reshape(NDEV, -1)
    for d in range(NDEV):
        live_k = out_key[d, :counts[d]]
        live_v = out_data[d, :counts[d]]
        # every row on device d must hash-partition to d
        got_pid = np.asarray(HashPartitioning((0,), NDEV).partition_ids(
            np, [_vec_i64(live_k)], np.ones(len(live_k), bool)))
        assert np.all(got_pid == d)
        want_v = np.sort(data[pid == d])
        np.testing.assert_array_equal(np.sort(live_v), want_v)


def test_broadcast_all_gather_8dev(rng):
    mesh = make_mesh(NDEV)
    cap = 16
    data = rng.integers(0, 1000, size=NDEV * cap).astype(np.int64)
    counts_per_dev = rng.integers(1, cap + 1, size=NDEV).astype(np.int32)

    def step(leaf, cnt):
        leaves, total = broadcast_all_gather([leaf], cnt[0], NDEV)
        return leaves[0], total[None]

    from spark_rapids_tpu.parallel.collective import shard_map
    f = jax.jit(shard_map(step, mesh, in_specs=(P("shuffle"), P("shuffle")),
                          out_specs=(P("shuffle"), P("shuffle"))))
    out, totals = f(_global_sharded(mesh, jnp.asarray(data)),
                    _global_sharded(mesh, jnp.asarray(counts_per_dev)))
    totals = np.asarray(totals)
    assert np.all(totals == counts_per_dev.sum())
    # each device's replica holds every device's live rows
    rep = np.asarray(out).reshape(NDEV, NDEV * cap)
    want = np.sort(np.concatenate(
        [data[d * cap: d * cap + counts_per_dev[d]] for d in range(NDEV)]))
    for d in range(NDEV):
        np.testing.assert_array_equal(np.sort(rep[d, :counts_per_dev.sum()]),
                                      want)


# -------------------------------------------------- exec-layer exchange (e2e)

def _session():
    from spark_rapids_tpu.plugin import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def _arrow_table(rng, n=500):
    import pyarrow as pa
    ids = rng.integers(0, 40, n)
    nulls = rng.random(n) < 0.1
    return pa.table({
        "id": pa.array(np.where(nulls, 0, ids), type=pa.int64(), mask=nulls),
        "val": pa.array(rng.normal(0, 10, n), type=pa.float64()),
    })


def test_repartition_hash_differential(rng):
    sess = _session()
    df = _arrow_table(rng)
    out = sess.from_arrow(df).repartition(4, "id").collect()
    cpu = sess.from_arrow(df).repartition(4, "id").collect_cpu()
    assert out.num_rows == cpu.num_rows == 500
    assert sorted(x if x is not None else -1 for x in
                  out.column("id").to_pylist()) == \
           sorted(x if x is not None else -1 for x in
                  cpu.column("id").to_pylist())


def test_repartition_then_aggregate(rng):
    from spark_rapids_tpu.expr import Sum, col
    sess = _session()
    t = _arrow_table(rng)
    df = sess.from_arrow(t).repartition(3, "id").group_by("id").agg(
        s=Sum(col("val")))
    tpu = df.collect().sort_by([("id", "ascending")])
    cpu = df.collect_cpu().sort_by([("id", "ascending")])
    assert tpu.num_rows == cpu.num_rows
    for a, b in zip(tpu.column("s").to_pylist(), cpu.column("s").to_pylist()):
        assert a == b or abs(a - b) < 1e-9 * max(abs(a), abs(b), 1.0)


def test_repartition_by_range(rng):
    sess = _session()
    t = _arrow_table(rng)
    out = sess.from_arrow(t).repartition_by_range(4, "id").collect()
    assert out.num_rows == 500
