"""Multi-process chip ownership (SURVEY §7 hard part; round-3 verdict #5).

One TpuDeviceService process owns the backend; REAL worker OS processes
(tests/service_worker.py via subprocess) contend through the cross-process
admission semaphore and submit Spark-plan JSON over the Arrow-IPC socket
ABI. Covers: FIFO admission ordering across processes with one token,
mutual exclusion (second worker admitted only after the first releases),
plan round-trips from two concurrent workers, token reclamation when a
worker dies holding admission, and wedged-service fail-fast
(DeviceStartupError under deadline — reference Plugin.scala:436-459;
admission analog GpuSemaphore.scala:67,125)."""

import json
import os
import signal
import socket as socketmod
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.errors import DeviceStartupError
from spark_rapids_tpu.service import TpuServiceClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "service_worker.py")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # workers don't need the 8-device mesh
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _start_server(sock, tokens=1):
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.service.server",
         "--socket", sock, "--platform", "cpu",
         "--conf", f"spark.rapids.sql.concurrentGpuTasks={tokens}"],
        cwd=REPO, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # wait for liveness (first connect also exercises the client deadline)
    try:
        TpuServiceClient(sock, deadline_s=60.0).connect().close()
    except BaseException:
        proc.kill()
        proc.wait()
        raise
    return proc


def _stop_server(proc, sock):
    try:
        with TpuServiceClient(sock, deadline_s=5.0) as cli:
            cli.shutdown()
    except Exception:
        pass
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _worker(sock, name, *extra):
    return subprocess.Popen(
        [sys.executable, WORKER, "--socket", sock, "--name", name, *extra],
        cwd=REPO, env=_env(),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _result(proc, timeout=60):
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, f"worker failed: {err[-2000:]}"
    return json.loads(out.strip().splitlines()[-1])


def _wait_for_file(path, msg, workers=(), deadline=30):
    """Poll for a marker file; on timeout kill outstanding workers so a
    failure cannot leave the module-scoped server's token held."""
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > deadline:
            for w in workers:
                w.kill()
            raise AssertionError(msg)
        time.sleep(0.01)


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("svc") / "tpu.sock")
    proc = _start_server(sock, tokens=1)
    yield sock
    _stop_server(proc, sock)


def scan_filter_plan():
    """FilterExec(v > 0) over FileSourceScanExec('t') as toJSON pre-order."""
    attr = lambda name, dt: [  # noqa: E731
        {"class": "org.apache.spark.sql.catalyst.expressions."
         "AttributeReference", "num-children": 0, "name": name,
         "dataType": dt, "nullable": True, "metadata": {},
         "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]
    filt = {"class": "org.apache.spark.sql.execution.FilterExec",
            "num-children": 1,
            "condition": [{"class": "org.apache.spark.sql.catalyst."
                           "expressions.GreaterThan", "num-children": 2}]
            + attr("v", "double")
            + [{"class": "org.apache.spark.sql.catalyst.expressions."
                "Literal", "num-children": 0, "value": "0.0",
                "dataType": "double"}]}
    scan = {"class": "org.apache.spark.sql.execution.FileSourceScanExec",
            "num-children": 0, "relation": "HadoopFsRelation(parquet)",
            "output": [attr("k", "long"), attr("v", "double")],
            "tableIdentifier": "t"}
    return json.dumps([filt, scan])


@pytest.fixture(scope="module")
def plan_and_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("svcdata")
    rng = np.random.default_rng(5)
    n = 3000
    t = pa.table({"k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
                  "v": pa.array(rng.normal(0.1, 1.0, n))})
    path = str(d / "t.parquet")
    pq.write_table(t, path)
    plan_path = str(d / "plan.json")
    with open(plan_path, "w") as f:
        f.write(scan_filter_plan())
    expected = int(np.sum(np.asarray(t.column("v")) > 0.0))
    return plan_path, path, expected


class TestCrossProcessAdmission:
    def test_fifo_order_and_mutual_exclusion(self, server, tmp_path):
        """With ONE token, worker B (a separate OS process) is admitted
        only after worker A releases, and admission sequence numbers are
        FIFO."""
        held = str(tmp_path / "a_held")
        go = str(tmp_path / "a_go")
        wa = _worker(server, "A", "--held-marker", held,
                     "--hold-until", go)
        _wait_for_file(held, "worker A never admitted", (wa,))
        b_enter = str(tmp_path / "b_enter")
        wb = _worker(server, "B", "--enter-marker", b_enter)
        _wait_for_file(b_enter, "worker B never reached acquire", (wa, wb))
        time.sleep(0.6)  # B is parked in acquire() behind A's token
        try:
            assert wb.poll() is None, \
                "worker B finished while A held the token"
        finally:
            with open(go, "w") as f:
                f.write("go")
        ra = _result(wa)
        rb = _result(wb)
        assert ra["order"] < rb["order"]
        # mutual exclusion across processes: B admitted after A released
        assert rb["t_acquired"] >= ra["t_released"] - 0.05
        # and B genuinely waited (it was started while A held the token)
        assert rb["t_acquired"] - rb["t_enter_acquire"] >= 0.4

    def test_two_workers_run_plans_concurrently(self, server,
                                                plan_and_data):
        """Two worker processes each submit a Spark executedPlan JSON and
        get identical Arrow results back through the batch ABI."""
        plan_path, data_path, expected = plan_and_data
        paths = json.dumps({"t": [data_path]})
        ws = [_worker(server, f"W{i}", "--plan", plan_path,
                      "--paths", paths) for i in range(2)]
        results = [_result(w) for w in ws]
        for r in results:
            assert r["num_rows"] == expected
            assert r["columns"] == ["k", "v"]
        # both went through the same global admission sequence
        assert results[0]["order"] != results[1]["order"]

    def test_dead_worker_releases_token(self, server, tmp_path):
        """A worker killed while HOLDING admission must not leak the token
        (server releases on disconnect) — the next worker still gets in."""
        held = str(tmp_path / "k_held")
        wa = _worker(server, "K", "--held-marker", held,
                     "--hold-until", str(tmp_path / "never"))
        _wait_for_file(held, "worker K never admitted", (wa,))
        wa.send_signal(signal.SIGKILL)
        wa.wait(timeout=10)
        wb = _worker(server, "B2")
        rb = _result(wb, timeout=30)
        assert rb["order"] > 0


class TestWedgedServiceFailFast:
    def test_no_service_raises_under_deadline(self, tmp_path):
        sock = str(tmp_path / "absent.sock")
        t0 = time.time()
        with pytest.raises(DeviceStartupError):
            TpuServiceClient(sock, deadline_s=0.8).connect()
        assert time.time() - t0 < 5.0

    def test_wedged_service_raises_under_deadline(self, tmp_path):
        """A service that accepts connections but never answers (the axon
        wedged-tunnel failure mode) must surface DeviceStartupError, not
        hang the worker."""
        sock = str(tmp_path / "wedged.sock")
        srv = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
        srv.bind(sock)
        srv.listen(4)
        try:
            t0 = time.time()
            with pytest.raises(DeviceStartupError):
                TpuServiceClient(sock, deadline_s=1.0).connect()
            assert time.time() - t0 < 6.0
        finally:
            srv.close()
