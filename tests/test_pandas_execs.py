"""Python-UDF exec variants (udf/pandas_execs.py): mapInPandas, grouped
applyInPandas, pandas-UDF aggregation, windowInPandas, cogrouped
applyInPandas — differential device-vs-CPU plus independent pandas
oracles computed in the tests (reference `GpuMapInPandasExec.scala`,
`GpuFlatMapGroupsInPandasExec.scala`, `GpuAggregateInPandasExec.scala`,
`GpuWindowInPandasExecBase.scala`,
`GpuFlatMapCoGroupsInPandasExec.scala`)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def make_table(rng, n=4000):
    keys = rng.integers(0, 23, n).astype(np.int64)
    return pa.table({
        "k": pa.array(keys),
        "v": pa.array(rng.normal(size=n)),
        "w": pa.array(rng.uniform(0.1, 1.0, n)),
        "s": pa.array([f"g{k % 5}" for k in keys]),
    })


OUT_SCHEMA = [("k", T.LongType()), ("doubled", T.DoubleType())]


class TestMapInPandas:
    def test_row_preserving_fn(self, session, rng):
        t = make_table(rng)

        def doubler(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"], "doubled": f["v"] * 2})

        df = session.from_arrow(t).map_in_pandas(doubler, OUT_SCHEMA)
        assert_same(df, sort_by=["k", "doubled"], approx_cols=("doubled",))
        # independent oracle
        got = df.collect().sort_by([("k", "ascending"),
                                    ("doubled", "ascending")])
        exp = pd.DataFrame({"k": t.column("k").to_numpy(),
                            "doubled": t.column("v").to_numpy() * 2}) \
            .sort_values(["k", "doubled"])
        assert np.allclose(got.column("doubled").to_numpy(),
                           exp["doubled"].to_numpy())

    def test_row_count_changing_fn(self, session, rng):
        t = make_table(rng)

        def filter_expand(frames):
            for f in frames:
                kept = f[f["v"] > 0.5]
                out = pd.DataFrame({"k": np.repeat(kept["k"].to_numpy(), 2),
                                    "doubled": np.repeat(
                                        kept["v"].to_numpy(), 2)})
                yield out

        df = session.from_arrow(t).map_in_pandas(filter_expand, OUT_SCHEMA)
        got = df.collect()
        exp_n = 2 * int((t.column("v").to_numpy() > 0.5).sum())
        assert got.num_rows == exp_n
        assert_same(df, sort_by=["k", "doubled"], approx_cols=("doubled",))

    def test_batch_size_roundoff(self, rng):
        """With batchSizeRows=300 over 1000 rows the UDF iterator must see
        ceil-chunked frames never larger than the limit, and the tail
        chunk carries the roundoff."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.batchSizeRows": 300,
                           "spark.rapids.sql.explain": "NONE"})
        t = pa.table({"k": pa.array(np.arange(1000, dtype=np.int64)),
                      "v": pa.array(rng.normal(size=1000)),
                      "w": pa.array(np.ones(1000)),
                      "s": pa.array(["x"] * 1000)})
        sizes = []

        def spy(frames):
            for f in frames:
                sizes.append(len(f))
                yield pd.DataFrame({"k": f["k"], "doubled": f["v"]})

        got = sess.from_arrow(t).map_in_pandas(spy, OUT_SCHEMA).collect()
        assert got.num_rows == 1000
        assert max(sizes) <= 300
        assert sum(sizes) == 1000
        assert any(s == 100 for s in sizes)  # the roundoff tail

    def test_eager_list_returning_fn(self, session, rng):
        """A fn returning a LIST of frames (not a generator) must work —
        iter() semantics, the shape plain-python users write."""
        t = make_table(rng, n=300)

        def eager(frames):
            return [pd.DataFrame({"k": f["k"], "doubled": f["v"] * 2})
                    for f in frames]

        df = session.from_arrow(t).map_in_pandas(eager, OUT_SCHEMA)
        assert_same(df, sort_by=["k", "doubled"], approx_cols=("doubled",))

    def test_empty_input(self, session):
        t = pa.table({"k": pa.array([], pa.int64()),
                      "v": pa.array([], pa.float64()),
                      "w": pa.array([], pa.float64()),
                      "s": pa.array([], pa.string())})

        def ident(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"], "doubled": f["v"]})

        assert session.from_arrow(t).map_in_pandas(
            ident, OUT_SCHEMA).collect().num_rows == 0

    def test_missing_output_column_raises(self, session, rng):
        t = make_table(rng, n=100)

        def bad(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"]})  # no "doubled"

        with pytest.raises((ValueError, RuntimeError),
                           match="missing declared output"):
            session.from_arrow(t).map_in_pandas(bad, OUT_SCHEMA).collect()


class TestSemaphoreReentrancy:
    def test_nested_map_in_pandas_one_permit(self, rng):
        """Stacked pandas execs pull their child iterator while holding
        the worker permit; with ONE permit this deadlocks unless the
        semaphore is reentrant per thread."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.concurrentGpuTasks": 1,
                           "spark.rapids.sql.explain": "NONE"})
        t = make_table(rng, n=200)

        def double(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"], "doubled": f["v"] * 2})

        def halve(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"],
                                    "doubled": f["doubled"] / 2})

        df = sess.from_arrow(t).map_in_pandas(double, OUT_SCHEMA) \
            .map_in_pandas(halve, OUT_SCHEMA)
        got = df.collect()
        assert got.num_rows == 200
        assert np.allclose(np.sort(got.column("doubled").to_numpy()),
                           np.sort(t.column("v").to_numpy()))


class TestApplyInPandas:
    def test_group_normalize(self, session, rng):
        t = make_table(rng)

        def center(g):
            return pd.DataFrame({"k": g["k"],
                                 "centered": g["v"] - g["v"].mean()})

        df = session.from_arrow(t).group_by("k").apply_in_pandas(
            center, [("k", T.LongType()), ("centered", T.DoubleType())])
        assert_same(df, sort_by=["k", "centered"],
                    approx_cols=("centered",))
        # independent oracle: per-group mean via pandas on raw data
        got = df.collect()
        pdf = t.to_pandas()
        exp = pdf.groupby("k")["v"].transform("mean")
        assert abs(float(np.sort(got.column("centered").to_numpy()).sum()
                         - np.sort((pdf["v"] - exp).to_numpy()).sum())
                   ) < 1e-9

    def test_row_count_changing_group_fn(self, session, rng):
        t = make_table(rng)

        def top2(g):
            top = g.nlargest(2, "v")
            return pd.DataFrame({"k": top["k"], "centered": top["v"]})

        df = session.from_arrow(t).group_by("k").apply_in_pandas(
            top2, [("k", T.LongType()), ("centered", T.DoubleType())])
        assert_same(df, sort_by=["k", "centered"],
                    approx_cols=("centered",))
        assert df.collect().num_rows == 2 * 23

    def test_string_group_keys(self, session, rng):
        t = make_table(rng)

        def count_rows(g):
            return pd.DataFrame({"s": [g["s"].iloc[0]], "n": [len(g)]})

        df = session.from_arrow(t).group_by("s").apply_in_pandas(
            count_rows, [("s", T.StringType()), ("n", T.LongType())])
        assert_same(df, sort_by=["s"])


class TestAggregateInPandas:
    def test_weighted_mean(self, session, rng):
        t = make_table(rng)

        def wmean(v, w):
            return float((v * w).sum() / w.sum())

        df = session.from_arrow(t).group_by("k").agg_in_pandas(
            wm=(wmean, T.DoubleType(), "v", "w"),
            n=(lambda v: int(len(v)), T.LongType(), "v"))
        assert_same(df, sort_by=["k"], approx_cols=("wm",))
        # independent oracle
        got = {r["k"]: r for r in df.collect().to_pylist()}
        pdf = t.to_pandas()
        for k, g in pdf.groupby("k"):
            exp = (g["v"] * g["w"]).sum() / g["w"].sum()
            assert abs(got[k]["wm"] - exp) < 1e-9
            assert got[k]["n"] == len(g)


class TestWindowInPandas:
    def test_partition_mean_broadcast(self, session, rng):
        t = make_table(rng)

        def pmean(v):
            return float(v.mean())

        df = session.from_arrow(t).window_in_pandas(
            partition_by="k", m=(pmean, T.DoubleType(), "v"))
        assert_same(df, sort_by=["k", "v"], approx_cols=("m", "v", "w"))
        # row count must be preserved and every row must carry its
        # partition's mean
        got = df.collect().to_pandas()
        assert len(got) == t.num_rows
        oracle = got.groupby("k")["v"].transform("mean")
        assert np.allclose(got["m"], oracle)

    def test_global_window(self, session, rng):
        t = make_table(rng, n=500)
        df = session.from_arrow(t).window_in_pandas(
            m=(lambda v: float(v.max()), T.DoubleType(), "v"))
        got = df.collect()
        assert got.num_rows == 500
        assert np.allclose(got.column("m").to_numpy(),
                           t.column("v").to_numpy().max())


class TestCoGroupsInPandas:
    def test_asof_style_cogroup(self, session, rng):
        n = 1000
        left = pa.table({
            "k": pa.array(rng.integers(0, 10, n).astype(np.int64)),
            "v": pa.array(rng.normal(size=n))})
        right = pa.table({
            "k": pa.array(rng.integers(3, 13, 200).astype(np.int64)),
            "adj": pa.array(rng.uniform(size=200))})

        def merge_stats(lg, rg):
            return pd.DataFrame({
                "k": [lg["k"].iloc[0] if len(lg) else rg["k"].iloc[0]],
                "lsum": [float(lg["v"].sum())],
                "rmean": [float(rg["adj"].mean()) if len(rg)
                          else float("nan")]})

        out_schema = [("k", T.LongType()), ("lsum", T.DoubleType()),
                      ("rmean", T.DoubleType())]
        dfl = session.from_arrow(left).group_by("k")
        dfr = session.from_arrow(right).group_by("k")
        df = dfl.cogroup(dfr).apply_in_pandas(merge_stats, out_schema)
        assert_same(df, sort_by=["k"], approx_cols=("lsum", "rmean"))
        # keys present on only one side still produce a co-group
        got = {r["k"] for r in df.collect().to_pylist()}
        assert got == set(range(0, 13))

    def test_null_keys_form_one_cogroup(self, session):
        """A null key on both sides is ONE co-group (Spark grouping
        semantics: null == null for grouping), not two half-empty ones."""
        left = pa.table({"k": pa.array([1.0, None, None]),
                         "v": pa.array([10.0, 20.0, 30.0])})
        right = pa.table({"k": pa.array([None, 2.0]),
                          "adj": pa.array([5.0, 6.0])})

        def counts(lg, rg):
            return pd.DataFrame({"ln": [len(lg)], "rn": [len(rg)]})

        df = session.from_arrow(left).group_by("k").cogroup(
            session.from_arrow(right).group_by("k")).apply_in_pandas(
            counts, [("ln", T.LongType()), ("rn", T.LongType())])
        rows = sorted((r["ln"], r["rn"]) for r in df.collect().to_pylist())
        # co-groups: k=1.0 -> (1, 0); k=2.0 -> (0, 1); k=null -> (2, 1)
        assert rows == [(0, 1), (1, 0), (2, 1)]
        assert_same(df, sort_by=["ln", "rn"])


class TestCpuPathConfParity:
    def test_cpu_engine_honors_session_batch_size(self, rng):
        """The CPU oracle path must chunk mapInPandas input by the SAME
        session batchSizeRows as the device path, or chunk-sensitive UDFs
        silently diverge between engines."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.batchSizeRows": 250,
                           "spark.rapids.sql.explain": "NONE"})
        t = pa.table({"k": pa.array(np.arange(1000, dtype=np.int64)),
                      "v": pa.array(rng.normal(size=1000)),
                      "w": pa.array(np.ones(1000)),
                      "s": pa.array(["x"] * 1000)})

        def chunk_sizes(frames):
            for f in frames:
                yield pd.DataFrame({"k": f["k"].iloc[:1],
                                    "doubled": [float(len(f))]})

        df = sess.from_arrow(t).map_in_pandas(chunk_sizes, OUT_SCHEMA)
        cpu = sorted(r["doubled"] for r in df.collect_cpu().to_pylist())
        tpu = sorted(r["doubled"] for r in df.collect().to_pylist())
        assert cpu == tpu == [250.0, 250.0, 250.0, 250.0]
