"""Native host runtime tests (native/ C++ via ctypes: LZ4 block codec, string
repack, staging arena). Reference roles: nvcomp / cudf JNI row-col kernels /
RMM+pinned pool (SURVEY.md §2.9)."""

import numpy as np
import pytest

from spark_rapids_tpu.native import runtime

pytestmark = pytest.mark.skipif(not runtime.available(),
                                reason="native lib not built (make -C native)")


class TestLz4:
    @pytest.mark.parametrize("n", [0, 1, 11, 12, 13, 17, 64, 4096, 1 << 20])
    def test_sizes(self, rng, n):
        data = rng.bytes(n)
        assert runtime.lz4_decompress(runtime.lz4_compress(data), n) == data

    def test_highly_compressible(self):
        data = b"\x00" * (1 << 20)
        c = runtime.lz4_compress(data)
        assert len(c) < len(data) // 100
        assert runtime.lz4_decompress(c, len(data)) == data

    def test_repeating_pattern(self, rng):
        data = bytes(rng.integers(0, 3, 100, dtype=np.uint8)) * 1000
        c = runtime.lz4_compress(data)
        assert len(c) < len(data) // 4
        assert runtime.lz4_decompress(c, len(data)) == data

    def test_long_match_lengths(self):
        # matches > 255+19 exercise the extended match-length encoding
        data = b"abcd" * 5000 + b"tail-literals"
        c = runtime.lz4_compress(data)
        assert runtime.lz4_decompress(c, len(data)) == data

    def test_corrupt_input_rejected(self, rng):
        data = rng.bytes(1000)
        c = runtime.lz4_compress(data)
        with pytest.raises(RuntimeError):
            runtime.lz4_decompress(c[:-5], 1000)  # truncated stream
        with pytest.raises(RuntimeError):
            runtime.lz4_decompress(c, 999)  # output-size mismatch


class TestStringRepack:
    def test_round_trip(self):
        strings = [b"", b"a", b"hello", b"x" * 31, b""]
        offsets = np.zeros(len(strings) + 1, np.int64)
        for i, s in enumerate(strings):
            offsets[i + 1] = offsets[i] + len(s)
        chars = np.frombuffer(b"".join(strings), np.uint8)
        m, l = runtime.offsets_to_matrix(chars, offsets, 32)
        assert m.shape == (5, 32)
        assert list(l) == [len(s) for s in strings]
        o2, c2 = runtime.matrix_to_offsets(m, l)
        assert list(o2) == list(offsets)
        assert c2.tobytes() == b"".join(strings)

    def test_width_overflow_rejected(self):
        offsets = np.array([0, 10], np.int64)
        chars = np.frombuffer(b"0123456789", np.uint8)
        with pytest.raises(ValueError):
            runtime.offsets_to_matrix(chars, offsets, 4)


class TestHostArena:
    def test_alloc_free_coalesce(self):
        a = runtime.HostArena(1 << 20)
        try:
            ps = [a.alloc(1 << 10) for _ in range(100)]
            assert a.in_use >= 100 << 10
            for p in ps:
                a.free(p)
            assert a.in_use == 0
            # after freeing everything, one max-size alloc must succeed
            # (free-list coalescing check)
            big = a.alloc((1 << 20) - (1 << 10))
            a.free(big)
        finally:
            a.destroy()

    def test_exhaustion_raises(self):
        a = runtime.HostArena(1 << 16)
        try:
            a.alloc(1 << 15)
            with pytest.raises(MemoryError):
                a.alloc(1 << 16)
        finally:
            a.destroy()

    def test_double_init_rejected(self):
        a = runtime.HostArena(1 << 16)
        try:
            with pytest.raises(RuntimeError, match="already initialized"):
                runtime.HostArena(1 << 16)
        finally:
            a.destroy()


class TestNativeAbsentFallback:
    """Delete-the-so negative path: with the native lib gone, every caller
    must produce BIT-IDENTICAL results through its numpy fallback."""

    @pytest.fixture
    def no_native(self, monkeypatch):
        from spark_rapids_tpu.native import runtime
        monkeypatch.setattr(runtime, "_LIB", None)
        monkeypatch.setattr(runtime, "_TRIED", True)
        assert not runtime.available()
        yield

    def test_string_repack_identical(self, rng, no_native):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        strs = [None if i % 7 == 0 else f"s{i}" * (i % 5 + 1)
                for i in range(200)]
        t = pa.table({"s": pa.array(strs)})
        fallback = batch_from_arrow(t)
        # reload the real lib for the reference result; without it the
        # comparison would be fallback-vs-fallback and prove nothing
        from spark_rapids_tpu.native import runtime
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(runtime, "_TRIED", False)
            mp.setattr(runtime, "_LIB", None)
            if not runtime.available():
                pytest.skip("native lib not built; nothing to compare")
            native = batch_from_arrow(t)
        col_f, col_n = fallback.columns[0], native.columns[0]
        assert np.array_equal(np.asarray(col_f.data), np.asarray(col_n.data))
        assert np.array_equal(np.asarray(col_f.lengths),
                              np.asarray(col_n.lengths))
        assert np.array_equal(np.asarray(col_f.validity),
                              np.asarray(col_n.validity))

    def test_lz4xla_codec_raises_cleanly(self, no_native):
        from spark_rapids_tpu.shuffle import codec
        codec._CACHE.pop("lz4xla", None)
        with pytest.raises(RuntimeError, match="native runtime"):
            codec.get_codec("lz4xla")
        codec._CACHE.pop("lz4xla", None)

    def test_zstd_path_unaffected(self, rng, no_native):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.shuffle.serializer import (concat_host_tables,
                                                         deserialize_table,
                                                         serialize_batch)
        t = pa.table({"x": pa.array(rng.integers(0, 100, 50),
                                    type=pa.int64())})
        blob = serialize_batch(batch_from_arrow(t), "zstd")
        table, _ = deserialize_table(blob)
        out = concat_host_tables([table])
        assert sorted(np.asarray(out.columns[0].data)[:50].tolist()) == \
            sorted(t.column("x").to_pylist())


class TestCatalogObservability:
    def test_debug_dump_and_leaks(self, rng):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.memory.catalog import BufferCatalog
        cat = BufferCatalog(host_limit=1 << 20)
        t = pa.table({"x": pa.array(rng.integers(0, 9, 64),
                                    type=pa.int64())})
        h1 = cat.add_batch(batch_from_arrow(t), label="probe-side")
        h2 = cat.add_batch(batch_from_arrow(t))
        dump = cat.debug_dump()
        assert "2 live handles" in dump
        assert "label=probe-side" in dump
        assert "tier=DEVICE" in dump
        leaks = cat.leak_report()
        assert {r["handle"] for r in leaks} == {h1, h2}
        cat.remove(h1)
        cat.remove(h2)
        assert cat.live_count == 0
        assert cat.leak_report() == []
