"""Device parquet decode (io/parquet_device.py): PLAIN values + RLE/bit-packed
definition levels decoded on device, differential against pyarrow on
generated files (reference GpuParquetScan device decode)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def plain_table(rng, n=5000, nulls=True):
    def mk(vals):
        if not nulls:
            return pa.array(vals)
        mask = rng.random(n) < 0.2
        return pa.array(vals, mask=mask)
    return pa.table({
        "i": mk(rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "l": mk(rng.integers(-2**62, 2**62, n)),
        "f": mk(rng.normal(0, 1e3, n).astype(np.float32)),
        "d": mk(rng.normal(0, 1e6, n)),
        "b": mk(rng.integers(0, 2, n).astype(bool)),
    })


def write_plain(tmp_path, t, name="t.parquet", **kw):
    path = str(tmp_path / name)
    pq.write_table(t, path, use_dictionary=False, compression=kw.pop(
        "compression", "snappy"), **kw)
    return path


def _used_device_decode(session, path):
    from spark_rapids_tpu.io.parquet_device import (
        DeviceDecodeUnsupported, device_decode_file, file_supported)
    df = session.read_parquet(path)
    session.initialize_device()
    try:
        pf = file_supported(path, df.plan.output)
        batches = list(device_decode_file(pf, path, df.plan.output))
    except Exception:
        return False, None
    return True, batches[0][0] if batches else None


def _col_strings(col, nrows: int):
    """Decode a (possibly chunked-layout) string column to python strings."""
    import numpy as np
    from spark_rapids_tpu.columnar.strings import assemble_matrix
    mat, lens = assemble_matrix(col.data, col.lengths, col.overflow, nrows)
    return [bytes(np.asarray(mat[i, :int(lens[i])])).decode()
            for i in range(nrows)]


class TestNativeChunkWalk:
    """native/src/chunk_walk.cpp vs the python page walk (the semantic
    spec): same pages, same run tables, same payloads, on files with
    dict+plain spill, nulls, strings and both codecs."""

    @pytest.mark.parametrize("compression", ["snappy", "none"])
    def test_walk_matches_python(self, rng, tmp_path, compression):
        from spark_rapids_tpu.io import parquet_device as P
        from spark_rapids_tpu.native import runtime as R
        if not R.available():
            pytest.skip("native lib not built")
        n = 30000
        mask = rng.random(n) < 0.15
        t = pa.table({
            "l": pa.array(rng.integers(-10**14, 10**14, n), mask=mask),
            "lo": pa.array(rng.integers(0, 30, n), mask=mask),  # dict
            "s": pa.array([f"s{i % 211}" for i in range(n)], mask=mask),
            "b": pa.array(rng.integers(0, 2, n).astype(bool), mask=mask),
        })
        path = str(tmp_path / "w.parquet")
        pq.write_table(t, path, compression=compression)
        pf = pq.ParquetFile(path)
        rgm = pf.metadata.row_group(0)
        sch = pf.metadata.schema
        for ci in range(rgm.num_columns):
            cm = rgm.column(ci)
            optional = sch.column(ci).max_definition_level > 0
            with open(path, "rb") as f:
                f.seek(cm.dictionary_page_offset or cm.data_page_offset)
                buf = f.read(cm.total_compressed_size)
            nat = P._decode_chunk(buf, cm, optional)
            assert nat.hold is not None, "native walk did not engage"
            # python walk (native disabled for the call)
            lib, R._LIB = R._LIB, None
            try:
                ref = P._decode_chunk_inner(buf, cm, optional)
            finally:
                R._LIB = lib
            assert nat.total == ref.total
            assert nat.dict_count == ref.dict_count
            if ref.dict_raw is not None:
                assert bytes(np.asarray(nat.dict_raw)) == ref.dict_raw
            assert len(nat.pages) == len(ref.pages)
            for a, b in zip(nat.pages, ref.pages):
                assert (a.kind, a.bw, a.num_values, a.ndef) == \
                    (b.kind, b.bw, b.num_values, b.ndef)
                if a.kind == "plain":
                    assert np.array_equal(
                        np.frombuffer(np.ascontiguousarray(a.payload),
                                      np.uint8),
                        np.frombuffer(b.payload, np.uint8)
                        if not isinstance(b.payload, np.ndarray)
                        else b.payload.view(np.uint8))
                elif a.payload is not None:
                    # expand both run tables on host and compare values
                    def expand(runs, ndef, bw):
                        kinds, counts, values, bitoffs, packed = runs
                        bits = np.unpackbits(np.asarray(packed),
                                             bitorder="little")
                        out = []
                        for k, c, v, bo in zip(kinds, counts, values,
                                               bitoffs):
                            c = int(c)
                            if k == 0:
                                out.extend([int(v)] * c)
                            else:
                                sl = bits[bo:bo + c * bw] \
                                    .reshape(c, bw).astype(np.uint64)
                                out.extend(
                                    (sl << np.arange(bw, dtype=np.uint64)
                                     ).sum(axis=1).tolist())
                        return out[:ndef]
                    assert expand(a.payload, a.ndef, a.bw) == \
                        expand(b.payload, b.ndef, b.bw)


class TestDeviceParquetDecode:
    @pytest.mark.parametrize("compression", ["snappy", "none", "zstd"])
    def test_plain_roundtrip(self, session, rng, tmp_path, compression):
        t = plain_table(rng)
        path = write_plain(tmp_path, t, compression=compression)
        df = session.read_parquet(path)
        tpu = df.collect()
        assert tpu.num_rows == t.num_rows
        exact = pq.read_table(path)
        for name in t.schema.names:
            a = tpu.column(name).to_pylist()
            b = exact.column(name).to_pylist()
            assert a == b or all(
                (x is None and y is None) or x == y or
                (isinstance(x, float) and abs(x - y) < 1e-12)
                for x, y in zip(a, b)), name

    def test_device_path_actually_used(self, session, rng, tmp_path):
        path = write_plain(tmp_path, plain_table(rng, n=800))
        used, first = _used_device_decode(session, path)
        assert used and first is not None

    def test_no_nulls_required_like(self, session, rng, tmp_path):
        t = plain_table(rng, n=1200, nulls=False)
        path = write_plain(tmp_path, t)
        df = session.read_parquet(path)
        assert df.collect().equals(pq.read_table(path))

    def test_multiple_row_groups(self, session, rng, tmp_path):
        t = plain_table(rng, n=4000)
        path = write_plain(tmp_path, t, row_group_size=700)
        df = session.read_parquet(path)
        out = df.collect()
        exact = pq.read_table(path)
        assert out.column("l").to_pylist() == exact.column("l").to_pylist()
        assert out.column("i").to_pylist() == exact.column("i").to_pylist()

    def test_dictionary_files_take_device_path(self, session, rng,
                                               tmp_path):
        # round-2 verdict item 3 INVERTED: default pyarrow output
        # (dictionary-encoded) now decodes on device
        t = plain_table(rng, n=500)
        path = str(tmp_path / "dict.parquet")
        pq.write_table(t, path, use_dictionary=True)
        used, first = _used_device_decode(session, path)
        assert used and first is not None
        df = session.read_parquet(path)
        got = df.collect()
        exact = pq.read_table(path)
        for name in t.schema.names:
            assert got.column(name).to_pylist() == \
                exact.column(name).to_pylist(), name

    def test_plain_strings_take_device_path(self, session, rng, tmp_path):
        t = pa.table({"s": pa.array(["a", "bb", None, "ccc", "", None,
                                     "ünïcødé 字", "x" * 100])})
        path = write_plain(tmp_path, t)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == \
            t.column("s").to_pylist()

    def test_dict_strings_take_device_path(self, session, rng, tmp_path):
        n = 3000
        words = ["alpha", "beta", "gamma", "δδδ", "", "longer-value-here"]
        vals = [None if rng.random() < 0.15 else
                words[int(rng.integers(0, len(words)))] for _ in range(n)]
        t = pa.table({"s": pa.array(vals, type=pa.string()),
                      "l": pa.array(rng.integers(0, 50, n))})
        path = str(tmp_path / "ds.parquet")
        pq.write_table(t, path, use_dictionary=True)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        got = df.collect()
        assert got.column("s").to_pylist() == vals
        assert got.column("l").to_pylist() == t.column("l").to_pylist()

    def test_dict_to_plain_spill_pages(self, session, rng, tmp_path):
        # parquet writers fall back to PLAIN mid-chunk once the dictionary
        # outgrows its limit: chunks carry BOTH dict and plain data pages
        n = 6000
        vals = ["s%08d" % int(v) for v in rng.integers(0, n, n)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "spill.parquet")
        pq.write_table(t, path, use_dictionary=True,
                       dictionary_pagesize_limit=1024, data_page_size=2048)
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == vals

    def test_dict_many_small_pages_with_nulls(self, session, rng,
                                              tmp_path):
        n = 4000
        base = rng.integers(0, 40, n)
        mask = rng.random(n) < 0.25
        t = pa.table({"v": pa.array(base * 1000, mask=mask),
                      "f": pa.array(base.astype(np.float64) / 3,
                                    mask=~mask)})
        path = str(tmp_path / "dsmall.parquet")
        pq.write_table(t, path, use_dictionary=True, data_page_size=300)
        used, _ = _used_device_decode(session, path)
        assert used
        got = session.read_parquet(path).collect()
        exact = pq.read_table(path)
        assert got.column("v").to_pylist() == exact.column("v").to_pylist()
        assert got.column("f").to_pylist() == exact.column("f").to_pylist()

    def test_overwide_strings_decode_to_chunked_layout(self, session, rng,
                                                       tmp_path):
        # beyond spark.rapids.tpu.string.maxWidth the decoder builds the
        # CHUNKED long-string layout ON DEVICE (round-4; previously a
        # per-row-group host fallback): the device path stays in use and
        # the column carries a head matrix + shared tail blob
        wide = "w" * 20000
        t = pa.table({"s": pa.array(["a", wide, "b"])})
        path = write_plain(tmp_path, t)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == ["a", wide, "b"]
        assert df.collect_cpu().column("s").to_pylist() == ["a", wide, "b"]

    def test_megabyte_string_bounded_memory(self, session, rng, tmp_path):
        # a 1MB value must cost ~its own bytes on device, not cap * 1MB
        import numpy as np
        from spark_rapids_tpu.io.parquet_device import (device_decode_file,
                                                        file_supported)
        big = "Z" * (1 << 20)
        vals = [f"v{i}" for i in range(500)] + [big]
        t = pa.table({"s": pa.array(vals)})
        path = write_plain(tmp_path, t)
        schema = session.read_parquet(path).plan.output
        pf = file_supported(path, schema)
        batches = list(device_decode_file(pf, path, schema))
        total_bytes = sum(
            int(c.data.size) + (int(c.overflow[0].size)
                                if c.overflow is not None else 0)
            for b, _ in batches for c in b.columns)
        # head matrix (512*256) + blob (~1MB bucket) << cap * 1MB
        assert total_bytes < 4 * (1 << 20)
        got = [s for b, nr in batches
               for s in _col_strings(b.columns[0], int(nr))]
        assert got == vals

    def test_bool_across_many_small_pages(self, session, rng, tmp_path):
        # page bit-packing restarts per page: misalignment regression test
        n = 4000
        mask = rng.random(n) < 0.3
        t = pa.table({"b": pa.array(rng.integers(0, 2, n).astype(bool),
                                    mask=mask),
                      "l": pa.array(rng.integers(0, 10, n))})
        path = str(tmp_path / "b.parquet")
        pq.write_table(t, path, use_dictionary=False, data_page_size=100)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("b").to_pylist() == \
            pq.read_table(path).column("b").to_pylist()

    def test_lz4_files_fall_back_cleanly(self, session, rng, tmp_path):
        t = plain_table(rng, n=300)
        path = str(tmp_path / "lz4.parquet")
        pq.write_table(t, path, use_dictionary=False, compression="lz4")
        used, _ = _used_device_decode(session, path)
        assert not used
        df = session.read_parquet(path)
        assert df.collect().num_rows == 300  # host path still works

    def test_v2_pages_fall_back_cleanly(self, session, rng, tmp_path):
        t = plain_table(rng, n=400)
        path = str(tmp_path / "v2.parquet")
        pq.write_table(t, path, use_dictionary=False,
                       data_page_version="2.0")
        df = session.read_parquet(path)  # must not crash
        got = df.collect()
        assert got.column("l").to_pylist() == \
            pq.read_table(path).column("l").to_pylist()

    def test_empty_file(self, session, tmp_path):
        t = pa.table({"i": pa.array([], type=pa.int32())})
        path = str(tmp_path / "empty.parquet")
        pq.write_table(t, path, use_dictionary=False)
        df = session.read_parquet(path)
        assert df.collect().num_rows == 0

    def test_query_over_device_decoded_scan(self, session, rng, tmp_path):
        from spark_rapids_tpu.expr import Count, Sum, col
        t = plain_table(rng, n=3000)
        path = write_plain(tmp_path, t)
        df = session.read_parquet(path)
        q = df.group_by("b").agg(c=Count(col("l")), s=Sum(col("i")))
        tpu = q.collect().sort_by([("b", "ascending")])
        cpu = q.collect_cpu().sort_by([("b", "ascending")])
        assert tpu.column("c").to_pylist() == cpu.column("c").to_pylist()
        assert tpu.column("s").to_pylist() == cpu.column("s").to_pylist()


def tpcds_like_table(rng, n=6000, nulls=True):
    """TPC-DS fact-table shape: decimal(7,2) money columns, surrogate-key
    longs, a date and a timestamp — the columns round-4's verdict said
    were evicting whole files from the device path."""
    import datetime
    import decimal

    def mk(vals, typ=None):
        mask = rng.random(n) < 0.1 if nulls else np.zeros(n, bool)
        if typ is not None and pa.types.is_decimal(typ):
            py = [None if mask[i] else
                  decimal.Decimal(int(vals[i])).scaleb(-typ.scale)
                  for i in range(n)]
            return pa.array(py, type=typ)
        return pa.array(vals, mask=mask, type=typ)

    epoch = datetime.date(1970, 1, 1)
    return pa.table({
        "ss_item_sk": pa.array(rng.integers(1, 200_000, n)),
        "ss_quantity": mk(rng.integers(1, 100, n).astype(np.int32)),
        "ss_sales_price": mk(rng.integers(0, 10**6, n),
                             pa.decimal128(7, 2)),
        "ss_ext_sales_price": mk(rng.integers(0, 10**8, n),
                                 pa.decimal128(9, 2)),
        "ss_net_paid_wide": mk(rng.integers(-10**18, 10**18, n),
                               pa.decimal128(30, 8)),
        "ss_sold_date": mk(np.array(
            [epoch + datetime.timedelta(days=int(x))
             for x in rng.integers(10_000, 12_000, n)]),
            pa.date32()),
        "ss_sold_ts": mk(rng.integers(-4 * 10**15, 4 * 10**15, n),
                         pa.timestamp("us")),
    })


class TestDecimalTimestampDeviceDecode:
    """Round-5 verdict item 1: decimal + date/timestamp device decode with
    PER-COLUMN fallback. The INVERTED tests assert TPC-DS-shaped columns
    now take the device path (decimal(7,2) FLBA, decimal(30,8) limb pairs,
    INT64 timestamps both units, INT96); golden oracle is pyarrow."""

    def _expected(self, path):
        from spark_rapids_tpu.io.scanbase import normalize_timestamps
        return normalize_timestamps(pq.read_table(path))

    def _assert_scan_matches(self, session, path):
        got = session.read_parquet(path).collect()
        exp = self._expected(path)
        for name in exp.schema.names:
            assert got.column(name).to_pylist() == \
                exp.column(name).to_pylist(), name

    def test_tpcds_shaped_file_fully_device_decoded(self, session, rng,
                                                    tmp_path):
        from spark_rapids_tpu.io.parquet_device import columns_supported
        t = tpcds_like_table(rng)
        path = str(tmp_path / "fact.parquet")
        pq.write_table(t, path, version="2.6")
        df = session.read_parquet(path)
        pf, bad = columns_supported(path, df.plan.output)
        assert bad == {}, bad  # INVERTED: nothing host-decodes
        self._assert_scan_matches(session, path)

    @pytest.mark.parametrize("use_dict", [True, False])
    def test_flba_decimals_plain_and_dict(self, session, rng, tmp_path,
                                          use_dict):
        import decimal
        n = 4000
        small = rng.integers(-10**6, 10**6, n)
        if use_dict:  # low cardinality so the dictionary engages
            small = rng.integers(0, 50, n) * 7 - 100
        vals = [decimal.Decimal(int(x)).scaleb(-2) for x in small]
        t = pa.table({"d": pa.array(vals, type=pa.decimal128(7, 2)),
                      "w": pa.array(
                          [decimal.Decimal(int(x)).scaleb(-8) * 10**9
                           for x in small], type=pa.decimal128(30, 8))})
        path = str(tmp_path / "d.parquet")
        pq.write_table(t, path, use_dictionary=use_dict)
        used, _ = _used_device_decode(session, path)
        assert used
        self._assert_scan_matches(session, path)

    def test_timestamp_millis_unit(self, session, rng, tmp_path):
        n = 2000
        t = pa.table({"ts": pa.array(rng.integers(-4 * 10**12,
                                                  4 * 10**12, n),
                                     pa.timestamp("ms"))})
        path = str(tmp_path / "ms.parquet")
        pq.write_table(t, path, version="2.4")
        used, _ = _used_device_decode(session, path)
        assert used
        self._assert_scan_matches(session, path)

    def test_int96_timestamps(self, session, rng, tmp_path):
        n = 2000
        micros = np.concatenate([
            rng.integers(-4 * 10**15, 4 * 10**15, n - 2),
            np.array([0, -1])])
        t = pa.table({"ts": pa.array(micros, pa.timestamp("us")),
                      "v": pa.array(rng.normal(size=n))})
        path = str(tmp_path / "i96.parquet")
        pq.write_table(t, path, use_deprecated_int96_timestamps=True)
        used, _ = _used_device_decode(session, path)
        assert used
        self._assert_scan_matches(session, path)

    def test_nanos_column_falls_back_siblings_on_device(
            self, session, rng, tmp_path):
        """PER-COLUMN fallback: a TIMESTAMP(NANOS) column host-decodes
        (Spark rejects NANOS outright) while its siblings still ride the
        device path; the merged batch matches pyarrow."""
        from spark_rapids_tpu.io.parquet_device import columns_supported
        n = 1500
        t = pa.table({
            "ns": pa.array(rng.integers(0, 10**15, n) * 1000,
                           pa.timestamp("ns")),
            "l": pa.array(rng.integers(-10**12, 10**12, n)),
            "s": pa.array([f"r{i % 53}" for i in range(n)])})
        path = str(tmp_path / "ns.parquet")
        pq.write_table(t, path, version="2.6")
        df = session.read_parquet(path)
        pf, bad = columns_supported(path, df.plan.output)
        assert set(bad) == {"ns"}
        self._assert_scan_matches(session, path)

    def test_file_decimal_scale_mismatch_falls_back(self, session, rng,
                                                    tmp_path):
        """A file whose decimal scale differs from the read schema must
        NOT silently decode with the wrong scale — that column host-falls
        back (where pyarrow casts), siblings stay on device."""
        import decimal
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar.batch import Schema
        from spark_rapids_tpu.io.parquet_device import columns_supported
        t = pa.table({"d": pa.array([decimal.Decimal("1.50")],
                                    type=pa.decimal128(7, 2)),
                      "l": pa.array([3], type=pa.int64())})
        path = str(tmp_path / "mm.parquet")
        pq.write_table(t, path)
        schema = Schema(("d", "l"), (T.DecimalType(7, 3), T.LongType()))
        pf, bad = columns_supported(path, schema)
        assert set(bad) == {"d"}
        # the merged batch must carry the SCAN schema's scale: 1.50 read
        # at decimal(7,3) is still 1.50 (unscaled 1500), not 0.150
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        from spark_rapids_tpu.io.parquet_device import decode_row_group
        with open(path, "rb") as f:
            b, _ = decode_row_group(pf, f, 0, schema, host_cols=bad)
        assert b.columns[0].dtype == T.DecimalType(7, 3)
        back = batch_to_arrow(b)
        assert back.column("d").to_pylist() == [decimal.Decimal("1.500")]
        assert back.column("l").to_pylist() == [3]
