"""Device parquet decode (io/parquet_device.py): PLAIN values + RLE/bit-packed
definition levels decoded on device, differential against pyarrow on
generated files (reference GpuParquetScan device decode)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def plain_table(rng, n=5000, nulls=True):
    def mk(vals):
        if not nulls:
            return pa.array(vals)
        mask = rng.random(n) < 0.2
        return pa.array(vals, mask=mask)
    return pa.table({
        "i": mk(rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "l": mk(rng.integers(-2**62, 2**62, n)),
        "f": mk(rng.normal(0, 1e3, n).astype(np.float32)),
        "d": mk(rng.normal(0, 1e6, n)),
        "b": mk(rng.integers(0, 2, n).astype(bool)),
    })


def write_plain(tmp_path, t, name="t.parquet", **kw):
    path = str(tmp_path / name)
    pq.write_table(t, path, use_dictionary=False, compression=kw.pop(
        "compression", "snappy"), **kw)
    return path


def _used_device_decode(session, path):
    from spark_rapids_tpu.io.parquet_device import (
        DeviceDecodeUnsupported, device_decode_file, file_supported)
    df = session.read_parquet(path)
    session.initialize_device()
    try:
        pf = file_supported(path, df.plan.output)
        batches = list(device_decode_file(pf, path, df.plan.output))
    except Exception:
        return False, None
    return True, batches[0][0] if batches else None


def _col_strings(col, nrows: int):
    """Decode a (possibly chunked-layout) string column to python strings."""
    import numpy as np
    from spark_rapids_tpu.columnar.strings import assemble_matrix
    mat, lens = assemble_matrix(col.data, col.lengths, col.overflow, nrows)
    return [bytes(np.asarray(mat[i, :int(lens[i])])).decode()
            for i in range(nrows)]


class TestDeviceParquetDecode:
    @pytest.mark.parametrize("compression", ["snappy", "none", "zstd"])
    def test_plain_roundtrip(self, session, rng, tmp_path, compression):
        t = plain_table(rng)
        path = write_plain(tmp_path, t, compression=compression)
        df = session.read_parquet(path)
        tpu = df.collect()
        assert tpu.num_rows == t.num_rows
        exact = pq.read_table(path)
        for name in t.schema.names:
            a = tpu.column(name).to_pylist()
            b = exact.column(name).to_pylist()
            assert a == b or all(
                (x is None and y is None) or x == y or
                (isinstance(x, float) and abs(x - y) < 1e-12)
                for x, y in zip(a, b)), name

    def test_device_path_actually_used(self, session, rng, tmp_path):
        path = write_plain(tmp_path, plain_table(rng, n=800))
        used, first = _used_device_decode(session, path)
        assert used and first is not None

    def test_no_nulls_required_like(self, session, rng, tmp_path):
        t = plain_table(rng, n=1200, nulls=False)
        path = write_plain(tmp_path, t)
        df = session.read_parquet(path)
        assert df.collect().equals(pq.read_table(path))

    def test_multiple_row_groups(self, session, rng, tmp_path):
        t = plain_table(rng, n=4000)
        path = write_plain(tmp_path, t, row_group_size=700)
        df = session.read_parquet(path)
        out = df.collect()
        exact = pq.read_table(path)
        assert out.column("l").to_pylist() == exact.column("l").to_pylist()
        assert out.column("i").to_pylist() == exact.column("i").to_pylist()

    def test_dictionary_files_take_device_path(self, session, rng,
                                               tmp_path):
        # round-2 verdict item 3 INVERTED: default pyarrow output
        # (dictionary-encoded) now decodes on device
        t = plain_table(rng, n=500)
        path = str(tmp_path / "dict.parquet")
        pq.write_table(t, path, use_dictionary=True)
        used, first = _used_device_decode(session, path)
        assert used and first is not None
        df = session.read_parquet(path)
        got = df.collect()
        exact = pq.read_table(path)
        for name in t.schema.names:
            assert got.column(name).to_pylist() == \
                exact.column(name).to_pylist(), name

    def test_plain_strings_take_device_path(self, session, rng, tmp_path):
        t = pa.table({"s": pa.array(["a", "bb", None, "ccc", "", None,
                                     "ünïcødé 字", "x" * 100])})
        path = write_plain(tmp_path, t)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == \
            t.column("s").to_pylist()

    def test_dict_strings_take_device_path(self, session, rng, tmp_path):
        n = 3000
        words = ["alpha", "beta", "gamma", "δδδ", "", "longer-value-here"]
        vals = [None if rng.random() < 0.15 else
                words[int(rng.integers(0, len(words)))] for _ in range(n)]
        t = pa.table({"s": pa.array(vals, type=pa.string()),
                      "l": pa.array(rng.integers(0, 50, n))})
        path = str(tmp_path / "ds.parquet")
        pq.write_table(t, path, use_dictionary=True)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        got = df.collect()
        assert got.column("s").to_pylist() == vals
        assert got.column("l").to_pylist() == t.column("l").to_pylist()

    def test_dict_to_plain_spill_pages(self, session, rng, tmp_path):
        # parquet writers fall back to PLAIN mid-chunk once the dictionary
        # outgrows its limit: chunks carry BOTH dict and plain data pages
        n = 6000
        vals = ["s%08d" % int(v) for v in rng.integers(0, n, n)]
        t = pa.table({"s": pa.array(vals)})
        path = str(tmp_path / "spill.parquet")
        pq.write_table(t, path, use_dictionary=True,
                       dictionary_pagesize_limit=1024, data_page_size=2048)
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == vals

    def test_dict_many_small_pages_with_nulls(self, session, rng,
                                              tmp_path):
        n = 4000
        base = rng.integers(0, 40, n)
        mask = rng.random(n) < 0.25
        t = pa.table({"v": pa.array(base * 1000, mask=mask),
                      "f": pa.array(base.astype(np.float64) / 3,
                                    mask=~mask)})
        path = str(tmp_path / "dsmall.parquet")
        pq.write_table(t, path, use_dictionary=True, data_page_size=300)
        used, _ = _used_device_decode(session, path)
        assert used
        got = session.read_parquet(path).collect()
        exact = pq.read_table(path)
        assert got.column("v").to_pylist() == exact.column("v").to_pylist()
        assert got.column("f").to_pylist() == exact.column("f").to_pylist()

    def test_overwide_strings_decode_to_chunked_layout(self, session, rng,
                                                       tmp_path):
        # beyond spark.rapids.tpu.string.maxWidth the decoder builds the
        # CHUNKED long-string layout ON DEVICE (round-4; previously a
        # per-row-group host fallback): the device path stays in use and
        # the column carries a head matrix + shared tail blob
        wide = "w" * 20000
        t = pa.table({"s": pa.array(["a", wide, "b"])})
        path = write_plain(tmp_path, t)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("s").to_pylist() == ["a", wide, "b"]
        assert df.collect_cpu().column("s").to_pylist() == ["a", wide, "b"]

    def test_megabyte_string_bounded_memory(self, session, rng, tmp_path):
        # a 1MB value must cost ~its own bytes on device, not cap * 1MB
        import numpy as np
        from spark_rapids_tpu.io.parquet_device import (device_decode_file,
                                                        file_supported)
        big = "Z" * (1 << 20)
        vals = [f"v{i}" for i in range(500)] + [big]
        t = pa.table({"s": pa.array(vals)})
        path = write_plain(tmp_path, t)
        schema = session.read_parquet(path).plan.output
        pf = file_supported(path, schema)
        batches = list(device_decode_file(pf, path, schema))
        total_bytes = sum(
            int(c.data.size) + (int(c.overflow[0].size)
                                if c.overflow is not None else 0)
            for b, _ in batches for c in b.columns)
        # head matrix (512*256) + blob (~1MB bucket) << cap * 1MB
        assert total_bytes < 4 * (1 << 20)
        got = [s for b, nr in batches
               for s in _col_strings(b.columns[0], int(nr))]
        assert got == vals

    def test_bool_across_many_small_pages(self, session, rng, tmp_path):
        # page bit-packing restarts per page: misalignment regression test
        n = 4000
        mask = rng.random(n) < 0.3
        t = pa.table({"b": pa.array(rng.integers(0, 2, n).astype(bool),
                                    mask=mask),
                      "l": pa.array(rng.integers(0, 10, n))})
        path = str(tmp_path / "b.parquet")
        pq.write_table(t, path, use_dictionary=False, data_page_size=100)
        used, _ = _used_device_decode(session, path)
        assert used
        df = session.read_parquet(path)
        assert df.collect().column("b").to_pylist() == \
            pq.read_table(path).column("b").to_pylist()

    def test_lz4_files_fall_back_cleanly(self, session, rng, tmp_path):
        t = plain_table(rng, n=300)
        path = str(tmp_path / "lz4.parquet")
        pq.write_table(t, path, use_dictionary=False, compression="lz4")
        used, _ = _used_device_decode(session, path)
        assert not used
        df = session.read_parquet(path)
        assert df.collect().num_rows == 300  # host path still works

    def test_v2_pages_fall_back_cleanly(self, session, rng, tmp_path):
        t = plain_table(rng, n=400)
        path = str(tmp_path / "v2.parquet")
        pq.write_table(t, path, use_dictionary=False,
                       data_page_version="2.0")
        df = session.read_parquet(path)  # must not crash
        got = df.collect()
        assert got.column("l").to_pylist() == \
            pq.read_table(path).column("l").to_pylist()

    def test_empty_file(self, session, tmp_path):
        t = pa.table({"i": pa.array([], type=pa.int32())})
        path = str(tmp_path / "empty.parquet")
        pq.write_table(t, path, use_dictionary=False)
        df = session.read_parquet(path)
        assert df.collect().num_rows == 0

    def test_query_over_device_decoded_scan(self, session, rng, tmp_path):
        from spark_rapids_tpu.expr import Count, Sum, col
        t = plain_table(rng, n=3000)
        path = write_plain(tmp_path, t)
        df = session.read_parquet(path)
        q = df.group_by("b").agg(c=Count(col("l")), s=Sum(col("i")))
        tpu = q.collect().sort_by([("b", "ascending")])
        cpu = q.collect_cpu().sort_by([("b", "ascending")])
        assert tpu.column("c").to_pylist() == cpu.column("c").to_pylist()
        assert tpu.column("s").to_pylist() == cpu.column("s").to_pylist()
