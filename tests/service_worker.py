"""Worker-process entry for the device-owner service tests.

Runs in its OWN OS process (launched by tests/test_service.py): connects
to the service socket, contends for cross-process admission, optionally
holds its token until the orchestrating test allows release, optionally
submits a Spark-plan JSON, and reports what happened as one JSON line on
stdout. Mirrors how a Spark executor process would use the service
(reference: tasks blocking on GpuSemaphore.scala:67 before touching the
device)."""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_tpu.service import TpuServiceClient  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    ap.add_argument("--name", required=True)
    ap.add_argument("--deadline", type=float, default=30.0)
    ap.add_argument("--enter-marker", default=None,
                    help="file to create just before calling acquire")
    ap.add_argument("--held-marker", default=None,
                    help="file to create once admitted")
    ap.add_argument("--hold-until", default=None,
                    help="file to wait for before releasing")
    ap.add_argument("--plan", default=None, help="plan JSON file")
    ap.add_argument("--paths", default=None, help="ident->paths JSON")
    args = ap.parse_args()

    out = {"name": args.name}
    with TpuServiceClient(args.socket, deadline_s=args.deadline) as cli:
        out["t_enter_acquire"] = time.time()
        if args.enter_marker:
            with open(args.enter_marker, "w") as f:
                f.write(args.name)
        out["order"] = cli.acquire(timeout=args.deadline)
        out["t_acquired"] = time.time()
        if args.held_marker:
            with open(args.held_marker, "w") as f:
                f.write(json.dumps(out))
        if args.hold_until:
            t0 = time.time()
            while not os.path.exists(args.hold_until):
                if time.time() - t0 > args.deadline:
                    raise TimeoutError("hold-until file never appeared")
                time.sleep(0.01)
        if args.plan:
            with open(args.plan) as f:
                plan_json = f.read()
            paths = json.loads(args.paths) if args.paths else {}
            table = cli.run_plan(plan_json, paths)
            out["num_rows"] = table.num_rows
            out["columns"] = table.schema.names
        cli.release()
        out["t_released"] = time.time()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
