"""Result & fragment cache suite (marker `rescache`;
scripts/rescache_matrix.sh runs these standalone).

Covers: canonical plan fingerprints (golden digests + property tests +
cross-process stability), the four caching seams (whole-query / scan /
exchange / broadcast) with bit-identical hit results, the cache-hit
admission fast path (a whole-query hit consumes no scheduler grant),
single-flight dedup of concurrent identical queries, cost-aware eviction
under a tight capacity, `cache.fragment` fault degrade, mid-flight
eviction degrade, source invalidation (file rewrite, delta commit), and
the off-path zero-state contract."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import faults, rescache, telemetry
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.rescache.fingerprint import fingerprint
from spark_rapids_tpu.utils.metrics import TaskMetrics

pytestmark = pytest.mark.rescache

GOLDEN_PATH = os.path.join(os.path.dirname(__file__),
                           "golden_fingerprints.json")


@pytest.fixture(autouse=True)
def _clean_cache():
    yield
    rescache.shutdown()
    telemetry.shutdown()
    TpuSemaphore._instance = None
    from spark_rapids_tpu.utils import durable
    durable.reset_for_tests()


def _session(**conf):
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.rescache.enabled": True}
    base.update(conf)
    return TpuSession(base)


def _table(n=6000, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 64, n)),
        "g": pa.array(rng.integers(0, 16, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
    })


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def _golden_plans(sess):
    """Range-rooted plans only: no in-memory table identity, no file
    stat — these digests are stable across processes AND regenerations,
    which is what the golden file asserts."""
    r = sess.range(1000)
    return {
        "range": r.plan,
        "project": r.select((col("id") * 2 + 1).alias("x")).plan,
        "filter": r.filter(col("id") % 7 == lit(3)).plan,
        "agg": r.select((col("id") % 10).alias("g"), col("id").alias("v"))
               .group_by("g").agg(total=Sum(col("v")),
                                  cnt=Count(col("v"))).plan,
        "sort_limit": r.sort(col("id"), ascending=False).limit(17).plan,
        "round2": r.select(
            (col("id").cast(T.DOUBLE) / 7).alias("d")).select(
            col("d").alias("r")).plan,
    }


class TestFingerprint:
    def test_structurally_equal_plans_hash_equal(self):
        sess = _session()
        a = _golden_plans(sess)
        b = _golden_plans(sess)
        for name in a:
            fa = fingerprint(a[name], sess.conf)
            fb = fingerprint(b[name], sess.conf)
            assert fa is not None and fa.digest == fb.digest, name

    def test_golden_fingerprints(self):
        """Golden digests pinned in tests/golden_fingerprints.json —
        regenerate deliberately with SRTPU_REGEN_GOLDEN_FP=1 when the
        fingerprint recipe changes (a silent change here silently
        invalidates every cache on upgrade, which is safe but should be
        a reviewed decision, and a silent ALIAS would be a wrong-results
        bug — hence the pin)."""
        sess = _session()
        digests = {name: fingerprint(plan, sess.conf).digest
                   for name, plan in _golden_plans(sess).items()}
        if os.environ.get("SRTPU_REGEN_GOLDEN_FP") or \
                not os.path.exists(GOLDEN_PATH):
            with open(GOLDEN_PATH, "w") as f:
                json.dump(digests, f, indent=2, sort_keys=True)
        with open(GOLDEN_PATH) as f:
            golden = json.load(f)
        assert digests == golden

    def test_cross_process_stability(self):
        """The same plan fingerprints to the same digest in a fresh
        process — the contract a persistent/shared cache tier would
        build on."""
        sess = _session()
        here = fingerprint(_golden_plans(sess)["agg"], sess.conf).digest
        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import sys; sys.path.insert(0, %r)\n"
            "sys.path.insert(0, %r)\n"
            "from test_rescache import _golden_plans, _session\n"
            "from spark_rapids_tpu.rescache.fingerprint import fingerprint\n"
            "s = _session()\n"
            "print(fingerprint(_golden_plans(s)['agg'], s.conf).digest)\n"
        ) % (os.path.dirname(os.path.dirname(__file__)),
             os.path.dirname(__file__))
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip().splitlines()[-1] == here

    def test_literal_and_expr_params_change_key(self):
        sess = _session()
        r = sess.range(100)
        from spark_rapids_tpu.expr.math_ import Round
        from spark_rapids_tpu.expr.predicates import In
        d = col("id").cast(T.DOUBLE)
        pairs = [
            (r.filter(col("id") > 5).plan, r.filter(col("id") > 6).plan),
            (r.select(Round(d, 0).alias("x")).plan,
             r.select(Round(d, 2).alias("x")).plan),
            (r.filter(In(col("id"), [1, 2])).plan,
             r.filter(In(col("id"), [1, 3])).plan),
        ]
        for a, b in pairs:
            fa, fb = fingerprint(a, sess.conf), fingerprint(b, sess.conf)
            assert fa is not None and fb is not None
            assert fa.digest != fb.digest

    def test_conf_changes_key(self):
        sess = _session()
        plan = sess.range(100).select((col("id") + 1).alias("x")).plan
        base = fingerprint(plan, sess.conf).digest
        ansi = _session(**{"spark.rapids.sql.ansi.enabled": True})
        assert fingerprint(plan, ansi.conf).digest != base
        # explicitly-set per-expression enable keys join the key too
        off = _session(**{"spark.rapids.sql.expression.Add": False})
        assert fingerprint(plan, off.conf).digest != base

    def test_file_identity_changes_key(self, tmp_path):
        sess = _session()
        p = str(tmp_path / "f.parquet")
        pq.write_table(_table(500), p)
        plan = sess.read_parquet(p).plan
        k1 = fingerprint(plan, sess.conf).digest
        time.sleep(0.02)
        pq.write_table(_table(500, seed=9), p)
        plan2 = sess.read_parquet(p).plan
        k2 = fingerprint(plan2, sess.conf).digest
        assert k1 != k2

    def test_delta_version_changes_key(self, tmp_path):
        from spark_rapids_tpu.datasources.delta.table import DeltaTable
        sess = _session()
        t = DeltaTable.create(sess, str(tmp_path / "dt"), _table(300))
        k1 = fingerprint(t.to_df().plan, sess.conf).digest
        k1b = fingerprint(t.to_df().plan, sess.conf).digest
        assert k1 == k1b  # same version: fresh arrow tables, same key
        t.delete(col("k") < lit(5))  # commits a new version
        k2 = fingerprint(t.to_df().plan, sess.conf).digest
        assert k2 != k1

    def test_nondeterministic_subtree_no_key(self):
        from spark_rapids_tpu.expr.misc import MonotonicallyIncreasingID
        sess = _session()
        plan = sess.range(100).select(
            MonotonicallyIncreasingID().alias("id2")).plan
        assert fingerprint(plan, sess.conf) is None

    def test_spi_udf_uncacheable_even_when_deterministic(self):
        """A ColumnarUDFExpr wraps an opaque user callable its repr cannot
        render: two UDFs registered under the same name with different
        logic would alias, so UDF subtrees are fail-closed uncacheable
        even with deterministic=True (the SPI default)."""
        from spark_rapids_tpu.udf.spi import TpuUDF

        class Doubler(TpuUDF):
            return_type = T.DOUBLE
            deterministic = True

            def evaluate_columnar(self, xp, v):
                from spark_rapids_tpu.expr.base import Vec
                return Vec(T.DOUBLE, v.data * 2, v.validity)

        sess = _session()
        plan = sess.range(100).select(
            Doubler()(col("id").cast(T.DOUBLE)).alias("x")).plan
        assert fingerprint(plan, sess.conf) is None

    def test_unknown_node_class_fails_closed(self):
        from spark_rapids_tpu.plan.nodes import CpuRangeExec, PhysicalPlan

        class MysteryExec(PhysicalPlan):
            @property
            def output(self):
                return self.children[0].output

        sess = _session()
        plan = MysteryExec([CpuRangeExec(0, 10)])
        assert fingerprint(plan, sess.conf) is None

    def test_in_memory_table_identity_and_weakref(self):
        sess = _session()
        t = _table(200)
        k1 = fingerprint(sess.from_arrow(t).plan, sess.conf)
        k2 = fingerprint(sess.from_arrow(t).plan, sess.conf)
        assert k1.digest == k2.digest  # same table object, same key
        assert k1.valid()
        t2 = _table(200)  # equal content, DIFFERENT object => different key
        k3 = fingerprint(sess.from_arrow(t2).plan, sess.conf)
        assert k3.digest != k1.digest
        del t2
        import gc
        gc.collect()
        assert not k3.valid()  # freed source: validators turn hits into misses


# ---------------------------------------------------------------------------
# whole-query seam
# ---------------------------------------------------------------------------

class TestQuerySeam:
    def test_hit_bit_identical_and_counted(self):
        sess = _session()
        df = sess.from_arrow(_table()).filter(col("v") > 0.3) \
            .group_by("g").agg(total=Sum(col("v")), cnt=Count(col("k")))
        r1 = df.collect()
        r2 = df.collect()
        assert r1.equals(r2)
        tm = TaskMetrics.get()
        assert tm.rescache_hits == 1
        s = rescache.stats()
        assert s["hits"]["query"] == 1 and s["stores"]["query"] == 1

    def test_hit_skips_device_admission(self):
        """The fast path: a whole-query hit answers without a scheduler
        grant — TaskMetrics.sched_admissions stays 0 (the acceptance
        assertion for 'no device admission token')."""
        sess = _session(**{"spark.rapids.tpu.sched.enabled": True})
        sess.initialize_device()
        TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)
        df = sess.from_arrow(_table()).group_by("g").agg(s=Sum(col("v")))
        r1 = df.collect()
        assert TaskMetrics.get().sched_admissions == 1  # cold run admits
        r2 = df.collect()
        tm = TaskMetrics.get()
        assert r1.equals(r2)
        assert tm.rescache_hits == 1
        assert tm.sched_admissions == 0
        assert tm.semaphore_wait_ns == 0

    def test_single_flight_dedups_concurrent_queries(self):
        sess = _session(**{"spark.rapids.tpu.sched.enabled": True})
        sess.initialize_device()
        TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)
        df = sess.from_arrow(_table(20000)).group_by("g") \
            .agg(s=Sum(col("v")), c=Count(col("k")))
        results, errs = [], []

        def worker():
            try:
                results.append(df.collect())
            except Exception as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60)
        assert not errs
        assert all(r.equals(results[0]) for r in results)
        s = rescache.stats()
        # ONE execution stored; every other identical query either parked
        # on the single-flight marker or arrived after the store — all
        # serve the same entry
        assert s["stores"]["query"] == 1
        assert s["hits"]["query"] == 5

    def test_fault_degrades_to_recompute(self):
        sess = _session()
        df = sess.from_arrow(_table()).group_by("g").agg(s=Sum(col("v")))
        r1 = df.collect()
        with faults.inject(faults.CACHE_FRAGMENT, kind="error", nth=0,
                           times=0):
            r2 = df.collect()
        assert r1.equals(r2)
        tm = TaskMetrics.get()
        assert tm.rescache_degraded >= 1 and tm.rescache_hits == 0

    def test_uncacheable_query_runs_and_stores_nothing(self):
        from spark_rapids_tpu.expr.misc import MonotonicallyIncreasingID
        sess = _session()
        df = sess.from_arrow(_table(100)).select(
            col("v"), MonotonicallyIncreasingID().alias("rid"))
        r1 = df.collect()
        r2 = df.collect()
        assert r1.num_rows == r2.num_rows == 100
        s = rescache.stats()
        assert s["stores"].get("query", 0) == 0

    def test_unstorable_result_latches_to_bypass(self):
        """A fingerprint whose result can never be stored (here: below
        the min-recompute floor) must not keep single-flighting — later
        identical queries bypass the owner protocol and run
        concurrently."""
        sess = _session(
            **{"spark.rapids.tpu.rescache.minRecomputeMs": 1e9})
        df = sess.from_arrow(_table(2000)).group_by("g").agg(
            s=Sum(col("v")))
        r1 = df.collect()
        r2 = df.collect()
        assert r1.equals(r2)
        s = rescache.stats()
        assert s["stores"].get("query", 0) == 0
        assert s["unstorable"] >= 1
        assert s["misses"]["query"] >= 2  # second run bypassed, not parked

    def test_off_path_zero_state(self):
        rescache.shutdown()
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        df = sess.from_arrow(_table(500)).group_by("g").agg(
            s=Sum(col("v")))
        df.collect()
        assert not rescache.is_enabled()
        assert rescache.get() is None
        assert rescache.stats() is None


# ---------------------------------------------------------------------------
# fragment seams
# ---------------------------------------------------------------------------

def _write_parquet(tmp_path, name="f.parquet", n=40000, seed=5,
                   row_group_size=4096):
    rng = np.random.default_rng(seed)
    t = pa.table({"k": pa.array(rng.integers(0, 64, n)),
                  "v": pa.array(rng.uniform(size=n))})
    p = str(tmp_path / name)
    pq.write_table(t, p, row_group_size=row_group_size)
    return p, t


class TestFragmentSeams:
    def test_scan_hit_bit_identical(self, tmp_path):
        p, _ = _write_parquet(tmp_path)
        sess = _session(
            **{"spark.rapids.tpu.rescache.query.enabled": False})

        def q():
            return (sess.read_parquet(p).filter(col("v") > 0.5)
                    .group_by("k").agg(total=Sum(col("v")))
                    ).collect().sort_by("k")

        r1 = q()
        r2 = q()
        assert r1.equals(r2)
        s = rescache.stats()
        assert s["hits"].get("scan", 0) >= 1

    def test_scan_invalidation_on_rewrite(self, tmp_path):
        p, _ = _write_parquet(tmp_path)
        sess = _session(
            **{"spark.rapids.tpu.rescache.query.enabled": False})

        def q():
            return (sess.read_parquet(p).group_by("k")
                    .agg(c=Count(col("v")))).collect().sort_by("k")

        r1 = q()
        time.sleep(0.02)
        _write_parquet(tmp_path, n=40000, seed=77)
        r2 = q()
        assert not r2.equals(r1)
        # the rewritten file's recompute matches a cache-dropped rerun
        rescache.invalidate()
        assert q().equals(r2)

    def test_exchange_hit(self):
        sess = _session(
            **{"spark.rapids.tpu.rescache.query.enabled": False})
        f = sess.from_arrow(_table(30000))

        def q():
            return (f.repartition(4, "k").group_by("k")
                    .agg(total=Sum(col("v")))).collect().sort_by("k")

        r1 = q()
        r2 = q()
        assert r1.equals(r2)
        assert rescache.stats()["hits"].get("exchange", 0) >= 1

    def test_broadcast_hit(self):
        rng = np.random.default_rng(7)
        n = 20000
        fact = pa.table({"k": pa.array(rng.integers(0, 100, n)),
                         "v": pa.array(rng.uniform(size=n))})
        dim = pa.table({"k": pa.array(np.arange(100)),
                        "w": pa.array(rng.uniform(size=100))})
        sess = _session(
            **{"spark.rapids.tpu.rescache.query.enabled": False})
        f, d = sess.from_arrow(fact), sess.from_arrow(dim)

        def q():
            return (f.join(d, on="k").group_by("k")
                    .agg(total=Sum(col("v") * col("w")))
                    ).collect().sort_by("k")

        r1 = q()
        r2 = q()
        assert r1.equals(r2)
        assert rescache.stats()["hits"].get("broadcast", 0) >= 1

    def test_eviction_under_tight_budget(self, tmp_path):
        """A capacity far below the working set evicts (cost-aware LRU)
        while every query stays correct."""
        cap = 1 << 20  # holds roughly one scan's fragments, not four
        sess = _session(**{
            "spark.rapids.tpu.rescache.query.enabled": False,
            "spark.rapids.tpu.rescache.maxBytes": cap,
        })
        paths = []
        for i in range(4):
            p, _ = _write_parquet(tmp_path, name=f"f{i}.parquet", n=20000,
                                  seed=i)
            paths.append(p)
        results = {}
        for p in paths:
            results[p] = (sess.read_parquet(p).group_by("k")
                          .agg(s=Sum(col("v")))).collect().sort_by("k")
        for p in paths:  # second sweep: some hit, some evicted+recompute
            again = (sess.read_parquet(p).group_by("k")
                     .agg(s=Sum(col("v")))).collect().sort_by("k")
            assert again.equals(results[p])
        s = rescache.stats()
        assert s["evictions"] >= 1
        assert s["bytes"] <= cap

    def test_mid_flight_eviction_degrades_to_recompute(self, tmp_path):
        """Start serving a scan hit, invalidate the cache under it (closes
        the fragments), and the stream degrades to a fresh produce that
        skips already-served batches — total output identical."""
        p, t = _write_parquet(tmp_path, row_group_size=4096)
        sess = _session(**{
            "spark.rapids.tpu.rescache.query.enabled": False,
            "spark.rapids.tpu.pipeline.enabled": False,  # 1 batch per rg
        })
        sess.initialize_device()
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        from spark_rapids_tpu.plan.overrides import Overrides

        def scan_exec():
            return Overrides(sess.conf).apply(sess.read_parquet(p).plan)

        # populate the cache
        cold = [batch_to_arrow(b) for b in scan_exec().execute()]
        assert len(cold) > 2
        # hit stream, killed mid-flight
        it = scan_exec().execute()
        got = [batch_to_arrow(next(it))]
        assert rescache.stats()["hits"].get("scan", 0) == 1
        rescache.invalidate()  # closes the fragments being served
        got.extend(batch_to_arrow(b) for b in it)
        warm = pa.concat_tables(got)
        assert warm.num_rows == t.num_rows
        assert warm.equals(pa.concat_tables(cold))
        assert TaskMetrics.get().rescache_degraded >= 1

    def test_fragment_fault_on_store_skips_silently(self, tmp_path):
        p, t = _write_parquet(tmp_path, n=8000)
        sess = _session(
            **{"spark.rapids.tpu.rescache.query.enabled": False})
        with faults.inject(faults.CACHE_FRAGMENT, kind="error", nth=0,
                           times=0):
            r1 = (sess.read_parquet(p).group_by("k")
                  .agg(s=Sum(col("v")))).collect().sort_by("k")
        s = rescache.stats()
        assert s["stores"].get("scan", 0) == 0
        r2 = (sess.read_parquet(p).group_by("k")
              .agg(s=Sum(col("v")))).collect().sort_by("k")
        assert r1.equals(r2)


# ---------------------------------------------------------------------------
# observability satellites
# ---------------------------------------------------------------------------

class TestObservability:
    def test_cached_relation_gauge_and_unpersist(self):
        import re
        telemetry.configure(TpuSession({
            "spark.rapids.tpu.telemetry.enabled": True}).conf)
        sess = _session(**{"spark.rapids.tpu.telemetry.enabled": True})
        dfc = sess.from_arrow(_table(4000)).select("g", "v").cache()
        dfc.collect()
        text = telemetry.render_prometheus()
        m = re.search(r"tpu_cached_relation_bytes (\d+)", text)
        assert m and int(m.group(1)) > 0
        dfc.unpersist()
        m2 = re.search(r"tpu_cached_relation_bytes (\d+)",
                       telemetry.render_prometheus())
        assert m2 and int(m2.group(1)) == 0

    def test_dpp_footer_error_counter(self, tmp_path):
        import re
        telemetry.configure(TpuSession({
            "spark.rapids.tpu.telemetry.enabled": True}).conf)
        from spark_rapids_tpu.io.dynamic_pruning import (DynamicKeyFilter,
                                                         prune_parquet_paths)
        f = DynamicKeyFilter("k")
        f.set_values(np.array([1, 2, 3]))
        bad = str(tmp_path / "bad.parquet")
        with open(bad, "wb") as fh:
            fh.write(b"not a parquet file")
        kept, pruned = prune_parquet_paths([bad], [f])
        assert kept == [bad] and pruned == 0  # kept, never a gate
        m = re.search(r"tpu_dpp_footer_errors_total (\d+)",
                      telemetry.render_prometheus())
        assert m and int(m.group(1)) >= 1

    def test_rescache_telemetry_families(self):
        import re
        sess = _session(**{"spark.rapids.tpu.telemetry.enabled": True})
        df = sess.from_arrow(_table(3000)).group_by("g").agg(
            s=Sum(col("v")))
        df.collect()
        df.collect()
        text = telemetry.render_prometheus()
        assert re.search(
            r'tpu_rescache_hits_total\{seam="query",tenant="default"\} 1',
            text)
        assert "tpu_rescache_bytes" in text
        assert "tpu_rescache_entries" in text

    def test_explain_string_reports_cache_counters(self):
        sess = _session()
        df = sess.from_arrow(_table(2000)).group_by("g").agg(
            s=Sum(col("v")))
        df.collect()
        df.collect()
        line = TaskMetrics.get().explain_string()
        assert "rescacheHits=1" in line

    def test_profile_report_cache_section(self, tmp_path):
        from spark_rapids_tpu.tools.profile_report import (build_model,
                                                           cache_summary,
                                                           load_records)
        log_dir = str(tmp_path / "logs")
        sess = _session(**{
            "spark.rapids.tpu.rescache.query.enabled": False,
            "spark.rapids.tpu.metrics.eventLog.dir": log_dir})
        f = sess.from_arrow(_table(8000))

        def q():
            return (f.repartition(2, "k").group_by("k")
                    .agg(s=Sum(col("v")))).collect()

        q()
        q()
        records, problems = load_records([log_dir], validate=True)
        assert not problems
        summary = cache_summary(build_model(records))
        assert summary, "cache section missing"
        assert summary["per_seam"].get("exchange", {}).get("hits", 0) >= 1


# ---------------------------------------------------------------------------
# service ops
# ---------------------------------------------------------------------------

class TestServiceOps:
    def test_cache_stats_and_invalidate_ops(self, tmp_path):
        import socket

        from spark_rapids_tpu.service.client import TpuServiceClient
        from spark_rapids_tpu.service.server import TpuDeviceService
        sock = str(tmp_path / "svc.sock")
        svc = TpuDeviceService({"spark.rapids.tpu.rescache.enabled": True},
                               sock)
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        try:
            with TpuServiceClient(sock, deadline_s=30) as c:
                stats = c.cache_stats()
                assert "entries" in stats and "hits" in stats
                assert c.cache_invalidate() == 0
        finally:
            try:
                with TpuServiceClient(sock, deadline_s=5) as c:
                    c.shutdown()
            except Exception:
                pass
            th.join(timeout=10)

    def test_cache_ops_disabled_error(self, tmp_path):
        import threading as _t

        from spark_rapids_tpu.service.client import TpuServiceClient
        from spark_rapids_tpu.service.server import TpuDeviceService
        rescache.shutdown()
        sock = str(tmp_path / "svc2.sock")
        svc = TpuDeviceService({}, sock)
        th = _t.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        try:
            with TpuServiceClient(sock, deadline_s=30) as c:
                with pytest.raises(RuntimeError):
                    c.cache_stats()
                with pytest.raises(RuntimeError):
                    c.cache_invalidate()
        finally:
            try:
                with TpuServiceClient(sock, deadline_s=5) as c:
                    c.shutdown()
            except Exception:
                pass
            th.join(timeout=10)


# ---------------------------------------------------------------------------
# determinism / repr audit regressions
# ---------------------------------------------------------------------------

class TestExprAudit:
    def test_nondeterministic_marks(self):
        from spark_rapids_tpu.expr.misc import (InputFileName,
                                                MonotonicallyIncreasingID,
                                                SparkPartitionID)
        from spark_rapids_tpu.udf.pandas_udf import PandasUDF
        for cls in (SparkPartitionID, MonotonicallyIncreasingID,
                    InputFileName, PandasUDF):
            assert cls.deterministic is False, cls.__name__

    def test_param_faithful_reprs(self):
        """Every expression param that changes the traced program must be
        visible in repr — the PR-3/PR-4 compile-cache aliasing bug class,
        which the rescache fingerprint inherits."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.expr.base import AttributeReference as A
        from spark_rapids_tpu.expr.collections import (CreateNamedStruct,
                                                       SortArray)
        from spark_rapids_tpu.expr.datetime_ import (MonthsBetween, NextDay,
                                                     TruncDate,
                                                     TruncTimestamp)
        from spark_rapids_tpu.expr.hashing import Murmur3Hash
        from spark_rapids_tpu.expr.hashing_ext import Sha2, XxHash64
        from spark_rapids_tpu.expr.json_ import JsonToStructs
        from spark_rapids_tpu.expr.maps import StringToMap
        from spark_rapids_tpu.expr.math_ import BRound, Round
        from spark_rapids_tpu.expr.predicates import In
        from spark_rapids_tpu.expr.splits import ArraysZip, StringSplit
        from spark_rapids_tpu.expr.windowexprs import Lag, Lead
        c = A("x", T.INT)
        s = A("s", T.STRING)
        d = A("d", T.DATE)
        arr = A("a", T.ArrayType(T.INT))
        pairs = [
            (Round(c, 0), Round(c, 2)),
            (BRound(c, 0), BRound(c, 2)),
            (In(c, [1]), In(c, [2, 3])),
            (TruncDate(d, "MM"), TruncDate(d, "YEAR")),
            (TruncTimestamp("MM", d), TruncTimestamp("YEAR", d)),
            (NextDay(d, "MO"), NextDay(d, "TU")),
            (MonthsBetween(d, d, True), MonthsBetween(d, d, False)),
            (Murmur3Hash(c, seed=42), Murmur3Hash(c, seed=7)),
            (Sha2(s, 256), Sha2(s, 512)),
            (XxHash64([c], 42), XxHash64([c], 7)),
            (SortArray(arr, True), SortArray(arr, False)),
            (CreateNamedStruct(["a"], [c]), CreateNamedStruct(["b"], [c])),
            (StringToMap(s, ",", ":"), StringToMap(s, ";", "=")),
            (JsonToStructs(s, T.StructType([T.StructField("a", T.INT)])),
             JsonToStructs(s, T.StructType([T.StructField("b", T.LONG)]))),
            (StringSplit(s, ",", -1), StringSplit(s, ",", 2)),
            (ArraysZip([arr], ["x"]), ArraysZip([arr], ["y"])),
            (Lead(c, 1, None), Lead(c, 1, 0)),
            (Lag(c, 1, None), Lag(c, 1, 9)),
        ]
        for a, b in pairs:
            assert repr(a) != repr(b), type(a).__name__

    def test_round_scale_no_longer_aliases_in_compile_cache(self):
        """End-to-end regression for the aliasing class: round(x, 0) and
        round(x, 2) in back-to-back queries must produce different
        results (a shared cached executable would serve the first's
        program for the second)."""
        from spark_rapids_tpu.expr.math_ import Round
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = pa.table({"v": pa.array([1.2345, 2.7182, 3.1415])})
        d = col("v")
        r0 = sess.from_arrow(t).select(Round(d, 0).alias("r")).collect()
        r2 = sess.from_arrow(t).select(Round(d, 2).alias("r")).collect()
        assert r0.column("r").to_pylist() == [1.0, 3.0, 3.0]
        assert r2.column("r").to_pylist() == [1.23, 2.72, 3.14]


# ---------------------------------------------------------------------------
# persistent whole-query result tier (PR 14: crash -> restart -> warm)
# ---------------------------------------------------------------------------
class TestPersistTier:
    def _write_data(self, tmp_path, seed=3):
        path = str(tmp_path / "t.parquet")
        pq.write_table(_table(2000, seed=seed), path)
        return path

    def _conf(self, tmp_path, **extra):
        base = {"spark.rapids.tpu.rescache.persist.dir":
                str(tmp_path / "persist"),
                "spark.rapids.tpu.rescache.persist.warmup.enabled": False}
        base.update(extra)
        return base

    def _query(self, sess, path):
        return sess.read_parquet(path).group_by("g").agg(s=Sum(col("v")))

    def _restart(self, tmp_path, **extra):
        """Simulate process restart: drop every in-memory cache object,
        re-configure from a fresh session (the persisted files are what
        survives)."""
        rescache.shutdown()
        return _session(**self._conf(tmp_path, **extra))

    def test_cold_store_restart_warm_zero_admissions(self, tmp_path):
        path = self._write_data(tmp_path)
        sess = _session(**self._conf(tmp_path))
        cold = self._query(sess, path).collect()
        p = rescache.persist_tier()
        assert p is not None and p.stats_dict()["stores"] == 1
        assert len(os.listdir(str(tmp_path / "persist"))) == 1

        sess2 = self._restart(tmp_path)
        TaskMetrics.reset()
        warm = self._query(sess2, path).collect()
        assert warm.equals(cold)
        tm = TaskMetrics.get()
        assert tm.rescache_persist_hits == 1
        assert tm.sched_admissions == 0, \
            "persistent-tier hit must not touch the device doors"
        assert rescache.persist_tier().stats_dict()["hits"] == 1
        # now resident in memory: the next hit is a plain memory hit
        warm2 = self._query(sess2, path).collect()
        assert warm2.equals(cold)
        assert rescache.persist_tier().stats_dict()["hits"] == 1

    def test_background_warmup_preloads_memory(self, tmp_path):
        path = self._write_data(tmp_path)
        sess = _session(**self._conf(tmp_path))
        self._query(sess, path).collect()
        rescache.shutdown()
        _session(**self._conf(
            tmp_path,
            **{"spark.rapids.tpu.rescache.persist.warmup.enabled": True})
        ).initialize_device()
        t0 = time.time()
        while time.time() - t0 < 20:
            if rescache.persist_tier().stats_dict()["warmed"] >= 1:
                break
            time.sleep(0.02)
        assert rescache.persist_tier().stats_dict()["warmed"] == 1
        assert rescache.get().entry_count == 1

    def test_corrupt_entry_is_miss_delete_then_repersist(self, tmp_path):
        path = self._write_data(tmp_path)
        sess = _session(**self._conf(tmp_path))
        cold = self._query(sess, path).collect()
        pdir = str(tmp_path / "persist")
        [entry] = os.listdir(pdir)
        fp = os.path.join(pdir, entry)
        with open(fp, "r+b") as f:
            f.seek(os.path.getsize(fp) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))

        sess2 = self._restart(tmp_path)
        warm = self._query(sess2, path).collect()
        assert warm.equals(cold), "poisoned entry must never serve bytes"
        stats = rescache.persist_tier().stats_dict()
        assert stats["poisoned"] == 1
        assert stats["hits"] == 0
        # the recompute re-persisted a good entry over the deleted one
        assert stats["stores"] == 1
        assert len(os.listdir(pdir)) == 1

    def test_validator_fingerprints_never_persist(self, tmp_path):
        sess = _session(**self._conf(tmp_path))
        t = _table(500)
        sess.from_arrow(t).group_by("g").agg(s=Sum(col("v"))).collect()
        # in-memory table identity = weakref validator = process-local:
        # nothing may reach disk
        assert os.listdir(str(tmp_path / "persist")) == []

    def test_invalidate_wipes_disk_too(self, tmp_path):
        path = self._write_data(tmp_path)
        sess = _session(**self._conf(tmp_path))
        self._query(sess, path).collect()
        assert len(os.listdir(str(tmp_path / "persist"))) == 1
        rescache.invalidate()
        # the invalidate hammer exists for in-place rewrites file
        # identity can't see — a restart must not resurrect them
        assert os.listdir(str(tmp_path / "persist")) == []

    def test_io_failure_degrades_to_memory_only(self, tmp_path):
        import warnings as _w
        from spark_rapids_tpu.errors import PersistenceDegradedWarning
        path = self._write_data(tmp_path)
        sess = _session(**self._conf(tmp_path))
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            with faults.inject(faults.PERSIST, "error", nth=1, times=1,
                               error=IOError) as rule:
                cold = self._query(sess, path).collect()
        assert rule.fired == 1
        assert cold.num_rows > 0
        assert any(isinstance(w.message, PersistenceDegradedWarning)
                   for w in caught)
        p = rescache.persist_tier()
        assert p.stats_dict()["degraded"] and not p.available()
        # memory tier still serves; the degraded tier stays silent
        warm = self._query(sess, path).collect()
        assert warm.equals(cold)
        # nth=1 hit the tier's very first op (mkdir): the dir may not
        # even exist — either way, nothing reached disk
        pdir = str(tmp_path / "persist")
        assert not os.path.isdir(pdir) or os.listdir(pdir) == []

    def test_rewritten_source_misses_naturally(self, tmp_path):
        path = self._write_data(tmp_path, seed=3)
        sess = _session(**self._conf(tmp_path))
        old = self._query(sess, path).collect()
        # rewrite the source with DIFFERENT data: mtime/size/content all
        # change, and they live INSIDE the fingerprint
        pq.write_table(_table(2100, seed=9), path)
        sess2 = self._restart(tmp_path)
        new = self._query(sess2, path).collect()
        assert not new.equals(old), "stale persisted result served"
        assert rescache.persist_tier().stats_dict()["hits"] == 0

    def test_gc_bounds_directory_bytes(self, tmp_path):
        from spark_rapids_tpu.rescache.persist import PersistentResultTier
        tier = PersistentResultTier(str(tmp_path / "p"), max_bytes=1)
        # every stored entry exceeds 1 byte: nothing may persist
        assert not tier.store("d" * 64, _table(100), "query", 10)
        tier2 = PersistentResultTier(str(tmp_path / "p2"),
                                     max_bytes=1 << 20)
        for i in range(6):
            assert tier2.store(f"{i:064x}", _table(3000, seed=i),
                               "query", 10)
            time.sleep(0.02)  # distinct mtimes for the GC ordering
        total = sum(os.path.getsize(os.path.join(str(tmp_path / "p2"), f))
                    for f in os.listdir(str(tmp_path / "p2")))
        assert total <= 1 << 20
