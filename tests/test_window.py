"""Window-function differential tests (reference coverage model:
`integration_tests/src/main/python/window_function_test.py` — each case runs on
the CPU oracle and the TPU engine and must agree exactly)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (Average, Count, CumeDist, DenseRank, First,
                                   Lag, Last, Lead, Max, Min, NTile,
                                   PercentRank, Rank, RowFrame, RowNumber, Sum,
                                   WindowAggregate, col)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def window_table(rng, n=500, null_frac=0.15):
    groups = rng.integers(0, 12, n)
    ts = rng.integers(0, 40, n)  # deliberately has ties -> peer groups
    vals = rng.normal(0, 50, n).round(3)
    nulls = rng.random(n) < null_frac
    cats = np.array(["aa", "bb", "cc", None], dtype=object)[
        rng.integers(0, 4, n)]
    return pa.table({
        "g": pa.array(groups, type=pa.int32()),
        "ts": pa.array(ts, type=pa.int64()),
        "v": pa.array(np.where(nulls, 0.0, vals), type=pa.float64(),
                      mask=nulls),
        "i": pa.array(rng.integers(-1000, 1000, n), type=pa.int32()),
        "s": pa.array(list(cats)),
    })


SORT = ["g", "ts", "i", "v"]


class TestRankFamily:
    def test_row_number(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      rn=RowNumber())
        assert_same(q, sort_by=SORT)

    def test_rank_dense_rank(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts"],
                      rk=Rank(), drk=DenseRank())
        assert_same(q, sort_by=SORT)

    def test_percent_rank_cume_dist(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts"],
                      pr=PercentRank(), cd=CumeDist())
        assert_same(q, sort_by=SORT)

    def test_ntile(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      n3=NTile(3), n7=NTile(7), n100=NTile(100))
        assert_same(q, sort_by=SORT)

    def test_rank_desc_nulls(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"],
                      order_by=[(col("v"), False, False)],
                      rk=Rank(), rn=RowNumber())
        assert_same(q, sort_by=SORT)

    def test_no_partition(self, session, rng):
        df = session.from_arrow(window_table(rng, n=100))
        q = df.window(order_by=["ts", "i"], rn=RowNumber(), rk=Rank())
        assert_same(q, sort_by=SORT)


class TestLeadLag:
    def test_lead_lag(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      ld=Lead(col("v")), lg=Lag(col("v")),
                      ld3=Lead(col("i"), 3), lg2=Lag(col("i"), 2))
        assert_same(q, sort_by=SORT)

    def test_lead_lag_default(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      ld=Lead(col("i"), 1, default=-999),
                      lg=Lag(col("i"), 2, default=42))
        assert_same(q, sort_by=SORT)

    def test_lead_lag_strings(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      ld=Lead(col("s")), lg=Lag(col("s"), 1, default="zz"))
        assert_same(q, sort_by=SORT)


class TestWindowAggregates:
    def test_unbounded_aggs(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(
            partition_by=["g"],
            ws=WindowAggregate(Sum(col("v"))),
            c=WindowAggregate(Count(col("v"))),
            mn=WindowAggregate(Min(col("i"))),
            mx=WindowAggregate(Max(col("i"))),
            av=WindowAggregate(Average(col("v"))))
        assert_same(q, sort_by=SORT, approx_cols=("ws", "av"))

    def test_running_rows(self, session, rng):
        df = session.from_arrow(window_table(rng))
        frame = RowFrame(None, 0)
        q = df.window(
            partition_by=["g"], order_by=["ts", "i"],
            rs=WindowAggregate(Sum(col("i")), frame),
            rc=WindowAggregate(Count(col("v")), frame),
            rmn=WindowAggregate(Min(col("v")), frame),
            rmx=WindowAggregate(Max(col("v")), frame))
        assert_same(q, sort_by=SORT)

    def test_default_range_frame(self, session, rng):
        # Spark default: RANGE UNBOUNDED PRECEDING..CURRENT ROW includes peers
        df = session.from_arrow(window_table(rng))
        q = df.window(partition_by=["g"], order_by=["ts"],
                      rs=Sum(col("i")), rc=Count(col("i")))
        assert_same(q, sort_by=SORT)

    def test_bounded_rows(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(
            partition_by=["g"], order_by=["ts", "i"],
            w1=WindowAggregate(Sum(col("i")), RowFrame(-2, 2)),
            w2=WindowAggregate(Count(col("v")), RowFrame(-1, 0)),
            w3=WindowAggregate(Average(col("i")), RowFrame(0, 3)),
            w4=WindowAggregate(Sum(col("i")), RowFrame(1, 5)))
        assert_same(q, sort_by=SORT, approx_cols=("w3",))

    def test_first_last(self, session, rng):
        df = session.from_arrow(window_table(rng))
        q = df.window(
            partition_by=["g"], order_by=["ts", "i"],
            f=WindowAggregate(First(col("v"))),
            l=WindowAggregate(Last(col("v")), RowFrame(None, None)),
            fs=WindowAggregate(First(col("s")), RowFrame(-1, 1)))
        assert_same(q, sort_by=SORT)

    def test_all_null_partitions(self, session):
        t = pa.table({
            "g": pa.array([1, 1, 1, 2, 2], type=pa.int32()),
            "ts": pa.array([1, 2, 3, 1, 2], type=pa.int64()),
            "v": pa.array([None, None, None, 1.5, None],
                          type=pa.float64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["ts"],
                      s=Sum(col("v")), mn=Min(col("v")),
                      c=Count(col("v")), av=Average(col("v")))
        assert_same(q, sort_by=["g", "ts"])

    def test_single_row_partitions(self, session):
        t = pa.table({
            "g": pa.array(list(range(8)), type=pa.int32()),
            "ts": pa.array([0] * 8, type=pa.int64()),
            "v": pa.array([float(x) for x in range(8)]),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["ts"],
                      rn=RowNumber(), rk=Rank(), pr=PercentRank(),
                      s=Sum(col("v")))
        assert_same(q, sort_by=["g"])


class TestRangeValueFrames:
    def test_value_offset_range_on_device(self, session):
        # value-offset RANGE frames run ON DEVICE (binary-searched bounds);
        # verify true peer-value windows, not running sums, vs hand oracle
        t = pa.table({
            "g": pa.array([1, 1, 1, 1], type=pa.int32()),
            "ts": pa.array([1, 2, 3, 4], type=pa.int64()),
            "v": pa.array([1.0, 2.0, 3.0, 4.0]),
        })
        from spark_rapids_tpu.expr import RangeFrame
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["ts"],
                      s=WindowAggregate(Sum(col("v")), RangeFrame(0, 0)),
                      s2=WindowAggregate(Sum(col("v")), RangeFrame(-1, 1)))
        assert "range frames" not in q.explain()
        out = assert_same(q, sort_by=["ts"])
        assert out.column("s").to_pylist() == [1.0, 2.0, 3.0, 4.0]
        assert out.column("s2").to_pylist() == [3.0, 6.0, 9.0, 7.0]

    def test_value_range_fuzz(self, session, rng):
        # value gaps, duplicate keys, nulls in order key and value, desc
        from spark_rapids_tpu.expr import Max, Min, RangeFrame
        n = 300
        key_nulls = rng.random(n) < 0.1
        t = pa.table({
            "g": pa.array(rng.integers(0, 8, n), type=pa.int32()),
            "k": pa.array(rng.integers(0, 60, n), type=pa.int64(),
                          mask=key_nulls),
            "v": pa.array(np.where(rng.random(n) < 0.15, None,
                                   rng.normal(0, 10, n).round(2)),
                          type=pa.float64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["k"],
                      s=WindowAggregate(Sum(col("v")), RangeFrame(-5, 5)),
                      c=WindowAggregate(Count(col("v")), RangeFrame(-3, 0)),
                      mn=WindowAggregate(Min(col("v")), RangeFrame(0, 10)),
                      a=WindowAggregate(Average(col("v")),
                                        RangeFrame(None, 4)),
                      mx=WindowAggregate(Max(col("v")), RangeFrame(-7, None)))
        # prefix-difference sums reorder float additions vs the CPU loop
        assert_same(q, sort_by=["g", "k", "v"], approx_cols=("s", "a"))

    def test_value_range_descending_float(self, session, rng):
        from spark_rapids_tpu.expr import Min, RangeFrame
        n = 200
        t = pa.table({
            "g": pa.array(rng.integers(0, 5, n), type=pa.int32()),
            "k": pa.array(rng.normal(0, 3, n).round(1), type=pa.float64()),
            "v": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"],
                      order_by=[(col("k"), False, False)],
                      s=WindowAggregate(Sum(col("v")), RangeFrame(-2.0, 2.0)),
                      mn=WindowAggregate(Min(col("v")),
                                         RangeFrame(-1.5, 0.0)))
        assert_same(q, sort_by=["g", "k", "v"])

    def test_count_empty_frame_is_zero(self, session):
        t = pa.table({
            "g": pa.array([1, 1, 1], type=pa.int32()),
            "ts": pa.array([1, 2, 3], type=pa.int64()),
            "v": pa.array([1.0, 2.0, 3.0]),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["ts"],
                      c=WindowAggregate(Count(col("v")), RowFrame(1, 5)),
                      s=WindowAggregate(Sum(col("v")), RowFrame(1, 5)))
        out = assert_same(q, sort_by=["ts"])
        assert out.column("c").to_pylist() == [2, 1, 0]
        assert out.column("s").to_pylist() == [5.0, 3.0, None]

    def test_sum_over_string_raises(self, session, rng):
        df = session.from_arrow(window_table(rng, n=20))
        with pytest.raises(TypeError, match="over\nSTRING|STRING"):
            df.window(partition_by=["g"], x=WindowAggregate(Sum(col("s"))))


class TestNullKeys:
    def test_count_over_string_column(self, session, rng):
        df = session.from_arrow(window_table(rng, n=100))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      c=WindowAggregate(Count(col("s"))))
        assert_same(q, sort_by=SORT)

    def test_null_partition_keys_from_expression(self, session):
        # nullable computed partition key: garbage under null slots must not
        # split the null partition on device
        t = pa.table({
            "a": pa.array([1, None, None, 2, None], type=pa.int64()),
            "b": pa.array([10, 20, 30, 40, 50], type=pa.int64()),
            "ts": pa.array([1, 2, 3, 4, 5], type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=[(col("a") * 0)], order_by=["ts"],
                      rn=RowNumber(), s=Sum(col("b")))
        assert_same(q, sort_by=["ts"])
        out = q.collect()
        # the three a-null rows form ONE partition
        by_ts = dict(zip(out.column("ts").to_pylist(),
                         out.column("rn").to_pylist()))
        assert [by_ts[t] for t in (2, 3, 5)] == [1, 2, 3]


class TestWindowFallback:
    def test_rank_without_order_falls_back(self, session, rng):
        df = session.from_arrow(window_table(rng, n=50))
        q = df.window(partition_by=["g"], rk=Rank())
        # must still produce correct results via CPU fallback
        assert_same(q, sort_by=SORT)
        assert "requires an ORDER BY" in q.explain()

    def test_bounded_minmax_on_device(self, session, rng):
        # bounded-frame MIN/MAX rides the sparse-table range query on device
        df = session.from_arrow(window_table(rng, n=400))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      m=WindowAggregate(Min(col("i")), RowFrame(-1, 1)),
                      mx=WindowAggregate(Max(col("v")), RowFrame(-3, 0)),
                      m2=WindowAggregate(Min(col("v")), RowFrame(0, 7)),
                      me=WindowAggregate(Max(col("i")), RowFrame(2, 4)))
        assert "MIN/MAX" not in q.explain()
        assert_same(q, sort_by=SORT)

    def test_string_minmax_on_device(self, session, rng):
        # unbounded + running string min/max ride the segmented lex scan
        from spark_rapids_tpu.expr import RangeFrame
        df = session.from_arrow(window_table(rng, n=300))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      mn=WindowAggregate(Min(col("s")),
                                         RowFrame(None, None)),
                      mx=WindowAggregate(Max(col("s")),
                                         RowFrame(None, None)),
                      rmn=WindowAggregate(Min(col("s")), RowFrame(None, 0)),
                      rmx=WindowAggregate(Max(col("s")),
                                          RangeFrame(None, 0)))
        assert "STRING" not in q.explain()
        assert_same(q, sort_by=SORT)

    def test_bounded_string_minmax_falls_back(self, session, rng):
        df = session.from_arrow(window_table(rng, n=60))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      m=WindowAggregate(Min(col("s")), RowFrame(-1, 1)))
        assert_same(q, sort_by=SORT)
        assert "STRING" in q.explain()


class TestValueRangeEdges:
    def test_nan_order_keys(self, session, rng):
        from spark_rapids_tpu.expr import Min, RangeFrame
        n = 120
        k = rng.normal(0, 5, n).round(1)
        k[rng.random(n) < 0.1] = np.nan
        t = pa.table({
            "g": pa.array(rng.integers(0, 4, n), type=pa.int32()),
            "k": pa.array(k, type=pa.float64()),
            "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        })
        df = session.from_arrow(t)
        for asc in (True, False):
            q = df.window(partition_by=["g"],
                          order_by=[(col("k"), asc, True)],
                          s=WindowAggregate(Sum(col("v")),
                                            RangeFrame(-2.0, 2.0)),
                          mn=WindowAggregate(Min(col("v")),
                                            RangeFrame(None, 1.0)))
            assert_same(q, sort_by=["g", "k", "v"])

    def test_first_last_value_range(self, session, rng):
        from spark_rapids_tpu.expr import RangeFrame
        n = 150
        t = pa.table({
            "g": pa.array(rng.integers(0, 5, n), type=pa.int32()),
            "k": pa.array(rng.integers(0, 30, n), type=pa.int64()),
            "v": pa.array(np.where(rng.random(n) < 0.2, None,
                                   rng.integers(0, 99, n)),
                          type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"], order_by=["k"],
                      f=WindowAggregate(First(col("v")), RangeFrame(-4, 4)),
                      l=WindowAggregate(Last(col("v")), RangeFrame(-4, 4)))
        assert_same(q, sort_by=["g", "k", "v"])

    def test_nulls_first_false_value_range(self, session, rng):
        from spark_rapids_tpu.expr import RangeFrame
        n = 100
        key_nulls = rng.random(n) < 0.15
        t = pa.table({
            "g": pa.array(rng.integers(0, 3, n), type=pa.int32()),
            "k": pa.array(rng.integers(0, 20, n), type=pa.int64(),
                          mask=key_nulls),
            "v": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.window(partition_by=["g"],
                      order_by=[(col("k"), True, False)],
                      c=WindowAggregate(Count(col("v")), RangeFrame(-3, 3)))
        assert_same(q, sort_by=["g", "k", "v"])


class TestNthValueAndIgnoreNulls:
    def test_nth_value(self, session, rng):
        from spark_rapids_tpu.expr import NthValue
        df = session.from_arrow(window_table(rng, n=300))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      n1=NthValue(col("v"), 1),
                      n3=NthValue(col("v"), 3),
                      n2f=NthValue(col("v"), 2, frame=RowFrame(-2, 2)),
                      big=NthValue(col("v"), 500))
        out = assert_same(q, sort_by=SORT)
        assert out.column("big").to_pylist() == [None] * out.num_rows

    def test_nth_value_ignore_nulls(self, session, rng):
        from spark_rapids_tpu.expr import NthValue
        df = session.from_arrow(window_table(rng, n=250, null_frac=0.4))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      n2=NthValue(col("v"), 2, ignore_nulls=True),
                      n1=NthValue(col("v"), 1, ignore_nulls=True,
                                  frame=RowFrame(None, None)))
        assert_same(q, sort_by=SORT)

    def test_first_last_ignore_nulls(self, session, rng):
        df = session.from_arrow(window_table(rng, n=250, null_frac=0.4))
        q = df.window(
            partition_by=["g"], order_by=["ts", "i"],
            f=WindowAggregate(First(col("v"), ignore_nulls=True),
                              RowFrame(None, None)),
            l=WindowAggregate(Last(col("v"), ignore_nulls=True),
                              RowFrame(None, None)),
            fb=WindowAggregate(First(col("v"), ignore_nulls=True),
                               RowFrame(-2, 2)),
            lb=WindowAggregate(Last(col("v"), ignore_nulls=True),
                               RowFrame(-3, 0)))
        assert "IGNORE NULLS" not in q.explain()
        assert_same(q, sort_by=SORT)

    def test_first_last_ignore_nulls_strings(self, session, rng):
        df = session.from_arrow(window_table(rng, n=120, null_frac=0.3))
        q = df.window(partition_by=["g"], order_by=["ts", "i"],
                      f=WindowAggregate(First(col("s"), ignore_nulls=True),
                                        RowFrame(None, None)))
        assert_same(q, sort_by=SORT)
