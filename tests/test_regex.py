"""Regex transpiler + Shift-And machine tests.

Reference coverage model: `RegexParserSuite` / `regexp_test.py` — every
device-compiled pattern is checked against an independent oracle (python `re`,
the role cuDF-vs-CPU-Spark plays in the reference). The device machine runs
under jit on the virtual device; the CPU engine path uses `re` directly, so
`assert_cpu_tpu_equal`-style comparison validates the machine itself."""

import re

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import batch_from_arrow
from spark_rapids_tpu.expr import col, lit
from spark_rapids_tpu.expr.regex import (RegexUnsupportedError, Like, RLike,
                                         RegExpExtract, RegExpReplace,
                                         compile_device_plan,
                                         device_supported_pattern,
                                         like_pattern_to_regex, match_plan)

from harness import assert_cpu_tpu_equal, eval_cpu, eval_tpu

SUBJECTS = [
    "", "a", "b", "ab", "ba", "aab", "abb", "aabb", "abc", "abcabc",
    "hello world", "  spaces  ", "123", "a1b2c3", "999-4444", "12-3456",
    "foo@bar.com", "not an email", "2023-01-15", "99/12/31",
    "aaaaaaaaab", "xyzzy", "line1\nline2", "tab\there", "CAPS", "MiXeD",
    "a.b", "a*b", "[bracket]", "(paren)", "x" * 60, "ab" * 25, None,
]

PATTERNS = [
    # literals and anchors
    "abc", "^abc", "abc$", "^abc$", "^$", "a",
    # classes
    "[abc]", "[^abc]", "[a-z]+", "[A-Z]", "[0-9]{3}", "[a-zA-Z0-9]+",
    # predefined classes
    r"\d+", r"\D+", r"\w+", r"\W", r"\s", r"\S+",
    # quantifiers
    "a*b", "a+b", "a?b", "ab{2}", "a{2,}b", "a{1,3}b", "colou?r",
    "x{0,2}y",
    # dot
    "a.c", "a.*c", "^.+$", "...",
    # alternation and groups
    "abc|xyz", "^(foo|bar)$", "(ab)+c" if False else "(ab){1,3}c",
    "(a|b)c", "a(b|c)d", "(?:ab|cd)+e" if False else "(?:ab|cd){1,2}e",
    # escapes
    r"a\.b", r"\(paren\)", r"\d{3}-\d{4}", r"\d{2}/\d{2}/\d{2}",
    r"[\d\s]+", r"\x61+",
    # lazy quantifiers (acceptance-equivalent)
    "a+?b", "a*?b",
]


def subjects_table():
    return pa.table({"s": pa.array(SUBJECTS, type=pa.string())})


def oracle(pattern, subjects, mode="search"):
    rx = re.compile(pattern)
    out = []
    for s in subjects:
        if s is None:
            out.append(None)
        elif mode == "search":
            out.append(bool(rx.search(s)))
        else:
            out.append(bool(rx.fullmatch(s)))
    return out


class TestDeviceMachine:
    @pytest.mark.parametrize("pattern", PATTERNS)
    def test_vs_python_re(self, pattern):
        assert device_supported_pattern(pattern) is None, pattern
        t = subjects_table()
        cpu = eval_cpu(lambda: RLike(col("s"), lit(pattern)), t)
        tpu = eval_tpu(lambda: RLike(col("s"), lit(pattern)), t)
        expected = oracle(pattern, SUBJECTS)
        assert cpu.to_pylist() == expected, f"CPU path wrong for {pattern!r}"
        assert tpu.to_pylist() == expected, f"device machine wrong for {pattern!r}"

    def test_long_subject_beyond_pattern(self):
        subjects = ["a" * 40 + "b", "b" + "a" * 50, "c" * 55 + "ab"]
        t = pa.table({"s": pa.array(subjects)})
        for pattern in ["a+b$", "^ba+$", "ab$", "^c+ab$"]:
            tpu = eval_tpu(lambda: RLike(col("s"), lit(pattern)), t)
            assert tpu.to_pylist() == oracle(pattern, subjects), pattern


class TestUnsupportedPatterns:
    @pytest.mark.parametrize("pattern", [
        r"(a)\1",          # backreference
        r"(?=abc)",        # lookahead
        r"(?<=a)b",        # lookbehind
        r"\bword\b",       # word boundary
        r"a*+",            # possessive
        r"\p{Alpha}+",     # unicode property
        "(ab)+",           # unbounded group repeat
        "(a|b|c|d|e)(f|g|h|i|j)(k|l|m|n|o)",  # alternative explosion
        "x{1,500}",        # expands past device item limit
    ])
    def test_rejected_with_reason(self, pattern):
        reason = device_supported_pattern(pattern)
        assert reason is not None, pattern

    def test_planner_tags_unsupported_to_cpu(self):
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.plan.overrides import lookup_expr_rule
        conf = TpuConf({})
        e = RLike(col("s"), lit(r"(a)\1"))
        m = lookup_expr_rule(e, conf)
        m.tag_for_device(None)
        assert any("not supported on TPU" in r for r in m.reasons)
        e2 = RLike(col("s"), lit("abc"))
        m2 = lookup_expr_rule(e2, conf)
        m2.tag_for_device(None)
        assert m2.can_run_on_device


class TestLike:
    def test_translation(self):
        assert like_pattern_to_regex("abc%") == "^abc.*$"
        assert like_pattern_to_regex("a_c") == "^a.c$"
        assert like_pattern_to_regex("100\\%") == "^100\\%$"
        assert like_pattern_to_regex("a.b") == "^a\\.b$"

    @pytest.mark.parametrize("pattern", ["abc", "a%", "%b", "%ll%", "a_c",
                                         "_b_", "%", "", "he__o%"])
    def test_like_vs_oracle(self, pattern):
        t = subjects_table()
        rx = re.compile(like_pattern_to_regex(pattern), re.DOTALL)
        expected = [None if s is None else bool(rx.match(s))
                    for s in SUBJECTS]
        cpu = eval_cpu(lambda: Like(col("s"), lit(pattern)), t)
        tpu = eval_tpu(lambda: Like(col("s"), lit(pattern)), t)
        assert cpu.to_pylist() == expected
        assert tpu.to_pylist() == expected


class TestReplaceExtract:
    def test_replace(self):
        t = pa.table({"s": pa.array(["a1b2", "nodigits", None, "33"])})
        out = eval_cpu(lambda: RegExpReplace(col("s"), lit(r"\d+"),
                                             lit("#")), t)
        assert out.to_pylist() == ["a#b#", "nodigits", None, "#"]

    def test_replace_group_ref(self):
        t = pa.table({"s": pa.array(["john smith", "ada lovelace"])})
        out = eval_cpu(lambda: RegExpReplace(col("s"), lit(r"(\w+) (\w+)"),
                                             lit("$2 $1")), t)
        assert out.to_pylist() == ["smith john", "lovelace ada"]

    def test_extract(self):
        t = pa.table({"s": pa.array(["2023-01-15", "no date", None])})
        out = eval_cpu(lambda: RegExpExtract(col("s"),
                                             lit(r"(\d+)-(\d+)-(\d+)"), 2), t)
        assert out.to_pylist() == ["01", "", None]


class TestFuzzRegressions:
    """Cases surfaced by differential fuzzing against python re."""

    @pytest.mark.parametrize("pattern", ["(a)+", r"(\d)*", "(x)?y"])
    def test_grouped_single_class_repeats_compile(self, pattern):
        assert device_supported_pattern(pattern) is None
        subjects = ["aaa", "b", "", "123", "xy", "y"]
        t = pa.table({"s": pa.array(subjects)})
        tpu = eval_tpu(lambda: RLike(col("s"), lit(pattern)), t)
        assert tpu.to_pylist() == oracle(pattern, subjects), pattern

    @pytest.mark.parametrize("pattern", ["a?$", "[ab]*$", r"\d{0,2}$",
                                         "b*$", "^a*$"])
    def test_nullable_end_anchored(self, pattern):
        subjects = ["bc", "", "a", "ba", "xyz", "99"]
        t = pa.table({"s": pa.array(subjects)})
        tpu = eval_tpu(lambda: RLike(col("s"), lit(pattern)), t)
        assert tpu.to_pylist() == oracle(pattern, subjects), pattern

    def test_dollar_matches_before_final_newline(self):
        subjects = ["a", "a\n", "a\nb", "ab\n", "\n"]
        t = pa.table({"s": pa.array(subjects)})
        tpu = eval_tpu(lambda: RLike(col("s"), lit("a$")), t)
        # python re '$' (no MULTILINE): end or before a final \n — same rule
        # the device machine implements
        assert tpu.to_pylist() == oracle("a$", subjects)

    def test_bad_hex_escape_is_fallback_not_crash(self):
        reason = device_supported_pattern(r"\xZZ")
        assert reason is not None and "escape" in reason

    def test_like_rejects_trailing_newline(self):
        subjects = ["a", "a\n"]
        t = pa.table({"s": pa.array(subjects)})
        cpu = eval_cpu(lambda: Like(col("s"), lit("a")), t)
        tpu = eval_tpu(lambda: Like(col("s"), lit("a")), t)
        assert cpu.to_pylist() == [True, False]
        assert tpu.to_pylist() == [True, False]


class TestParserEdges:
    def test_unclosed_class(self):
        with pytest.raises(RegexUnsupportedError):
            compile_device_plan("[abc")

    def test_literal_open_brace(self):
        # Java treats '{x' as a literal brace
        assert device_supported_pattern("a{x}") is None
        subjects = ["a{x}", "a", "ax"]
        t = pa.table({"s": pa.array(subjects)})
        tpu = eval_tpu(lambda: RLike(col("s"), lit("a\\{x\\}")), t)
        assert tpu.to_pylist() == [True, False, False]

    def test_class_with_metachars(self):
        subjects = ["a.b", "axb", "a]b"]
        t = pa.table({"s": pa.array(subjects)})
        tpu = eval_tpu(lambda: RLike(col("s"), lit(r"a[.\]]b")), t)
        assert tpu.to_pylist() == [True, False, True]
