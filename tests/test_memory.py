"""Memory runtime tests (reference suites: RapidsDeviceMemoryStoreSuite,
RapidsHostMemoryStoreSuite, RapidsDiskStoreSuite, DeviceMemoryEventHandlerSuite,
GpuSemaphoreSuite, *RetrySuite with RmmSpark OOM injection)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import batch_from_arrow, batch_to_arrow
from spark_rapids_tpu.errors import RetryOOM, SplitAndRetryOOM
from spark_rapids_tpu.memory.budget import MemoryBudget
from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
from spark_rapids_tpu.memory.retry import (split_batch_halves, with_retry,
                                           with_retry_no_split)
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch


def _batch(n=100):
    return batch_from_arrow(pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "s": pa.array([f"row{i}" for i in range(n)]),
    }))


class TestSpillCatalog:
    def test_spill_to_host_and_back(self):
        cat = BufferCatalog(host_limit=1 << 30)
        b = _batch()
        h = cat.add_batch(b)
        assert cat.tier_of(h) == StorageTier.DEVICE
        freed = cat.synchronous_spill(1)
        assert freed > 0
        assert cat.tier_of(h) == StorageTier.HOST
        back = cat.acquire_batch(h)
        assert cat.tier_of(h) == StorageTier.DEVICE
        assert batch_to_arrow(back).equals(batch_to_arrow(b))
        cat.remove(h)

    def test_spill_overflows_to_disk(self):
        cat = BufferCatalog(host_limit=1)  # anything overflows
        b = _batch()
        h = cat.add_batch(b)
        cat.synchronous_spill(1)
        assert cat.tier_of(h) == StorageTier.DISK
        back = cat.acquire_batch(h)
        assert batch_to_arrow(back).column("s").to_pylist() == \
            [f"row{i}" for i in range(100)]
        cat.remove(h)

    def test_spill_priority_order(self):
        from spark_rapids_tpu.memory.catalog import SpillPriority
        cat = BufferCatalog(host_limit=1 << 30)
        low = cat.add_batch(_batch(), SpillPriority.SPILL_FIRST)
        high = cat.add_batch(_batch(), SpillPriority.ACTIVE_BATCH)
        cat.synchronous_spill(1)  # needs little; should take the low one only
        assert cat.tier_of(low) == StorageTier.HOST
        assert cat.tier_of(high) == StorageTier.DEVICE


class TestSpillableBatch:
    def test_roundtrip(self):
        sb = SpillableColumnarBatch(_batch(50))
        assert sb.num_rows == 50
        got = sb.get_batch()
        assert got.row_count() == 50
        sb.close()
        with pytest.raises(ValueError):
            sb.get_batch()


class TestRetry:
    def test_retry_oom_then_success(self):
        calls = {"n": 0}

        def fn(x):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RetryOOM("pressure")
            return x * 2

        assert with_retry_no_split(21, fn) == 42
        assert calls["n"] == 3

    def test_retry_gives_up(self):
        def fn(x):
            raise RetryOOM("always")

        with pytest.raises(RetryOOM):
            list(with_retry(1, fn))

    def test_split_and_retry(self):
        split_log = []

        def fn(sb):
            if sb.num_rows > 25:
                raise SplitAndRetryOOM("too big")
            return sb.get_batch().row_count()

        def split(sb):
            halves = split_batch_halves(sb)
            split_log.append(len(halves))
            return halves

        sb = SpillableColumnarBatch(_batch(100))
        out = list(with_retry(sb, fn, split))
        assert sum(out) == 100
        assert len(out) == 4  # 100 -> 50+50 -> 25*4
        assert all(x == 2 for x in split_log)

    def test_injection_via_budget(self):
        MemoryBudget.initialize(1 << 40)
        MemoryBudget.get().reset_injection(retry_at=1)
        with pytest.raises(RetryOOM, match="injected"):
            MemoryBudget.get().reserve(1024)
        # next allocation succeeds
        MemoryBudget.get().reserve(1024)
        MemoryBudget.get().release(1024)
        MemoryBudget.get().reset_injection()


class TestBudget:
    def test_exhaustion_raises_split(self):
        MemoryBudget.initialize(1000)
        BufferCatalog._instance = BufferCatalog()  # empty catalog: nothing to spill
        b = MemoryBudget.get()
        b.reserve(900)
        with pytest.raises(SplitAndRetryOOM):
            b.reserve(500)
        b.release(900)
        MemoryBudget.initialize(1 << 40)

    def test_oom_dump_dir_writes_allocator_state(self, tmp_path):
        # spark.rapids.memory.gpu.oomDumpDir analog: terminal OOM drops a
        # debug-dump file before raising
        import os
        from spark_rapids_tpu.config import TpuConf
        conf = TpuConf({"spark.rapids.memory.gpu.oomDumpDir":
                        str(tmp_path / "dumps")})
        MemoryBudget._instance = MemoryBudget(1000, conf)
        cat = BufferCatalog()
        BufferCatalog._instance = cat
        h = cat.add_batch(_batch(), label="suspect")
        cat.synchronous_spill(1 << 40)  # already host-tier: nothing frees
        b = MemoryBudget.get()
        with pytest.raises(SplitAndRetryOOM):
            b.reserve(5000)
        files = os.listdir(str(tmp_path / "dumps"))
        assert len(files) == 1 and files[0].startswith("oom_dump_")
        text = open(str(tmp_path / "dumps" / files[0])).read()
        assert "MemoryBudget: need=5000" in text
        assert "suspect" in text and "BufferCatalog" in text
        cat.remove(h)
        MemoryBudget.initialize(1 << 40)

    def test_shutdown_logs_leaked_handles(self, caplog):
        import logging
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        cat = BufferCatalog()
        BufferCatalog._instance = cat
        h = cat.add_batch(_batch(), label="leaky")
        with caplog.at_level(logging.WARNING, "spark_rapids_tpu.memory"):
            DeviceManager.shutdown()
        assert any("leaked buffer handle" in r.message and
                   "leaky" in r.message for r in caplog.records)
        cat.remove(h)
        BufferCatalog._instance = BufferCatalog()

    def test_pressure_spills_catalog(self):
        MemoryBudget.initialize(1 << 40)
        cat = BufferCatalog(host_limit=1 << 30)
        BufferCatalog._instance = cat
        batch = _batch()
        h = cat.add_batch(batch)
        size = batch.device_memory_size()
        MemoryBudget.initialize(size + 100)
        MemoryBudget.get().reserve(size)  # budget accounted for the batch
        # next reservation triggers synchronous spill of the catalog entry and
        # then SUCCEEDS (spill freed enough; RetryOOM only when still short)
        MemoryBudget.get().reserve(size)
        assert cat.tier_of(h) == StorageTier.HOST
        MemoryBudget.initialize(1 << 40)
        BufferCatalog._instance = None


class TestSemaphore:
    def test_limits_concurrency(self):
        TpuSemaphore._instance = None
        TpuSemaphore.initialize(2)
        sem = TpuSemaphore.get()
        active = []
        peak = []
        lock = threading.Lock()

        def task():
            sem.acquire_if_necessary()
            with lock:
                active.append(1)
                peak.append(len(active))
            import time
            time.sleep(0.02)
            with lock:
                active.pop()
            sem.complete_task()

        threads = [threading.Thread(target=task) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert max(peak) <= 2

    def test_reentrant(self):
        TpuSemaphore._instance = None
        TpuSemaphore.initialize(1)
        sem = TpuSemaphore.get()
        sem.acquire_if_necessary()
        sem.acquire_if_necessary()  # same thread: no deadlock
        sem.complete_task()


class TestCompressedSpill:
    def test_host_spill_compressed_roundtrip(self, rng):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import (batch_from_arrow,
                                                     batch_to_arrow)
        from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
        cat = BufferCatalog(host_limit=1 << 24, spill_codec="zstd")
        n = 4096
        t = pa.table({
            "a": pa.array(np.arange(n) % 5, type=pa.int64()),
            "b": pa.array(np.zeros(n), type=pa.float64()),
            "s": pa.array([f"tag{i % 3}" for i in range(n)]),
        })
        b = batch_from_arrow(t)
        raw = b.device_memory_size()
        h = cat.add_batch(b)
        del b
        freed = cat.synchronous_spill(raw)
        assert freed == raw
        assert cat.tier_of(h) == StorageTier.HOST
        # compressed footprint well under raw for this redundant data
        assert 0 < cat.host_used < raw // 4
        back = cat.acquire_batch(h)
        assert cat.host_used == 0
        got = batch_to_arrow(back)
        assert got.equals(t)
        cat.remove(h)

    def test_disk_spill_compressed_roundtrip(self, rng, tmp_path):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import (batch_from_arrow,
                                                     batch_to_arrow)
        from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
        cat = BufferCatalog(spill_dir=str(tmp_path), host_limit=1,
                            spill_codec="zstd")  # tiny limit -> straight to disk
        t = pa.table({"x": pa.array(np.arange(512), type=pa.int64())})
        b = batch_from_arrow(t)
        h = cat.add_batch(b)
        del b
        cat.synchronous_spill(1 << 30)
        assert cat.tier_of(h) == StorageTier.DISK
        back = cat.acquire_batch(h)
        assert batch_to_arrow(back).equals(t)
        cat.remove(h)

    def test_spill_codec_none_unchanged(self, rng):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import (batch_from_arrow,
                                                     batch_to_arrow)
        from spark_rapids_tpu.memory.catalog import BufferCatalog
        cat = BufferCatalog(host_limit=1 << 24, spill_codec="none")
        t = pa.table({"x": pa.array(np.arange(256), type=pa.int64())})
        b = batch_from_arrow(t)
        h = cat.add_batch(b)
        del b
        cat.synchronous_spill(1 << 30)
        back = cat.acquire_batch(h)
        assert batch_to_arrow(back).equals(t)
        cat.remove(h)
