"""Shuffle subsystem tests (reference model: RapidsShuffleClientSuite /
RapidsShuffleServerSuite / WindowedBlockIteratorSuite run the client/server
state machines entirely in-process over a mocked transport —
`tests/.../shuffle/RapidsShuffleTestHelper.scala`)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import batch_from_arrow, batch_to_arrow
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.shuffle import (BlockId, BlockRange, BounceBufferManager,
                                      HeartbeatManager, LocalTransport,
                                      ShuffleClient, ShuffleServer,
                                      TpuShuffleManager, WindowedBlockIterator,
                                      concat_host_tables, decode_meta,
                                      deserialize_table, get_codec,
                                      serialize_batch)
from spark_rapids_tpu.shuffle.manager import next_shuffle_id


def sample_table(rng, n=500):
    nulls = rng.random(n) < 0.2
    cats = np.array(["x", "medium", "a-much-longer-string", None],
                    dtype=object)[rng.integers(0, 4, n)]
    return pa.table({
        "a": pa.array(np.where(nulls, 0, rng.integers(-10**9, 10**9, n)),
                      type=pa.int64(), mask=nulls),
        "b": pa.array(rng.normal(0, 1, n), type=pa.float64()),
        "s": pa.array(list(cats)),
        "c": pa.array(rng.integers(0, 2, n), type=pa.bool_()),
    })


class TestSerializer:
    @pytest.mark.parametrize("codec", ["none", "zstd", "lz4xla"])
    def test_round_trip(self, rng, codec):
        t = sample_table(rng)
        batch = batch_from_arrow(t)
        blob = serialize_batch(batch, codec)
        table, consumed = deserialize_table(blob)
        assert consumed == len(blob)
        out = batch_to_arrow(concat_host_tables([table]))
        assert out.equals(t)

    def test_concat_many(self, rng):
        tables = [sample_table(rng, n) for n in (100, 1, 257, 64)]
        blobs = [serialize_batch(batch_from_arrow(t), "zstd") for t in tables]
        hts = [deserialize_table(b)[0] for b in blobs]
        merged = batch_to_arrow(concat_host_tables(hts))
        expected = pa.concat_tables(tables)
        assert merged.equals(expected)

    def test_metadata_header(self, rng):
        t = sample_table(rng, 50)
        blob = serialize_batch(batch_from_arrow(t), "zstd")
        meta, _ = decode_meta(blob)
        assert meta.num_rows == 50
        # the frame stamps the ACTUAL codec: zstd, or the zlib fallback
        # when the zstandard wheel is absent in this environment
        assert meta.codec == get_codec("zstd").name
        assert [c.name for c in meta.columns] == ["a", "b", "s", "c"]
        assert isinstance(meta.columns[2].dtype, T.StringType)
        assert meta.columns[2].string_width > 0
        assert meta.compressed_len <= meta.uncompressed_len


class TestCodecs:
    @pytest.mark.parametrize("codec", ["none", "zstd", "lz4xla"])
    def test_codec_round_trip(self, codec, rng):
        c = get_codec(codec)
        for data in (b"", b"abc" * 10000, rng.bytes(10000)):
            comp = c.compress(data)
            assert c.decompress(comp, len(data)) == data


class TestWindowedBlockIterator:
    def test_splits_large_block(self):
        bid = BlockId(1, 0, 0)
        windows = list(WindowedBlockIterator([(bid, 1000)], 300))
        assert len(windows) == 4
        assert [w[0].length for w in windows] == [300, 300, 300, 100]
        assert windows[-1][0].is_final
        assert not windows[0][0].is_final

    def test_packs_small_blocks(self):
        blocks = [(BlockId(1, m, 0), 100) for m in range(10)]
        windows = list(WindowedBlockIterator(blocks, 350))
        assert len(windows) == 3
        assert sum(len(w) for w in windows) >= 10
        total = sum(r.length for w in windows for r in w)
        assert total == 1000

    def test_block_spanning_windows(self):
        blocks = [(BlockId(1, 0, 0), 250), (BlockId(1, 1, 0), 500)]
        windows = list(WindowedBlockIterator(blocks, 300))
        ranges = [r for w in windows for r in w]
        per_block = {}
        for r in ranges:
            per_block.setdefault(r.block.map_id, []).append(r)
        for m, rs in per_block.items():
            assert rs[0].offset == 0
            for a, b in zip(rs, rs[1:]):
                assert a.offset + a.length == b.offset
            assert rs[-1].is_final


class TestBounceBuffers:
    def test_pool_blocks_and_releases(self):
        mgr = BounceBufferManager(count=2, buf_size=128)
        b1, b2 = mgr.acquire(), mgr.acquire()
        assert mgr.num_free == 0
        with pytest.raises(TimeoutError):
            mgr.acquire(timeout=0.05)
        b1.close()
        b3 = mgr.acquire(timeout=1)
        assert b3 is not None
        b2.close()
        b3.close()
        assert mgr.num_free == 2


class TestClientServer:
    def _make_peer(self, rng, blocks):
        store = {}
        for (sid, mid, rid), table in blocks.items():
            store[BlockId(sid, mid, rid)] = serialize_batch(
                batch_from_arrow(table), "zstd")
        server = ShuffleServer("peer-1", store.get)
        transport = LocalTransport()
        transport.register(server)
        return transport, store

    def test_fetch_blocks_end_to_end(self, rng):
        tables = {(7, m, 0): sample_table(rng, 100 + m) for m in range(4)}
        transport, store = self._make_peer(rng, tables)
        client = ShuffleClient(transport.connect("peer-1"),
                               BounceBufferManager(2, 1 << 12))  # tiny windows
        got = {}
        errors = []
        n = client.fetch_blocks(
            [BlockId(7, m, 0) for m in range(6)],  # 2 don't exist
            on_block=lambda bid, data: got.__setitem__(bid.map_id, data),
            on_error=lambda bid, e: errors.append(bid))
        assert n == 4
        # absent blocks are reported as per-block failures, never dropped
        assert sorted(b.map_id for b in errors) == [4, 5]
        for m in range(4):
            assert got[m] == store[BlockId(7, m, 0)]
            ht, _ = deserialize_table(got[m])
            assert batch_to_arrow(concat_host_tables([ht])).equals(
                tables[(7, m, 0)])

    def test_fetch_error_surfaces_per_block(self, rng):
        tables = {(7, 0, 0): sample_table(rng, 50)}
        transport, store = self._make_peer(rng, tables)

        class FlakyConnection:
            def __init__(self, inner):
                self._inner = inner
                self.calls = 0

            def request_metadata(self, ids):
                metas = self._inner.request_metadata(ids)
                # lie about a block the server cannot serve
                from spark_rapids_tpu.shuffle.metadata import TableMeta
                metas.append((BlockId(9, 9, 9),
                              TableMeta(0, "none", 0, 0, []), 64))
                return metas

            def fetch_range(self, r):
                return self._inner.fetch_range(r)

        client = ShuffleClient(FlakyConnection(transport.connect("peer-1")),
                               BounceBufferManager(1, 1 << 16))
        errors = []
        got = []
        n = client.fetch_blocks([BlockId(7, 0, 0)],
                                on_block=lambda b, d: got.append(d),
                                on_error=lambda b, e: errors.append((b, e)))
        assert n == 1 and len(got) == 1
        assert len(errors) == 1 and errors[0][0] == BlockId(9, 9, 9)

    def test_unknown_peer_raises(self):
        with pytest.raises(ConnectionError):
            LocalTransport().connect("nobody")

    def test_fetch_partition_discovers_blocks(self, rng):
        tables = {(5, m, 2): sample_table(rng, 30 + m) for m in range(3)}
        tables[(5, 0, 1)] = sample_table(rng, 10)  # different reduce id
        transport, store = self._make_peer_with_lister(rng, tables)
        client = ShuffleClient(transport.connect("peer-1"),
                               BounceBufferManager(2, 1 << 16))
        got = {}
        n = client.fetch_partition(
            5, 2, on_block=lambda bid, data: got.__setitem__(bid.map_id,
                                                             data))
        assert n == 3 and sorted(got) == [0, 1, 2]

    def _make_peer_with_lister(self, rng, blocks):
        store = {}
        for (sid, mid, rid), table in blocks.items():
            store[BlockId(sid, mid, rid)] = serialize_batch(
                batch_from_arrow(table), "zstd")

        def lister(sid, rid):
            return sorted((b for b in store
                           if b.shuffle_id == sid and b.reduce_id == rid),
                          key=lambda b: b.map_id)

        server = ShuffleServer("peer-1", store.get, lister)
        transport = LocalTransport()
        transport.register(server)
        return transport, store

    def test_midblock_failure_never_delivers_truncated(self, rng):
        # one large block spanning many windows; a transient failure on an
        # early range must poison the whole block, not deliver a tail-only
        # reassembly as success
        t = sample_table(rng, 5000)
        transport, store = self._make_peer(rng, {(3, 0, 0): t})

        class OneFailure:
            def __init__(self, inner):
                self._inner = inner
                self._failed = False

            def request_metadata(self, ids):
                return self._inner.request_metadata(ids)

            def fetch_range(self, r):
                if not self._failed and r.offset > 0:
                    self._failed = True
                    raise IOError("transient")
                return self._inner.fetch_range(r)

        client = ShuffleClient(OneFailure(transport.connect("peer-1")),
                               BounceBufferManager(1, 1 << 12))
        got, errors = [], []
        n = client.fetch_blocks([BlockId(3, 0, 0)],
                                on_block=lambda b, d: got.append(d),
                                on_error=lambda b, e: errors.append(e))
        assert n == 0 and got == [] and len(errors) == 1


class TestHeartbeat:
    def test_register_and_discover(self):
        clock = [0.0]
        hb = HeartbeatManager(expiry_seconds=10, clock=lambda: clock[0])
        assert hb.register_executor("e1", "host1:1") == []
        peers = hb.register_executor("e2", "host2:1")
        assert [p.executor_id for p in peers] == ["e1"]
        peers = hb.executor_heartbeat("e1")
        assert [p.executor_id for p in peers] == ["e2"]

    def test_expiry(self):
        clock = [0.0]
        hb = HeartbeatManager(expiry_seconds=10, clock=lambda: clock[0])
        hb.register_executor("e1", "h1")
        hb.register_executor("e2", "h2")
        clock[0] = 5.0
        hb.executor_heartbeat("e2")
        clock[0] = 12.0  # e1 silent for 12s -> dead
        assert [p.executor_id for p in hb.known_peers()] == ["e2"]
        with pytest.raises(KeyError):
            hb.executor_heartbeat("e1")


class TestShuffleManager:
    def _round_trip(self, rng, mode, codec="zstd"):
        conf = TpuConf({"spark.rapids.shuffle.mode": mode,
                        "spark.rapids.shuffle.compression.codec": codec})
        mgr = TpuShuffleManager(conf)
        try:
            t = sample_table(rng, 300)
            batch = batch_from_arrow(t)
            sid = next_shuffle_id()
            writer = mgr.get_writer(sid, map_id=0)
            writer.write(0, batch)
            writer.close()
            out = list(mgr.read_partition(sid, 0))
            assert len(out) == 1
            assert batch_to_arrow(out[0]).equals(t)
            mgr.unregister_shuffle(sid)
            if mode == "MULTITHREADED":
                assert mgr.block_store.total_bytes() == 0
        finally:
            mgr.shutdown()

    def test_multithreaded_mode(self, rng):
        self._round_trip(rng, "MULTITHREADED")

    def test_multithreaded_lz4(self, rng):
        self._round_trip(rng, "MULTITHREADED", codec="lz4xla")

    def test_cache_only_mode(self, rng):
        self._round_trip(rng, "CACHE_ONLY")

    def test_query_repartition_through_manager(self, rng):
        # default mode is MULTITHREADED: df.repartition routes device batches
        # through serialize/compress/store/read (the full reference path)
        from spark_rapids_tpu.plugin import TpuSession
        from spark_rapids_tpu.expr import col
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = sample_table(rng, 400)
        df = sess.from_arrow(t).repartition(4, "a")
        out = df.collect()
        keys = [(k, "ascending") for k in ("a", "b")]
        assert out.sort_by(keys).equals(
            pa.Table.from_arrays(t.columns, names=t.column_names)
            .sort_by(keys))

    def test_multi_map_concat(self, rng):
        conf = TpuConf({"spark.rapids.shuffle.mode": "MULTITHREADED"})
        mgr = TpuShuffleManager(conf)
        try:
            tables = [sample_table(rng, n) for n in (64, 100, 3)]
            sid = next_shuffle_id()
            for m, t in enumerate(tables):
                w = mgr.get_writer(sid, map_id=m)
                w.write(0, batch_from_arrow(t))
                w.close()
            out = list(mgr.read_partition(sid, 0))
            assert len(out) == 1  # single H2D after host concat
            assert batch_to_arrow(out[0]).equals(pa.concat_tables(tables))
        finally:
            mgr.shutdown()


class TestShuffleDiskTier:
    def test_overflow_to_disk_and_back(self, rng, tmp_path):
        # budget far below the shuffle size: most blocks must land on disk
        # and reads must still reassemble exactly (RapidsDiskBlockManager
        # analog)
        conf = TpuConf({"spark.rapids.shuffle.mode": "MULTITHREADED",
                        "spark.rapids.shuffle.hostStoreSize": 4096,
                        "spark.rapids.shuffle.spillPath": str(tmp_path),
                        "spark.rapids.shuffle.compression.codec": "none"})
        mgr = TpuShuffleManager(conf)
        try:
            tables = [sample_table(rng, 500) for _ in range(6)]
            sid = next_shuffle_id()
            for m, t in enumerate(tables):
                w = mgr.get_writer(sid, map_id=m)
                w.write(0, batch_from_arrow(t))
                w.close()
            assert mgr.block_store.disk_block_count() >= 4
            assert mgr.block_store.mem_bytes() <= 4096 or \
                len(tables) == mgr.block_store.disk_block_count() + 1
            out = list(mgr.read_partition(sid, 0))
            got = pa.concat_tables(batch_to_arrow(b) for b in out)
            assert got.equals(pa.concat_tables(tables))
            mgr.unregister_shuffle(sid)
            assert mgr.block_store.total_bytes() == 0
            import os
            assert not [f for f in os.listdir(tmp_path)
                        if f.endswith(".blk")]
        finally:
            mgr.shutdown()

    def test_query_shuffle_over_tiny_budget(self, rng):
        # end-to-end repartition whose blocks exceed the configured host
        # store: the disk tier must keep the query green and exact.
        # Exchange uses the process-singleton manager whose FIRST caller's
        # conf wins — reset around so the tiny budget actually applies and
        # does not leak into later tests.
        from spark_rapids_tpu.plugin import TpuSession
        TpuShuffleManager.reset()
        try:
            sess = TpuSession({"spark.rapids.sql.enabled": True,
                               "spark.rapids.sql.explain": "NONE",
                               "spark.rapids.shuffle.hostStoreSize": 2048})
            t = sample_table(rng, 2000)
            df = sess.from_arrow(t).repartition(8, "a")
            out = df.collect()
            mgr = TpuShuffleManager.get(sess.conf)
            assert mgr.block_store._budget == 2048
            keys = [(k, "ascending") for k in ("a", "b")]
            assert out.sort_by(keys).equals(
                pa.Table.from_arrays(t.columns, names=t.column_names)
                .sort_by(keys))
        finally:
            TpuShuffleManager.reset()
