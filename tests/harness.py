"""CPU-vs-TPU differential harness — the analog of the reference's
`assert_gpu_and_cpu_are_equal_collect` (`integration_tests/.../asserts.py:261-536`):
the same expression/plan is evaluated by the CPU engine (numpy, exact-length) and the
device engine (jax.numpy under jit, padded batches, traced row count) and results are
compared exactly (or approximately for floats where reduction order differs)."""

import math

import jax
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar import batch_from_arrow
from spark_rapids_tpu.columnar.column import to_arrow as col_to_arrow
from spark_rapids_tpu.cpu.hostbatch import (host_batch_from_arrow,
                                            host_vec_to_arrow)
from spark_rapids_tpu.expr.base import EvalContext, Vec, bind_references


def eval_cpu(expr_factory, table: pa.Table):
    hb = host_batch_from_arrow(table)
    expr = bind_references(expr_factory(), hb.schema)
    ctx = EvalContext(np, row_mask=np.ones(hb.num_rows, dtype=bool))
    out = expr.eval(ctx, hb.vecs)
    return host_vec_to_arrow(out, hb.num_rows)


def eval_tpu(expr_factory, table: pa.Table):
    import jax.numpy as jnp
    batch = batch_from_arrow(table)
    hb_schema = batch.schema
    expr = bind_references(expr_factory(), hb_schema)

    def fn(b):
        ctx = EvalContext(jnp, row_mask=b.row_mask())
        vecs = [Vec.from_column(c) for c in b.columns]
        return expr.eval(ctx, vecs).to_column()

    col = jax.jit(fn)(batch)
    return col_to_arrow(col, batch.row_count())


def _values_equal(a, b, approx=False):
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) or math.isnan(b):
            return math.isnan(a) and math.isnan(b)
        if approx:
            return a == b or abs(a - b) <= 1e-6 * max(abs(a), abs(b))
        return a == b
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _values_equal(x, y, approx) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _values_equal(a[k], b[k], approx) for k in a)
    return a == b


def assert_arrays_equal(cpu, tpu, approx=False):
    cl, tl = cpu.to_pylist(), tpu.to_pylist()
    assert len(cl) == len(tl), f"length {len(cl)} vs {len(tl)}"
    for i, (a, b) in enumerate(zip(cl, tl)):
        assert _values_equal(a, b, approx), f"row {i}: {a!r} vs {b!r}"


def assert_cpu_tpu_equal(expr_factory, table: pa.Table, approx=False):
    cpu = eval_cpu(expr_factory, table)
    tpu = eval_tpu(expr_factory, table)
    assert_arrays_equal(cpu, tpu, approx=approx)
    return cpu
