"""IO tests: format scans under the three reader strategies + writers
(reference: parquet/orc/csv tests in integration_tests)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from data_gen import basic_gens, gen_table
from spark_rapids_tpu.expr import Count, Sum, col
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.explain": "NONE"})


@pytest.fixture
def gen_tbl(rng):
    return gen_table(rng, basic_gens(), n=500)


def _assert_df_equal(df, expected: pa.Table, sort_col="i64"):
    got = df.collect()
    got_cpu = df.collect_cpu()
    key = [(sort_col, "ascending"), ("f64", "ascending")]
    for t in (got, got_cpu):
        assert t.num_rows == expected.num_rows
    gs = got.sort_by(key)
    es = expected.sort_by(key)
    for name in expected.schema.names:
        a, b = gs.column(name).to_pylist(), es.column(name).to_pylist()
        for x, y in zip(a, b):
            if isinstance(x, float) and x != x:
                assert y != y
            else:
                assert x == y, f"{name}: {x!r} != {y!r}"


class TestParquet:
    def test_single_file_roundtrip(self, session, gen_tbl, tmp_path):
        p = str(tmp_path / "t.parquet")
        pq.write_table(gen_tbl, p)
        df = session.read_parquet(p)
        _assert_df_equal(df, gen_tbl)

    def test_multi_file_coalescing(self, session, gen_tbl, tmp_path):
        paths = []
        for i in range(4):
            p = str(tmp_path / f"t{i}.parquet")
            pq.write_table(gen_tbl.slice(i * 125, 125), p)
            paths.append(p)
        from spark_rapids_tpu.io.multifile import choose_reader_type
        assert choose_reader_type(paths, session.conf) == "COALESCING"
        df = session.read_parquet(*paths)
        _assert_df_equal(df, gen_tbl)

    def test_multithreaded_reader(self, session, gen_tbl, tmp_path):
        session.conf.set("spark.rapids.sql.format.parquet.reader.type",
                         "MULTITHREADED")
        try:
            paths = []
            for i in range(4):
                p = str(tmp_path / f"m{i}.parquet")
                pq.write_table(gen_tbl.slice(i * 125, 125), p)
                paths.append(p)
            df = session.read_parquet(*paths)
            _assert_df_equal(df, gen_tbl)
        finally:
            session.conf.set("spark.rapids.sql.format.parquet.reader.type",
                             "AUTO")

    def test_column_pruning(self, session, gen_tbl, tmp_path):
        p = str(tmp_path / "t.parquet")
        pq.write_table(gen_tbl, p)
        df = session.read_parquet(p, columns=["i64", "s"])
        out = df.collect()
        assert out.schema.names == ["i64", "s"]

    def test_predicate_pushdown(self, session, tmp_path):
        t = pa.table({"a": pa.array(range(1000), type=pa.int64())})
        p = str(tmp_path / "t.parquet")
        pq.write_table(t, p, row_group_size=100)
        df = session.read_parquet(p, filters=[("a", "<", 150)])
        out = df.collect()
        assert out.num_rows <= 200  # row-group pruned
        assert max(out.column("a").to_pylist()) < 200

    def test_scan_then_query(self, session, gen_tbl, tmp_path):
        p = str(tmp_path / "t.parquet")
        pq.write_table(gen_tbl, p)
        q = session.read_parquet(p).filter(col("i32") > 0) \
            .group_by("b").agg(n=Count(), s=Sum(col("i64")))
        tpu = q.collect().sort_by([("b", "ascending")])
        cpu = q.collect_cpu().sort_by([("b", "ascending")])
        assert tpu.equals(cpu)


class TestCsvJson:
    def test_csv_roundtrip(self, session, tmp_path):
        t = pa.table({"a": pa.array([1, 2, None], type=pa.int64()),
                      "s": pa.array(["x", None, "z"])})
        p = str(tmp_path / "t.csv")
        import pyarrow.csv as pacsv
        pacsv.write_csv(t, p)
        df = session.read_csv(p)
        out = df.collect()
        assert out.column("a").to_pylist() == [1, 2, None]

    def test_json_roundtrip(self, session, tmp_path):
        p = str(tmp_path / "t.jsonl")
        with open(p, "w") as f:
            f.write('{"a": 1, "s": "x"}\n{"a": null, "s": "y"}\n')
        df = session.read_json(p)
        out = df.collect()
        assert out.column("a").to_pylist() == [1, None]
        assert out.column("s").to_pylist() == ["x", "y"]


class TestOrc:
    def test_orc_roundtrip(self, session, tmp_path):
        t = pa.table({"a": pa.array([1, None, 3], type=pa.int64()),
                      "s": pa.array(["x", "y", None])})
        p = str(tmp_path / "t.orc")
        from pyarrow import orc
        orc.write_table(t, p)
        df = session.read_orc(p)
        out = df.collect()
        assert out.column("a").to_pylist() == [1, None, 3]


class TestWriter:
    def test_write_parquet_roundtrip(self, session, gen_tbl, tmp_path):
        df = session.from_arrow(gen_tbl)
        out_dir = str(tmp_path / "out")
        stats = df.write_parquet(out_dir)
        assert stats.num_files == 1 and stats.num_rows == 500
        back = session.read_parquet(
            *[os.path.join(out_dir, f) for f in os.listdir(out_dir)])
        assert back.collect().num_rows == 500

    def test_partitioned_write(self, session, tmp_path):
        t = pa.table({"k": pa.array(["a", "b", "a", None]),
                      "v": pa.array([1, 2, 3, 4], type=pa.int64())})
        out_dir = str(tmp_path / "part")
        stats = session.from_arrow(t).write_parquet(out_dir,
                                                    partition_by=["k"])
        assert stats.num_files == 3
        assert sorted(os.listdir(out_dir)) == \
            ["k=__HIVE_DEFAULT_PARTITION__", "k=a", "k=b"]
        sub = pq.read_table(os.path.join(out_dir, "k=a"))
        assert sorted(sub.column("v").to_pylist()) == [1, 3]

    def test_write_mode_error(self, session, tmp_path):
        t = pa.table({"v": pa.array([1], type=pa.int64())})
        out_dir = str(tmp_path / "dup")
        session.from_arrow(t).write_parquet(out_dir)
        with pytest.raises(FileExistsError):
            session.from_arrow(t).write_parquet(out_dir)
        session.from_arrow(t).write_parquet(out_dir, mode="overwrite")


class TestReviewRegressions:
    def test_csv_pruning_and_schema(self, session, tmp_path):
        import pyarrow.csv as pacsv
        t = pa.table({"a": pa.array([1, 2], type=pa.int64()),
                      "b": pa.array(["x", "y"]),
                      "c": pa.array([1.5, 2.5], type=pa.float64())})
        p = str(tmp_path / "t.csv")
        pacsv.write_csv(t, p)
        out = session.read_csv(p, columns=["a"]).collect()
        assert out.schema.names == ["a"]
        assert out.column("a").to_pylist() == [1, 2]

    def test_csv_headerless_schema(self, session, tmp_path):
        from spark_rapids_tpu.columnar import Schema
        from spark_rapids_tpu import types as T
        p = str(tmp_path / "nh.csv")
        with open(p, "w") as f:
            f.write("007,foo\n042,bar\n")
        schema = Schema(("code", "name"), (T.STRING, T.STRING))
        out = session.read_csv(p, header=False, schema=schema).collect()
        assert out.column("code").to_pylist() == ["007", "042"]  # stays string

    def test_csv_timestamp_normalized(self, session, tmp_path):
        p = str(tmp_path / "ts.csv")
        with open(p, "w") as f:
            f.write("ts\n2023-11-14T22:13:20Z\n")
        out = session.read_csv(p).collect()
        v = out.column("ts").to_pylist()[0]
        assert v.year == 2023 and v.hour == 22 and v.second == 20

    def test_coalescing_all_empty(self, session, tmp_path):
        t = pa.table({"a": pa.array([], type=pa.int64())})
        paths = []
        for i in range(2):
            p = str(tmp_path / f"e{i}.parquet")
            pq.write_table(t, p)
            paths.append(p)
        out = session.read_parquet(*paths).collect()
        assert out.num_rows == 0 and out.schema.names == ["a"]

    def test_write_mode_ignore_and_bad_mode(self, session, tmp_path):
        t = pa.table({"v": pa.array([1], type=pa.int64())})
        out_dir = str(tmp_path / "ig")
        session.from_arrow(t).write_parquet(out_dir)
        stats = session.from_arrow(t).write_parquet(out_dir, mode="ignore")
        assert stats.num_files == 0
        with pytest.raises(ValueError, match="unknown write mode"):
            session.from_arrow(t).write_parquet(out_dir, mode="overwite")

    def test_per_format_reader_type_key(self, session):
        session.conf.set("spark.rapids.sql.format.orc.reader.type", "PERFILE")
        from spark_rapids_tpu.io.multifile import choose_reader_type
        assert choose_reader_type(["a.orc", "b.orc"], session.conf,
                                  "orc") == "PERFILE"
        assert choose_reader_type(["a.pq", "b.pq"], session.conf,
                                  "parquet") == "COALESCING"


class TestDeviceParquetWrite:
    def _num_table(self, rng, n=1500):
        return pa.table({
            "i": pa.array(np.where(rng.random(n) < 0.15, None,
                                   rng.integers(-10**9, 10**9, n)),
                          type=pa.int64()),
            "f": pa.array(np.where(rng.random(n) < 0.1, None,
                                   rng.normal(0, 1e5, n)),
                          type=pa.float64()),
            "s32": pa.array(rng.integers(-100, 100, n), type=pa.int32()),
            "flag": pa.array(rng.random(n) < 0.5, type=pa.bool_()),
        })

    def test_device_write_roundtrip(self, session, rng, tmp_path):
        t = self._num_table(rng)
        df = session.from_arrow(t)
        stats = df.write_parquet(str(tmp_path / "out"))
        assert stats.num_rows == t.num_rows
        import pyarrow.dataset as pads
        back = pads.dataset(str(tmp_path / "out")).to_table()
        key = [("s32", "ascending"), ("i", "ascending"), ("f", "ascending")]
        assert back.cast(t.schema).sort_by(key).equals(t.sort_by(key))
        # the file must declare our device writer, proving the path taken
        import pyarrow.parquet as _pq
        f = [p for p in (tmp_path / "out").iterdir()][0]
        assert b"device writer" in open(f, "rb").read()

    def test_string_schema_falls_back_to_host(self, session, rng, tmp_path):
        t = pa.table({"s": pa.array(["a", "b", None]),
                      "x": pa.array([1, 2, 3], type=pa.int64())})
        df = session.from_arrow(t)
        df.write_parquet(str(tmp_path / "out"))
        import pyarrow.dataset as pads
        back = pads.dataset(str(tmp_path / "out")).to_table()
        assert back.num_rows == 3
        f = [p for p in (tmp_path / "out").iterdir()][0]
        assert b"device writer" not in open(f, "rb").read()

    def test_device_write_then_device_read(self, session, rng, tmp_path):
        """Full device loop: encode on device, decode on device."""
        t = self._num_table(rng, n=4000)
        session.from_arrow(t).write_parquet(str(tmp_path / "out"),
                                            compression="uncompressed")
        import pyarrow.dataset as pads
        files = [str(p) for p in (tmp_path / "out").iterdir()]
        from spark_rapids_tpu.io.parquet_device import file_supported
        # PLAIN + optional: exactly what the device decoder supports
        for f in files:
            file_supported(f, session.from_arrow(t).schema)
        df2 = session.read_parquet(*files)
        key = [("s32", "ascending"), ("i", "ascending"), ("f", "ascending")]
        got = df2.collect().cast(t.schema).sort_by(key)
        assert got.equals(t.sort_by(key))

    def test_mode_handling(self, session, rng, tmp_path):
        t = self._num_table(rng, n=50)
        df = session.from_arrow(t)
        df.write_parquet(str(tmp_path / "out"))
        with pytest.raises(FileExistsError):
            df.write_parquet(str(tmp_path / "out"))
        df.write_parquet(str(tmp_path / "out"), mode="overwrite")

    def test_byte_short_columns_roundtrip(self, session, rng, tmp_path):
        # INT8/INT16 widen to physical INT32 on device; footer declares the
        # logical type so readers restore the narrow type
        t = pa.table({
            "b8": pa.array(rng.integers(-100, 100, 200).astype("int8")),
            "s16": pa.array(rng.integers(-1000, 1000, 200).astype("int16")),
            "x": pa.array(rng.integers(0, 9, 200), type=pa.int64()),
        })
        session.from_arrow(t).write_parquet(str(tmp_path / "out"))
        import pyarrow.dataset as pads
        back = pads.dataset(str(tmp_path / "out")).to_table()
        key = [("x", "ascending"), ("b8", "ascending"), ("s16", "ascending")]
        assert back.cast(t.schema).sort_by(key).equals(t.sort_by(key))

    def test_unsupported_codec_falls_back_safely(self, session, rng,
                                                 tmp_path):
        t = pa.table({"x": pa.array(np.arange(50), type=pa.int64())})
        df = session.from_arrow(t)
        df.write_parquet(str(tmp_path / "out"), compression="gzip")
        df.write_parquet(str(tmp_path / "out"), compression="gzip",
                         mode="overwrite")  # must not destroy-and-crash
        import pyarrow.dataset as pads
        assert pads.dataset(str(tmp_path / "out")).to_table().num_rows == 50


class TestCsvDeviceDecode:
    """Device CSV line parse (csv_device.py): host frames lines, device
    splits fields and types them through the cast kernels."""

    def _write(self, tmp_path, text, name="t.csv"):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            f.write(text)
        return p

    def _schema(self):
        from spark_rapids_tpu.columnar.batch import Schema
        from spark_rapids_tpu import types as T
        return Schema(("id", "name", "score", "flag"),
                      (T.LONG, T.STRING, T.DOUBLE, T.BOOLEAN))

    def test_device_parse_matches_host(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        text = ("id,name,score,flag\n"
                "1,alpha,1.5,true\n"
                "2,,2.25,false\n"
                "3,NULL,bad,true\n"
                "4,delta,-0.5,\n")
        p = self._write(tmp_path, text)
        df = s.read_csv(p, schema=self._schema(), header=True)
        dev = df.collect()
        rows = dev.sort_by([("id", "ascending")]).to_pylist()
        assert rows[0] == {"id": 1, "name": "alpha", "score": 1.5,
                           "flag": True}
        assert rows[1]["name"] is None            # empty -> null marker
        assert rows[2]["name"] is None            # NULL marker
        assert rows[2]["score"] is None           # unparseable double
        assert rows[3]["flag"] is None            # empty bool
        # device path actually used: quote-free file + declared schema
        from spark_rapids_tpu.io.csv_device import (csv_device_supported,
                                                    device_decode_csv_file)
        assert csv_device_supported(df.plan)
        got = list(device_decode_csv_file(df.plan, p))
        assert got and int(got[0][1]) == 4

    def test_quoted_file_falls_back(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        text = 'id,name,score,flag\n1,"a,b",2.0,true\n'
        p = self._write(tmp_path, text)
        df = s.read_csv(p, schema=self._schema(), header=True)
        out = df.collect()  # host reader handles the quoted field
        assert out.column("name").to_pylist() == ["a,b"]

    def test_crlf_and_headerless(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        p = self._write(tmp_path, "5,x,1.0,true\r\n6,y,2.0,false\r\n")
        df = s.read_csv(p, schema=self._schema(), header=False)
        out = df.collect().sort_by([("id", "ascending")])
        assert out.column("id").to_pylist() == [5, 6]
        assert out.column("name").to_pylist() == ["x", "y"]

    def test_query_over_device_csv(self, tmp_path):
        from spark_rapids_tpu.expr import Sum, col, lit
        from spark_rapids_tpu.plugin import TpuSession
        import numpy as np
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        rng = np.random.default_rng(23)
        lines = ["id,name,score,flag"]
        tot = 0.0
        for i in range(2000):
            sc = round(float(rng.normal()), 4)
            fl = "true" if i % 2 else "false"
            lines.append(f"{i},n{i},{sc},{fl}")
            if i % 2:
                tot += sc
        p = self._write(tmp_path, "\n".join(lines) + "\n")
        df = s.read_csv(p, schema=self._schema(), header=True)
        q = df.filter(col("flag")).agg(t=Sum(col("score")))
        got = q.collect().column("t").to_pylist()[0]
        cpu = q.collect_cpu().column("t").to_pylist()[0]
        assert abs(got - tot) < 1e-6 and abs(cpu - tot) < 1e-6

    def test_blank_crlf_lines_and_chunking(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.batchSizeRows": 3})
        text = "1,a,1.0,true\r\n\r\n2,b,2.0,false\r\n\n3,c,3.0,true\r\n" \
               "4,d,4.0,false\r\n5,e,5.0,true\r\n"
        p = self._write(tmp_path, text)
        df = s.read_csv(p, schema=self._schema(), header=False)
        out = df.collect().sort_by([("id", "ascending")])
        # blank lines drop like the host reader; batches chunk at 3 rows
        assert out.column("id").to_pylist() == [1, 2, 3, 4, 5]
        assert out.column("name").to_pylist() == ["a", "b", "c", "d", "e"]

    def test_tiny_decimals_parse_exactly(self, tmp_path):
        # review regression: leading zeros must not consume the mantissa
        # budget; sub-1e-308 exponents need the two-step divide
        from spark_rapids_tpu.plugin import TpuSession
        from spark_rapids_tpu.columnar.batch import Schema
        from spark_rapids_tpu import types as T
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        text = "0.000000000000001\n2.5e-310\n0.001234567890123\n"
        p = self._write(tmp_path, text, name="tiny.csv")
        sch = Schema(("v",), (T.DOUBLE,))
        df = s.read_csv(p, schema=sch, header=False)
        got = df.collect().column("v").to_pylist()
        assert got[0] == 1e-15
        # XLA flushes subnormals: 2.5e-310 parses to an honest 0.0 on
        # device (never a wrong magnitude)
        assert got[1] == 0.0
        assert got[2] == 0.001234567890123

    def test_empty_file_and_zero_exponent(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        from spark_rapids_tpu.columnar.batch import Schema
        from spark_rapids_tpu import types as T
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        pe = self._write(tmp_path, "", name="empty.csv")
        df = s.read_csv(pe, schema=self._schema(), header=False)
        assert df.collect().num_rows == 0
        pz = self._write(tmp_path, "0e999\n1e400\n", name="z.csv")
        sch = Schema(("v",), (T.DOUBLE,))
        got = s.read_csv(pz, schema=sch, header=False).collect()
        vals = got.column("v").to_pylist()
        assert vals[0] == 0.0          # zero mantissa never overflows
        assert vals[1] == float("inf")
