"""Independent-oracle golden tests (r3 verdict directive #4, mirroring the
role of reference `integration_tests/src/main/python/asserts.py:261-536`,
which diffs the accelerator against *real Spark*).

Every expected value here is computed by pandas / numpy / python
`decimal` / `datetime` code written directly in the test — sharing NO
code with the engine's expression or exec implementations — so a
wrong-but-consistent Spark-semantics bug in the shared-xp kernels cannot
cancel out the way it can in the `assert_same` device-vs-CPU harness.
Coverage targets the highest-divergence-risk areas named by the verdict:
decimal aggregation, datetime extraction, window frames, null ordering,
plus the full TPC-DS-shaped corpus and the mortgage app end to end.

The engine side always runs `.collect()` (the device engine)."""

import datetime as dt
import decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (Average, CaseWhen, Count, If, Max, Min,
                                   RowNumber, Sum, col, lit)
from spark_rapids_tpu.plugin import TpuSession

D = decimal.Decimal


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE",
                       "spark.rapids.sql.adaptive.enabled": True,
                       "spark.rapids.sql.optimizer.enabled": True})


# ---------------------------------------------------------------------------
# star schema (same shapes as test_tpcds_shapes, independently generated)
# ---------------------------------------------------------------------------

N_DATES, N_ITEMS, N_STORES, N_CUSTOMERS, N_SALES = 365, 60, 8, 150, 4000


@pytest.fixture(scope="module")
def star_tables():
    rng = np.random.default_rng(7)
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(N_DATES, dtype=np.int64)),
        "d_year": pa.array((2020 + np.arange(N_DATES) // 365)
                           .astype(np.int32)),
        "d_moy": pa.array((np.arange(N_DATES) % 365 // 31 + 1)
                          .astype(np.int32)),
        "d_dow": pa.array((np.arange(N_DATES) % 7).astype(np.int32)),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(N_ITEMS, dtype=np.int64)),
        "i_brand": pa.array([f"brand{i % 9}" for i in range(N_ITEMS)]),
        "i_category": pa.array([f"cat{i % 5}" for i in range(N_ITEMS)]),
        "i_price": pa.array(rng.uniform(1, 200, N_ITEMS).round(2)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(N_STORES, dtype=np.int64)),
        "s_state": pa.array([f"ST{i % 3}" for i in range(N_STORES)]),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(N_CUSTOMERS, dtype=np.int64)),
        "c_band": pa.array((np.arange(N_CUSTOMERS) % 10).astype(np.int32)),
    })
    nulls = rng.random(N_SALES) < 0.03
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(0, N_DATES, N_SALES).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(0, N_ITEMS, N_SALES).astype(np.int64)),
        "ss_store_sk": pa.array(
            rng.integers(0, N_STORES, N_SALES).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(0, N_CUSTOMERS, N_SALES).astype(np.int64)),
        "ss_quantity": pa.array(
            rng.integers(1, 20, N_SALES).astype(np.int32)),
        "ss_sales_price": pa.array(
            np.where(nulls, 0.0, rng.uniform(1, 250, N_SALES).round(2)),
            mask=nulls),
    })
    return {"date_dim": date_dim, "item": item, "store": store,
            "customer": customer, "store_sales": store_sales}


@pytest.fixture(scope="module")
def star(session, star_tables):
    return {k: session.from_arrow(v, label=k)
            for k, v in star_tables.items()}


@pytest.fixture(scope="module")
def pdf(star_tables):
    return {k: v.to_pandas() for k, v in star_tables.items()}


def _rows(table: pa.Table, keys):
    """Engine output -> {key tuple: row dict} (keys as python values)."""
    out = {}
    for r in table.to_pylist():
        out[tuple(r[k] for k in keys)] = r
    return out


class TestTpcdsGolden:
    def test_q3_brand_report(self, star, pdf):
        got = (star["store_sales"]
               .join(star["date_dim"],
                     condition=col("ss_sold_date_sk") == col("d_date_sk"),
                     how="inner")
               .filter(col("d_moy") == lit(11))
               .join(star["item"],
                     condition=col("ss_item_sk") == col("i_item_sk"),
                     how="inner")
               .group_by("d_year", "i_brand")
               .agg(s=Sum(col("ss_sales_price")))).collect()
        m = (pdf["store_sales"]
             .merge(pdf["date_dim"], left_on="ss_sold_date_sk",
                    right_on="d_date_sk"))
        m = m[m.d_moy == 11].merge(pdf["item"], left_on="ss_item_sk",
                                   right_on="i_item_sk")
        exp = m.groupby(["d_year", "i_brand"])["ss_sales_price"] \
            .sum(min_count=1)
        rows = _rows(got, ("d_year", "i_brand"))
        assert set(rows) == set(exp.index)
        for k, v in exp.items():
            gv = rows[k]["s"]
            if pd.isna(v):
                assert gv is None
            else:
                assert gv == pytest.approx(v, rel=1e-9)

    def test_q7_category_averages(self, star, pdf):
        got = (star["store_sales"]
               .join(star["item"],
                     condition=col("ss_item_sk") == col("i_item_sk"),
                     how="inner")
               .join(star["store"],
                     condition=col("ss_store_sk") == col("s_store_sk"),
                     how="inner")
               .filter(col("s_state") == lit("ST1"))
               .group_by("i_category")
               .agg(q=Average(col("ss_quantity")),
                    p=Average(col("ss_sales_price")),
                    n=Count(lit(1)))).collect()
        m = (pdf["store_sales"]
             .merge(pdf["item"], left_on="ss_item_sk",
                    right_on="i_item_sk")
             .merge(pdf["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        m = m[m.s_state == "ST1"]
        g = m.groupby("i_category")
        rows = _rows(got, ("i_category",))
        assert set(k for (k,) in rows) == set(g.groups)
        for cat, grp in g:
            r = rows[(cat,)]
            assert r["q"] == pytest.approx(grp.ss_quantity.mean(), rel=1e-9)
            assert r["p"] == pytest.approx(grp.ss_sales_price.mean(skipna=True),
                                           rel=1e-9)
            assert r["n"] == len(grp)

    def test_q68_customer_rollup_with_rank(self, star, pdf):
        per_cust = (star["store_sales"]
                    .join(star["customer"],
                          condition=col("ss_customer_sk")
                          == col("c_customer_sk"), how="inner")
                    .group_by("c_customer_sk", "c_band")
                    .agg(spend=Sum(col("ss_sales_price")),
                         qty=Sum(col("ss_quantity"))))
        got = per_cust.window(partition_by=["c_band"],
                              order_by=[(col("spend"), False, False)],
                              rnk=RowNumber()).collect()
        m = (pdf["store_sales"]
             .merge(pdf["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk"))
        exp = m.groupby(["c_customer_sk", "c_band"]).agg(
            spend=("ss_sales_price", lambda s: s.sum(min_count=1)),
            qty=("ss_quantity", "sum")).reset_index()
        rows = _rows(got, ("c_customer_sk",))
        assert len(rows) == len(exp)
        for _, e in exp.iterrows():
            r = rows[(e.c_customer_sk,)]
            assert r["qty"] == e.qty
            if pd.isna(e.spend):
                assert r["spend"] is None
            else:
                assert r["spend"] == pytest.approx(e.spend, rel=1e-9)
        # row_number semantics per band: spends listed by rank must equal
        # spends sorted descending (nulls last — Spark desc NULLS LAST)
        gdf = got.to_pandas()
        for band, grp in gdf.groupby("c_band"):
            by_rank = grp.sort_values("rnk")["spend"].tolist()
            want = sorted([s for s in by_rank if not pd.isna(s)],
                          reverse=True) + [s for s in by_rank if pd.isna(s)]
            assert [s if not pd.isna(s) else None for s in by_rank] == \
                [s if not pd.isna(s) else None for s in want]
            assert sorted(grp["rnk"]) == list(range(1, len(grp) + 1))

    def test_q96_selective_count(self, star, pdf):
        got = (star["store_sales"]
               .join(star["date_dim"],
                     condition=col("ss_sold_date_sk") == col("d_date_sk"),
                     how="inner")
               .filter((col("d_dow") == lit(6)) &
                       (col("ss_quantity") > lit(10)))
               .join(star["store"],
                     condition=col("ss_store_sk") == col("s_store_sk"),
                     how="inner")
               .agg(cnt=Count(lit(1)))).collect()
        m = pdf["store_sales"].merge(
            pdf["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m[(m.d_dow == 6) & (m.ss_quantity > 10)]
        m = m.merge(pdf["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        assert got.column("cnt").to_pylist() == [len(m)]

    def test_q19_semi_anti(self, star, pdf):
        nov = star["date_dim"].filter(col("d_moy") == lit(11))
        sold_nov = star["store_sales"].join(
            nov, condition=col("ss_sold_date_sk") == col("d_date_sk"),
            how="semi")
        got = (sold_nov.group_by("ss_store_sk")
               .agg(n=Count(lit(1)), s=Sum(col("ss_sales_price")))).collect()
        nov_dates = set(pdf["date_dim"][pdf["date_dim"].d_moy == 11]
                        .d_date_sk)
        sold = pdf["store_sales"][
            pdf["store_sales"].ss_sold_date_sk.isin(nov_dates)]
        g = sold.groupby("ss_store_sk")
        rows = _rows(got, ("ss_store_sk",))
        assert set(k for (k,) in rows) == set(g.groups)
        for sk, grp in g:
            assert rows[(sk,)]["n"] == len(grp)
            assert rows[(sk,)]["s"] == pytest.approx(
                grp.ss_sales_price.sum(min_count=1), rel=1e-9)
        # anti: items never sold in november
        never = star["item"].join(
            star["store_sales"].join(
                nov, condition=col("ss_sold_date_sk") == col("d_date_sk"),
                how="semi"),
            condition=col("i_item_sk") == col("ss_item_sk"), how="anti")
        got2 = never.agg(n=Count(lit(1))).collect()
        sold_items = set(sold.ss_item_sk)
        exp_n = (~pdf["item"].i_item_sk.isin(sold_items)).sum()
        assert got2.column("n").to_pylist() == [int(exp_n)]

    def test_q36_case_rollup(self, star, pdf):
        got = (star["store_sales"]
               .join(star["item"],
                     condition=col("ss_item_sk") == col("i_item_sk"),
                     how="inner")
               .select("i_category", "ss_quantity",
                       margin=col("ss_sales_price") - col("i_price"),
                       bucket=CaseWhen(
                           [(col("ss_sales_price") > lit(200), lit("lux")),
                            (col("ss_sales_price") > lit(50), lit("mid"))],
                           lit("base")))
               .group_by("i_category", "bucket")
               .agg(m=Average(col("margin")), n=Count(lit(1)),
                    hi=Max(col("margin")), lo=Min(col("margin")))).collect()
        m = pdf["store_sales"].merge(pdf["item"], left_on="ss_item_sk",
                                     right_on="i_item_sk")
        price = m.ss_sales_price
        m = m.assign(
            margin=price - m.i_price,
            bucket=np.select([price > 200, price > 50], ["lux", "mid"],
                             "base"))
        g = m.groupby(["i_category", "bucket"])
        rows = _rows(got, ("i_category", "bucket"))
        assert set(rows) == set(g.groups)
        for k, grp in g:
            r = rows[k]
            assert r["n"] == len(grp)
            if grp.margin.notna().any():
                assert r["m"] == pytest.approx(grp.margin.mean(), rel=1e-9)
                assert r["hi"] == pytest.approx(grp.margin.max(), rel=1e-9)
                assert r["lo"] == pytest.approx(grp.margin.min(), rel=1e-9)
            else:
                assert r["m"] is None and r["hi"] is None and r["lo"] is None

    def test_q65_join_of_aggregates(self, star, pdf):
        per_si = (star["store_sales"]
                  .group_by("ss_store_sk", "ss_item_sk")
                  .agg(rev=Sum(col("ss_sales_price"))))
        per_s = (per_si.group_by("ss_store_sk")
                 .agg(avg_rev=Average(col("rev"))))
        got = (per_si.join(per_s, on="ss_store_sk", how="inner")
               .filter(col("rev") > col("avg_rev"))
               .agg(n=Count(lit(1)), tot=Sum(col("rev")))).collect()
        si = pdf["store_sales"].groupby(["ss_store_sk", "ss_item_sk"])[
            "ss_sales_price"].sum(min_count=1).rename("rev").reset_index()
        s = si.groupby("ss_store_sk")["rev"].mean().rename(
            "avg_rev").reset_index()
        j = si.merge(s, on="ss_store_sk")
        j = j[j.rev > j.avg_rev]
        assert got.column("n").to_pylist() == [len(j)]
        assert got.column("tot").to_pylist()[0] == \
            pytest.approx(j.rev.sum(), rel=1e-9)


# ---------------------------------------------------------------------------
# mortgage app golden
# ---------------------------------------------------------------------------

class TestMortgageGolden:
    @pytest.fixture(scope="class")
    def data(self):
        from apps.mortgage import gen_acquisition, gen_performance
        rng = np.random.default_rng(42)
        return gen_performance(rng), gen_acquisition(rng)

    def test_etl_golden(self, session, data):
        from apps.mortgage import NAME_MAP, mortgage_etl
        perf, acq = data
        got = mortgage_etl(session, session.from_arrow(perf),
                           session.from_arrow(acq)).collect()
        p = perf.to_pandas()
        a = acq.to_pandas()
        summary = p.groupby("loan_id").agg(
            months=("period", "count"),
            max_dlq=("dlq_status", "max"),
            ever_30=("dlq_status", lambda s: int((s >= 1).any())),
            ever_90=("dlq_status", lambda s: int((s >= 3).any())),
            ever_180=("dlq_status", lambda s: int((s >= 6).any())),
            min_upb=("upb", "min"),
            avg_rate=("interest_rate", "mean")).reset_index()
        a = a.assign(seller=a.seller_name.map(NAME_MAP).fillna("Unknown"))
        j = summary.merge(a, on="loan_id")
        j = j.assign(
            rate_spread=j.avg_rate - j.orig_rate,
            risk=np.select([j.ever_180 == 1, j.ever_90 == 1,
                            j.ever_30 == 1],
                           ["severe", "high", "watch"], "performing"))
        rows = _rows(got, ("loan_id",))
        assert len(rows) == len(j)
        for _, e in j.iterrows():
            r = rows[(e.loan_id,)]
            assert r["months"] == e.months
            assert r["max_dlq"] == e.max_dlq
            assert (r["ever_30"], r["ever_90"], r["ever_180"]) == \
                (e.ever_30, e.ever_90, e.ever_180)
            assert r["risk"] == e.risk
            assert r["min_upb"] == pytest.approx(e.min_upb, rel=1e-9)
            if pd.isna(e.avg_rate):
                assert r["avg_rate"] is None
            else:
                assert r["avg_rate"] == pytest.approx(e.avg_rate, rel=1e-9)
                assert r["rate_spread"] == pytest.approx(e.rate_spread,
                                                         rel=1e-9)

    def test_simple_aggregates_golden(self, session, data):
        from apps.mortgage import simple_aggregates
        perf, _ = data
        got = simple_aggregates(session,
                                session.from_arrow(perf)).collect()
        p = perf.to_pandas()
        g = p.groupby("servicer")
        rows = _rows(got, ("servicer",))
        assert set(k for (k,) in rows) == set(g.groups)
        for sv, grp in g:
            r = rows[(sv,)]
            assert r["loans"] == len(grp)
            assert r["avg_upb"] == pytest.approx(grp.upb.mean(), rel=1e-9)
            assert r["total_upb"] == pytest.approx(grp.upb.sum(), rel=1e-9)
            assert r["worst"] == grp.dlq_status.max()
            assert r["d30"] == int((grp.dlq_status >= 1).sum())
            assert r["d90"] == int((grp.dlq_status >= 3).sum())


# ---------------------------------------------------------------------------
# targeted high-divergence-risk areas
# ---------------------------------------------------------------------------

class TestDecimalAggGolden:
    def test_decimal_sum_exact_vs_python_decimal(self, session):
        # decimal(25,3): wide enough for the 128-bit limb path; exact sums
        # computed with python decimal, no float in the oracle
        rng = np.random.default_rng(11)
        n = 500
        vals = [D(int(rng.integers(-10**12, 10**12))).scaleb(-3)
                for _ in range(n)]
        keys = rng.integers(0, 7, n).astype(np.int32)
        t = pa.table({"k": pa.array(keys),
                      "d": pa.array(vals, type=pa.decimal128(25, 3))})
        got = (session.from_arrow(t).group_by("k")
               .agg(s=Sum(col("d")))).collect()
        exp = {}
        for k, v in zip(keys.tolist(), vals):
            exp[k] = exp.get(k, D(0)) + v
        rows = _rows(got, ("k",))
        assert set(k for (k,) in rows) == set(exp)
        for k, v in exp.items():
            assert rows[(k,)]["s"] == v  # exact decimal equality

    def test_decimal_sum_with_nulls(self, session):
        t = pa.table({"k": pa.array([1, 1, 2, 2], type=pa.int32()),
                      "d": pa.array([D("1.5"), None, None, None],
                                    type=pa.decimal128(20, 2))})
        got = (session.from_arrow(t).group_by("k")
               .agg(s=Sum(col("d")))).collect()
        rows = _rows(got, ("k",))
        assert rows[(1,)]["s"] == D("1.50")
        assert rows[(2,)]["s"] is None  # all-null group sums to NULL


class TestDatetimeGolden:
    def test_extract_fields_vs_python_datetime(self, session):
        from spark_rapids_tpu.expr import (DayOfMonth, DayOfWeek, DayOfYear,
                                           Month, Quarter, Year)
        dates = [dt.date(1970, 1, 1), dt.date(2000, 2, 29),
                 dt.date(2020, 12, 31), dt.date(1969, 7, 20),
                 dt.date(2024, 2, 29), dt.date(1900, 3, 1),
                 dt.date(2038, 1, 19)]
        t = pa.table({"d": pa.array(dates, type=pa.date32()),
                      "i": pa.array(range(len(dates)), type=pa.int64())})
        got = session.from_arrow(t).select(
            "i", y=Year(col("d")), m=Month(col("d")),
            dom=DayOfMonth(col("d")), doy=DayOfYear(col("d")),
            q=Quarter(col("d")), dow=DayOfWeek(col("d"))).collect()
        rows = _rows(got, ("i",))
        for i, d in enumerate(dates):
            r = rows[(i,)]
            assert r["y"] == d.year
            assert r["m"] == d.month
            assert r["dom"] == d.day
            assert r["doy"] == d.timetuple().tm_yday
            assert r["q"] == (d.month - 1) // 3 + 1
            # Spark dayofweek: 1 = Sunday ... 7 = Saturday
            assert r["dow"] == d.isoweekday() % 7 + 1


class TestStringToDateGolden:
    def test_spark_stringtodate_grammar(self, session):
        # Spark DateTimeUtils.stringToDate: yyyy | yyyy-[m]m |
        # yyyy-[m]m-[d]d (+ optional 'T'/space tail after the full form),
        # with isValidDigits segment rules (year 4-7 digits, month/day
        # 1-2) — '99' and '2020-012-01' are NULL, '02020-1-1' is a date
        from spark_rapids_tpu.expr import Cast
        cases = ["2020", "2020-03", "2020-3-7", "2020-01-01",
                 "2020-01-01T12:30:00", "2020-01-01 12:30", "2020T12",
                 "2020-1", "abc", "2020-13-01", "2020-02-30",
                 " 2021-06-05 ", "99", "2020-012-01", "02020-1-1",
                 "2020-01-01Trubbish", None]
        exp = [dt.date(2020, 1, 1), dt.date(2020, 3, 1),
               dt.date(2020, 3, 7), dt.date(2020, 1, 1),
               dt.date(2020, 1, 1), dt.date(2020, 1, 1), None,
               dt.date(2020, 1, 1), None, None, None,
               dt.date(2021, 6, 5), None, None, dt.date(2020, 1, 1),
               dt.date(2020, 1, 1), None]
        df = session.from_arrow(pa.table({"s": pa.array(cases)}))
        q = df.select(d=Cast(col("s"), T.DATE))
        assert q.collect().column("d").to_pylist() == exp
        assert q.collect_cpu().column("d").to_pylist() == exp


class TestWindowFrameGolden:
    def test_running_sum_rows_frame_vs_pandas_cumsum(self, session):
        from spark_rapids_tpu.expr.windowexprs import (RowFrame,
                                                       WindowAggregate)
        rng = np.random.default_rng(3)
        n = 200
        t = pa.table({
            "g": pa.array(rng.integers(0, 5, n).astype(np.int32)),
            "o": pa.array(np.arange(n, dtype=np.int64)),
            "v": pa.array(rng.integers(-50, 50, n).astype(np.int64)),
        })
        got = session.from_arrow(t).window(
            partition_by=["g"], order_by=[(col("o"), True, True)],
            run=WindowAggregate(Sum(col("v")), RowFrame(None, 0)),
            last3=WindowAggregate(Sum(col("v")), RowFrame(-2, 0)),
            center=WindowAggregate(Min(col("v")), RowFrame(-1, 1))).collect()
        p = t.to_pandas().sort_values(["g", "o"])
        p["run"] = p.groupby("g")["v"].cumsum()
        p["last3"] = p.groupby("g")["v"].transform(
            lambda s: s.rolling(3, min_periods=1).sum())
        p["center"] = p.groupby("g")["v"].transform(
            lambda s: s.rolling(3, min_periods=1, center=True).min())
        rows = _rows(got, ("o",))
        for _, e in p.iterrows():
            r = rows[(e.o,)]
            assert r["run"] == e.run
            assert r["last3"] == e.last3
            assert r["center"] == e.center

    def test_rank_vs_pandas_rank(self, session):
        from spark_rapids_tpu.expr import DenseRank, Rank
        t = pa.table({
            "g": pa.array([1, 1, 1, 1, 2, 2, 2], type=pa.int32()),
            "v": pa.array([10, 10, 20, 30, 5, 5, 5], type=pa.int64()),
            "i": pa.array(range(7), type=pa.int64()),
        })
        got = session.from_arrow(t).window(
            partition_by=["g"], order_by=[(col("v"), True, True)],
            r=Rank(), dr=DenseRank()).collect()
        p = t.to_pandas()
        p["r"] = p.groupby("g")["v"].rank(method="min").astype(int)
        p["dr"] = p.groupby("g")["v"].rank(method="dense").astype(int)
        rows = _rows(got, ("i",))
        for _, e in p.iterrows():
            assert rows[(e.i,)]["r"] == e.r
            assert rows[(e.i,)]["dr"] == e.dr


class TestJoinSemanticsGolden:
    def test_null_keys_never_match(self, session):
        # SQL: NULL = NULL is not true, so null keys match nothing —
        # including other null keys. NOTE pandas merge MATCHES NaN keys
        # to each other (non-SQL semantics), so the expectations here are
        # hand-written, not pandas-derived.
        left = session.from_arrow(pa.table(
            {"k": pa.array([1, None, 2, None], type=pa.int64()),
             "a": pa.array([10, 20, 30, 40], type=pa.int64())}))
        right = session.from_arrow(pa.table(
            {"k": pa.array([1, None, 3], type=pa.int64()),
             "b": pa.array([100, 200, 300], type=pa.int64())}))
        inner = left.join(right, on="k", how="inner").collect()
        assert inner.to_pylist() == [{"k": 1, "a": 10, "b": 100}]
        louter = left.join(right, on="k", how="left").collect() \
            .sort_by([("a", "ascending")]).to_pylist()
        assert [r["b"] for r in louter] == [100, None, None, None]
        anti = left.join(right, on="k", how="anti").collect() \
            .sort_by([("a", "ascending")]).to_pylist()
        # null-key left rows survive an anti join (they match nothing)
        assert [r["a"] for r in anti] == [20, 30, 40]

    def test_full_outer_vs_pandas(self, session):
        rng = np.random.default_rng(13)
        lk = rng.integers(0, 30, 120)
        rk = rng.integers(10, 40, 80)
        left = pa.table({"k": pa.array(lk, type=pa.int64()),
                         "a": pa.array(np.arange(120), type=pa.int64())})
        right = pa.table({"k": pa.array(rk, type=pa.int64()),
                          "b": pa.array(np.arange(80), type=pa.int64())})
        q = session.from_arrow(left).join(session.from_arrow(right),
                                          on="k", how="full")
        t = q.collect()
        # ON-join semantics: BOTH key columns survive (read positionally —
        # to_pylist() dicts would collapse the duplicate names)
        lk_c, a_c, rk_c, b_c = (t.column(i).to_pylist() for i in range(4))

        def key(tup):
            return tuple(-1 if v is None else v + 1 for v in tup)

        got = sorted(zip(lk_c, a_c, rk_c, b_c), key=key)
        exp = left.to_pandas().merge(right.to_pandas(), on="k",
                                     how="outer")
        want = sorted(
            ((None if pd.isna(r.a) else int(r.k),
              None if pd.isna(r.a) else int(r.a),
              None if pd.isna(r.b) else int(r.k),
              None if pd.isna(r.b) else int(r.b))
             for r in exp.itertuples()), key=key)
        assert got == want


class TestNullOrderingGolden:
    def test_sort_null_placement_explicit(self, session):
        t = pa.table({"v": pa.array([3, None, 1, None, 2],
                                    type=pa.int64()),
                      "i": pa.array(range(5), type=pa.int64())})
        df = session.from_arrow(t)
        # asc nulls first (Spark default for asc)
        got = df.sort((col("v"), True, True)).collect()
        assert got.column("v").to_pylist() == [None, None, 1, 2, 3]
        # asc nulls last
        got = df.sort((col("v"), True, False)).collect()
        assert got.column("v").to_pylist() == [1, 2, 3, None, None]
        # desc nulls last (Spark default for desc)
        got = df.sort((col("v"), False, False)).collect()
        assert got.column("v").to_pylist() == [3, 2, 1, None, None]
        # desc nulls first
        got = df.sort((col("v"), False, True)).collect()
        assert got.column("v").to_pylist() == [None, None, 3, 2, 1]

    def test_sort_string_nulls_and_ties_stable_keys(self, session):
        t = pa.table({"s": pa.array(["b", None, "a", "", None, "b"]),
                      "i": pa.array(range(6), type=pa.int64())})
        got = session.from_arrow(t).sort((col("s"), True, True),
                                         (col("i"), True, True)).collect()
        assert got.column("s").to_pylist() == \
            [None, None, "", "a", "b", "b"]
        assert got.column("i").to_pylist() == [1, 4, 3, 2, 0, 5]

    def test_groupby_null_key_is_a_group(self, session):
        t = pa.table({"k": pa.array([1, None, 1, None, 2],
                                    type=pa.int64()),
                      "v": pa.array([10, 20, 30, 40, 50],
                                    type=pa.int64())})
        got = (session.from_arrow(t).group_by("k")
               .agg(s=Sum(col("v")), n=Count(col("v")))).collect()
        rows = _rows(got, ("k",))
        assert rows[(1,)] == {"k": 1, "s": 40, "n": 2}
        assert rows[(2,)] == {"k": 2, "s": 50, "n": 1}
        assert rows[(None,)] == {"k": None, "s": 60, "n": 2}
