"""Spark adapter EXPRESSION breadth (round-5 verdict #4): toJSON fixtures
per Catalyst expression family — string fns, date fns, In/InSet,
Like/RLike, CaseWhen/Coalesce/If, GetStructField, round/abs/sign,
stddev/variance/collect aggregates — translate through
`integration/spark_plan.py` and answer identically on the device and CPU
engines. A coverage test enumerates the adapter's translatable class set
against the engine's override registry (reference surface:
`GpuOverrides.scala:866-3475`)."""

import json

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.integration import translate_spark_plan
from spark_rapids_tpu.integration.spark_plan import (UnsupportedSparkPlan,
                                                     translatable_expr_classes)
from spark_rapids_tpu.plugin import TpuSession

EXPR = "org.apache.spark.sql.catalyst.expressions."
EXEC = "org.apache.spark.sql.execution."


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def attr(name, dtype):
    return [{"class": EXPR + "AttributeReference", "num-children": 0,
             "name": name, "dataType": dtype, "nullable": True,
             "metadata": {}, "exprId": {"id": 1, "jvmId": "x"},
             "qualifier": []}]


def lit(value, dtype):
    return [{"class": EXPR + "Literal", "num-children": 0,
             "value": None if value is None else str(value),
             "dataType": dtype}]


def ex(cls_name, *children, **fields):
    """Generic expression node: pre-order flattening of children."""
    out = [{"class": EXPR + cls_name, "num-children": len(children),
            **fields}]
    for ch in children:
        out += ch
    return out


def alias(expr, name):
    return [{"class": EXPR + "Alias", "num-children": 1, "name": name,
             "exprId": {"id": 9, "jvmId": "x"}}] + expr


def scan(ident, cols):
    return {"class": EXEC + "FileSourceScanExec", "num-children": 0,
            "relation": "HadoopFsRelation(parquet)",
            "output": [attr(n, t) for n, t in cols],
            "tableIdentifier": ident}


_COLS = [("k", "long"), ("v", "double"), ("s", "string"), ("d", "date"),
         ("i", "integer")]


def project_plan(projs):
    node = {"class": EXEC + "ProjectExec", "num-children": 1,
            "projectList": [alias(p, f"c{i}")
                            for i, p in enumerate(projs)]}
    return json.dumps([node, scan("t", _COLS)])


def filter_plan(cond):
    node = {"class": EXEC + "FilterExec", "num-children": 1,
            "condition": cond}
    return json.dumps([node, scan("t", _COLS)])


def agg_plan(fn_cls, child, extra_children=()):
    ae = [{"class": EXPR + "aggregate.AggregateExpression",
           "num-children": 1, "mode": "Complete", "isDistinct": False}] + \
        [{"class": EXPR + f"aggregate.{fn_cls}",
          "num-children": 1 + len(extra_children)}] + child
    for e in extra_children:
        ae += e
    node = {"class": EXEC + "aggregate.HashAggregateExec",
            "num-children": 1,
            "groupingExpressions": [attr("k", "long")],
            "aggregateExpressions": [ae], "resultExpressions": []}
    return json.dumps([node, scan("t", _COLS)])


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("adapter_exprs")
    rng = np.random.default_rng(31)
    n = 1500
    import datetime
    epoch = datetime.date(1970, 1, 1)
    t = pa.table({
        "k": pa.array(rng.integers(0, 12, n).astype(np.int64)),
        "v": pa.array(rng.normal(0.0, 100.0, n)),
        "s": pa.array([f"Item_{i % 37}_x{'y' * (i % 5)}"
                       for i in range(n)]),
        "d": pa.array([epoch + datetime.timedelta(days=int(x))
                       for x in rng.integers(10000, 14000, n)],
                      type=pa.date32()),
        "i": pa.array(rng.integers(-1000, 1000, n).astype(np.int32)),
    })
    p = str(d / "t.parquet")
    pq.write_table(t, p)
    return p, t


def run_both(session, plan_json, path, sort_first_col=True):
    plan = translate_spark_plan(plan_json, session.conf, {"t": [path]})
    dev = session.execute_plan(plan)
    cpu = session.execute_plan(plan, use_device=False)
    assert dev.schema.names == cpu.schema.names
    keys = [(dev.schema.names[0], "ascending")] if sort_first_col else []
    if keys:
        dev, cpu = dev.sort_by(keys), cpu.sort_by(keys)
    assert dev.num_rows == cpu.num_rows
    for name in dev.schema.names:
        a, b = dev.column(name).to_pylist(), cpu.column(name).to_pylist()
        for x, y in zip(a, b):
            if isinstance(x, float) and x is not None and y is not None:
                assert x == y or abs(x - y) <= 1e-9 * max(
                    abs(x), abs(y), 1.0), (name, x, y)
            else:
                assert x == y, (name, x, y)
    return dev


class TestStringFamily:
    def test_substring_upper_length_concat(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("Substring", attr("s", "string"), lit(1, "integer"),
               lit(4, "integer")),
            ex("Upper", attr("s", "string")),
            ex("Lower", attr("s", "string")),
            ex("Length", attr("s", "string")),
            ex("Concat", attr("s", "string"), lit("!", "string")),
            ex("StringTrim", lit("  pad  ", "string")),
            ex("StringReplace", attr("s", "string"), lit("_", "string"),
               lit("-", "string")),
            ex("StringLPad", attr("s", "string"), lit(20, "integer"),
               lit("*", "string")),
            ex("StartsWith", attr("s", "string"), lit("Item_1", "string")),
            ex("Contains", attr("s", "string"), lit("_x", "string")),
            ex("EndsWith", attr("s", "string"), lit("y", "string")),
        ])
        run_both(session, plan, path, sort_first_col=False)

    def test_like_rlike_split(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("Like", attr("s", "string"), lit("Item\\_1%", "string"),
               escapeChar="\\"),
            ex("RLike", attr("s", "string"), lit("Item_[0-9]+_xy*",
                                                 "string")),
            ex("StringSplit", attr("s", "string"), lit("_", "string"),
               lit(-1, "integer")),
        ])
        run_both(session, plan, path, sort_first_col=False)


class TestDateFamily:
    def test_date_parts_and_arith(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("Year", attr("d", "date")),
            ex("Month", attr("d", "date")),
            ex("DayOfMonth", attr("d", "date")),
            ex("DayOfWeek", attr("d", "date")),
            ex("Quarter", attr("d", "date")),
            ex("DateAdd", attr("d", "date"), lit(30, "integer")),
            ex("DateSub", attr("d", "date"), lit(7, "integer")),
            ex("DateDiff", attr("d", "date"),
               lit("2000-01-01", "date")),
            ex("LastDay", attr("d", "date")),
            ex("DateFormatClass", attr("d", "date"),
               lit("yyyy-MM", "string")),
            ex("TruncDate", attr("d", "date"), lit("MONTH", "string")),
        ])
        run_both(session, plan, path, sort_first_col=False)


class TestConditionalFamily:
    def test_case_when_if_coalesce(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("CaseWhen",
               ex("GreaterThan", attr("v", "double"), lit(0.0, "double")),
               lit("pos", "string"),
               ex("LessThan", attr("v", "double"), lit(-50.0, "double")),
               lit("veryneg", "string"),
               lit("neg", "string")),
            ex("If",
               ex("GreaterThan", attr("i", "integer"), lit(0, "integer")),
               attr("i", "integer"),
               ex("UnaryMinus", attr("i", "integer"))),
            ex("Coalesce", lit(None, "double"), attr("v", "double")),
            ex("Greatest", attr("v", "double"), lit(0.0, "double")),
            ex("Least", attr("v", "double"), lit(0.0, "double")),
            ex("NaNvl", attr("v", "double"), lit(0.0, "double")),
        ])
        run_both(session, plan, path, sort_first_col=False)

    def test_in_and_inset(self, session, data):
        path, _ = data
        plan = filter_plan(
            ex("In", attr("k", "long"), lit(1, "long"), lit(3, "long"),
               lit(7, "long")))
        run_both(session, plan, path)
        plan2 = json.dumps([
            {"class": EXEC + "FilterExec", "num-children": 1,
             "condition": [{"class": EXPR + "InSet", "num-children": 1,
                            "hset": [2, 5, 11]}] + attr("k", "long")},
            scan("t", _COLS)])
        dev = run_both(session, plan2, path)
        assert set(dev.column("k").to_pylist()) <= {2, 5, 11}


class TestMathFamily:
    def test_round_abs_sign_and_friends(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("Round", attr("v", "double"), lit(1, "integer")),
            ex("BRound", attr("v", "double"), lit(1, "integer")),
            ex("Abs", attr("v", "double")),
            ex("Signum", attr("v", "double")),
            ex("Ceil", attr("v", "double")),
            ex("Floor", attr("v", "double")),
            ex("Sqrt", ex("Abs", attr("v", "double"))),
            ex("Exp", ex("Multiply", attr("v", "double"),
                         lit(0.01, "double"))),
            ex("Pow", lit(2.0, "double"),
               ex("Remainder", attr("k", "long"), lit(5, "long"))),
            ex("Pmod", attr("i", "integer"), lit(7, "integer")),
            ex("IntegralDivide", attr("k", "long"), lit(3, "long")),
        ])
        run_both(session, plan, path, sort_first_col=False)


class TestStructAndHash:
    def test_named_struct_and_get_field(self, session, data):
        path, _ = data
        struct = ex("CreateNamedStruct",
                    lit("a", "string"), attr("k", "long"),
                    lit("b", "string"), attr("v", "double"))
        get = [{"class": EXPR + "GetStructField", "num-children": 1,
                "ordinal": 0, "name": "a"}] + struct
        plan = project_plan([get])
        run_both(session, plan, path, sort_first_col=False)

    def test_murmur3_hash(self, session, data):
        path, _ = data
        plan = project_plan([
            ex("Murmur3Hash", attr("k", "long"), attr("s", "string"),
               seed=42)])
        run_both(session, plan, path, sort_first_col=False)


class TestAggregateFamily:
    @pytest.mark.parametrize("fn", ["StddevSamp", "StddevPop",
                                    "VarianceSamp", "VariancePop"])
    def test_stddev_variance(self, session, data, fn):
        path, _ = data
        run_both(session, agg_plan(fn, attr("v", "double")), path)

    def test_collect_list(self, session, data):
        path, _ = data
        plan = translate_spark_plan(
            agg_plan("CollectList", attr("i", "integer")), session.conf,
            {"t": [data[0]]})
        dev = session.execute_plan(plan)
        cpu = session.execute_plan(plan, use_device=False)
        ks = [(dev.schema.names[0], "ascending")]
        dev, cpu = dev.sort_by(ks), cpu.sort_by(ks)
        for a, b in zip(dev.column(1).to_pylist(),
                        cpu.column(1).to_pylist()):
            assert sorted(a) == sorted(b)

    def test_distinct_raises(self, session, data):
        ae = [{"class": EXPR + "aggregate.AggregateExpression",
               "num-children": 1, "mode": "Complete",
               "isDistinct": True}] + \
            [{"class": EXPR + "aggregate.Sum", "num-children": 1}] + \
            attr("v", "double")
        node = {"class": EXEC + "aggregate.HashAggregateExec",
                "num-children": 1, "groupingExpressions": [],
                "aggregateExpressions": [ae], "resultExpressions": []}
        with pytest.raises(UnsupportedSparkPlan):
            translate_spark_plan(json.dumps([node, scan("t", _COLS)]),
                                 session.conf, {"t": [data[0]]})


class TestDecimalWrappers:
    def test_checkoverflow_promoteprecision(self, session, data):
        """Catalyst decimal arithmetic wraps operands in PromotePrecision
        and results in CheckOverflow — both translate (passthrough / cast
        to the checked type)."""
        path, _ = data
        inner = ex("Add",
                   [{"class": EXPR + "PromotePrecision",
                     "num-children": 1}] +
                   ex("Cast", attr("k", "long"),
                      dataType="decimal(12,2)"),
                   [{"class": EXPR + "PromotePrecision",
                     "num-children": 1}] +
                   ex("Cast", lit(3, "integer"), dataType="decimal(12,2)"))
        checked = [{"class": EXPR + "CheckOverflow", "num-children": 1,
                    "dataType": "decimal(13,2)",
                    "nullOnOverflow": True}] + inner
        plan = project_plan([checked])
        run_both(session, plan, path, sort_first_col=False)


class TestCoverage:
    def test_translatable_covers_registry(self):
        """The adapter's translatable set must cover the bulk of the
        engine's own override registry — the two surfaces grow together.
        Exclusions are the classes with no Catalyst serialized form
        (BoundReference, engine-internal) or whose translation is
        context-bound (window fns, lambdas, UDF plumbing)."""
        from spark_rapids_tpu.plan import overrides as O
        for fn in [getattr(O, n) for n in dir(O)
                   if n.startswith("_register")]:
            try:
                fn()
            except TypeError:
                pass
        registry = {cls.__name__ for cls in O._EXPR_RULES}
        adapter = translatable_expr_classes()
        # context-bound / engine-internal classes the adapter handles
        # elsewhere (window path, agg path) or legitimately cannot meet
        # in a serialized Catalyst tree
        window = {"RowNumber", "Rank", "DenseRank", "PercentRank",
                  "CumeDist", "NTile", "Lead", "Lag", "NthValue",
                  "WindowAggregate"}
        aggs = {"Sum", "Min", "Max", "Average", "Count", "First", "Last",
                "StddevPop", "StddevSamp", "VariancePop", "VarianceSamp",
                "Skewness", "Kurtosis", "CollectList", "CollectSet",
                "BoolAnd", "BoolOr", "BitAndAgg", "BitOrAgg", "BitXorAgg",
                "CountIf", "ApproximatePercentile"}
        internal = {"BoundReference", "ColumnarUDFExpr", "PandasUDF",
                    "NamedLambdaVariable", "NullLike", "Empty2Null",
                    "MonotonicallyIncreasingID", "SparkPartitionID",
                    "InputFileName", "RaiseError", "AssertTrue",
                    "JsonToStructs", "GetJsonObject", "JsonTuple",
                    "ArrayTransform", "ArrayFilter", "ArrayExists",
                    "ArrayForAll", "ArrayAggregate", "MapFilter",
                    "TransformKeys", "TransformValues", "ZipWith",
                    "Explode"}
        missing = registry - adapter - window - aggs - internal
        # the adapter must cover at least 85% of the registry's
        # point-expression surface; list the residue for the next round
        frac = 1 - len(missing) / max(len(registry), 1)
        assert frac >= 0.85, sorted(missing)
        # and every family the verdict named must be present
        for must in ["Substring", "Like", "RLike", "In", "InSet",
                     "CaseWhen", "Coalesce", "If", "GetStructField",
                     "Round", "Abs", "Signum", "Year", "DateAdd",
                     "DateDiff", "UnixTimestamp", "DateFormatClass"]:
            assert must in adapter or must in {"InSet"}, must
