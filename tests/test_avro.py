"""Avro scan tests (reference: GpuAvroScan.scala + avro_test.py).

The writer here is an independent OCF encoder (not shared with io/avro.py) so
the round-trip actually exercises the decoder, plus a hand-built golden file
asserting exact byte-level decode of known values."""

import io
import json
import struct
import zlib

import pyarrow as pa
import pytest

from spark_rapids_tpu.io.avro import (AvroError, infer_avro_schema,
                                      read_avro_table)
from spark_rapids_tpu.plugin import TpuSession


# ---------------------------------------------------------------------------
# independent test-side encoder
# ---------------------------------------------------------------------------

def zz(n: int) -> bytes:
    """Zigzag varint encode."""
    u = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return zz(len(b)) + b


def enc_value(schema, v, defs=None, ns=None) -> bytes:
    if defs is None:
        defs = {}
    if isinstance(schema, list):  # union
        if v is None:
            ix = schema.index("null")
            return zz(ix)
        non_null = [i for i, b in enumerate(schema) if b != "null"]
        ix = non_null[0]
        return zz(ix) + enc_value(schema[ix], v, defs, ns)
    if isinstance(schema, str) and schema in defs:
        return enc_value(defs[schema], v, defs, ns)
    if isinstance(schema, dict):
        t = schema["type"]
        if t in ("record", "enum", "fixed"):
            nm = schema["name"]
            if "." in nm:
                full, ns = nm, nm.rsplit(".", 1)[0]
            else:
                ns = schema.get("namespace", ns)
                full = f"{ns}.{nm}" if ns else nm
            defs[nm.rsplit(".", 1)[-1]] = schema
            defs[full] = schema
        if t == "array":
            out = b""
            if v:
                out += zz(len(v))
                for item in v:
                    out += enc_value(schema["items"], item, defs, ns)
            return out + zz(0)
        if t == "map":
            out = b""
            if v:
                out += zz(len(v))
                for k, val in v.items():
                    out += enc_str(k) + enc_value(schema["values"], val,
                                                  defs, ns)
            return out + zz(0)
        if t == "record":
            return b"".join(enc_value(f["type"], v[f["name"]], defs, ns)
                            for f in schema["fields"])
        if t == "enum":
            return zz(schema["symbols"].index(v))
        if t == "fixed":
            assert len(v) == schema["size"]
            return v
        return enc_value(t, v, defs, ns)  # {"type": "int", "logicalType": ..}
    if schema in ("int", "long"):
        return zz(v)
    if schema == "boolean":
        return b"\x01" if v else b"\x00"
    if schema == "float":
        return struct.pack("<f", v)
    if schema == "double":
        return struct.pack("<d", v)
    if schema == "string":
        return enc_str(v)
    if schema == "bytes":
        return zz(len(v)) + v
    if schema == "null":
        return b""
    raise AssertionError(schema)


SYNC = bytes(range(16))


def write_ocf(path, schema: dict, rows, codec="null", block_rows=None):
    blocks = []
    rows = list(rows)
    block_rows = block_rows or max(len(rows), 1)
    for i in range(0, len(rows), block_rows):
        chunk = rows[i:i + block_rows]
        payload = b"".join(enc_value(schema, r) for r in chunk)
        if codec == "deflate":
            co = zlib.compressobj(wbits=-15)
            payload = co.compress(payload) + co.flush()
        blocks.append(zz(len(chunk)) + zz(len(payload)) + payload + SYNC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    buf = io.BytesIO()
    buf.write(b"Obj\x01")
    buf.write(zz(len(meta)))
    for k, v in meta.items():
        buf.write(enc_str(k))
        buf.write(zz(len(v)) + v)
    buf.write(zz(0))
    buf.write(SYNC)
    for b in blocks:
        buf.write(b)
    with open(path, "wb") as f:
        f.write(buf.getvalue())


FLAT_SCHEMA = {
    "type": "record", "name": "r", "fields": [
        {"name": "i32", "type": "int"},
        {"name": "i64", "type": ["null", "long"]},
        {"name": "f32", "type": "float"},
        {"name": "f64", "type": ["null", "double"]},
        {"name": "b", "type": "boolean"},
        {"name": "s", "type": ["null", "string"]},
    ]}

# binary columns decode fine (arrow) but the engine's host batches don't
# carry BinaryType yet, so "bin" only appears in decoder-level tests
BIN_SCHEMA = {
    "type": "record", "name": "rb",
    "fields": FLAT_SCHEMA["fields"] + [{"name": "bin", "type": "bytes"}]}


def flat_rows(n=257, with_bin=False):
    rows = []
    for i in range(n):
        r = {
            "i32": i - 100, "i64": None if i % 7 == 0 else i * 12345678901,
            "f32": float(i) / 3, "f64": None if i % 11 == 0 else i * 1.5e-3,
            "b": i % 2 == 0, "s": None if i % 5 == 0 else f"s{i}é",
        }
        if with_bin:
            r["bin"] = bytes([i % 256, (i * 3) % 256])
        rows.append(r)
    return rows


class TestAvroDecode:
    def test_flat_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.avro")
        rows = flat_rows(with_bin=True)
        write_ocf(p, BIN_SCHEMA, rows, block_rows=64)
        t = read_avro_table(p)
        assert t.num_rows == len(rows)
        assert t.column("i32").to_pylist() == [r["i32"] for r in rows]
        assert t.column("i64").to_pylist() == [r["i64"] for r in rows]
        assert t.column("s").to_pylist() == [r["s"] for r in rows]
        assert t.column("bin").to_pylist() == [r["bin"] for r in rows]
        got_f32 = t.column("f32").to_pylist()
        for g, r in zip(got_f32, rows):
            # compare against the f32-rounded original, bit-exact
            assert g == struct.unpack("<f", struct.pack("<f", r["f32"]))[0]

    def test_deflate_codec(self, tmp_path):
        p = str(tmp_path / "t.avro")
        rows = flat_rows(100)
        write_ocf(p, FLAT_SCHEMA, rows, codec="deflate", block_rows=32)
        t = read_avro_table(p)
        assert t.column("i32").to_pylist() == [r["i32"] for r in rows]

    def test_nested_types(self, tmp_path):
        schema = {
            "type": "record", "name": "r", "fields": [
                {"name": "arr", "type": {"type": "array", "items": "int"}},
                {"name": "m", "type": {"type": "map", "values": "long"}},
                {"name": "st", "type": {"type": "record", "name": "inner",
                                        "fields": [
                                            {"name": "x", "type": "int"},
                                            {"name": "y",
                                             "type": ["null", "string"]}]}},
                {"name": "e", "type": {"type": "enum", "name": "col",
                                       "symbols": ["RED", "GREEN", "BLUE"]}},
                {"name": "fx", "type": {"type": "fixed", "name": "f4",
                                        "size": 4}},
            ]}
        rows = [
            {"arr": [1, 2, 3], "m": {"a": 1, "b": 2},
             "st": {"x": 1, "y": "one"}, "e": "GREEN", "fx": b"abcd"},
            {"arr": [], "m": {}, "st": {"x": -5, "y": None}, "e": "RED",
             "fx": b"\x00\x01\x02\x03"},
        ]
        p = str(tmp_path / "n.avro")
        write_ocf(p, schema, rows)
        t = read_avro_table(p)
        assert t.column("arr").to_pylist() == [[1, 2, 3], []]
        assert t.column("m").to_pylist() == [
            [("a", 1), ("b", 2)], []]
        assert t.column("st").to_pylist() == [
            {"x": 1, "y": "one"}, {"x": -5, "y": None}]
        assert t.column("e").to_pylist() == ["GREEN", "RED"]
        assert t.column("fx").to_pylist() == [b"abcd", b"\x00\x01\x02\x03"]

    def test_logical_types(self, tmp_path):
        schema = {
            "type": "record", "name": "r", "fields": [
                {"name": "d", "type": {"type": "int", "logicalType": "date"}},
                {"name": "ts_us", "type": {"type": "long",
                                           "logicalType": "timestamp-micros"}},
                {"name": "ts_ms", "type": {"type": "long",
                                           "logicalType": "timestamp-millis"}},
            ]}
        rows = [{"d": 19000, "ts_us": 1_700_000_000_000_000,
                 "ts_ms": 1_700_000_000_123}]
        p = str(tmp_path / "l.avro")
        write_ocf(p, schema, rows)
        t = read_avro_table(p)
        assert t.schema.field("d").type == pa.date32()
        assert t.schema.field("ts_us").type == pa.timestamp("us", tz="UTC")
        assert t.column("ts_us").cast(pa.int64()).to_pylist() == \
            [1_700_000_000_000_000]
        assert t.column("ts_ms").cast(pa.int64()).to_pylist() == \
            [1_700_000_000_123_000]

    def test_golden_bytes(self, tmp_path):
        """Hand-assembled file: 1 block, 2 rows of {\"a\": int, \"b\": string}."""
        schema = {"type": "record", "name": "g", "fields": [
            {"name": "a", "type": "int"}, {"name": "b", "type": "string"}]}
        payload = (b"\x02" + b"\x04" + b"hi"      # a=1 (zigzag 02), b="hi"
                   + b"\x03" + b"\x02" + b"x")    # a=-2 (zigzag 03), b="x"
        meta_schema = json.dumps(schema).encode()
        body = (b"Obj\x01" + zz(1)
                + enc_str("avro.schema") + zz(len(meta_schema)) + meta_schema
                + zz(0) + SYNC
                + zz(2) + zz(len(payload)) + payload + SYNC)
        p = str(tmp_path / "g.avro")
        with open(p, "wb") as f:
            f.write(body)
        t = read_avro_table(p)
        assert t.column("a").to_pylist() == [1, -2]
        assert t.column("b").to_pylist() == ["hi", "x"]

    def test_corrupt_sync_raises(self, tmp_path):
        p = str(tmp_path / "c.avro")
        write_ocf(p, FLAT_SCHEMA, flat_rows(10))
        with open(p, "rb") as f:
            buf = bytearray(f.read())
        buf[-1] ^= 0xFF  # flip last sync byte
        with open(p, "wb") as f:
            f.write(buf)
        with pytest.raises(AvroError):
            read_avro_table(p)

    def test_unsupported_union_raises(self, tmp_path):
        schema = {"type": "record", "name": "r", "fields": [
            {"name": "u", "type": ["int", "string"]}]}
        p = str(tmp_path / "u.avro")
        write_ocf(p, schema, [{"u": 1}])
        with pytest.raises(AvroError, match="union"):
            read_avro_table(p)

    def test_schema_inference(self, tmp_path):
        p = str(tmp_path / "t.avro")
        write_ocf(p, FLAT_SCHEMA, flat_rows(5))
        s = infer_avro_schema(p)
        assert s.field("i32").type == pa.int32()
        assert s.field("i64").type == pa.int64()
        assert s.field("s").type == pa.string()


class TestAvroScan:
    @pytest.fixture(scope="class")
    def session(self):
        return TpuSession({"spark.rapids.sql.explain": "NONE"})

    def test_scan_device_vs_cpu(self, session, tmp_path):
        p = str(tmp_path / "t.avro")
        rows = flat_rows(300)
        write_ocf(p, FLAT_SCHEMA, rows, block_rows=100)
        df = session.read_avro(p)
        got = df.collect().sort_by([("i32", "ascending")])
        cpu = df.collect_cpu().sort_by([("i32", "ascending")])
        assert got.column("i64").to_pylist() == cpu.column("i64").to_pylist()
        assert got.column("s").to_pylist() == cpu.column("s").to_pylist()
        assert got.num_rows == len(rows)

    def test_scan_query(self, session, tmp_path):
        from spark_rapids_tpu.expr import Sum, col
        p = str(tmp_path / "t.avro")
        write_ocf(p, FLAT_SCHEMA, flat_rows(300))
        df = session.read_avro(p)
        out = (df.filter(col("b"))
                 .group_by()
                 .agg(s=Sum(col("i32"))).collect())
        want = sum(r["i32"] for r in flat_rows(300) if r["b"])
        assert out.column("s").to_pylist() == [want]

    def test_multifile(self, session, tmp_path):
        paths = []
        rows = flat_rows(300)
        for i in range(3):
            p = str(tmp_path / f"t{i}.avro")
            write_ocf(p, FLAT_SCHEMA, rows[i * 100:(i + 1) * 100])
            paths.append(p)
        df = session.read_avro(*paths)
        got = df.collect()
        assert got.num_rows == 300
        assert sorted(got.column("i32").to_pylist()) == \
            sorted(r["i32"] for r in rows)

    def test_column_pruning(self, session, tmp_path):
        p = str(tmp_path / "t.avro")
        write_ocf(p, FLAT_SCHEMA, flat_rows(50))
        df = session.read_avro(p, columns=["i64", "s"])
        got = df.collect()
        assert got.schema.names == ["i64", "s"]
        assert got.num_rows == 50

    def test_disabled_by_conf(self, tmp_path):
        s = TpuSession({"spark.rapids.sql.format.avro.enabled": False,
                        "spark.rapids.sql.explain": "NONE"})
        p = str(tmp_path / "t.avro")
        write_ocf(p, FLAT_SCHEMA, flat_rows(5))
        with pytest.raises(ValueError, match="avro"):
            s.read_avro(p)


class TestAvroNamedTypes:
    def test_fullname_reference(self, tmp_path):
        """Java Avro writers reference previously-defined named types by
        fullname (namespace.name)."""
        schema = {
            "type": "record", "name": "outer", "namespace": "com.x",
            "fields": [
                {"name": "a", "type": {"type": "record", "name": "Inner",
                                       "fields": [{"name": "v",
                                                   "type": "int"}]}},
                {"name": "b", "type": "com.x.Inner"},
                {"name": "c", "type": "Inner"},
            ]}
        rows = [{"a": {"v": 1}, "b": {"v": 2}, "c": {"v": 3}}]
        p = str(tmp_path / "ns.avro")
        write_ocf(p, schema, rows)
        t = read_avro_table(p)
        assert t.column("a").to_pylist() == [{"v": 1}]
        assert t.column("b").to_pylist() == [{"v": 2}]
        assert t.column("c").to_pylist() == [{"v": 3}]

    def test_dotted_name_is_fullname(self, tmp_path):
        schema = {
            "type": "record", "name": "org.ex.rec",
            "fields": [
                {"name": "f", "type": {"type": "fixed",
                                       "name": "org.ex.f8", "size": 2}},
                {"name": "g", "type": "org.ex.f8"},
            ]}
        rows = [{"f": b"ab", "g": b"cd"}]
        p = str(tmp_path / "dn.avro")
        write_ocf(p, schema, rows)
        t = read_avro_table(p)
        assert t.column("g").to_pylist() == [b"cd"]


def test_recursive_schema_raises(tmp_path):
    schema = {"type": "record", "name": "Node", "fields": [
        {"name": "val", "type": "int"},
        {"name": "next", "type": ["null", "Node"]}]}
    p = str(tmp_path / "rec.avro")
    write_ocf(p, schema, [{"val": 1, "next": None}])
    with pytest.raises(AvroError, match="recursive"):
        read_avro_table(p)
