"""Round-3 expression tail: digests (md5/sha1/sha2/crc32), xxhash64,
hive hash, split, regexp_extract_all, arrays_zip, stack. Differential
device-vs-CPU plus python-library oracles."""

import hashlib
import zlib

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (ArraysZip, Crc32, HiveHash, Md5,
                                   RegExpExtractAll, Sha1, Sha2,
                                   StringSplit, XxHash64, col, lit)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same

STRS = ["", "abc", "hello world", "ünïcødé", "a" * 55, "b" * 56,
        "c" * 64, None, "The quick brown fox jumps over the lazy dog",
        "x" * 200]


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


@pytest.fixture(scope="module")
def str_df(session):
    t = pa.table({"s": pa.array(STRS),
                  "i": pa.array(range(len(STRS)), type=pa.int64())})
    return session.from_arrow(t)


class TestDigests:
    def test_md5_sha1_sha256(self, str_df):
        q = str_df.select("i", m=Md5(col("s")), s1=Sha1(col("s")),
                          s2=Sha2(col("s"), 256))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r, s in zip(out.to_pylist(), STRS):
            if s is None:
                assert r["m"] is None and r["s1"] is None
                continue
            b = s.encode()
            assert r["m"] == hashlib.md5(b).hexdigest()
            assert r["s1"] == hashlib.sha1(b).hexdigest()
            assert r["s2"] == hashlib.sha256(b).hexdigest()

    def test_sha2_variants(self, str_df):
        q = str_df.select("i", a=Sha2(col("s"), 224),
                          z=Sha2(col("s"), 0), bad=Sha2(col("s"), 100))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r, s in zip(out.to_pylist(), STRS):
            if s is None:
                continue
            b = s.encode()
            assert r["a"] == hashlib.sha224(b).hexdigest()
            assert r["z"] == hashlib.sha256(b).hexdigest()  # 0 -> 256
            assert r["bad"] is None

    def test_sha2_384_512_on_device(self, str_df):
        # 64-bit-word schedule (SHA-512 family) runs on device; bit-exact
        # vs hashlib on both engines, incl. lengths straddling the
        # 112-byte single-block padding boundary (covered by STRS widths)
        q = str_df.select("i", h384=Sha2(col("s"), 384),
                          h512=Sha2(col("s"), 512))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r, s in zip(out.to_pylist(), STRS):
            if s is None:
                continue
            assert r["h384"] == hashlib.sha384(s.encode()).hexdigest()
            assert r["h512"] == hashlib.sha512(s.encode()).hexdigest()

    def test_sha2_512_block_boundaries(self, session):
        # exact 111/112/127/128/129-byte messages: the 16-byte length field
        # forces a second block starting at 112
        strs = ["q" * n for n in (111, 112, 127, 128, 129, 240)]
        t = pa.table({"s": pa.array(strs),
                      "i": pa.array(range(len(strs)), type=pa.int64())})
        q = session.from_arrow(t).select("i", h=Sha2(col("s"), 512))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r, s in zip(out.to_pylist(), strs):
            assert r["h"] == hashlib.sha512(s.encode()).hexdigest()

    def test_crc32(self, str_df):
        q = str_df.select("i", c=Crc32(col("s")))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r, s in zip(out.to_pylist(), STRS):
            if s is not None:
                assert r["c"] == zlib.crc32(s.encode())


class TestRowHashes:
    def test_xxhash64_strings_known_vectors(self, session):
        # canonical XXH64 with seed 0 via direct kernel use is validated
        # in-module; here: engine-level chaining with Spark's seed 42
        t = pa.table({"s": pa.array(["", "abc", None, "xyz" * 40]),
                      "v": pa.array([1, 2, 3, None], type=pa.int64()),
                      "i": pa.array(range(4), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", h=XxHash64([col("s"), col("v")]),
                      hs=XxHash64([col("s")]))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        rows = out.to_pylist()
        assert len({r["h"] for r in rows}) == 4  # all distinct
        # null child leaves the running hash unchanged:
        q2 = df.select("i", a=XxHash64([col("v")]))
        o2 = assert_same(q2, sort_by=["i"]).sort_by([("i", "ascending")])
        assert o2.to_pylist()[3]["a"] == 42  # both inputs null -> seed

    def test_hive_hash(self, session):
        t = pa.table({"s": pa.array(["abc", "", None]),
                      "n": pa.array([123, -5, 7], type=pa.int32()),
                      "l": pa.array([2 ** 40, 1, None], type=pa.int64()),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", h=HiveHash([col("s"), col("n"), col("l")]),
                      hs=HiveHash([col("s")]))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        rows = out.to_pylist()

        def java_str_hash(s):
            h = 0
            for ch in s.encode():
                h = (h * 31 + (ch if ch < 128 else ch - 256)) & 0xFFFFFFFF
            return h - (1 << 32) if h >= (1 << 31) else h

        assert rows[0]["hs"] == java_str_hash("abc")
        assert rows[1]["hs"] == 0
        lv = 2 ** 40
        want0 = ((java_str_hash("abc") * 31 + 123) * 31 +
                 ((lv ^ (lv >> 32)) & 0xFFFFFFFF))
        want0 &= 0xFFFFFFFF
        if want0 >= 1 << 31:
            want0 -= 1 << 32
        assert rows[0]["h"] == want0


class TestSplitAndZip:
    def test_split_basic(self, session):
        vals = ["a,b,c", "", None, ",", "x,,y,", "nosep"]
        t = pa.table({"s": pa.array(vals),
                      "i": pa.array(range(len(vals)), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", p=StringSplit(col("s"), ","))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("p").to_pylist()
        assert got[0] == ["a", "b", "c"]
        assert got[1] == [""]
        assert got[2] is None
        assert got[3] == ["", ""]
        assert got[4] == ["x", "", "y", ""]  # limit -1 keeps trailing ""
        assert got[5] == ["nosep"]

    def test_split_limits(self, session):
        t = pa.table({"s": pa.array(["a:b:c:d", "q:", "z"]),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", two=StringSplit(col("s"), ":", 2),
                      zero=StringSplit(col("s"), ":", 0))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        rows = out.to_pylist()
        assert rows[0]["two"] == ["a", "b:c:d"]  # remainder in last part
        assert rows[1]["two"] == ["q", ""]
        assert rows[0]["zero"] == ["a", "b", "c", "d"]
        assert rows[1]["zero"] == ["q"]  # limit 0 drops trailing empty

    def test_split_in_where_clause_on_device(self, session):
        # needs_eager split() inside a FILTER condition: the kernel runs
        # un-jitted on device instead of tagging the exec to CPU
        t = pa.table({"s": pa.array(["a,b,c", "x", "p,q", None]),
                      "i": pa.array(range(4), type=pa.int64())})
        df = session.from_arrow(t)
        from spark_rapids_tpu.expr import Size
        q = df.filter(Size(StringSplit(col("s"), ",")) > lit(1)) \
              .select("i")
        assert sorted(q.collect().column("i").to_pylist()) == [0, 2]
        assert sorted(q.collect_cpu().column("i").to_pylist()) == [0, 2]

    def test_split_in_aggregation_on_device(self, session):
        # needs_eager split() as an agg input / group key: eager kernels
        from spark_rapids_tpu.expr import GetArrayItem, Size, Sum
        from spark_rapids_tpu.expr.base import Alias
        t = pa.table({"s": pa.array(["a,b", "a,b,c", "z", "a,b"]),
                      "v": pa.array([1, 2, 3, 4], type=pa.int64())})
        df = session.from_arrow(t)
        q = df.group_by(
            Alias(GetArrayItem(StringSplit(col("s"), ","), lit(0)),
                  "k")).agg(
            n=Sum(Size(StringSplit(col("s"), ","))))
        tpu = {r["k"]: r["n"] for r in q.collect().to_pylist()}
        cpu = {r["k"]: r["n"] for r in q.collect_cpu().to_pylist()}
        assert tpu == cpu == {"a": 7, "z": 1}

    def test_split_regex_falls_back(self, session):
        t = pa.table({"s": pa.array(["a1b22c333d"])})
        df = session.from_arrow(t).select(p=StringSplit(col("s"), r"\d+"))
        got = df.collect()  # planner tags it off; host regex answers
        assert got.column("p").to_pylist() == [["a", "b", "c", "d"]]

    def test_regexp_extract_all(self, session):
        t = pa.table({"s": pa.array(["a1b22c333", "none", None]),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", m=RegExpExtractAll(col("s"), r"(\d+)", 1))
        out = q.collect().sort_by([("i", "ascending")])
        got = out.column("m").to_pylist()
        assert got[0] == ["1", "22", "333"]
        assert got[1] == []
        assert got[2] is None

    def test_arrays_zip(self, session):
        la = [[1, 2, 3], [5], None]
        ra = [["x", "y"], ["p", "q"], ["z"]]
        t = pa.table({"a": pa.array(la, pa.list_(pa.int64())),
                      "b": pa.array(ra, pa.list_(pa.string())),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", z=ArraysZip([col("a"), col("b")],
                                       names=["a", "b"]))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("z").to_pylist()
        assert got[0] == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"},
                          {"a": 3, "b": None}]
        assert got[1] == [{"a": 5, "b": "p"}, {"a": None, "b": "q"}]
        assert got[2] is None


class TestStack:
    def test_stack_basic(self, session):
        t = pa.table({"a": pa.array([1, 2], type=pa.int64()),
                      "b": pa.array([10, 20], type=pa.int64()),
                      "c": pa.array([100, 200], type=pa.int64())})
        df = session.from_arrow(t)
        q = df.stack(3, col("a"), col("b"), col("c"))
        out = assert_same(q, sort_by=["col0"])
        vals = sorted(out.column("col0").to_pylist())
        assert vals == [1, 2, 10, 20, 100, 200] or \
            vals == sorted([1, 10, 100, 2, 20, 200])

    def test_stack_two_cols_with_padding(self, session):
        t = pa.table({"a": pa.array([7], type=pa.int64())})
        df = session.from_arrow(t)
        # stack(2, 1,2,3): rows (1,2), (3,NULL)
        q = df.stack(2, lit(1, T.LONG), lit(2, T.LONG), lit(3, T.LONG))
        out = assert_same(q, sort_by=["col0"]).sort_by(
            [("col0", "ascending")])
        rows = out.to_pylist()
        assert [(r["col0"], r["col1"]) for r in rows] == \
            [(1, 2), (3, None)]


class TestGroupIndexValidation:
    # advisor r3: Spark raises IllegalArgumentException for an out-of-range
    # regex group index (RegExpExtractBase.checkGroupIndex); silently
    # returning "" diverged from the parity contract
    def test_extract_all_idx_too_large(self):
        with pytest.raises(ValueError, match="group count is 1.*index is 2"):
            RegExpExtractAll(col("s"), r"(\d+)", 2)

    def test_extract_all_negative_idx(self):
        with pytest.raises(ValueError, match="less than zero"):
            RegExpExtractAll(col("s"), r"(\d+)", -1)

    def test_extract_idx_too_large(self):
        from spark_rapids_tpu.expr.regex import RegExpExtract
        with pytest.raises(ValueError, match="group count is 0.*index is 1"):
            RegExpExtract(col("s"), lit(r"\d+"), 1)

    def test_zero_idx_whole_match_ok(self, session):
        t = pa.table({"s": pa.array(["a1b22"])})
        df = session.from_arrow(t)
        q = df.select(m=RegExpExtractAll(col("s"), r"\d+", 0))
        assert q.collect().column("m").to_pylist() == [["1", "22"]]
