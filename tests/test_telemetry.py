"""Live-telemetry suite (ISSUE-8, marker `telemetry`): metrics registry
(concurrency-exact totals, bounded label cardinality, Prometheus render/
parse round-trip), engine gauge feeds, flight-recorder ring + incident
dumps, health snapshot, schema-v2 trace correlation, event-log rotation,
and the telemetry-off zero-state contract.

scripts/telemetry_matrix.sh runs these standalone plus the off-gate /
scrape-golden / dump-on-OOM / cross-process trace gates."""

import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import faults, telemetry
from spark_rapids_tpu.telemetry import (FlightRecorder, MetricsRegistry,
                                        OVERFLOW_LABEL, parse_prometheus)
from spark_rapids_tpu.utils import spans
from spark_rapids_tpu.utils.spans import validate_record

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _telemetry_teardown():
    """Every test leaves telemetry OFF (no registry, no HTTP thread) so
    suites sharing this process keep their zero-thread assumptions."""
    yield
    telemetry.shutdown()
    assert not telemetry.is_enabled()
    assert telemetry.registry() is None


def _conf(tmp_path=None, **extra):
    base = {"spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.tpu.telemetry.enabled": True}
    if tmp_path is not None:
        base["spark.rapids.tpu.telemetry.flightRecorder.dir"] = str(tmp_path)
    base.update(extra)
    return base


# ---------------------------------------------------------------------------
# registry: exact totals under concurrency, cardinality cap, round-trip
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_histogram_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("t_requests_total", "requests", ["code"])
        reg.gauge("t_depth", "queue depth")
        reg.histogram("t_wait_seconds", "wait", buckets=(0.01, 0.1, 1.0))
        reg.inc("t_requests_total", 3, code="200")
        reg.inc("t_requests_total", 1, code="500")
        reg.set("t_depth", 7)
        for v in (0.005, 0.05, 0.5, 5.0):
            reg.observe("t_wait_seconds", v)
        parsed = parse_prometheus(reg.render())
        assert parsed["t_requests_total"]['code="200"'] == 3
        assert parsed["t_requests_total"]['code="500"'] == 1
        assert parsed["t_depth"][""] == 7
        assert parsed["t_wait_seconds_count"][""] == 4
        assert parsed["t_wait_seconds_bucket"]['le="0.01"'] == 1
        assert parsed["t_wait_seconds_bucket"]['le="+Inf"'] == 4
        assert abs(parsed["t_wait_seconds_sum"][""] - 5.555) < 1e-9

    def test_concurrent_hammer_totals_exact_and_scrape_never_throws(self):
        """ISSUE-8 satellite: N writer threads vs a continuous scrape —
        totals exact, render never raises, histogram count conserved."""
        reg = MetricsRegistry()
        reg.counter("h_total", "hammered", ["worker"])
        reg.histogram("h_wait", "hammered waits", buckets=(0.5,))
        N, PER = 8, 2000
        stop = threading.Event()
        scrape_errors = []

        def scrape():
            while not stop.is_set():
                try:
                    parse_prometheus(reg.render())
                except Exception as e:  # pragma: no cover - the assertion
                    scrape_errors.append(e)

        def hammer(i):
            for k in range(PER):
                reg.inc("h_total", 1, worker=str(i % 4))
                reg.observe("h_wait", 0.1 if k % 2 else 0.9)

        scraper = threading.Thread(target=scrape)
        scraper.start()
        workers = [threading.Thread(target=hammer, args=(i,))
                   for i in range(N)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        stop.set()
        scraper.join()
        assert not scrape_errors
        parsed = parse_prometheus(reg.render())
        total = sum(parsed["h_total"].values())
        assert total == N * PER
        assert parsed["h_wait_count"][""] == N * PER
        assert parsed["h_wait_bucket"]['le="0.5"'] == N * PER // 2

    def test_label_cardinality_cap_bucketed_not_unbounded(self):
        reg = MetricsRegistry(max_series_per_family=4)
        reg.counter("c_total", "capped", ["q"])
        for i in range(100):
            reg.inc("c_total", 1, q=f"query-{i}")
        parsed = parse_prometheus(reg.render())
        series = parsed["c_total"]
        assert len(series) == 5  # 4 real + the overflow bucket
        assert series[f'q="{OVERFLOW_LABEL}"'] == 96
        assert sum(series.values()) == 100  # totals stay exact

    def test_failing_gauge_callback_yields_no_sample_not_a_throw(self):
        reg = MetricsRegistry()
        reg.gauge("g_bad", "boom", callback=lambda: 1 / 0)
        reg.gauge("g_ok", "fine", callback=lambda: 5)
        parsed = parse_prometheus(reg.render())
        assert parsed["g_ok"][""] == 5
        assert parsed["g_bad"][""] == 0  # renders the zero series

    def test_unregistered_write_is_noop(self):
        reg = MetricsRegistry()
        reg.inc("never_registered", 1)  # must not raise
        reg.observe("never_registered", 1.0)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraps_at_capacity(self):
        rec = FlightRecorder(capacity=16)
        for i in range(50):
            rec.record("k", f"ev{i}")
        evs = rec.snapshot()
        assert len(evs) == 16
        assert evs[0][3] == "ev34" and evs[-1][3] == "ev49"

    def test_dump_is_schema_valid_and_rate_limited(self, tmp_path):
        rec = FlightRecorder(capacity=32, dump_dir=str(tmp_path))
        for i in range(5):
            rec.record("memory", "oom_pressure", trace_id="t1",
                       attrs={"need": i})
        p = rec.dump("terminal_oom", trace_id="t1", attrs={"need": 99})
        assert p and os.path.exists(p)
        lines = [json.loads(l) for l in open(p)]
        assert lines[0]["type"] == "incident"
        assert lines[0]["reason"] == "terminal_oom"
        assert lines[0]["n_events"] == 5
        assert [l["type"] for l in lines[1:]] == ["event"] * 5
        for rec_ in lines:
            assert validate_record(rec_) == [], rec_
        # same reason again inside the rate window: suppressed
        assert rec.dump("terminal_oom") is None
        # a different reason is its own budget
        assert rec.dump("cancelled") is not None

    def test_no_dump_dir_means_no_file(self):
        rec = FlightRecorder(capacity=8, dump_dir="")
        rec.record("k", "e")
        assert rec.dump("whatever") is None

    def test_reject_storm_threshold(self):
        rec = FlightRecorder(reject_storm_threshold=3,
                             reject_storm_window_s=60.0)
        assert not rec.note_rejection()
        assert not rec.note_rejection()
        assert rec.note_rejection()  # third inside the window


# ---------------------------------------------------------------------------
# off-path contract
# ---------------------------------------------------------------------------


class TestTelemetryOff:
    def test_off_is_zero_state_zero_threads(self):
        from spark_rapids_tpu.expr import Sum, col
        from spark_rapids_tpu.plugin import TpuSession
        threads0 = threading.active_count()
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = pa.table({"g": pa.array(np.arange(500) % 4),
                      "v": pa.array(np.ones(500))})
        out = sess.from_arrow(t).group_by("g").agg(s=Sum(col("v"))).collect()
        assert out.num_rows == 4
        assert not telemetry.is_enabled()
        assert telemetry.registry() is None
        assert telemetry.flight_recorder() is None
        assert telemetry.http_server() is None
        assert threading.active_count() <= threads0
        # hooks are no-ops, not errors
        telemetry.inc("tpu_queries_total")
        telemetry.flight("query", "begin")
        assert telemetry.incident("nope") is None
        assert telemetry.render_prometheus() == ""


# ---------------------------------------------------------------------------
# end-to-end: engine feeds, health, HTTP, incidents
# ---------------------------------------------------------------------------


class TestEngineFeeds:
    def _run_query(self, sess, n=800, groups=8):
        from spark_rapids_tpu.expr import Sum, col
        t = pa.table({"g": pa.array(np.arange(n) % groups,
                                    type=pa.int32()),
                      "v": pa.array(np.ones(n))})
        return sess.from_arrow(t).group_by("g").agg(s=Sum(col("v"))) \
            .collect()

    def test_query_and_op_counters_move(self, tmp_path):
        from spark_rapids_tpu.plugin import TpuSession
        sess = TpuSession(_conf(tmp_path))
        out = self._run_query(sess)
        assert out.num_rows == 8
        reg = telemetry.registry()
        assert reg.get_value("tpu_queries_total", status="ok") >= 1
        assert reg.get_value("tpu_op_output_rows_total",
                             op="TpuScanExec") >= 800
        # every registered family renders and parses back (scrape golden)
        parsed = parse_prometheus(reg.render())
        for fam in reg.families():
            assert any(k == fam or k.startswith(fam + "_")
                       for k in parsed), f"family {fam} not rendered"

    def test_cpu_fallback_rerun_counter_moves(self, tmp_path):
        """ISSUE-8 satellite: silent CpuFallbackRequired re-runs are
        visible on the scrape surface."""
        from spark_rapids_tpu.expr import Count, col
        from spark_rapids_tpu.plugin import TpuSession
        sess = TpuSession(_conf(tmp_path))
        sess.initialize_device()  # telemetry comes up with the device
        n = 120
        keys = [("K%03d" % (i % 3)) * 120 for i in range(n)]  # >headWidth
        t = pa.table({"s": pa.array(keys), "v": pa.array(np.ones(n))})
        before = telemetry.registry().get_value(
            "tpu_cpu_fallback_reruns_total")
        out = sess.from_arrow(t).group_by("s").agg(n_=Count(col("v"))) \
            .collect()
        assert out.num_rows == 3
        assert telemetry.registry().get_value(
            "tpu_cpu_fallback_reruns_total") >= before + 1

    def test_sched_rejection_and_deadline_counters_move(self, tmp_path):
        """ISSUE-8 satellite: overload statuses land in the registry from
        BOTH admission outcomes (shed + deadline)."""
        from spark_rapids_tpu.plugin import TpuSession
        from spark_rapids_tpu.sched import CancelToken
        from spark_rapids_tpu.sched.scheduler import AdmissionQueue
        from spark_rapids_tpu.errors import (DeadlineExceededError,
                                             QueryRejectedError)
        TpuSession(_conf(tmp_path)).initialize_device()
        reg = telemetry.registry()
        q = AdmissionQueue(1, max_depth=1)
        assert q.acquire(tenant="tA") == 1  # token taken
        th = threading.Thread(
            target=lambda: q.acquire(tenant="tA", timeout=5))
        th.start()
        time.sleep(0.1)  # parked waiter fills the depth-1 queue
        with pytest.raises(QueryRejectedError):
            q.acquire(tenant="tA")  # arrival beyond max_depth sheds
        assert reg.get_value("tpu_sched_rejected_total", tenant="tA") >= 1
        q2 = AdmissionQueue(0)  # zero tokens: tB can only park
        with pytest.raises(DeadlineExceededError):
            q2.acquire(tenant="tB", token=CancelToken(0.05))
        assert reg.get_value("tpu_sched_deadline_total", tenant="tB") >= 1
        q.release()
        th.join(timeout=10)
        assert reg.get_value("tpu_sched_admissions_total", tenant="tA") >= 2
        q.release()
        # wait histogram observed the grants
        parsed = parse_prometheus(reg.render())
        counts = {k: v for k, v in
                  parsed["tpu_sched_admission_wait_seconds_count"].items()}
        assert sum(counts.values()) >= 2

    def test_health_snapshot_and_http(self, tmp_path):
        import urllib.request
        from spark_rapids_tpu.plugin import TpuSession
        sess = TpuSession(_conf(
            tmp_path, **{"spark.rapids.tpu.telemetry.http.port": 0,
                         "spark.rapids.tpu.metrics.eventLog.dir":
                             str(tmp_path)}))
        self._run_query(sess)
        snap = telemetry.health_snapshot(sess.conf)
        assert snap["ok"] is True
        assert snap["device"]["initialized"] is True
        assert snap["event_log"]["writable"] is True
        srv = telemetry.http_server()
        assert srv is not None
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "tpu_queries_total" in body
        parse_prometheus(body)
        h = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert h["ok"] is True

    def test_injected_terminal_oom_dumps_incident(self, tmp_path):
        from spark_rapids_tpu.errors import RetryOOM
        from spark_rapids_tpu.plugin import TpuSession
        sess = TpuSession(_conf(tmp_path))
        with faults.inject(faults.ALLOC, "error", nth=0, times=0,
                           error=RetryOOM):
            with pytest.raises(RetryOOM):
                self._run_query(sess)
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("incident-") and "terminal_oom" in f]
        assert dumps, os.listdir(tmp_path)
        recs = [json.loads(l) for l in open(tmp_path / dumps[0])]
        assert recs[0]["type"] == "incident"
        assert recs[0]["trace_id"]  # stamped with the dying query's trace
        for r in recs:
            assert validate_record(r) == [], r
        assert telemetry.registry().get_value(
            "tpu_incidents_total", reason="terminal_oom") >= 1
        assert telemetry.registry().get_value(
            "tpu_queries_total", status="oom") >= 1


# ---------------------------------------------------------------------------
# schema v2 + trace correlation
# ---------------------------------------------------------------------------


class TestTraceCorrelation:
    def test_v1_and_v2_records_both_validate(self):
        v1 = {"v": 1, "type": "query", "query_id": "1-1", "label": "q",
              "wall_ns": 5, "task_metrics": {}, "n_operators": 0,
              "n_spans": 1}
        assert validate_record(v1) == []
        v2 = dict(v1, v=2, trace_id="abc", ts=1.5)
        assert validate_record(v2) == []
        # v2 without a trace id is invalid; v1 never needed one
        missing = dict(v1, v=2, ts=1.5)
        assert any("trace_id" in e for e in validate_record(missing))

    def test_profile_stamps_scope_trace(self, tmp_path):
        with spans.trace_scope("feedbeefcafe0001"):
            prof = spans.begin_profile("traced")
            with spans.span("phase"):
                pass
            spans.end_profile(prof)
            prof.finish()
        recs = prof.to_records()
        assert all(r["trace_id"] == "feedbeefcafe0001" for r in recs)
        assert all(validate_record(r) == [] for r in recs)
        assert spans.current_trace() is None

    def test_cross_process_style_stitch(self, tmp_path):
        """Client record (this 'process') + server profile sharing one
        trace id stitch into one --trace timeline."""
        from spark_rapids_tpu.tools.profile_report import (load_records,
                                                           trace_view)
        tid = spans.new_trace_id()
        rec = spans.client_op_record("run_plan", tid, 7_000_000,
                                     status="ok", query_id="q-77")
        spans.write_client_record(str(tmp_path), rec)
        with spans.trace_scope(tid):
            prof = spans.begin_profile("served")
            spans.end_profile(prof)
            prof.finish()
        spans.write_event_log(prof, str(tmp_path))
        records, problems = load_records([str(tmp_path)], validate=True)
        assert not problems
        view = trace_view(records, trace=tid)
        assert "client:run_plan" in view
        assert "server query" in view
        assert tid in view

    def test_query_context_carries_trace(self):
        from spark_rapids_tpu.sched import QueryContext
        ctx = QueryContext(trace_id="aa11bb22cc33dd44")
        assert ctx.trace_id == "aa11bb22cc33dd44"
        assert QueryContext().trace_id is None  # session mints at start


# ---------------------------------------------------------------------------
# event-log rotation
# ---------------------------------------------------------------------------


class TestEventLogRotation:
    def _profile(self):
        prof = spans.begin_profile("rot")
        spans.end_profile(prof)
        prof.finish()
        return prof

    def test_rotation_caps_live_file_and_keeps_generations(self, tmp_path):
        d = str(tmp_path)
        prof = self._profile()
        one = len("".join(json.dumps(r) + "\n" for r in prof.to_records()))
        cap = int(one * 1.5)  # fits one profile, not two
        paths = set()
        for _ in range(4):
            p = self._profile()
            paths.add(spans.write_event_log(p, d, max_bytes=cap,
                                            max_files=2))
        (live,) = paths
        assert os.path.getsize(live) <= cap
        gens = sorted(f for f in os.listdir(d) if ".jsonl." in f)
        assert gens == [os.path.basename(live) + ".1",
                        os.path.basename(live) + ".2"]

    def test_report_tool_reads_rotated_generations(self, tmp_path):
        from spark_rapids_tpu.tools.profile_report import (build_model,
                                                           load_records)
        d = str(tmp_path)
        prof = self._profile()
        one = len("".join(json.dumps(r) + "\n" for r in prof.to_records()))
        for _ in range(3):
            spans.write_event_log(self._profile(), d,
                                  max_bytes=int(one * 1.5), max_files=5)
        records, problems = load_records([d], validate=True)
        assert not problems
        model = build_model(records)
        assert len(model["queries"]) == 3  # live + rotated all read

    def test_rotation_off_by_default_appends_unbounded(self, tmp_path):
        d = str(tmp_path)
        p1 = spans.write_event_log(self._profile(), d)
        p2 = spans.write_event_log(self._profile(), d)
        assert p1 == p2
        assert not [f for f in os.listdir(d) if ".jsonl." in f]


# ---------------------------------------------------------------------------
# service ops (in-process server plumbing)
# ---------------------------------------------------------------------------


class TestServiceOps:
    def test_stats_and_health_ops_over_socket(self, tmp_path):
        import socket as socketmod
        from spark_rapids_tpu.service.server import TpuDeviceService
        from spark_rapids_tpu.service import TpuServiceClient
        sock = str(tmp_path / "svc.sock")
        svc = TpuDeviceService(_conf(tmp_path), sock)
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        try:
            cli = TpuServiceClient(sock, deadline_s=60.0).connect()
            try:
                text = cli.stats()
                parsed = parse_prometheus(text)
                assert "tpu_queries_total" in parsed
                health = cli.health()
                assert health["ok"] is True
                assert health["device"]["initialized"] is True
            finally:
                cli.close()
        finally:
            try:
                with TpuServiceClient(sock, deadline_s=5.0) as c2:
                    c2.shutdown()
            except Exception:
                pass
            th.join(timeout=10)
            from spark_rapids_tpu.memory.semaphore import TpuSemaphore
            TpuSemaphore._instance = None
