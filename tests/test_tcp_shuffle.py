"""Cross-process TCP shuffle transport (round-3 verdict #9): the
server/client/windowed/bounce state machines run between two REAL OS
processes over sockets, fetching a multi-block shuffle with the disk
tier engaged on the serving side (reference `RapidsShuffleClient.scala:89`,
`RapidsShuffleServer.scala:70`, UCX/netty concrete transports)."""

import hashlib
import json
import os
import socket as socketmod
import subprocess
import sys

import pytest

from spark_rapids_tpu.shuffle.serializer import deserialize_table
from spark_rapids_tpu.shuffle.tcp_transport import TcpTransport
from spark_rapids_tpu.shuffle.transport import (BlockId,
                                                BounceBufferManager,
                                                ShuffleClient)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PEER = os.path.join(REPO, "tests", "shuffle_peer.py")
SHUFFLE_ID = 7


@pytest.fixture(scope="module")
def peer():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, PEER], cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    try:
        info = json.loads(line)
    except Exception:
        proc.kill()
        raise
    yield info
    proc.kill()
    proc.wait(timeout=10)


def _client(info, window_bytes=8192, buffers=2, deadline=30.0):
    transport = TcpTransport(deadline_s=deadline)
    transport.register_peer("peer-1", ("127.0.0.1", info["port"]))
    conn = transport.connect("peer-1")
    return ShuffleClient(conn, BounceBufferManager(buffers, window_bytes)), \
        transport


class TestTcpShuffle:
    def test_disk_tier_engaged_on_server(self, peer):
        """The serving process's tiny host budget must have pushed blocks
        to its disk tier — the fetch crosses BOTH the wire and the tier."""
        assert peer["disk_blocks"] > 0

    def test_fetch_multiblock_partition_across_processes(self, peer):
        """Pull every block of reduce partition 0 from the peer process
        through windowed bounce-buffer transfers; windows (8KB) are much
        smaller than blocks (~100KB), so each block spans many fetches."""
        client, transport = _client(peer)
        got = {}

        def on_block(bid, data):
            table, _ = deserialize_table(data)
            got[bid] = table

        n = client.fetch_partition(SHUFFLE_ID, 0, on_block)
        transport.shutdown()
        assert n == 4  # four map outputs
        import numpy as np
        for bid, table in got.items():
            key = f"{bid.map_id}:{bid.reduce_id}"
            exp = peer["sums"][key]
            assert table.num_rows == exp["rows"], key
            arrays = dict(zip(table.schema.names, table.arrays))
            vdata, _, _ = arrays["v"]
            assert int(np.asarray(vdata)[:exp["rows"]].sum()) \
                == exp["vsum"], key
            chars, _, lens = arrays["s"]
            chars = np.asarray(chars)
            lens = np.asarray(lens)
            strings = "".join(
                bytes(chars[i, :lens[i]]).decode()
                for i in range(exp["rows"]))
            assert hashlib.sha256(
                strings.encode()).hexdigest() == exp["ssha"], key

    def test_both_partitions_complete(self, peer):
        client, transport = _client(peer, window_bytes=64 * 1024)
        rows = []
        total = 0
        for rid in (0, 1):
            n = client.fetch_partition(
                SHUFFLE_ID, rid,
                lambda bid, data: rows.append(
                    deserialize_table(data)[0].num_rows))
            total += n
        transport.shutdown()
        assert total == 8
        assert sum(rows) == sum(v["rows"] for v in peer["sums"].values())

    def test_missing_block_is_an_error_not_silence(self, peer):
        client, transport = _client(peer)
        errors = []
        n = client.fetch_blocks(
            [BlockId(SHUFFLE_ID, 0, 0), BlockId(SHUFFLE_ID, 99, 0)],
            on_block=lambda bid, data: None,
            on_error=lambda bid, e: errors.append((bid, e)))
        transport.shutdown()
        assert n == 1
        assert len(errors) == 1 and errors[0][0].map_id == 99

    def test_wedged_peer_times_out(self):
        """A peer that accepts but never answers surfaces an IOError
        under the deadline instead of hanging the fetch."""
        srv = socketmod.socket(socketmod.AF_INET, socketmod.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        try:
            transport = TcpTransport(deadline_s=1.0)
            transport.register_peer("wedged", srv.getsockname())
            conn = transport.connect("wedged")
            with pytest.raises(IOError, match="did not answer"):
                conn.list_blocks(1, 0)
            # the connection is POISONED after a timeout: a late reply
            # must never be read as the next request's response
            with pytest.raises(IOError, match="closed"):
                conn.list_blocks(1, 0)
            transport.shutdown()
        finally:
            srv.close()
