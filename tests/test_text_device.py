"""Device JSON-lines and Hive-text parse (json_device.py + the hive
parameterization of csv_device.py): host frames lines, device splits
structure and types fields through the cast kernels — closing the
"JSON and Hive-text scans still parse rows on host" gap (r3 verdict,
component #42; reference `GpuTextBasedPartitionReader.scala`)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def _write(tmp_path, text, name):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        f.write(text)
    return p


class TestJsonDeviceDecode:
    def test_device_parse_flat_lines(self, session, tmp_path):
        text = ('{"id": 1, "name": "alpha", "score": 1.5, "ok": true}\n'
                '{"id": 2, "score": 2.25, "ok": false, "name": "beta"}\n'
                '{"id": 3, "name": null, "ok": true}\n'
                '{"id": 4, "name": "d,elta", "score": -0.5}\n')
        p = _write(tmp_path, text, "t.json")
        schema = Schema(("id", "name", "score", "ok"),
                        (T.LONG, T.STRING, T.DOUBLE, T.BOOLEAN))
        df = session.read_json(p, schema=schema)
        from spark_rapids_tpu.io.json_device import (
            device_decode_json_file, json_device_supported)
        assert json_device_supported(df.plan)
        got = list(device_decode_json_file(df.plan, p))
        assert got and int(got[0][1]) == 4  # device path actually used
        rows = df.collect().sort_by([("id", "ascending")]).to_pylist()
        assert rows[0] == {"id": 1, "name": "alpha", "score": 1.5,
                           "ok": True}
        # key order is irrelevant; missing key and json null are SQL NULL
        assert rows[1] == {"id": 2, "name": "beta", "score": 2.25,
                           "ok": False}
        assert rows[2]["name"] is None
        assert rows[2]["score"] is None
        assert rows[3]["name"] == "d,elta"  # comma inside a string value
        assert rows[3]["ok"] is None

    def test_device_matches_host_reader(self, session, tmp_path):
        rng = np.random.default_rng(5)
        lines = []
        for i in range(500):
            sc = round(float(rng.normal()), 4)
            lines.append('{"id": %d, "name": "n%d", "score": %s}'
                         % (i, i, sc))
        p = _write(tmp_path, "\n".join(lines) + "\n", "m.json")
        schema = Schema(("id", "name", "score"),
                        (T.LONG, T.STRING, T.DOUBLE))
        df = session.read_json(p, schema=schema)
        dev = df.collect().sort_by([("id", "ascending")])
        import pyarrow.json as pajson
        host = pajson.read_json(p).sort_by([("id", "ascending")])
        assert dev.column("id").to_pylist() == \
            host.column("id").to_pylist()
        assert dev.column("name").to_pylist() == \
            host.column("name").to_pylist()
        for a, b in zip(dev.column("score").to_pylist(),
                        host.column("score").to_pylist()):
            assert a == pytest.approx(b, rel=1e-12)

    def test_escapes_fall_back_to_host(self, session, tmp_path):
        text = '{"id": 1, "name": "a\\"b"}\n'
        p = _write(tmp_path, text, "esc.json")
        schema = Schema(("id", "name"), (T.LONG, T.STRING))
        df = session.read_json(p, schema=schema)
        from spark_rapids_tpu.io.json_device import device_decode_json_file
        from spark_rapids_tpu.io.parquet_device import \
            DeviceDecodeUnsupported
        with pytest.raises(DeviceDecodeUnsupported):
            list(device_decode_json_file(df.plan, p))
        assert df.collect().column("name").to_pylist() == ['a"b']

    def test_arrays_and_nesting_fall_back(self, session, tmp_path):
        from spark_rapids_tpu.io.json_device import device_decode_json_file
        from spark_rapids_tpu.io.parquet_device import \
            DeviceDecodeUnsupported
        schema = Schema(("id",), (T.LONG,))
        p1 = _write(tmp_path, '{"id": 1, "xs": [1, 2]}\n', "arr.json")
        df1 = session.read_json(p1, schema=schema)
        with pytest.raises(DeviceDecodeUnsupported):
            list(device_decode_json_file(df1.plan, p1))
        p2 = _write(tmp_path, '{"id": 2, "o": {"x": 1}}\n', "nest.json")
        df2 = session.read_json(p2, schema=schema)
        with pytest.raises(DeviceDecodeUnsupported):
            list(device_decode_json_file(df2.plan, p2))
        # the scan itself still answers via the host reader
        assert df1.collect().column("id").to_pylist() == [1]
        assert df2.collect().column("id").to_pylist() == [2]

    def test_blank_lines_spaces_and_braces_in_strings(self, session,
                                                      tmp_path):
        text = ('\n'
                '{ "id" : 1 , "name" : "br{ce}" }\n'
                '   \n'
                '{"id": 2, "name": ": , {"}\n')
        p = _write(tmp_path, text, "tricky.json")
        schema = Schema(("id", "name"), (T.LONG, T.STRING))
        df = session.read_json(p, schema=schema)
        from spark_rapids_tpu.io.json_device import device_decode_json_file
        got = list(device_decode_json_file(df.plan, p))
        assert int(sum(n for _, n in got)) == 2
        rows = df.collect().sort_by([("id", "ascending")]).to_pylist()
        assert rows[0]["name"] == "br{ce}"
        assert rows[1]["name"] == ": , {"

    def test_ignored_extra_keys_and_date(self, session, tmp_path):
        text = ('{"d": "2020-02-29", "junk": 9, "id": 1}\n'
                '{"id": 2, "d": "1970-01-01"}\n')
        p = _write(tmp_path, text, "d.json")
        schema = Schema(("id", "d"), (T.LONG, T.DATE))
        df = session.read_json(p, schema=schema)
        import datetime as dt
        rows = df.collect().sort_by([("id", "ascending")]).to_pylist()
        assert rows[0]["d"] == dt.date(2020, 2, 29)
        assert rows[1]["d"] == dt.date(1970, 1, 1)


class TestHiveTextDeviceDecode:
    def _schema(self):
        return Schema(("id", "name", "score"),
                      (T.LONG, T.STRING, T.DOUBLE))

    def test_device_parse_serde_semantics(self, session, tmp_path):
        # \x01 splits, \N nulls, short row null-padded, extra field
        # dropped, blank line IS a row (first col empty string -> cast
        # null for LONG), quote bytes are data
        text = ("1\x01al\"pha\x011.5\n"
                "2\x01\\N\x012.5\x01extra\n"
                "3\x01short\n"
                "\n"
                "4\x01last\x014.0")
        p = _write(tmp_path, text, "t.hive")
        df = session.read_hive_text(p, schema=self._schema())
        from spark_rapids_tpu.io.csv_device import (
            device_decode_hive_file, hive_device_supported)
        assert hive_device_supported(df.plan)
        got = list(device_decode_hive_file(df.plan, p))
        assert got and int(sum(n for _, n in got)) == 5
        rows = df.collect().to_pylist()
        by_id = {r["id"]: r for r in rows}
        assert by_id[1]["name"] == 'al"pha'
        assert by_id[1]["score"] == 1.5
        assert by_id[2]["name"] is None          # \N marker
        assert by_id[2]["score"] == 2.5          # extra field dropped
        assert by_id[3]["name"] == "short"
        assert by_id[3]["score"] is None         # short row padded
        assert by_id[4]["score"] == 4.0          # no trailing newline
        blank = [r for r in rows if r["id"] is None]
        assert len(blank) == 1                   # blank line row
        assert blank[0]["name"] is None and blank[0]["score"] is None

    def test_device_matches_host_reader(self, session, tmp_path):
        rng = np.random.default_rng(9)
        lines = []
        for i in range(400):
            sc = round(float(rng.normal()), 4)
            nm = f"n{i}" if i % 7 else "\\N"
            lines.append(f"{i}\x01{nm}\x01{sc}")
        p = _write(tmp_path, "\n".join(lines) + "\n", "m.hive")
        df = session.read_hive_text(p, schema=self._schema())
        dev = df.collect().sort_by([("id", "ascending")])
        cpu = df.collect_cpu().sort_by([("id", "ascending")])
        assert dev.column("id").to_pylist() == cpu.column("id").to_pylist()
        assert dev.column("name").to_pylist() == \
            cpu.column("name").to_pylist()
        for a, b in zip(dev.column("score").to_pylist(),
                        cpu.column("score").to_pylist()):
            assert a == pytest.approx(b, rel=1e-12)

    def test_empty_string_is_not_null_for_strings(self, session, tmp_path):
        text = "1\x01\x012.0\n"
        p = _write(tmp_path, text, "e.hive")
        df = session.read_hive_text(p, schema=self._schema())
        rows = df.collect().to_pylist()
        assert rows[0]["name"] == ""  # empty != \N for string columns
