"""Nested types end-to-end: arrays + structs through the columnar layer,
collection expressions, explode/posexplode(+outer), nested join payloads,
spill of nested batches — differential CPU-vs-TPU (reference:
complexTypeExtractors.scala, complexTypeCreator.scala, collectionOperations.scala,
GpuGenerateExec.scala)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (ArrayContains, Count, CreateArray,
                                   CreateNamedStruct, ElementAt, Explode,
                                   GetArrayItem, GetStructField, Max, Min,
                                   Size, Sum, col, lit)
from spark_rapids_tpu.plugin import TpuSession

from data_gen import ArrayGen, FloatGen, IntGen, StringGen, StructGen, gen_table
from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def nested_table(rng, n=300):
    return gen_table(rng, [
        ("k", IntGen(64, lo=0, hi=20, nullable=False)),
        ("arr", ArrayGen(IntGen(64))),
        ("sarr", ArrayGen(StringGen())),
        ("st", StructGen([("x", IntGen(32)), ("y", StringGen()),
                          ("z", FloatGen())])),
        ("v", FloatGen()),
    ], n)


def _eq(x, y):
    if isinstance(x, float) and isinstance(y, float):
        return x == y or (x != x and y != y)  # NaN == NaN for testing
    if isinstance(x, list) and isinstance(y, list):
        return len(x) == len(y) and all(_eq(a, b) for a, b in zip(x, y))
    if isinstance(x, dict) and isinstance(y, dict):
        return x.keys() == y.keys() and all(_eq(x[k], y[k]) for k in x)
    return x == y


def assert_tables_equal(t1, t2):
    """Arrow Table.equals treats NaN as unequal; compare logically instead."""
    assert t1.schema.equals(t2.schema), f"{t1.schema} != {t2.schema}"
    assert t1.num_rows == t2.num_rows
    for name in t1.schema.names:
        a, b = t1.column(name).to_pylist(), t2.column(name).to_pylist()
        for i, (x, y) in enumerate(zip(a, b)):
            assert _eq(x, y), f"{name}[{i}]: {x!r} vs {y!r}"


class TestNestedScan:
    def test_scan_roundtrip(self, session, rng):
        t = nested_table(rng)
        df = session.from_arrow(t)
        assert_tables_equal(df.collect(), df.collect_cpu())

    def test_nested_through_limit_union(self, session, rng):
        t = nested_table(rng, n=120)
        df = session.from_arrow(t)
        q = df.union(df).limit(150, offset=30)
        assert_tables_equal(q.collect(), q.collect_cpu())


class TestCollectionExprs:
    def test_size_get_element(self, session, rng):
        df = session.from_arrow(nested_table(rng))
        q = df.select(
            sz=Size(col("arr")),
            g0=GetArrayItem(col("arr"), lit(0)),
            g5=GetArrayItem(col("arr"), lit(5)),
            gneg=GetArrayItem(col("arr"), lit(-1)),
            e1=ElementAt(col("arr"), lit(1)),
            elast=ElementAt(col("arr"), lit(-1)),
            s0=GetArrayItem(col("sarr"), lit(0)),
        )
        assert_same(q, sort_by=None)

    def test_array_contains(self, session, rng):
        df = session.from_arrow(nested_table(rng))
        q = df.select(c1=ArrayContains(col("arr"), lit(3)),
                      c2=ArrayContains(col("arr"), col("k")))
        assert_same(q, sort_by=None)

    def test_struct_field_access(self, session, rng):
        df = session.from_arrow(nested_table(rng))
        q = df.select(x=GetStructField(col("st"), name="x"),
                      y=GetStructField(col("st"), name="y"),
                      z=GetStructField(col("st"), name="z"))
        assert_same(q, sort_by=None)

    def test_create_array_struct(self, session, rng):
        df = session.from_arrow(nested_table(rng))
        q = df.select(
            ca=CreateArray([col("k"), GetStructField(col("st"), name="x"),
                            lit(7)]),
            ns=CreateNamedStruct(["a", "b"],
                                 [col("k"), GetStructField(col("st"),
                                                           name="y")]))
        assert_tables_equal(q.collect(), q.collect_cpu())

    def test_filter_on_size(self, session, rng):
        df = session.from_arrow(nested_table(rng))
        q = df.filter(Size(col("arr")) > lit(2)) \
            .select("k", "arr", e=ElementAt(col("arr"), lit(2)))
        assert_tables_equal(q.collect(), q.collect_cpu())


class TestExplode:
    @pytest.mark.parametrize("outer", [False, True])
    @pytest.mark.parametrize("position", [False, True])
    def test_explode_variants(self, session, rng, outer, position):
        df = session.from_arrow(nested_table(rng, n=200))
        q = df.explode("arr", outer=outer, position=position) \
            .select("k", *( ["pos"] if position else []), "col")
        assert_same(q, sort_by=["k", "col"] + (["pos"] if position else []))

    def test_explode_strings(self, session, rng):
        df = session.from_arrow(nested_table(rng, n=150))
        q = df.explode("sarr").select("k", "col")
        assert_same(q, sort_by=["k", "col"])

    def test_explode_then_agg(self, session, rng):
        df = session.from_arrow(nested_table(rng, n=250))
        q = df.explode("arr", outer=True).group_by("k") \
            .agg(s=Sum(col("col")), c=Count(col("col")),
                 mn=Min(col("col")), mx=Max(col("col")))
        assert_same(q, sort_by=["k"])

    def test_explode_of_created_array(self, session, rng):
        df = session.from_arrow(nested_table(rng, n=100))
        q = df.select("k", ca=CreateArray([col("k"), col("k") + lit(1)])) \
            .explode("ca").select("k", "col")
        assert_same(q, sort_by=["k", "col"])


class TestMixedFanoutConcat:
    def test_union_of_different_fanout_buckets(self, session):
        # one side's max list size lands in fanout bucket 8, the other in 24:
        # the concat must pad EVERY child buffer, not just data
        t1 = pa.table({"a": pa.array([[1, 2, 3], [4]],
                                     type=pa.list_(pa.int64()))})
        t2 = pa.table({"a": pa.array([list(range(20)), [1]],
                                     type=pa.list_(pa.int64()))})
        q = session.from_arrow(t1).union(session.from_arrow(t2))
        assert_tables_equal(q.collect(), q.collect_cpu())

    def test_join_build_concat_mixed_fanout(self, session):
        lt = pa.table({"k": pa.array([1, 2], type=pa.int64())})
        rt1 = pa.table({"k": pa.array([1], type=pa.int64()),
                        "a": pa.array([[1, 2]], type=pa.list_(pa.int64()))})
        rt2 = pa.table({"k": pa.array([2], type=pa.int64()),
                        "a": pa.array([list(range(30))],
                                      type=pa.list_(pa.int64()))})
        right = session.from_arrow(rt1).union(session.from_arrow(rt2))
        q = session.from_arrow(lt).join(right, on="k", how="left") \
            .select("a")
        assert_tables_equal(q.collect(), q.collect_cpu())


class TestPosExplodeOuterNulls:
    def test_filler_row_pos_is_null(self, session):
        t = pa.table({"k": pa.array([1, 2, 3], type=pa.int64()),
                      "a": pa.array([[10, 20], [], None],
                                    type=pa.list_(pa.int64()))})
        q = session.from_arrow(t).explode("a", outer=True, position=True) \
            .select("k", "pos", "col")
        tpu = q.collect().sort_by([("k", "ascending")])
        # Spark semantics: the filler row of an empty/null array has NULL pos
        assert tpu.to_pylist() == [
            {"k": 1, "pos": 0, "col": 10}, {"k": 1, "pos": 1, "col": 20},
            {"k": 2, "pos": None, "col": None},
            {"k": 3, "pos": None, "col": None}]
        assert_tables_equal(tpu, q.collect_cpu().sort_by([("k", "ascending")]))


class TestNestedThroughJoins:
    def test_nested_payload_join(self, session, rng):
        left = session.from_arrow(nested_table(rng, n=200))
        rt = gen_table(rng, [("k", IntGen(64, lo=0, hi=20, nullable=False)),
                             ("w", IntGen(32))], 50)
        right = session.from_arrow(rt)
        q = left.join(right, on="k", how="left").select(
            "k", "w", sz=Size(col("arr")),
            x=GetStructField(col("st"), name="x"))
        assert_same(q, sort_by=["k", "w", "sz", "x"])


class TestNestedSpill:
    def test_nested_batch_spills_and_restores(self, rng):
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
        t = nested_table(rng, n=100)
        b = batch_from_arrow(t)
        cat = BufferCatalog.get()
        h = cat.add_batch(b)
        cat._spill_entry(cat._entries[h])
        assert cat.tier_of(h) == StorageTier.HOST
        restored = cat.acquire_batch(h)
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        assert_tables_equal(batch_to_arrow(restored), t)
        cat.remove(h)


class TestNestedFallback:
    def test_nested_group_key_falls_back(self, session, rng):
        # grouping by an array column must fall back to CPU but still work
        df = session.from_arrow(nested_table(rng, n=80))
        q = df.group_by("arr").agg(c=Count(col("k")))
        tpu = q.collect()
        cpu = q.collect_cpu()
        assert tpu.num_rows == cpu.num_rows
