"""Mortgage ETL app — the benchmark-as-test analog of the reference's
`integration_tests/.../tests/mortgage/MortgageSpark.scala` (FannieMae-style
performance + acquisition pipeline). The data is synthetic with the same
relational shape; every stage is expressed through the engine's frontend so
the whole app exercises scans, expressions, joins (incl. a broadcast dim
join), grouped aggregation, windows, and case-when labeling end to end."""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.expr import (Average, CaseWhen, Count, If, Max, Min,
                                   Sum, col, lit)

SELLERS = ["ACME BANK", "acme bank inc", "BIG LENDER CO", "big lender",
           "HOME FUNDS", "home funds llc", "OTHER"]
# canonical name mapping (reference NameMapping table)
NAME_MAP = {
    "ACME BANK": "Acme", "acme bank inc": "Acme",
    "BIG LENDER CO": "BigLender", "big lender": "BigLender",
    "HOME FUNDS": "HomeFunds", "home funds llc": "HomeFunds",
    "OTHER": "Other",
}


def gen_performance(rng, n_loans=120, periods=18) -> pa.Table:
    """Monthly performance rows per loan: balance decay + delinquency walk."""
    loan_ids = np.repeat(np.arange(1, n_loans + 1, dtype=np.int64), periods)
    month = np.tile(np.arange(periods, dtype=np.int32), n_loans)
    year = 2018 + month // 12
    period = year * 100 + (month % 12) + 1  # yyyymm
    upb0 = rng.uniform(50_000, 500_000, n_loans)
    upb = np.repeat(upb0, periods) * (1 - 0.01 * month / periods)
    # delinquency status random walk, clipped at 0
    steps = rng.integers(-1, 2, n_loans * periods)
    dlq = np.maximum(np.add.accumulate(
        steps.reshape(n_loans, periods), axis=1), 0).reshape(-1)
    dlq = np.minimum(dlq, 9).astype(np.int32)
    rate = np.repeat(rng.uniform(2.5, 7.5, n_loans).round(3), periods)
    servicer = np.array(SELLERS, dtype=object)[
        np.repeat(rng.integers(0, len(SELLERS), n_loans), periods)]
    nulls = rng.random(n_loans * periods) < 0.02
    return pa.table({
        "loan_id": pa.array(loan_ids),
        "period": pa.array(period.astype(np.int32)),
        "servicer": pa.array(list(servicer)),
        "interest_rate": pa.array(np.where(nulls, 0.0, rate), mask=nulls),
        "upb": pa.array(upb.round(2)),
        "loan_age": pa.array(month),
        "dlq_status": pa.array(dlq),
    })


def gen_acquisition(rng, n_loans=120) -> pa.Table:
    ids = np.arange(1, n_loans + 1, dtype=np.int64)
    return pa.table({
        "loan_id": pa.array(ids),
        "seller_name": pa.array(
            [SELLERS[i] for i in rng.integers(0, len(SELLERS), n_loans)]),
        "orig_rate": pa.array(rng.uniform(2.5, 7.5, n_loans).round(3)),
        "orig_upb": pa.array(rng.uniform(50_000, 500_000,
                                         n_loans).round(2)),
        "orig_term": pa.array(
            np.array([180, 240, 360])[rng.integers(0, 3, n_loans)]
            .astype(np.int32)),
        "credit_score": pa.array(
            rng.integers(550, 820, n_loans).astype(np.int32)),
    })


def name_mapping_table() -> pa.Table:
    return pa.table({
        "from_name": pa.array(list(NAME_MAP.keys())),
        "to_name": pa.array(list(NAME_MAP.values())),
    })


def prepare_performance(perf):
    """Derive quarter + delinquency buckets (CreatePerformanceDelinquency
    prepare stage)."""
    quarter = (col("period") % lit(100) + lit(2)) / lit(3)
    return perf.select(
        "loan_id", "period", "servicer", "interest_rate", "upb",
        "loan_age", "dlq_status",
        q=Cast_int(quarter),
        ever_30=If(col("dlq_status") >= lit(1), lit(1), lit(0)),
        ever_90=If(col("dlq_status") >= lit(3), lit(1), lit(0)),
        ever_180=If(col("dlq_status") >= lit(6), lit(1), lit(0)),
    )


def Cast_int(e):
    from spark_rapids_tpu.expr import Cast
    from spark_rapids_tpu import types as T
    return Cast(e, T.INT)


def loan_delinquency(perf_prepared):
    """Per-loan delinquency summary (CreatePerformanceDelinquency apply)."""
    return (perf_prepared.group_by("loan_id").agg(
        months=Count(col("period")),
        max_dlq=Max(col("dlq_status")),
        ever_30=Max(col("ever_30")),
        ever_90=Max(col("ever_90")),
        ever_180=Max(col("ever_180")),
        min_upb=Min(col("upb")),
        avg_rate=Average(col("interest_rate")),
    ))


def clean_acquisition(session, acq):
    """Canonicalize seller names via the small mapping dim (NameMapping) —
    a broadcast join by construction."""
    mapping = session.from_arrow(name_mapping_table(), label="name-map")
    joined = acq.join(mapping, condition=col("seller_name") == col("from_name"),
                      how="left")
    return joined.select(
        "loan_id", "orig_rate", "orig_upb", "orig_term", "credit_score",
        seller=CoalesceStr(col("to_name"), lit("Unknown")))


def CoalesceStr(a, b):
    from spark_rapids_tpu.expr import Coalesce
    return Coalesce(a, b)


def mortgage_etl(session, perf, acq):
    """Full pipeline (Run.csv analog): performance summary x acquisition,
    risk labeling."""
    summary = loan_delinquency(prepare_performance(perf))
    acq_clean = clean_acquisition(session, acq)
    joined = summary.join(acq_clean, on="loan_id", how="inner")
    return joined.select(
        "loan_id", "months", "max_dlq", "ever_30", "ever_90", "ever_180",
        "min_upb", "avg_rate", "orig_rate", "orig_upb", "orig_term",
        "credit_score", "seller",
        rate_spread=col("avg_rate") - col("orig_rate"),
        risk=CaseWhen(
            [(col("ever_180") == lit(1), lit("severe")),
             (col("ever_90") == lit(1), lit("high")),
             (col("ever_30") == lit(1), lit("watch"))],
            lit("performing")),
    )


def simple_aggregates(session, perf):
    """SimpleAggregates analog: servicer-level portfolio stats."""
    p = prepare_performance(perf)
    return p.group_by("servicer").agg(
        loans=Count(col("loan_id")),
        avg_upb=Average(col("upb")),
        total_upb=Sum(col("upb")),
        worst=Max(col("dlq_status")),
        d30=Sum(col("ever_30")),
        d90=Sum(col("ever_90")),
    )


def aggregates_with_join(session, perf, acq):
    """AggregatesWithJoin analog: per-seller risk after the full ETL."""
    etl = mortgage_etl(session, perf, acq)
    return etl.group_by("seller", "risk").agg(
        n=Count(col("loan_id")),
        avg_score=Average(col("credit_score")),
        spread=Average(col("rate_spread")),
        upb=Sum(col("orig_upb")),
    )
