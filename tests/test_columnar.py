import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import (
    batch_from_arrow, batch_from_dict, batch_to_arrow, from_arrow, row_bucket,
    to_arrow, width_bucket)


def test_row_bucket():
    assert row_bucket(0) == 128
    assert row_bucket(1) == 128
    assert row_bucket(128) == 128
    assert row_bucket(129) == 256
    assert row_bucket(1000) == 1024


def test_width_bucket():
    assert width_bucket(1) == 8
    assert width_bucket(9) == 16
    assert width_bucket(128) == 128
    assert width_bucket(129) == 256


@pytest.mark.parametrize("at,vals", [
    (pa.int32(), [1, 2, None, 4]),
    (pa.int64(), [10, None, -3, 2**62]),
    (pa.float64(), [1.5, None, float("nan"), -0.0]),
    (pa.bool_(), [True, None, False, True]),
    (pa.int8(), [1, -1, None, 127]),
])
def test_primitive_arrow_roundtrip(at, vals):
    arr = pa.array(vals, type=at)
    col, n = from_arrow(arr)
    assert n == len(vals)
    assert col.capacity == 128
    back = to_arrow(col, n)
    for got, want in zip(back.to_pylist(), arr.to_pylist()):
        if isinstance(want, float) and want != want:
            assert got != got  # NaN round-trips as NaN
        else:
            assert got == want


def test_string_arrow_roundtrip():
    vals = ["hello", None, "", "wörld", "a" * 300, "x"]
    arr = pa.array(vals, type=pa.string())
    col, n = from_arrow(arr)
    assert col.is_string
    # 300 utf8 bytes > headWidth(256): chunked layout — head stays at the
    # head bucket, the tail rides the blob (no cap x 512 matrix)
    assert col.string_width == 256
    assert col.overflow is not None
    back = to_arrow(col, n)
    assert back.to_pylist() == vals


def test_string_arrow_roundtrip_short_flat():
    vals = ["hello", None, "", "wörld", "a" * 200, "x"]
    arr = pa.array(vals, type=pa.string())
    col, n = from_arrow(arr)
    assert col.string_width == 256  # 200 utf8 bytes -> bucket 256, flat
    assert col.overflow is None
    assert to_arrow(col, n).to_pylist() == vals


def test_batch_roundtrip():
    tbl = pa.table({
        "a": pa.array([1, 2, None, 4], type=pa.int64()),
        "b": pa.array(["x", "yy", None, "zzzz"]),
        "c": pa.array([1.0, 2.5, 3.5, None], type=pa.float64()),
    })
    b = batch_from_arrow(tbl)
    assert b.row_count() == 4
    assert b.capacity == 128
    assert np.asarray(b.row_mask()).sum() == 4
    out = batch_to_arrow(b)
    assert out.equals(tbl)


def test_batch_from_dict_infer():
    b = batch_from_dict({"i": [1, None, 3], "s": ["a", "b", None],
                         "f": np.array([1.0, 2.0, 3.0])})
    assert b.schema.types == (T.LONG, T.STRING, T.DOUBLE)
    t = batch_to_arrow(b)
    assert t.column("i").to_pylist() == [1, None, 3]
    assert t.column("s").to_pylist() == ["a", "b", None]


def test_repadded():
    b = batch_from_dict({"a": np.arange(10, dtype=np.int64)})
    big = b.repadded(256)
    assert big.capacity == 256
    assert big.row_count() == 10
    t = batch_to_arrow(big)
    assert t.column("a").to_pylist() == list(range(10))


def test_decimal_roundtrip():
    from decimal import Decimal
    arr = pa.array([None, Decimal("1.23"), Decimal("-99999.99")],
                   type=pa.decimal128(10, 2))
    col, n = from_arrow(arr)
    assert col.dtype == T.DecimalType(10, 2)
    back = to_arrow(col, n)
    assert back.to_pylist() == arr.to_pylist()


def test_date_timestamp_roundtrip():
    d = pa.array([0, 19000, None], type=pa.date32())
    ts = pa.array([0, 1700000000_000000, None], type=pa.timestamp("us", tz="UTC"))
    cd, n = from_arrow(d)
    ct, _ = from_arrow(ts)
    assert cd.dtype == T.DATE and ct.dtype == T.TIMESTAMP
    assert to_arrow(cd, n).to_pylist() == d.to_pylist()
    assert to_arrow(ct, n).to_pylist() == ts.to_pylist()


def test_int64_nulls_precision():
    # regression: nullable int64 must not round-trip through float64
    arr = pa.array([2**62 + 1, None, 5], type=pa.int64())
    col, n = from_arrow(arr)
    assert to_arrow(col, n).to_pylist() == [2**62 + 1, None, 5]


def test_string_beyond_old_width_limit_now_builds():
    # the pre-round-4 layout raised StringWidthExceeded past maxWidth; the
    # chunked layout has no construction cliff — the giant value lands in
    # the tail blob and round-trips exactly
    from spark_rapids_tpu.config import get_default_conf
    limit = get_default_conf().string_max_width
    vals = ["x" * (limit + 1), "small"]
    col, n = from_arrow(pa.array(vals))
    assert col.overflow is not None
    assert col.string_width <= 256
    assert to_arrow(col, n).to_pylist() == vals


def test_wide_decimal_now_device_backed():
    # precision > 18 rides the two-limb [n, 2] representation
    from decimal import Decimal
    arr = pa.array([Decimal("123456789012345678.90"), None],
                   type=pa.decimal128(20, 2))
    col, n = from_arrow(arr)
    assert col.data.shape[1] == 2
    from spark_rapids_tpu.columnar.column import to_arrow
    assert to_arrow(col, n).equals(arr)


def test_unsupported_scalar_type_message():
    arr = pa.array([b"ab"], type=pa.binary())
    with pytest.raises(TypeError, match="binary"):
        from_arrow(arr)


class TestStringRebucket:
    def test_coalesce_narrows_width_after_filter(self):
        """Round-3: one long string widens the whole column; after a
        filter drops it, the coalesce point must narrow the byte matrix
        back down (width-cliff healing)."""
        import pyarrow as pa
        from spark_rapids_tpu.exec.coalesce import (TpuCoalesceBatchesExec,
                                                    RequireSingleBatch)
        from spark_rapids_tpu.expr import col, lit
        from spark_rapids_tpu.plan.overrides import Overrides
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE"})
        vals = ["short"] * 50 + ["w" * 3000] + ["tiny"] * 50
        t = pa.table({"s": pa.array(vals),
                      "i": pa.array(range(len(vals)), type=pa.int64())})
        df = s.from_arrow(t).filter(col("i") != lit(50))
        s.initialize_device()
        result = Overrides(s.conf).apply(df.plan)
        coal = TpuCoalesceBatchesExec(result, RequireSingleBatch(),
                                      s.conf)
        out = list(coal.execute())
        assert len(out) == 1
        scol = out[0].columns[out[0].schema.names.index("s")]
        assert scol.data.shape[-1] <= 8  # narrowed from the 4096 bucket
        # data survives intact
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        back = batch_to_arrow(out[0])
        assert back.column("s").to_pylist() == \
            [v for i, v in enumerate(vals) if i != 50]

    def test_nested_string_width_rebucket(self):
        """Strings inside arrays/structs heal too (slot-mask recursion)."""
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow, \
            batch_to_arrow
        from spark_rapids_tpu.exec.coalesce import rebucket_string_widths
        arrs = [["short", "tiny"]] * 20
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.string()))})
        b = batch_from_arrow(t)
        # simulate a stale wide layout with garbage padding lengths
        import jax.numpy as jnp
        from spark_rapids_tpu.columnar.column import Column
        col = b.columns[0]
        elem = col.children[0]
        wide = jnp.pad(elem.data, ((0, 0), (0, 0), (0, 2048 - 8)))
        lens = elem.lengths.at[-1, -1].set(2000)  # padding garbage
        elem2 = Column(elem.dtype, wide, elem.validity, lens)
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        b2 = ColumnarBatch(b.schema, (Column(col.dtype, col.data,
                                             col.validity, None,
                                             (elem2,)),), b.num_rows)
        out = rebucket_string_widths(b2)
        assert out.columns[0].children[0].data.shape[-1] <= 8
        assert batch_to_arrow(out).column("a").to_pylist() == arrs
