"""Bit-exact string -> float64 device cast (expr/floatparse.py; round-5
verdict item 7 — the last ANSI cast fallback, closed). Oracle: python
float(), which is the platform strtod and bit-identical to the JVM on
this corpus. Runs through BOTH engines (the numpy path and the jit
kernel path share the integer-rounding composer)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import Cast, col
from harness import assert_cpu_tpu_equal


def _corpus():
    rng = np.random.default_rng(11)
    out = []
    # random decimal spellings across digit counts and exponents
    for _ in range(2000):
        nd = int(rng.integers(1, 39))
        digits = "".join(str(d) for d in rng.integers(0, 10, nd))
        digits = digits.lstrip("0") or "0"
        e = int(rng.integers(-330, 320))
        out.append(f"{digits}e{e}")
        if nd > 3:
            out.append(f"{digits[:2]}.{digits[2:]}e{e}")
    # 17-digit round trips of random doubles (shortest repr must
    # round-trip bit-exactly)
    for _ in range(1000):
        d = float(rng.uniform(-1, 1)) * 10.0 ** int(rng.integers(-300, 300))
        out.append(repr(d))
        out.append(f"{d:.17e}")
    # subnormal range and boundaries
    out += ["4.9e-324", "5e-324", "2.4e-324", "2.5e-324", "1e-323",
            "2.2250738585072014e-308", "2.2250738585072011e-308",
            "1.7976931348623157e308", "1.7976931348623159e308",
            "1e309", "-1e309", "1e-400", "-1e-400", "0e99999",
            # the infamous hanging-parse value from CVE-2010-4476
            "2.2250738585072012e-308",
            # many digits
            "0." + "0" * 50 + "1", "1" + "0" * 60, "9" * 40,
            "0.1", "0.2", "0.3", "0.5", "123.456", "-123.456",
            "1e22", "1e23", "1e-22", "1e-23",
            "9007199254740993", "9007199254740992", "9007199254740991"]
    return out


class TestExactFloatParse:
    def test_corpus_bit_identical_to_python_float(self):
        corpus = _corpus()
        tbl = pa.table({"s": pa.array(corpus)})
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.DOUBLE), tbl)
        got = out.to_pylist()
        for s, g in zip(corpus, got):
            try:
                exp = float(s)
            except OverflowError:
                exp = float("inf") if not s.startswith("-") else \
                    float("-inf")
            assert g is not None, s
            assert np.float64(g).tobytes() == np.float64(exp).tobytes(), \
                (s, float(g).hex(), exp.hex())

    def test_words_and_malformed(self):
        vals = ["nan", "NaN", "-NAN", "inf", "Infinity", "-infinity",
                "+inf", " 1.5 ", "", "  ", "1.2.3", "e5", "1e", "--3",
                "5e+", None, "0x12", "1f"]
        tbl = pa.table({"s": pa.array(vals)})
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.DOUBLE), tbl)
        got = out.to_pylist()
        assert np.isnan(got[0]) and np.isnan(got[1]) and np.isnan(got[2])
        assert got[3] == float("inf") and got[4] == float("inf")
        assert got[5] == float("-inf") and got[6] == float("inf")
        assert got[7] == 1.5
        assert got[8:15] == [None] * 7
        assert got[15] is None and got[16] is None and got[17] is None

    def test_ansi_cast_stays_on_device(self):
        """The override layer no longer falls back for ANSI
        string->float (round-4 Missing #6)."""
        from spark_rapids_tpu import types as TT
        from spark_rapids_tpu.expr import cast as EC
        assert EC.device_supported(TT.STRING, TT.DOUBLE)
        assert EC.device_supported(TT.STRING, TT.FLOAT)

    def test_ansi_malformed_raises_valid_parses(self):
        from spark_rapids_tpu.plugin import TpuSession
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.sql.ansi.enabled": True})
        ok = sess.from_arrow(pa.table({"s": pa.array(
            ["1.5", "2.25e10", "-0.125"])}))
        got = ok.select(d=col("s").cast(T.DOUBLE)).collect()
        assert got.column("d").to_pylist() == [1.5, 2.25e10, -0.125]
        bad = sess.from_arrow(pa.table({"s": pa.array(["1.5", "oops"])}))
        with pytest.raises(Exception) as ei:
            bad.select(d=col("s").cast(T.DOUBLE)).collect()
        assert "oops" in str(ei.value) or "cast" in str(ei.value).lower() \
            or "CAST" in str(ei.value)
