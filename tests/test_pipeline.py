"""Pipelined-execution suite (ISSUE-6): bounded async batch prefetch
(exec/base.py PrefetchIterator), the fused multi-chunk packed scan decode
(io/parquet_device.py), pipeline-on vs pipeline-off golden equality across
scan->filter->join->agg, the exchange slot-overflow grow-and-rerun loop
under a tight MemoryBudget with spill active, and the CPU-fallback
stage-re-run counter. Marker `pipeline`; scripts/pipeline_matrix.sh runs
these standalone plus the zero-threads / bit-exactness / fault gates."""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar.batch import batch_from_arrow, batch_to_arrow
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.errors import CpuFallbackRequired
from spark_rapids_tpu.exec import base as EB
from spark_rapids_tpu.exec.base import PrefetchIterator, maybe_prefetch
from spark_rapids_tpu.expr import Count, Sum, col
from spark_rapids_tpu.memory.budget import MemoryBudget
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.utils.metrics import TaskMetrics

pytestmark = pytest.mark.pipeline


def _small_batch(i: int, n: int = 64):
    t = pa.table({"a": pa.array(np.arange(n, dtype=np.int64) + i * n),
                  "b": pa.array(np.full(n, float(i)))})
    return batch_from_arrow(t)


@pytest.fixture
def fresh_budget():
    MemoryBudget.initialize(1 << 62)
    yield MemoryBudget.get()
    MemoryBudget.initialize(1 << 62)


class TestPrefetchIterator:
    def test_order_and_values_preserved(self, fresh_budget):
        src = [_small_batch(i) for i in range(8)]
        out = list(PrefetchIterator(iter(src), depth=2, name="t"))
        assert len(out) == 8
        for i, b in enumerate(out):
            got = batch_to_arrow(b)
            assert got.column("a").to_pylist()[0] == i * 64

    def test_depth_bounds_producer_lookahead(self, fresh_budget):
        produced = []
        gate = threading.Event()

        def slow_src():
            for i in range(10):
                produced.append(i)
                yield _small_batch(i)

        pf = PrefetchIterator(slow_src(), depth=2, name="t")
        it = iter(pf)
        # producer fills the queue then blocks; depth 2 + 1 in flight
        deadline = time.monotonic() + 5
        while len(produced) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # would overrun here if the bound were broken
        assert len(produced) <= 4  # depth(2) + queued put + 1 being built
        out = list(it)
        assert len(out) == 10
        assert len(produced) == 10
        gate.set()

    def test_parked_batches_are_budget_visible(self, fresh_budget):
        budget = fresh_budget
        base = budget.used
        TaskMetrics.reset()  # fresh counters: the wait below reads them

        def src():
            for i in range(4):
                yield _small_batch(i)

        pf = PrefetchIterator(src(), depth=2, name="t")
        deadline = time.monotonic() + 5
        while TaskMetrics.get().prefetch_batches < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        # at least the queued batches are parked spillable and accounted
        assert budget.used > base
        list(pf)
        assert budget.used == base  # all parked accounting released

    def test_typed_error_propagates_with_original_type(self, fresh_budget):
        def src():
            yield _small_batch(0)
            raise CpuFallbackRequired("wide string key")

        pf = PrefetchIterator(src(), depth=2, name="t")
        it = iter(pf)
        next(it)
        with pytest.raises(CpuFallbackRequired, match="wide string"):
            next(it)
        pf._thread.join(timeout=5)
        assert not pf._thread.is_alive()

    def test_early_close_joins_thread_and_frees_parked(self, fresh_budget):
        before = len(BufferCatalog.get()._entries)

        def src():
            for i in range(100):
                yield _small_batch(i)

        pf = PrefetchIterator(src(), depth=3, name="t")
        it = iter(pf)
        next(it)
        it.close()  # consumer stops early (LIMIT analog)
        pf._thread.join(timeout=5)
        assert not pf._thread.is_alive()
        assert len(BufferCatalog.get()._entries) == before

    def test_fault_during_prefetched_pull_no_deadlock(self, fresh_budget):
        """ISSUE-6 CI case: a fault injected at the pipeline.prefetch
        point must cross the queue as the typed error and the producer
        thread must terminate — no deadlock, no hang."""
        def src():
            for i in range(10):
                yield _small_batch(i)

        with faults.inject(faults.PREFETCH, "error", nth=3,
                           error=ConnectionResetError) as rule:
            pf = PrefetchIterator(src(), depth=2, name="t")
            out = []
            t0 = time.monotonic()
            with pytest.raises(ConnectionResetError):
                for b in pf:
                    out.append(b)
            assert time.monotonic() - t0 < 10  # propagated, not wedged
            assert rule.fired == 1
            assert len(out) == 2  # the two pulls before the fault
        pf._thread.join(timeout=5)
        assert not pf._thread.is_alive()

    def test_pipeline_off_spawns_zero_threads(self):
        conf = TpuConf({"spark.rapids.tpu.pipeline.enabled": False})
        before = EB.PREFETCH_THREADS_STARTED
        src = [_small_batch(i) for i in range(3)]
        it = maybe_prefetch(iter(src), conf, name="t")
        assert list(it) == src  # the exact inner iterator, pass-through
        assert EB.PREFETCH_THREADS_STARTED == before

    def test_semaphore_not_held_by_dead_producer(self, fresh_budget):
        """Producer threads must release every admission permit they
        acquired (permits are per-thread; a leak would wedge the engine
        after `concurrentGpuTasks` prefetch threads)."""
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch

        def src():
            # materializing a spillable acquires the semaphore on the
            # producer thread — the leak-prone shape
            sp = SpillableColumnarBatch(_small_batch(0))
            yield sp.get_batch()
            sp.close()

        sem = TpuSemaphore.get()
        for _ in range(3 * sem.permits):  # would deadlock on a leak
            out = list(PrefetchIterator(src(), depth=1, name="t"))
            assert len(out) == 1


class TestFusedMultiChunkDecode:
    @pytest.fixture(scope="class")
    def corpus(self, tmp_path_factory):
        import decimal
        rng = np.random.default_rng(5)
        n = 16_000
        mask = rng.uniform(size=n) < 0.15
        t = pa.table({
            "k": pa.array(rng.integers(0, 1 << 40, n), mask=mask),
            "v": pa.array(rng.uniform(size=n)),
            "g": pa.array(rng.integers(0, 99, n).astype(np.int32)),
            "s": pa.array(["s%d" % i if i % 7 else None
                           for i in range(n)]),
            "b": pa.array(rng.integers(0, 2, n).astype(bool)),
            "d": pa.array([decimal.Decimal(int(x)).scaleb(-2)
                           for x in rng.integers(-10**6, 10**6, n)],
                          pa.decimal128(9, 2)),
            "ts": pa.array(rng.integers(0, 10**15, n),
                           pa.timestamp("us", tz="UTC")),
        })
        path = str(tmp_path_factory.mktemp("pipe") / "c.parquet")
        pq.write_table(t, path, row_group_size=4096)
        return path

    def _decode(self, path, chunks):
        from spark_rapids_tpu.io.parquet import CpuParquetScanExec
        from spark_rapids_tpu.io.parquet_device import (device_decode_file,
                                                        file_supported)
        schema = CpuParquetScanExec([path]).output
        pf = file_supported(path, schema)
        tables = [batch_to_arrow(b) for b, _ in device_decode_file(
            pf, path, schema, chunks_per_dispatch=chunks)]
        return pa.concat_tables(tables)

    def test_multi_chunk_bit_equal_to_serial_and_host(self, corpus):
        from spark_rapids_tpu.io.scanbase import normalize_timestamps
        ref = normalize_timestamps(pq.read_table(corpus))
        serial = self._decode(corpus, 1)
        multi = self._decode(corpus, 4)
        assert serial.equals(ref)
        assert multi.equals(ref)

    def test_dispatches_reduced_at_least_4x(self, corpus):
        tm = TaskMetrics.get()
        tm.scan_dispatches = tm.scan_chunks = 0
        self._decode(corpus, 1)
        per_chunk_serial = tm.scan_dispatches / max(tm.scan_chunks, 1)
        tm.scan_dispatches = tm.scan_chunks = 0
        self._decode(corpus, 4)
        per_chunk_multi = tm.scan_dispatches / max(tm.scan_chunks, 1)
        assert per_chunk_serial >= 4 * per_chunk_multi, \
            (per_chunk_serial, per_chunk_multi)

    def test_overwide_string_group_falls_back_correct(self, tmp_path):
        """A value wider than string.maxWidth declines the string fast
        path: the dispatch group falls back to per-row-group decode
        (which builds the chunked long-string layout) — correct rows,
        never a crash."""
        n = 2000
        vals = ["x%d" % i for i in range(n)]
        vals[137] = "W" * 9000  # > default maxWidth 8192
        t = pa.table({"s": pa.array(vals),
                      "i": pa.array(np.arange(n, dtype=np.int64))})
        path = str(tmp_path / "wide.parquet")
        pq.write_table(t, path, row_group_size=256)
        out = self._decode(path, 4)
        assert out.column("i").to_pylist() == list(range(n))
        assert out.column("s").to_pylist() == vals


def _sweep_table(rng, n=12_000):
    return pa.table({
        "k": pa.array(rng.integers(0, 512, n)),
        "g": pa.array(rng.integers(0, 16, n).astype(np.int32)),
        "v": pa.array(rng.uniform(size=n)),
        "c": pa.array(rng.integers(0, 1 << 30, n)),
        "s": pa.array(["n%d" % (i % 997) if i % 11 else None
                       for i in range(n)]),
    })


class TestPipelineGoldenSweep:
    """Pipeline-on vs pipeline-off across scan -> filter -> join -> agg
    (ISSUE-6 satellite): rows and integer aggregates bit-identical; f64
    sums allclose (batch regrouping reorders additions, the documented
    variableFloatAgg caveat)."""

    @pytest.fixture(scope="class")
    def scene(self, tmp_path_factory):
        rng = np.random.default_rng(17)
        t = _sweep_table(rng)
        path = str(tmp_path_factory.mktemp("sweep") / "fact.parquet")
        pq.write_table(t, path, row_group_size=4096)
        dim = pa.table({"k": pa.array(np.arange(512)),
                        "w": pa.array(rng.integers(0, 1000, 512))})
        return path, dim

    @staticmethod
    def _run(scene, pipeline, agg):
        path, dim = scene
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.tpu.pipeline.enabled": pipeline})
        q = (sess.read_parquet(path)
             .filter(col("v") > 0.2)
             .join(sess.from_arrow(dim), on="k"))
        if agg:
            q = q.group_by("g").agg(total=Sum(col("c") + col("w")),
                                    fsum=Sum(col("v")),
                                    cnt=Count(col("k")))
            return q.collect().sort_by("g")
        return q.collect().sort_by([("c", "ascending")])

    @pytest.fixture(scope="class")
    def results(self, scene):
        """Each of the four engine runs executes ONCE for the class; the
        tests below assert different facets of the same outputs."""
        before = EB.PREFETCH_THREADS_STARTED
        off_rows = self._run(scene, False, agg=False)
        off_agg = self._run(scene, False, agg=True)
        off_threads = EB.PREFETCH_THREADS_STARTED - before
        before = EB.PREFETCH_THREADS_STARTED
        on_rows = self._run(scene, True, agg=False)
        on_agg = self._run(scene, True, agg=True)
        on_threads = EB.PREFETCH_THREADS_STARTED - before
        prefetched = TaskMetrics.get().prefetch_batches
        return (off_rows, off_agg, on_rows, on_agg, off_threads,
                on_threads, prefetched)

    def test_rows_bit_identical(self, results):
        off_rows, _, on_rows = results[0], results[1], results[2]
        assert on_rows.equals(off_rows)

    def test_agg_int_exact_float_close(self, results):
        off, on = results[1], results[3]
        assert on.column("g").equals(off.column("g"))
        assert on.column("total").equals(off.column("total"))
        assert on.column("cnt").equals(off.column("cnt"))
        np.testing.assert_allclose(np.array(on.column("fsum")),
                                   np.array(off.column("fsum")),
                                   rtol=1e-12)

    def test_prefetch_actually_engaged(self, results):
        assert results[5] > 0  # pipeline-on spawned prefetch threads
        assert results[6] > 0  # and batches actually flowed through them

    def test_pipeline_off_exact_serial_path(self, results):
        assert results[4] == 0  # pipeline-off spawned none


NDEV = 8


class TestExchangeOverflowUnderPressure:
    def test_slot_overflow_grow_rerun_with_spill_active(self, rng):
        """ISSUE-6 satellite (VERDICT weak #7): the ICI slot-overflow
        grow-and-rerun loop exercised under a TIGHT MemoryBudget with
        spill active — skewed rows overflow the bounded slot (retry
        larger), while parked spillables exceed the budget and spill to
        host for real. Rows must land exactly once."""
        from spark_rapids_tpu.exec import exchange as EX
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.shuffle.mode": "ICI",
                           "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}",
                           "spark.rapids.shuffle.ici.slotRows": 16,
                           "spark.rapids.sql.batchSizeRows": 512,
                           "spark.rapids.sql.batchSizeBytes": 1 << 18})
        sess.initialize_device()
        n = 3000
        t = pa.table({
            "id": pa.array(np.full(n, 7), type=pa.int64()),  # one hot key
            "val": pa.array(rng.normal(0, 1, n), type=pa.float64()),
            "o": pa.array(np.arange(n, dtype=np.int64)),
        })
        df = sess.from_arrow(t)
        q = (df.repartition(NDEV, "id")
               .sort("o"))
        try:
            # calibration pass: learn this query's peak device footprint
            # (bucket-tuner state from earlier tests shifts padded sizes,
            # so a hard-coded budget is brittle); then rerun under 70% of
            # it — parked spillables must spill, single reserves still fit
            MemoryBudget.initialize(1 << 62, sess.conf)
            MemoryBudget.get().reset_peak()
            q.collect()
            peak = MemoryBudget.get().peak_used
            MemoryBudget.initialize(max(int(peak * 0.7), 64 << 10),
                                    sess.conf)
            before_ov = EX.SLOT_OVERFLOW_RETRIES
            out = q.collect()
            tm = TaskMetrics.get()
            assert out.num_rows == n
            assert out.column("o").to_pylist() == list(range(n))
            assert EX.SLOT_OVERFLOW_RETRIES > before_ov  # grow-and-rerun ran
            assert tm.spill_to_host_ns > 0  # pressure really spilled
        finally:
            MemoryBudget.initialize(1 << 62)


class TestCpuFallbackRerunCounter:
    def test_long_key_groupby_counts_rerun(self, rng):
        """ISSUE-6 satellite (VERDICT weak #8): a GROUP BY on a key wider
        than string.headWidth re-runs the stage on host via
        CpuFallbackRequired — silently, before this counter."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        n = 300
        keys = [("K%03d" % (i % 3)) * 120 for i in range(n)]  # ~600B keys
        t = pa.table({"s": pa.array(keys),
                      "v": pa.array(np.ones(n))})
        q = sess.from_arrow(t).group_by("s").agg(n_=Count(col("v")))
        out = q.collect()
        assert out.num_rows == 3
        tm = TaskMetrics.get()
        assert tm.cpu_fallback_reruns >= 1
        assert "cpuFallbackReruns" in tm.explain_string()

    def test_no_fallback_counts_zero(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = pa.table({"g": pa.array(np.arange(100, dtype=np.int64) % 5),
                      "v": pa.array(np.ones(100))})
        sess.from_arrow(t).group_by("g").agg(n_=Count(col("v"))).collect()
        assert TaskMetrics.get().cpu_fallback_reruns == 0
