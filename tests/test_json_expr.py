"""JSON expression tests (reference GpuGetJsonObject.scala /
GpuJsonToStructs.scala): differential device-vs-CPU plus hand oracles."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (GetJsonObject, JsonToStructs, JsonTuple,
                                   GetStructField, col, lit, parse_json_path)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same

ROWS = [
    '{"a": 1, "b": "xy", "c": {"d": 5}}',
    '{"a": -2.5, "b": null, "arr": [10, 20, 30]}',
    '{"b": "has,comma", "a": 7}',
    '{"nested": {"a": 99}, "a": 3}',
    'not json at all',
    None,
    '{"other": 1, "arr": []}',
    '{"arr": [{"x": 1}, {"x": 2}]}',
    '{ "a" :  42 , "b":"s p a c e" }',
]


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


@pytest.fixture(scope="module")
def jdf(session):
    t = pa.table({"j": pa.array(ROWS),
                  "i": pa.array(range(len(ROWS)), type=pa.int64())})
    return session.from_arrow(t)


def col_list(out, name):
    return out.sort_by([("i", "ascending")]).column(name).to_pylist()


class TestGetJsonObject:
    def test_paths(self, session, jdf):
        q = jdf.select("i",
                       a=GetJsonObject(col("j"), lit("$.a")),
                       b=GetJsonObject(col("j"), lit("$.b")),
                       cd=GetJsonObject(col("j"), lit("$.c.d")),
                       a1=GetJsonObject(col("j"), lit("$.arr[1]")),
                       nx=GetJsonObject(col("j"), lit("$.arr[1].x")),
                       whole=GetJsonObject(col("j"), lit("$.arr")))
        out = assert_same(q, sort_by=["i"])
        assert col_list(out, "a") == [
            "1", "-2.5", "7", "3", None, None, None, None, "42"]
        assert col_list(out, "b") == [
            "xy", None, "has,comma", None, None, None, None, None,
            "s p a c e"]
        assert col_list(out, "cd") == [
            "5", None, None, None, None, None, None, None, None]
        assert col_list(out, "a1") == [
            None, "20", None, None, None, None, None, '{"x": 2}', None]
        assert col_list(out, "nx") == [
            None, None, None, None, None, None, None, "2", None]
        assert col_list(out, "whole") == [
            None, "[10, 20, 30]", None, None, None, None, "[]",
            '[{"x": 1}, {"x": 2}]', None]

    def test_bad_paths_raise(self):
        with pytest.raises(ValueError):
            parse_json_path("a.b")
        with pytest.raises(ValueError):
            parse_json_path("$.a[*]")
        with pytest.raises(ValueError):
            GetJsonObject(col("j"), col("p"))

    def test_fuzz_vs_python_json(self, session, rng):
        import json as pyjson
        rows = []
        for i in range(200):
            obj = {"k%d" % (i % 5): int(rng.integers(-100, 100)),
                   "s": "v%d" % i,
                   "f": round(float(rng.normal()), 3),
                   "l": [int(x) for x in rng.integers(0, 9, i % 4)]}
            rows.append(pyjson.dumps(obj))
        t = pa.table({"j": pa.array(rows),
                      "i": pa.array(range(len(rows)), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", s=GetJsonObject(col("j"), lit("$.s")),
                      f=GetJsonObject(col("j"), lit("$.f")),
                      l0=GetJsonObject(col("j"), lit("$.l[0]")))
        out = assert_same(q, sort_by=["i"])
        for i, raw in enumerate(rows):
            obj = pyjson.loads(raw)
            assert out.column("s").to_pylist()[i] == obj["s"]
            assert float(out.column("f").to_pylist()[i]) == obj["f"]
            want = str(obj["l"][0]) if obj["l"] else None
            assert out.column("l0").to_pylist()[i] == want


class TestJsonTupleAndStructs:
    def test_json_tuple(self, session, jdf):
        q = jdf.select("i", a=JsonTuple(col("j"), lit("a")),
                       b=JsonTuple(col("j"), lit("b")))
        out = assert_same(q, sort_by=["i"])
        assert col_list(out, "a") == [
            "1", "-2.5", "7", "3", None, None, None, None, "42"]

    def test_from_json_flat_struct(self, session, rng):
        import json as pyjson
        rows = [pyjson.dumps({"id": i, "name": f"n{i}", "flag": i % 2 == 0})
                for i in range(50)] + [None, "garbage"]
        t = pa.table({"j": pa.array(rows),
                      "i": pa.array(range(len(rows)), type=pa.int64())})
        df = session.from_arrow(t)
        schema = T.StructType([
            T.StructField("id", T.LONG),
            T.StructField("name", T.STRING),
            T.StructField("flag", T.BOOLEAN),
        ])
        st = JsonToStructs(col("j"), schema)
        q = df.select("i", id=GetStructField(st, 0),
                      name=GetStructField(st, 1),
                      flag=GetStructField(st, 2))
        out = assert_same(q, sort_by=["i"])
        ids = out.column("id").to_pylist()
        names = out.column("name").to_pylist()
        flags = out.column("flag").to_pylist()
        for i in range(50):
            assert ids[i] == i and names[i] == f"n{i}" and \
                flags[i] == (i % 2 == 0)
        assert ids[50] is None and ids[51] is None

    def test_from_json_double_field_falls_back(self, session, jdf):
        # string -> double parse is not device-supported: tagged to CPU
        schema = T.StructType([T.StructField("a", T.DOUBLE)])
        st = JsonToStructs(col("j"), schema)
        q = jdf.select("i", a=GetStructField(st, 0))
        assert "runs on CPU" in q.explain()
        out = q.collect()  # still correct via fallback
        a = col_list(out, "a")
        assert a[0] == 1.0 and a[1] == -2.5

    def test_from_json_rejects_nested_schema(self):
        with pytest.raises(ValueError, match="flat"):
            JsonToStructs(col("j"), T.StructType([
                T.StructField("x", T.ArrayType(T.LONG))]))


class TestKeyShadowing:
    def test_value_equal_to_key_pattern(self, session):
        rows = ['{"x": "a", "a": 1}',
                '{"x": ",\\"a\\":", "a": 2}',
                '{"a": "a"}']
        t = pa.table({"j": pa.array(rows),
                      "i": pa.array(range(len(rows)), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", a=GetJsonObject(col("j"), lit("$.a")))
        out = assert_same(q, sort_by=["i"])
        got = out.sort_by([("i", "ascending")]).column("a").to_pylist()
        assert got[0] == "1"   # value "a" must not shadow the key
        assert got[2] == "a"

    def test_underscore_float_rejected(self, session):
        from spark_rapids_tpu.expr import Cast
        from spark_rapids_tpu import types as TT
        t = pa.table({"s": pa.array(["1_000", "1.5", "2e3", "bad"])})
        df = session.from_arrow(t)
        out = df.select(d=Cast(col("s"), TT.DOUBLE)).collect_cpu()
        assert out.column("d").to_pylist() == [None, 1.5, 2000.0, None]
