"""Expression semantics tests: CPU-vs-TPU differential + handwritten Spark-semantic
expectations (the reference's CastOpSuite / arithmetic suites model)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (
    Abs, Add, And, Cast, CaseWhen, Coalesce, Concat, Contains, DateAdd, DateDiff,
    DayOfMonth, DayOfWeek, Divide, EndsWith, EqualNullSafe, EqualTo, Greatest, Hour,
    If, In, IntegralDivide, IsNaN, IsNotNull, IsNull, Least, Length, LessThan,
    Literal, Lower, Minute, Month, Murmur3Hash, Not, Or, Pmod, Remainder, Round,
    Second, ShiftLeft, ShiftRight, ShiftRightUnsigned, StartsWith, StringTrim,
    Substring, Upper, Year, col, lit)
from harness import assert_cpu_tpu_equal, eval_cpu

I = lambda *v: pa.array(v, type=pa.int32())
L = lambda *v: pa.array(v, type=pa.int64())
D = lambda *v: pa.array(v, type=pa.float64())
S = lambda *v: pa.array(v, type=pa.string())
B = lambda *v: pa.array(v, type=pa.bool_())


def t(**cols):
    return pa.table(dict(cols))


class TestArithmetic:
    def test_add_promote(self):
        out = assert_cpu_tpu_equal(lambda: Add(col("a"), col("b")),
                                   t(a=I(1, None, 3), b=L(10, 20, None)))
        assert out.to_pylist() == [11, None, None]
        assert out.type == pa.int64()

    def test_int_overflow_wraps(self):
        out = assert_cpu_tpu_equal(lambda: Add(col("a"), col("a")),
                                   t(a=L(2**62, -5)))
        assert out.to_pylist() == [-2**63, -10]

    def test_divide_by_zero_null(self):
        out = assert_cpu_tpu_equal(lambda: Divide(col("a"), col("b")),
                                   t(a=I(10, 7, None), b=I(0, 2, 3)))
        assert out.to_pylist() == [None, 3.5, None]

    def test_integral_divide_trunc(self):
        out = assert_cpu_tpu_equal(lambda: IntegralDivide(col("a"), col("b")),
                                   t(a=I(7, -7, 7, -7, 5), b=I(2, 2, -2, -2, 0)))
        assert out.to_pylist() == [3, -3, -3, 3, None]

    def test_remainder_java_sign(self):
        out = assert_cpu_tpu_equal(lambda: Remainder(col("a"), col("b")),
                                   t(a=I(7, -7, 7, -7), b=I(3, 3, -3, -3)))
        assert out.to_pylist() == [1, -1, 1, -1]  # sign follows dividend

    def test_pmod(self):
        out = assert_cpu_tpu_equal(lambda: Pmod(col("a"), col("b")),
                                   t(a=I(7, -7), b=I(3, 3)))
        assert out.to_pylist() == [1, 2]

    def test_float_remainder(self):
        out = assert_cpu_tpu_equal(lambda: Remainder(col("a"), col("b")),
                                   t(a=D(5.5, -5.5), b=D(2.0, 2.0)))
        assert out.to_pylist() == [1.5, -1.5]

    def test_abs(self):
        out = assert_cpu_tpu_equal(lambda: Abs(col("a")), t(a=I(-3, 4, None)))
        assert out.to_pylist() == [3, 4, None]


class TestPredicates:
    def test_compare_nan_semantics(self):
        nan = float("nan")
        tbl = t(a=D(1.0, nan, nan, 2.0), b=D(nan, nan, 1.0, 2.0))
        assert assert_cpu_tpu_equal(
            lambda: EqualTo(col("a"), col("b")), tbl).to_pylist() == \
            [False, True, False, True]
        assert assert_cpu_tpu_equal(
            lambda: LessThan(col("a"), col("b")), tbl).to_pylist() == \
            [True, False, False, False]

    def test_string_compare(self):
        tbl = t(a=S("apple", "b", "abc", "", None),
                b=S("apricot", "a", "abc", "x", "y"))
        assert assert_cpu_tpu_equal(
            lambda: LessThan(col("a"), col("b")), tbl).to_pylist() == \
            [True, False, False, True, None]
        assert assert_cpu_tpu_equal(
            lambda: EqualTo(col("a"), col("b")), tbl).to_pylist() == \
            [False, False, True, False, None]

    def test_kleene_and_or(self):
        tbl = t(a=B(True, True, False, None, None),
                b=B(None, False, None, None, False))
        assert assert_cpu_tpu_equal(lambda: And(col("a"), col("b")), tbl) \
            .to_pylist() == [None, False, False, None, False]
        assert assert_cpu_tpu_equal(lambda: Or(col("a"), col("b")), tbl) \
            .to_pylist() == [True, True, None, None, None]

    def test_null_safe_equal(self):
        tbl = t(a=I(1, None, None, 2), b=I(1, None, 3, 5))
        assert assert_cpu_tpu_equal(
            lambda: EqualNullSafe(col("a"), col("b")), tbl).to_pylist() == \
            [True, True, False, False]

    def test_in(self):
        tbl = t(a=I(1, 2, 3, None))
        assert assert_cpu_tpu_equal(lambda: In(col("a"), [1, 3]), tbl) \
            .to_pylist() == [True, False, True, None]
        assert assert_cpu_tpu_equal(lambda: In(col("a"), [1, None]), tbl) \
            .to_pylist() == [True, None, None, None]

    def test_not(self):
        assert assert_cpu_tpu_equal(lambda: Not(col("a")),
                                    t(a=B(True, False, None))).to_pylist() == \
            [False, True, None]


class TestConditional:
    def test_if(self):
        tbl = t(c=B(True, False, None), a=I(1, 2, 3), b=I(10, 20, 30))
        assert assert_cpu_tpu_equal(lambda: If(col("c"), col("a"), col("b")),
                                    tbl).to_pylist() == [1, 20, 30]

    def test_case_when(self):
        tbl = t(x=I(1, 5, 15, None))
        assert assert_cpu_tpu_equal(
            lambda: CaseWhen([(LessThan(col("x"), lit(3)), lit(100)),
                              (LessThan(col("x"), lit(10)), lit(200))],
                             lit(300)), tbl).to_pylist() == [100, 200, 300, 300]

    def test_coalesce(self):
        tbl = t(a=I(None, 2, None), b=I(1, 5, None))
        assert assert_cpu_tpu_equal(lambda: Coalesce(col("a"), col("b")), tbl) \
            .to_pylist() == [1, 2, None]

    def test_coalesce_strings(self):
        tbl = t(a=S(None, "x", None), b=S("fallback", "y", None))
        assert assert_cpu_tpu_equal(lambda: Coalesce(col("a"), col("b")), tbl) \
            .to_pylist() == ["fallback", "x", None]

    def test_least_greatest(self):
        tbl = t(a=I(1, None, 5), b=I(3, 2, None))
        assert assert_cpu_tpu_equal(lambda: Least(col("a"), col("b")), tbl) \
            .to_pylist() == [1, 2, 5]
        assert assert_cpu_tpu_equal(lambda: Greatest(col("a"), col("b")), tbl) \
            .to_pylist() == [3, 2, 5]


class TestNullExprs:
    def test_is_null(self):
        tbl = t(a=I(1, None))
        assert assert_cpu_tpu_equal(lambda: IsNull(col("a")), tbl).to_pylist() \
            == [False, True]
        assert assert_cpu_tpu_equal(lambda: IsNotNull(col("a")), tbl) \
            .to_pylist() == [True, False]

    def test_is_nan(self):
        tbl = t(a=D(1.0, float("nan"), None))
        assert assert_cpu_tpu_equal(lambda: IsNaN(col("a")), tbl).to_pylist() \
            == [False, True, False]


class TestStrings:
    def test_length_chars(self):
        tbl = t(s=S("hello", "", "日本語", "a🎉b", None))
        assert assert_cpu_tpu_equal(lambda: Length(col("s")), tbl).to_pylist() \
            == [5, 0, 3, 3, None]

    def test_upper_lower(self):
        tbl = t(s=S("MiXeD", "abc", None))
        assert assert_cpu_tpu_equal(lambda: Upper(col("s")), tbl).to_pylist() \
            == ["MIXED", "ABC", None]
        assert assert_cpu_tpu_equal(lambda: Lower(col("s")), tbl).to_pylist() \
            == ["mixed", "abc", None]

    def test_substring(self):
        tbl = t(s=S("hello world", "ab", "日本語テキスト", ""))
        assert assert_cpu_tpu_equal(
            lambda: Substring(col("s"), lit(1), lit(5)), tbl).to_pylist() == \
            ["hello", "ab", "日本語テキ", ""]
        # Spark: start=len+pos may be <0; end=start+len computed before clamping,
        # so substring('ab', -3, 2) = 'a' (window shortened, not shifted)
        assert assert_cpu_tpu_equal(
            lambda: Substring(col("s"), lit(-3), lit(2)), tbl).to_pylist() == \
            ["rl", "a", "キス", ""]
        assert assert_cpu_tpu_equal(
            lambda: Substring(col("s"), lit(7), lit(100)), tbl).to_pylist() == \
            ["world", "", "ト", ""]

    def test_concat(self):
        tbl = t(a=S("foo", "", None), b=S("bar", "x", "y"))
        assert assert_cpu_tpu_equal(lambda: Concat(col("a"), col("b")), tbl) \
            .to_pylist() == ["foobar", "x", None]

    def test_starts_ends_contains(self):
        tbl = t(s=S("hello world", "world", "hell", None))
        assert assert_cpu_tpu_equal(
            lambda: StartsWith(col("s"), lit("hell")), tbl).to_pylist() == \
            [True, False, True, None]
        assert assert_cpu_tpu_equal(
            lambda: EndsWith(col("s"), lit("world")), tbl).to_pylist() == \
            [True, True, False, None]
        assert assert_cpu_tpu_equal(
            lambda: Contains(col("s"), lit("o w")), tbl).to_pylist() == \
            [True, False, False, None]
        assert assert_cpu_tpu_equal(
            lambda: Contains(col("s"), lit("")), tbl).to_pylist() == \
            [True, True, True, None]

    def test_trim(self):
        tbl = t(s=S("  hi  ", "hi", "   ", ""))
        assert assert_cpu_tpu_equal(lambda: StringTrim(col("s")), tbl) \
            .to_pylist() == ["hi", "hi", "", ""]


class TestDatetime:
    def test_date_parts(self):
        # 2023-11-14 = 19675 days; 1970-01-01; 2000-02-29
        tbl = pa.table({"d": pa.array([19675, 0, 11016, None], type=pa.date32())})
        assert assert_cpu_tpu_equal(lambda: Year(col("d")), tbl).to_pylist() == \
            [2023, 1970, 2000, None]
        assert assert_cpu_tpu_equal(lambda: Month(col("d")), tbl).to_pylist() == \
            [11, 1, 2, None]
        assert assert_cpu_tpu_equal(lambda: DayOfMonth(col("d")), tbl) \
            .to_pylist() == [14, 1, 29, None]
        assert assert_cpu_tpu_equal(lambda: DayOfWeek(col("d")), tbl) \
            .to_pylist() == [3, 5, 3, None]  # Tue=3, Thu=5, Tue=3

    def test_negative_days(self):
        tbl = pa.table({"d": pa.array([-1, -365], type=pa.date32())})
        assert assert_cpu_tpu_equal(lambda: Year(col("d")), tbl).to_pylist() == \
            [1969, 1969]
        assert assert_cpu_tpu_equal(lambda: Month(col("d")), tbl).to_pylist() == \
            [12, 1]

    def test_time_parts(self):
        us = 1_700_000_000_000_000  # 2023-11-14T22:13:20Z
        tbl = pa.table({"ts": pa.array([us, 0, -1_000_000],
                                       type=pa.timestamp("us", tz="UTC"))})
        assert assert_cpu_tpu_equal(lambda: Hour(col("ts")), tbl).to_pylist() == \
            [22, 0, 23]
        assert assert_cpu_tpu_equal(lambda: Minute(col("ts")), tbl).to_pylist() \
            == [13, 0, 59]
        assert assert_cpu_tpu_equal(lambda: Second(col("ts")), tbl).to_pylist() \
            == [20, 0, 59]

    def test_date_add_diff(self):
        tbl = pa.table({"d": pa.array([19675, 0], type=pa.date32()),
                        "k": I(10, -10)})
        assert assert_cpu_tpu_equal(lambda: DateAdd(col("d"), col("k")), tbl) \
            .to_pylist()[0].isoformat() == "2023-11-24"


class TestCast:
    def test_long_to_int_wraps(self):
        tbl = t(a=L(2**31, -2**31 - 1, 5))
        out = assert_cpu_tpu_equal(lambda: Cast(col("a"), T.INT), tbl)
        assert out.to_pylist() == [-2**31, 2**31 - 1, 5]

    def test_double_to_int_java(self):
        tbl = t(a=D(1.9, -1.9, float("nan"), 1e20, -1e20))
        out = assert_cpu_tpu_equal(lambda: Cast(col("a"), T.INT), tbl)
        assert out.to_pylist() == [1, -1, 0, 2**31 - 1, -2**31]

    def test_int_to_string(self):
        tbl = t(a=L(0, -1, 1234567890123, -2**63, None))
        out = assert_cpu_tpu_equal(lambda: Cast(col("a"), T.STRING), tbl)
        assert out.to_pylist() == ["0", "-1", "1234567890123",
                                   "-9223372036854775808", None]

    def test_bool_to_string(self):
        out = assert_cpu_tpu_equal(lambda: Cast(col("a"), T.STRING),
                                   t(a=B(True, False, None)))
        assert out.to_pylist() == ["true", "false", None]

    def test_string_to_int(self):
        tbl = t(s=S(" 42 ", "-7", "+13", "abc", "12.5", "", None,
                    "99999999999999999999"))
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.INT), tbl)
        assert out.to_pylist() == [42, -7, 13, None, None, None, None, None]

    def test_string_to_bool(self):
        tbl = t(s=S("true", "FALSE", "t", "no", "1", "maybe", None))
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.BOOLEAN), tbl)
        assert out.to_pylist() == [True, False, True, False, True, None, None]

    def test_date_to_string(self):
        tbl = pa.table({"d": pa.array([19675, 0, 11016], type=pa.date32())})
        out = assert_cpu_tpu_equal(lambda: Cast(col("d"), T.STRING), tbl)
        assert out.to_pylist() == ["2023-11-14", "1970-01-01", "2000-02-29"]

    def test_string_to_date(self):
        tbl = t(s=S("2023-11-14", "1970-01-01", "2000-02-29", "2001-02-29",
                    "not a date", "2023-13-01", None))
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.DATE), tbl)
        assert [d.isoformat() if d else None for d in out.to_pylist()] == \
            ["2023-11-14", "1970-01-01", "2000-02-29", None, None, None, None]

    def test_ts_date_roundtrip(self):
        tbl = pa.table({"ts": pa.array([1_700_000_000_000_000, -1],
                                       type=pa.timestamp("us", tz="UTC"))})
        out = assert_cpu_tpu_equal(lambda: Cast(col("ts"), T.DATE), tbl)
        assert [d.isoformat() for d in out.to_pylist()] == \
            ["2023-11-14", "1969-12-31"]


def _py_spark_murmur3_int(v, seed):
    """Independent scalar reimplementation of Murmur3_x86_32.hashInt."""
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    def mixk1(k1):
        k1 = (k1 * 0xcc9e2d51) & M
        k1 = rotl(k1, 15)
        return (k1 * 0x1b873593) & M

    def mixh1(h1, k1):
        h1 ^= k1
        h1 = rotl(h1, 13)
        return (h1 * 5 + 0xe6546b64) & M

    h1 = mixh1(seed & M, mixk1(v & M))
    h1 ^= 4
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85ebca6b) & M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xc2b2ae35) & M
    h1 ^= h1 >> 16
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


class TestHash:
    def test_murmur3_int_matches_reference_impl(self):
        vals = [0, 1, -1, 42, 2**31 - 1, -2**31]
        tbl = t(a=pa.array(vals, type=pa.int32()))
        out = assert_cpu_tpu_equal(lambda: Murmur3Hash(col("a")), tbl)
        assert out.to_pylist() == [_py_spark_murmur3_int(v, 42) for v in vals]

    def test_murmur3_null_passthrough(self):
        # null column passes seed through: hash(null) == seed mixed with nothing
        tbl = t(a=I(None, None))
        out = assert_cpu_tpu_equal(lambda: Murmur3Hash(col("a")), tbl)
        assert out.to_pylist() == [42, 42]

    def test_murmur3_string_cpu_tpu(self):
        tbl = t(s=S("", "a", "abcd", "abcde", "hello world, this is long",
                    None, "日本語"))
        out = assert_cpu_tpu_equal(lambda: Murmur3Hash(col("s")), tbl)
        assert out.to_pylist()[5] == 42  # null row -> seed

    def test_murmur3_multi_column(self):
        tbl = t(a=I(1, 2), s=S("x", None), d=D(1.5, -0.0))
        assert_cpu_tpu_equal(lambda: Murmur3Hash(col("a"), col("s"), col("d")),
                             tbl)


class TestMathExprs:
    def test_log_domain(self):
        from spark_rapids_tpu.expr import Log
        tbl = t(a=D(1.0, 0.0, -1.0, None))
        out = assert_cpu_tpu_equal(lambda: Log(col("a")), tbl)
        assert out.to_pylist() == [0.0, None, None, None]

    def test_round_half_up(self):
        tbl = t(a=D(2.5, 3.5, -2.5, 1.25))
        out = assert_cpu_tpu_equal(lambda: Round(col("a"), 0), tbl)
        assert out.to_pylist() == [3.0, 4.0, -3.0, 1.0]

    def test_shifts(self):
        tbl = t(a=I(8, -8), k=I(1, 1))
        assert assert_cpu_tpu_equal(lambda: ShiftLeft(col("a"), col("k")), tbl) \
            .to_pylist() == [16, -16]
        assert assert_cpu_tpu_equal(lambda: ShiftRight(col("a"), col("k")), tbl) \
            .to_pylist() == [4, -4]
        assert assert_cpu_tpu_equal(
            lambda: ShiftRightUnsigned(col("a"), col("k")), tbl).to_pylist() == \
            [4, 2147483644]


class TestDoubleBits:
    def test_murmur3_double_edge_values(self):
        # NOTE: subnormals (e.g. 5e-324) excluded — XLA flushes f64 subnormals to
        # zero on device (documented incompat in hashing._double_bits)
        vals = [0.0, -0.0, 1.5, -1.5, float("inf"), float("-inf"),
                float("nan"), 2.2250738585072014e-308, 1e308, None]
        tbl = t(a=pa.array(vals, type=pa.float64()))
        assert_cpu_tpu_equal(lambda: Murmur3Hash(col("a")), tbl)


class TestReviewRegressions:
    def test_trunc_div_int_min(self):
        tbl = t(a=L(-2**63, -2**63, -2**31), b=L(3, -1, 3))
        assert assert_cpu_tpu_equal(lambda: IntegralDivide(col("a"), col("b")),
                                    tbl).to_pylist() == \
            [-3074457345618258602, -2**63, -715827882]
        assert assert_cpu_tpu_equal(lambda: Remainder(col("a"), col("b")), tbl) \
            .to_pylist() == [-2, 0, -2]

    def test_string_to_long_overflow_null(self):
        tbl = t(s=S("99999999999999999999", "9223372036854775807",
                    "-9223372036854775808", "9223372036854775808",
                    "-9223372036854775809"))
        out = assert_cpu_tpu_equal(lambda: Cast(col("s"), T.LONG), tbl)
        assert out.to_pylist() == [None, 2**63 - 1, -2**63, None, None]

    def test_double_to_long_bounds(self):
        tbl = t(a=D(1e20, -1e20, 9.3e18, float("nan")))
        out = assert_cpu_tpu_equal(lambda: Cast(col("a"), T.LONG), tbl)
        assert out.to_pylist() == [2**63 - 1, -2**63, 2**63 - 1, 0]

    def test_string_nul_ordering(self):
        tbl = t(a=S("a", "a\x00", "a"), b=S("a\x00", "a", "ab"))
        assert assert_cpu_tpu_equal(lambda: LessThan(col("a"), col("b")), tbl) \
            .to_pylist() == [True, False, True]


class TestOperatorSugar:
    def test_bool_context_raises(self):
        with pytest.raises(ValueError, match="Cannot convert"):
            bool(col("a") == 1)
        with pytest.raises(ValueError, match="Cannot convert"):
            (col("a") == 1) and (col("b") == 2)

    def test_reflected_operators(self):
        tbl = t(a=L(10, 20))
        out = assert_cpu_tpu_equal(lambda: 1 - col("a"), tbl)
        assert out.to_pylist() == [-9, -19]
        out = assert_cpu_tpu_equal(lambda: 100 / col("a"), tbl)
        assert out.to_pylist() == [10.0, 5.0]


class TestDocsGeneration:
    def test_supported_ops_docs_cover_registry(self):
        from spark_rapids_tpu.plan import overrides as O
        from spark_rapids_tpu.plan.typesig import generate_supported_ops_docs
        md = generate_supported_ops_docs()
        for cls in O._EXPR_RULES:
            assert f"`{cls.__name__}`" in md, cls
        for cls in O._EXEC_RULES:
            assert f"`{cls.__name__}`" in md, cls

    def test_config_docs_cover_registry(self):
        from spark_rapids_tpu import config as C
        md = C.generate_docs()
        for k, e in C.entries().items():
            if getattr(e, "internal", False):
                continue  # internal keys are excluded from docs by design
            assert k in md, k
