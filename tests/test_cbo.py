"""Cost-based optimizer + adaptive re-planning (reference
CostBasedOptimizer.scala, AQE query-stage re-planning)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import Add, Count, Sum, col, lit
from spark_rapids_tpu.plan.overrides import Overrides
from spark_rapids_tpu.plan.cbo import row_estimate
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


def small_table(rng, n=50):
    return pa.table({"k": pa.array(rng.integers(0, 5, n), type=pa.int64()),
                     "v": pa.array(rng.integers(-9, 9, n), type=pa.int64())})


def _plan_marks(sess, df):
    """explain tree lines for the would-be conversion."""
    sess.initialize_device()
    ov = Overrides(sess.conf)
    ov.apply(df.plan)
    return ov


class TestCboPlacement:
    def test_high_transition_cost_keeps_plan_on_cpu(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "ALL",
                           "spark.rapids.sql.optimizer.enabled": True,
                           "spark.rapids.sql.optimizer.transitionCost": 1e6})
        df = sess.from_arrow(small_table(rng)).select(x=Add(col("v"), lit(1)))
        sess.initialize_device()
        ov = Overrides(sess.conf)
        result = ov.apply(df.plan)
        from spark_rapids_tpu.exec.base import TpuExec
        assert not isinstance(result, TpuExec)
        assert any("cost-based optimizer" in l for l in ov.explain_log), \
            ov.explain_log

    def test_zero_transition_cost_converts_everything(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.optimizer.enabled": True,
                           "spark.rapids.sql.optimizer.transitionCost": 0.0})
        df = sess.from_arrow(small_table(rng)).select(x=Add(col("v"), lit(1)))
        sess.initialize_device()
        ov = Overrides(sess.conf)
        result = ov.apply(df.plan)
        from spark_rapids_tpu.exec.base import TpuExec
        assert isinstance(result, TpuExec)

    def test_cheap_tail_after_forced_cpu_stays_on_cpu(self, rng):
        """scan -> agg (device-capable, big input) -> forced-CPU op -> tiny
        device-capable tail: the tail must NOT bounce back to the device
        (the VERDICT scenario: a tiny CPU-cheap subtree deliberately kept on
        CPU to avoid transition thrash)."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.expr.base import Expression

        class OpaqueExpr(Expression):  # no rule registered -> CPU-only
            def __init__(self, child):
                super().__init__([child])

            @property
            def data_type(self):
                return T.LONG

            def _compute(self, ctx, c):
                return c

        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.optimizer.enabled": True,
                           "spark.rapids.sql.optimizer.transitionCost": 1.0})
        big = pa.table({"k": pa.array(np.arange(20000) % 40,
                                      type=pa.int64()),
                        "v": pa.array(np.arange(20000), type=pa.int64())})
        q = sess.from_arrow(big).group_by("k").agg(s=Sum(col("v"))) \
            .select(u=OpaqueExpr(col("s"))) \
            .select(y=Add(col("u"), lit(1)))
        sess.initialize_device()
        ov = Overrides(sess.conf)
        sess.conf.set("spark.rapids.sql.explain", "ALL")
        try:
            ov.apply(q.plan)
        finally:
            sess.conf.set("spark.rapids.sql.explain", "NONE")
        lines = ov.explain_log
        # the big aggregation converts; the tiny tail projection is kept on
        # CPU by the CBO (not by a capability reason)
        agg_line = next(l for l in lines if "HashAggregate" in l)
        assert agg_line.lstrip().startswith("*"), lines
        tail = next(l for l in lines if "Project" in l)  # outermost project
        assert "cost-based optimizer" in tail, lines
        # and the result is still correct end to end
        out = q.collect().sort_by("y")
        exp = q.collect_cpu().sort_by("y")
        assert out.column("y").to_pylist() == exp.column("y").to_pylist()

    def test_row_estimates(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        t = small_table(rng, n=100)
        df = sess.from_arrow(t)
        assert row_estimate(df.plan) == 100
        assert row_estimate(df.filter(col("v") > lit(0)).plan) == 50
        assert row_estimate(df.limit(7).plan) == 7
        assert row_estimate(df.union(df).plan) == 200

    def test_cbo_result_still_correct(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.optimizer.enabled": True})
        df = sess.from_arrow(small_table(rng, n=400))
        q = df.group_by("k").agg(s=Sum(col("v")), c=Count(col("v")))
        assert_same(q, sort_by=["k"])


def T_long():
    from spark_rapids_tpu import types as T
    return T.LONG


class TestAdaptive:
    def test_adaptive_stages_execute_and_match(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.adaptive.enabled": True})
        t = small_table(rng, n=300)
        df = sess.from_arrow(t).repartition(4, "k") \
            .group_by("k").agg(s=Sum(col("v")))
        out = df.collect().sort_by("k")
        exp = df.collect_cpu().sort_by("k")
        assert out.column("s").to_pylist() == exp.column("s").to_pylist()

    def test_adaptive_does_not_mutate_logical_plan(self, rng):
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.adaptive.enabled": True})
        df = sess.from_arrow(small_table(rng, n=120)).repartition(3, "k") \
            .group_by("k").agg(s=Sum(col("v")))
        before = repr(df.plan)
        first = df.collect().sort_by("k")
        assert repr(df.plan) == before  # staging rewrote a CLONE
        second = df.collect().sort_by("k")  # re-collect re-executes cleanly
        assert first.column("s").to_pylist() == second.column("s").to_pylist()

    def test_coalesce_partitions_uses_observed_bytes(self, rng):
        """Round-5 verdict #5a: a staged exchange whose observed output is
        tiny must coalesce its partition count toward the advisory size —
        the static 32 partitions become few observed-size slices."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.adaptive.enabled": True})
        t = small_table(rng, n=500)
        df = sess.from_arrow(t).repartition(32, "k") \
            .group_by("k").agg(s=Sum(col("v")))
        out = df.collect().sort_by("k")
        exp = df.collect_cpu().sort_by("k")
        assert out.column("s").to_pylist() == exp.column("s").to_pylist()
        log = sess._adaptive_log
        entries = [e for e in log if e["rule"] == "coalescePartitions"]
        assert entries, log
        assert entries[0]["from"] == 32
        assert entries[0]["to"] == 1  # ~12KB observed vs 64MB advisory

    def test_coalesce_respects_kill_switch(self, rng):
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.sql.adaptive.coalescePartitions.enabled": False})
        df = sess.from_arrow(small_table(rng, n=200)) \
            .repartition(8, "k").group_by("k").agg(s=Sum(col("v")))
        df.collect()
        assert not [e for e in sess._adaptive_log
                    if e["rule"] == "coalescePartitions"]

    def test_skew_join_splits_hot_partition(self, rng):
        """Round-5 verdict #5b: one key holding ~50% of probe rows
        re-plans the staged join into N bounded sub-joins (union of pair
        joins) and still matches the CPU engine."""
        import pyarrow as pa
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.sql.adaptive.skewJoin."
            "skewedPartitionRowThreshold": 1000,
            # small advisory so the hot partition splits into many chunks
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                64 * 1024})
        n = 20000
        hot = n // 2
        keys = np.concatenate([np.zeros(hot, np.int64),
                               rng.integers(1, 200, n - hot)])
        rng.shuffle(keys)
        probe = pa.table({"k": pa.array(keys),
                          "v": pa.array(rng.normal(size=n))})
        build = pa.table({"k": pa.array(np.arange(200, dtype=np.int64)),
                          "w": pa.array(rng.uniform(size=200))})
        lf = sess.from_arrow(probe).repartition(8, "k")
        rf = sess.from_arrow(build).repartition(8, "k")
        q = lf.join(rf, on="k", how="inner")
        out = q.collect().sort_by([("v", "ascending")])
        exp = q.collect_cpu().sort_by([("v", "ascending")])
        assert out.column("v").to_pylist() == exp.column("v").to_pylist()
        assert out.column("w").to_pylist() == exp.column("w").to_pylist()
        skews = [e for e in sess._adaptive_log if e["rule"] == "skewJoin"]
        assert skews, sess._adaptive_log
        assert skews[0]["rows"] >= hot  # the hot key's partition
        assert skews[0]["chunks"] > 1  # genuinely split into sub-joins

    def test_skew_join_nulls_and_mixed_key_types(self, rng):
        """The split must keep equal keys in equal partitions even when
        one side's key column carries nulls (pandas would silently turn
        it float64) and the other side is int32 — the canonicalized hash
        guards exactly this."""
        import pyarrow as pa
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.sql.adaptive.skewJoin."
            "skewedPartitionRowThreshold": 500,
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                16 * 1024})
        n = 8000
        keys = np.concatenate([np.full(n // 2, 7, np.int64),
                               rng.integers(1, 100, n - n // 2)])
        rng.shuffle(keys)
        mask = rng.random(n) < 0.05
        probe = pa.table({"k": pa.array(keys, mask=mask),
                          "v": pa.array(rng.normal(size=n))})
        build = pa.table({"k": pa.array(np.arange(100, dtype=np.int32)),
                          "w": pa.array(rng.uniform(size=100))})
        lf = sess.from_arrow(probe).repartition(6, "k")
        rf = sess.from_arrow(build).repartition(6, "k")
        q = lf.join(rf, on="k", how="left")
        out = q.collect().sort_by([("v", "ascending")])
        exp = q.collect_cpu().sort_by([("v", "ascending")])
        assert out.column("w").to_pylist() == exp.column("w").to_pylist()
        assert [e for e in sess._adaptive_log if e["rule"] == "skewJoin"]

    def test_skew_join_not_applied_to_full_outer(self, rng):
        """Splitting the probe would duplicate unmatched build rows per
        chunk — full joins must stay whole (and still answer right)."""
        import pyarrow as pa
        sess = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.explain": "NONE",
            "spark.rapids.sql.adaptive.enabled": True,
            "spark.rapids.sql.adaptive.skewJoin."
            "skewedPartitionRowThreshold": 100,
            "spark.rapids.sql.adaptive.advisoryPartitionSizeInBytes":
                8 * 1024})
        n = 4000
        keys = np.concatenate([np.zeros(n // 2, np.int64),
                               rng.integers(1, 50, n - n // 2)])
        probe = pa.table({"k": pa.array(keys),
                          "v": pa.array(rng.normal(size=n))})
        build = pa.table({"k": pa.array(np.arange(60, dtype=np.int64)),
                          "w": pa.array(rng.uniform(size=60))})
        lf = sess.from_arrow(probe).repartition(4, "k")
        rf = sess.from_arrow(build).repartition(4, "k")
        q = lf.join(rf, on="k", how="full")
        out = q.collect()
        exp = q.collect_cpu()
        assert out.num_rows == exp.num_rows
        assert not [e for e in sess._adaptive_log
                    if e["rule"] == "skewJoin"]

    def test_adaptive_replan_uses_observed_rows(self, rng, monkeypatch):
        """After the stage materializes, the re-plan must see the EXACT stage
        cardinality (scan row estimate), not a heuristic."""
        from spark_rapids_tpu.plan import adaptive as A
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.sql.adaptive.enabled": True})
        t = small_table(rng, n=200)
        df = sess.from_arrow(t).filter(col("v") > lit(0)) \
            .repartition(2, "k").group_by("k").agg(s=Sum(col("v")))
        seen = []
        orig = sess._execute_rewritten

        def spy(plan, use_device=None):
            out = orig(plan, use_device)
            seen.append((type(plan).__name__, out.num_rows))
            return out

        monkeypatch.setattr(sess, "_execute_rewritten", spy)
        df.collect()
        # two stages: the exchange child first, then the remainder
        assert len(seen) == 2
        stage_rows = seen[0][1]
        assert 0 < stage_rows < 200  # filter genuinely reduced the stage


class TestFooterStats:
    """Round-3: CBO estimates from parquet footers (row counts + min/max
    driven filter selectivity) instead of flat heuristics."""

    def _write(self, tmp_path, n=5000, lo=0, hi=1000):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        rng = np.random.default_rng(5)
        t = pa.table({"k": pa.array(
            rng.integers(lo, hi, n).astype(np.int64)),
            "v": pa.array(rng.normal(size=n))})
        p = str(tmp_path / "stats.parquet")
        pq.write_table(t, p)
        return p

    def test_scan_estimate_exact_from_footer(self, tmp_path):
        from spark_rapids_tpu.plan.cbo import row_estimate
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.explain": "NONE"})
        p = self._write(tmp_path, n=5000)
        df = s.read_parquet(p)
        assert row_estimate(df.plan) == 5000.0

    def test_filter_selectivity_from_min_max(self, tmp_path):
        from spark_rapids_tpu.expr import col, lit
        from spark_rapids_tpu.plan.cbo import row_estimate
        from spark_rapids_tpu.plugin import TpuSession
        s = TpuSession({"spark.rapids.sql.explain": "NONE"})
        p = self._write(tmp_path, n=5000, lo=0, hi=1000)
        df = s.read_parquet(p)
        # k < 100 over uniform [0, 1000): ~10%, not the flat 50%
        est = row_estimate(df.filter(col("k") < lit(100)).plan)
        assert 300 <= est <= 700, est
        # k > 5000 is impossible per stats
        est0 = row_estimate(df.filter(col("k") > lit(5000)).plan)
        assert est0 == 0.0
        # conjunction multiplies
        both = row_estimate(df.filter((col("k") < lit(100)) &
                                      (col("k") > lit(-1))).plan)
        assert both <= est + 1

    def test_stats_flip_placement(self, tmp_path):
        """A stats-informed near-zero filter keeps the tail on CPU where
        the flat heuristic would put it on device: footer stats change a
        real placement decision."""
        from spark_rapids_tpu.expr import col, lit
        from spark_rapids_tpu.plan.overrides import Overrides
        from spark_rapids_tpu.plugin import TpuSession
        p = self._write(tmp_path, n=5000, lo=0, hi=1000)
        conf = {"spark.rapids.sql.enabled": True,
                "spark.rapids.sql.explain": "ALL",
                "spark.rapids.sql.optimizer.enabled": True,
                # device pays off only beyond ~1k rows under these weights
                "spark.rapids.sql.optimizer.cpuExecCost": 1.0,
                "spark.rapids.sql.optimizer.gpuExecCost": 0.5,
                "spark.rapids.sql.optimizer.transitionCost": 1.0}
        s = TpuSession(conf)
        # impossible predicate: stats say ~0 rows flow out of the filter,
        # so everything above it is cost-prevented
        df = s.read_parquet(p).filter(col("k") > lit(10 ** 6)) \
            .select(x=col("v") + lit(1.0))
        ov = Overrides(s.conf)
        ov.apply(df.plan)
        assert any("cost-based optimizer" in l for l in ov.explain_log), \
            ov.explain_log

    def test_corpus_green_with_aqe_and_cbo(self, tmp_path):
        # smoke: scan+filter+join+agg end-to-end with AQE and CBO both on
        import numpy as np
        import pyarrow as pa
        from spark_rapids_tpu.expr import Count, Sum, col, lit
        from spark_rapids_tpu.plugin import TpuSession
        from test_queries import assert_same
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.adaptive.enabled": True,
                        "spark.rapids.sql.optimizer.enabled": True})
        p = self._write(tmp_path, n=3000)
        dim = s.from_arrow(pa.table({
            "k": pa.array(range(0, 1000, 10), type=pa.int64()),
            "w": pa.array([float(i) for i in range(100)])}))
        q = (s.read_parquet(p).filter(col("k") < lit(500))
             .join(dim, on="k", how="inner")
             .group_by("k").agg(n=Count(lit(1)), sw=Sum(col("w"))))
        out = q.collect()
        cpu = q.collect_cpu()
        ks = [("k", "ascending")]
        assert out.sort_by(ks).equals(cpu.sort_by(ks))
