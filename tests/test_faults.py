"""Fault-injection matrix and recovery-path tests.

The reference proves its robustness claims with RmmSpark OOM injection
(*RetrySuite) and a mocked droppable transport (RapidsShuffleClientSuite);
here the deterministic injector (spark_rapids_tpu/faults.py) drives full
queries and subsystem flows through every registered injection point and
asserts the documented contract: a correct result after recovery for
transient faults, a typed error within the deadline for permanent ones —
never a hang, never wrong rows.

Run standalone via scripts/fault_matrix.sh (pytest -m faults)."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.errors import (AdmissionTimeoutError, DeviceStartupError,
                                     InjectedFault, RetryOOM,
                                     ShuffleCorruptionError,
                                     ShuffleFetchFailedError,
                                     SplitAndRetryOOM)
from spark_rapids_tpu.expr import Count, Sum, col
from spark_rapids_tpu.faults import FaultInjector, FaultRule, inject
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.utils.metrics import TaskMetrics

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_slate():
    """Every test starts with no installed rules and fresh task metrics."""
    FaultInjector.reset()
    TaskMetrics.reset()
    yield
    FaultInjector.reset()


@pytest.fixture
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def _table(rng, n=600):
    return pa.table({
        "id": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "val": pa.array(rng.normal(0, 100, n), type=pa.float64()),
        "small": pa.array(rng.integers(-100, 100, n), type=pa.int32()),
    })


def _assert_same(df, sort_by):
    tpu = df.collect().sort_by([(k, "ascending") for k in sort_by])
    cpu = df.collect_cpu().sort_by([(k, "ascending") for k in sort_by])
    assert tpu.num_rows == cpu.num_rows
    for name in tpu.schema.names:
        assert tpu.column(name).to_pylist() == cpu.column(name).to_pylist(), \
            name
    return tpu


# ---------------------------------------------------------------------------
# The injector itself
# ---------------------------------------------------------------------------


class TestInjector:
    def test_nth_schedule_fires_once(self):
        with inject(faults.ALLOC, "error", nth=2, error=RetryOOM) as rule:
            faults.fire(faults.ALLOC)           # call 1: no fire
            with pytest.raises(RetryOOM):
                faults.fire(faults.ALLOC)       # call 2: fires
            faults.fire(faults.ALLOC)           # call 3: budget spent
            assert rule.calls == 3 and rule.fired == 1

    def test_every_call_unlimited(self):
        with inject(faults.FETCH, "error", nth=0, times=0) as rule:
            for _ in range(3):
                with pytest.raises(InjectedFault):
                    faults.fire(faults.FETCH)
            assert rule.fired == 3

    def test_probability_is_seeded_deterministic(self):
        def run():
            FaultInjector.reset()
            FaultInjector.get().reseed(7)
            fired = []
            with inject(faults.TCP_RECV, "error", probability=0.5, times=0):
                for i in range(32):
                    try:
                        faults.fire(faults.TCP_RECV)
                        fired.append(0)
                    except InjectedFault:
                        fired.append(1)
            return fired
        a, b = run(), run()
        assert a == b and 0 < sum(a) < 32

    def test_corrupt_default_flips_one_byte(self):
        payload = bytes(range(64))
        with inject(faults.BLOCK_READ, "corrupt"):
            out = faults.fire(faults.BLOCK_READ, payload)
        assert out != payload and len(out) == len(payload)
        assert sum(x != y for x, y in zip(out, payload)) == 1

    def test_disabled_passthrough(self):
        assert faults.fire(faults.ALLOC, b"x") == b"x"

    def test_spec_parsing(self):
        r = FaultRule.parse("shuffle.fetch:error,nth=3,times=2,err=conn")
        assert (r.point, r.kind, r.nth, r.times) == \
            ("shuffle.fetch", "error", 3, 2)
        assert r.error is ConnectionResetError
        r = FaultRule.parse("tcp.recv:delay,nth=0,times=0,delay=0.25")
        assert r.kind == "delay" and r.delay_s == 0.25
        r = FaultRule.parse("service.admission:wedge")
        assert r.kind == "wedge" and r.delay_s == 3600.0
        with pytest.raises(ValueError):
            FaultRule.parse("no-kind-here")
        with pytest.raises(ValueError):
            FaultRule.parse("p:zap,nth=1")

    def test_install_from_conf(self):
        conf = TpuConf({"spark.rapids.tpu.test.faults":
                        "memory.alloc:error,nth=1,err=oom; "
                        "shuffle.fetch:corrupt,nth=2"})
        rules = faults.install_from_conf(conf)
        assert len(rules) == 2
        with pytest.raises(RetryOOM):
            faults.fire(faults.ALLOC)


# ---------------------------------------------------------------------------
# Shuffle frame integrity (CRC32C satellite)
# ---------------------------------------------------------------------------


class TestChecksum:
    def _frame(self, rng, codec="zstd", checksum=True):
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.shuffle import serialize_batch
        return serialize_batch(batch_from_arrow(_table(rng, 100)), codec,
                               checksum=checksum)

    def test_clean_frame_verifies_and_deserializes(self, rng):
        from spark_rapids_tpu.shuffle import deserialize_table, verify_frame
        blob = self._frame(rng)
        verify_frame(blob)
        table, consumed = deserialize_table(blob)
        assert consumed == len(blob) and table.num_rows == 100

    def test_flipped_payload_byte_raises_typed(self, rng):
        from spark_rapids_tpu.shuffle import deserialize_table, verify_frame
        blob = bytearray(self._frame(rng))
        blob[-10] ^= 0xFF  # payload corruption (tail is compressed bytes)
        with pytest.raises(ShuffleCorruptionError):
            verify_frame(bytes(blob), block="b1", source="peer-x")
        with pytest.raises(ShuffleCorruptionError):
            deserialize_table(bytes(blob))

    def test_smashed_header_raises_typed(self, rng):
        from spark_rapids_tpu.shuffle import verify_frame
        blob = bytearray(self._frame(rng))
        blob[0] ^= 0xFF  # magic
        with pytest.raises(ShuffleCorruptionError):
            verify_frame(bytes(blob))

    def test_checksum_disabled_frames_are_unchecked(self, rng):
        from spark_rapids_tpu.shuffle import decode_meta, verify_frame
        blob = self._frame(rng, codec="none", checksum=False)
        assert decode_meta(blob)[0].checksum == 0
        corrupted = bytearray(blob)
        corrupted[-10] ^= 0xFF
        verify_frame(bytes(corrupted))  # no checksum -> no verification


# ---------------------------------------------------------------------------
# with_retry mechanics (deque + backoff metrics satellite)
# ---------------------------------------------------------------------------


class TestRetryMechanics:
    def test_split_preserves_order_depth_first(self):
        from spark_rapids_tpu.memory.retry import with_retry
        split_once = {"done": False}

        def fn(x):
            if x == "ab" and not split_once["done"]:
                raise SplitAndRetryOOM("too big")
            return x

        def split(x):
            split_once["done"] = True
            return [x[:1], x[1:]]

        assert list(with_retry("ab", fn, split)) == ["a", "b"]

    def test_backoff_recorded_per_attempt(self):
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        TaskMetrics.reset()
        calls = {"n": 0}

        def fn(x):
            calls["n"] += 1
            if calls["n"] < 4:
                raise RetryOOM("pressure")
            return x

        assert with_retry_no_split(41, fn) == 41
        tm = TaskMetrics.get()
        assert tm.retry_count == 3
        assert len(tm.retry_backoff_ms) == 3
        # exponential schedule: each wait doubles (2ms, 4ms, 8ms)
        assert tm.retry_backoff_ms[1] == pytest.approx(
            2 * tm.retry_backoff_ms[0])
        line = tm.explain_string()
        assert "oomRetries=3" in line and "backoffsMs=" in line

    def test_shuffle_counters_in_explain_string(self):
        TaskMetrics.reset()
        tm = TaskMetrics.get()
        tm.shuffle_retry_count = 2
        tm.shuffle_failover_count = 1
        s = tm.explain_string()
        assert "shuffleFetchRetries=2" in s and "shuffleFailovers=1" in s


# ---------------------------------------------------------------------------
# HeartbeatManager (satellite): expiry, re-registration, fetch-path skip
# ---------------------------------------------------------------------------


class TestHeartbeat:
    def _hb(self, expiry=10.0):
        from spark_rapids_tpu.shuffle import HeartbeatManager
        clock = [0.0]
        hb = HeartbeatManager(expiry_seconds=expiry,
                              clock=lambda: clock[0])
        return hb, clock

    def test_peer_expiry_after_missed_heartbeats(self):
        hb, clock = self._hb()
        hb.register_executor("a", "addr-a")
        hb.register_executor("b", "addr-b")
        clock[0] = 5.0
        hb.executor_heartbeat("a")     # b misses its beats
        clock[0] = 12.0                # b last seen at 0, expiry 10
        assert [p.executor_id for p in hb.known_peers()] == ["a"]
        with pytest.raises(KeyError):
            hb.executor_heartbeat("b")  # aged out: must re-register

    def test_returning_executor_reregisters(self):
        hb, clock = self._hb()
        hb.register_executor("a", "addr-a")
        hb.register_executor("b", "addr-b")
        clock[0] = 8.0
        hb.executor_heartbeat("a")     # a stays fresh
        clock[0] = 16.0                # b (last seen 0) ages out
        hb.executor_heartbeat("a")
        assert [p.executor_id for p in hb.known_peers()] == ["a"]
        peers_seen = hb.register_executor("b", "addr-b2")  # b comes back
        assert [p.executor_id for p in peers_seen] == ["a"]
        back = {p.executor_id: p for p in hb.known_peers()}["b"]
        assert back.endpoint == "addr-b2"
        # the new registration is ordered after the survivor
        assert back.registration_order > \
            {p.executor_id: p for p in hb.known_peers()}["a"].registration_order

    def _two_managers(self, rng, hb=None):
        """Manager A (reader, empty store) + manager B (holds map output),
        connected over one LocalTransport."""
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.shuffle import LocalTransport
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        conf = TpuConf({"spark.rapids.shuffle.fetch.retryWaitMs": 1,
                        "spark.rapids.shuffle.fetch.maxRetries": 2})
        transport = LocalTransport()
        a = TpuShuffleManager(conf, executor_id="exec-a",
                              transport=transport, heartbeat=hb)
        b = TpuShuffleManager(conf, executor_id="exec-b",
                              transport=transport)
        writer = b.get_writer(shuffle_id=9, map_id=0)
        self._expected = _table(rng, 300)
        writer.write(0, batch_from_arrow(self._expected))
        writer.close()
        return a, b

    def test_fetch_path_skips_aged_out_peer(self, rng):
        """An aged-out peer gets NO fetch attempt (no retries, no backoff,
        no timeout wait) — but because it may hold rows nobody else can
        enumerate, the read fails fast with the typed error instead of
        silently returning without its blocks."""
        hb, clock = self._hb()
        a, b = self._two_managers(rng, hb)
        try:
            a.register_with_heartbeat(hb)
            hb.register_executor("exec-b", "exec-b")
            clock[0] = 8.0
            hb.executor_heartbeat("exec-a")  # a beats; b goes silent
            clock[0] = 16.0                  # b (last seen 0) ages out
            hb.executor_heartbeat("exec-a")
            t0 = time.monotonic()
            with pytest.raises(ShuffleFetchFailedError) as ei:
                list(a.read_partition(9, 0, remote_peers=["exec-b"]))
            assert time.monotonic() - t0 < 1.0  # no fetch, no backoff
            assert ei.value.peer == "exec-b" and ei.value.attempts == 0
            assert "aged out" in str(ei.value)
            # b re-registers -> the same fetch now works
            hb.register_executor("exec-b", "exec-b")
            out = list(a.read_partition(9, 0, remote_peers=["exec-b"]))
            assert sum(int(o.row_count()) for o in out) == 300
        finally:
            a.shutdown()
            b.shutdown()


# ---------------------------------------------------------------------------
# Shuffle fetch retry / refetch / failover (tentpole)
# ---------------------------------------------------------------------------


class TestFetchRecovery:
    def _peer_pair(self, rng, **conf_extra):
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.shuffle import LocalTransport
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        conf = TpuConf({"spark.rapids.shuffle.fetch.retryWaitMs": 1,
                        **conf_extra})
        transport = LocalTransport()
        a = TpuShuffleManager(conf, executor_id="exec-a",
                              transport=transport)
        b = TpuShuffleManager(conf, executor_id="exec-b",
                              transport=transport)
        writer = b.get_writer(shuffle_id=11, map_id=0)
        self._expected = _table(rng, 400)
        writer.write(0, batch_from_arrow(self._expected))
        writer.close()
        return a, b

    def _collect(self, mgr, sid=11, rid=0, peers=("exec-b",)):
        from spark_rapids_tpu.columnar import batch_to_arrow
        out = list(mgr.read_partition(sid, rid, remote_peers=list(peers)))
        assert len(out) == 1
        return batch_to_arrow(out[0])

    def test_transient_fetch_error_retried(self, rng):
        a, b = self._peer_pair(rng)
        try:
            with inject(faults.FETCH, "error", nth=1, times=1,
                        error=ConnectionResetError) as rule:
                got = self._collect(a)
            assert rule.fired == 1
            assert got.equals(self._expected)
            assert TaskMetrics.get().shuffle_retry_count >= 1
        finally:
            a.shutdown()
            b.shutdown()

    def test_corrupt_frame_refetched_once(self, rng):
        a, b = self._peer_pair(rng)
        try:
            with inject(faults.FETCH, "corrupt", nth=1, times=1) as rule:
                got = self._collect(a)
            assert rule.fired == 1
            assert got.equals(self._expected)
            assert TaskMetrics.get().shuffle_refetch_count == 1
        finally:
            a.shutdown()
            b.shutdown()

    def test_persistent_corruption_is_typed_error(self, rng):
        a, b = self._peer_pair(rng)
        try:
            with inject(faults.FETCH, "corrupt", nth=0, times=0):
                with pytest.raises(ShuffleCorruptionError) as ei:
                    self._collect(a)
            assert "exec-b" in str(ei.value)
        finally:
            a.shutdown()
            b.shutdown()

    def test_dead_peer_exhausts_budget_with_typed_error(self, rng):
        a, b = self._peer_pair(
            rng, **{"spark.rapids.shuffle.fetch.maxRetries": 2})
        try:
            t0 = time.monotonic()
            with inject(faults.FETCH, "error", nth=0, times=0,
                        error=ConnectionResetError):
                with pytest.raises(ShuffleFetchFailedError) as ei:
                    self._collect(a)
            assert time.monotonic() - t0 < 10.0  # bounded, never hangs
            err = ei.value
            assert err.peer == "exec-b" and err.attempts == 3
            assert err.blocks  # listing succeeded, so blocks are known
            assert TaskMetrics.get().shuffle_retry_count == 2
        finally:
            a.shutdown()
            b.shutdown()

    def test_failover_to_replica_peer(self, rng):
        """Peer that lists blocks but fails every byte transfer; a replica
        holds the same blocks — the fetch fails over and recovers all rows
        exactly once."""
        from spark_rapids_tpu.shuffle import LocalTransport, ShuffleServer
        from spark_rapids_tpu.shuffle.manager import TpuShuffleManager
        from spark_rapids_tpu.columnar import batch_from_arrow
        conf = TpuConf({"spark.rapids.shuffle.fetch.retryWaitMs": 1,
                        "spark.rapids.shuffle.fetch.maxRetries": 1})
        transport = LocalTransport()
        a = TpuShuffleManager(conf, executor_id="exec-a",
                              transport=transport)
        c = TpuShuffleManager(conf, executor_id="exec-c",
                              transport=transport)
        writer = c.get_writer(shuffle_id=13, map_id=0)
        expected = _table(rng, 250)
        writer.write(0, batch_from_arrow(expected))
        writer.close()

        # exec-b: advertises the same blocks but every read explodes (a
        # half-dead executor; its listing still answers)
        def dead_resolver(bid):
            raise IOError("disk gone")

        transport.register(ShuffleServer(
            "exec-b", dead_resolver,
            c.block_store.blocks_for_reduce))
        try:
            got = self._collect(a, sid=13, peers=("exec-b", "exec-c"))
            assert got.equals(expected)
            assert TaskMetrics.get().shuffle_failover_count == 1
        finally:
            a.shutdown()
            c.shutdown()

    def test_local_corruption_refetches_from_store(self, rng, session):
        """End-to-end repartition query with a corrupted local block read:
        the CRC catches it, the store read retries, rows stay correct."""
        df = session.from_arrow(_table(rng, 500)).repartition(4, "id")
        with inject(faults.BLOCK_READ, "corrupt", nth=1, times=1) as rule:
            _assert_same(df, sort_by=["id", "val", "small"])
        assert rule.fired == 1


# ---------------------------------------------------------------------------
# TCP transport faults (reset / delay) against a real socket server
# ---------------------------------------------------------------------------


class TestTcpFaults:
    def _tcp_rig(self, rng, deadline_s=0.5):
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.shuffle.manager import (ShuffleBlockStore,
                                                      TpuShuffleManager)
        from spark_rapids_tpu.shuffle.serializer import serialize_batch
        from spark_rapids_tpu.shuffle.tcp_transport import (TcpShuffleServer,
                                                            TcpTransport)
        from spark_rapids_tpu.shuffle.transport import BlockId, ShuffleServer
        store = ShuffleBlockStore()
        self._expected = _table(rng, 200)
        store.put(BlockId(21, 0, 0),
                  serialize_batch(batch_from_arrow(self._expected), "zstd"))
        srv = TcpShuffleServer(ShuffleServer("exec-remote", store.get,
                                             store.blocks_for_reduce)).start()
        transport = TcpTransport(deadline_s=deadline_s)
        transport.register_peer("exec-remote", srv.address)
        conf = TpuConf({"spark.rapids.shuffle.fetch.retryWaitMs": 1,
                        "spark.rapids.shuffle.fetch.maxRetries": 2})
        mgr = TpuShuffleManager(conf, executor_id="exec-local",
                                transport=transport)
        return mgr, srv, store

    def test_connection_reset_retried_on_fresh_socket(self, rng):
        from spark_rapids_tpu.columnar import batch_to_arrow
        mgr, srv, store = self._tcp_rig(rng, deadline_s=5.0)
        try:
            with inject(faults.TCP_RECV, "error", nth=1, times=1,
                        error=ConnectionResetError) as rule:
                out = list(mgr.read_partition(21, 0,
                                              remote_peers=["exec-remote"]))
            assert rule.fired == 1
            assert batch_to_arrow(out[0]).equals(self._expected)
            assert TaskMetrics.get().shuffle_retry_count >= 1
        finally:
            mgr.shutdown()
            srv.close()
            store.close()

    def test_wedged_peer_hits_deadline_not_hang(self, rng):
        """Server-side reads wedge (slow disk); the client deadline converts
        every attempt into an error and the typed failure surfaces inside a
        bounded wall-clock window."""
        mgr, srv, store = self._tcp_rig(rng, deadline_s=0.4)
        try:
            t0 = time.monotonic()
            with inject(faults.BLOCK_READ, "delay", nth=0, times=0,
                        delay_s=1.0):
                with pytest.raises(ShuffleFetchFailedError):
                    list(mgr.read_partition(21, 0,
                                            remote_peers=["exec-remote"]))
            assert time.monotonic() - t0 < 15.0
        finally:
            mgr.shutdown()
            srv.close()
            store.close()


# ---------------------------------------------------------------------------
# Memory-pressure matrix: alloc OOM + spill I/O through real queries
# ---------------------------------------------------------------------------


class TestMemoryFaultMatrix:
    def test_sort_survives_retry_oom(self, rng, session):
        df = session.from_arrow(_table(rng)).sort("val")
        with inject(faults.ALLOC, "error", nth=1, times=1,
                    error=RetryOOM) as rule:
            _assert_same(df, sort_by=["val", "id", "small"])
        assert rule.fired == 1
        assert TaskMetrics.get().retry_count >= 1

    def test_window_survives_retry_oom(self, rng, session):
        from spark_rapids_tpu.expr.windowexprs import RowNumber
        df = session.from_arrow(_table(rng)).window(
            partition_by=["id"], order_by=["val"], rn=RowNumber())
        with inject(faults.ALLOC, "error", nth=1, times=1,
                    error=RetryOOM) as rule:
            _assert_same(df, sort_by=["id", "val", "rn"])
        assert rule.fired == 1

    def test_aggregate_survives_split_and_retry(self, rng, session):
        df = session.from_arrow(_table(rng)).group_by("id").agg(
            n=Count(col("val")), total=Sum(col("small")))
        with inject(faults.ALLOC, "error", nth=1, times=1,
                    error=SplitAndRetryOOM) as rule:
            _assert_same(df, sort_by=["id"])
        assert rule.fired == 1
        assert TaskMetrics.get().split_retry_count >= 1

    def test_exchange_survives_split_and_retry(self, rng, session):
        """Memory pressure during the shuffle write splits the input and
        writes each half under its own map id — rows land exactly once."""
        df = session.from_arrow(_table(rng, 500)).repartition(3, "id")
        with inject(faults.ALLOC, "error", nth=1, times=1,
                    error=SplitAndRetryOOM) as rule:
            _assert_same(df, sort_by=["id", "val", "small"])
        assert rule.fired == 1
        assert TaskMetrics.get().split_retry_count >= 1

    def test_spill_write_failure_degrades_not_dies(self):
        from spark_rapids_tpu.columnar import batch_from_arrow, batch_to_arrow
        from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
        cat = BufferCatalog(host_limit=1, spill_codec="none")
        t = pa.table({"a": pa.array(np.arange(64, dtype=np.int64))})
        h = cat.add_batch(batch_from_arrow(t))
        with inject(faults.SPILL_WRITE, "error", nth=1, times=1,
                    error=IOError) as rule:
            cat.synchronous_spill(1)  # disk overflow fails -> stays HOST
        assert rule.fired == 1
        assert cat.tier_of(h) == StorageTier.HOST
        assert batch_to_arrow(cat.acquire_batch(h)).equals(t)
        cat.remove(h)

    def test_spill_read_transient_error_retried(self):
        from spark_rapids_tpu.columnar import batch_from_arrow, batch_to_arrow
        from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
        cat = BufferCatalog(host_limit=1, spill_codec="none")
        t = pa.table({"a": pa.array(np.arange(64, dtype=np.int64))})
        h = cat.add_batch(batch_from_arrow(t))
        cat.synchronous_spill(1)
        assert cat.tier_of(h) == StorageTier.DISK
        with inject(faults.SPILL_READ, "error", nth=1, times=1,
                    error=IOError) as rule:
            back = cat.acquire_batch(h)  # first read fails, retry lands
        assert rule.fired == 1
        assert batch_to_arrow(back).equals(t)
        cat.remove(h)

    def test_spill_read_persistent_error_is_typed(self):
        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.memory.catalog import BufferCatalog
        cat = BufferCatalog(host_limit=1, spill_codec="none")
        t = pa.table({"a": pa.array(np.arange(64, dtype=np.int64))})
        h = cat.add_batch(batch_from_arrow(t))
        cat.synchronous_spill(1)
        with inject(faults.SPILL_READ, "error", nth=0, times=0,
                    error=IOError):
            with pytest.raises(OSError):
                cat.acquire_batch(h)
        cat.remove(h)


# ---------------------------------------------------------------------------
# Device-decode buffer lifetime: spill churn must never corrupt a scan
# ---------------------------------------------------------------------------


class TestDecodeLifetime:
    def test_parquet_decode_survives_spill_churn(self, rng, tmp_path):
        """Regression: the device parquet decode shipped zero-copy views of
        _ChunkHold-owned native memory to asynchronously-dispatched jax
        programs; the hold was freed when the decode returned, so catalog
        spill churn recycling that memory corrupted decoded columns (wrong
        values, all-null validity) nondeterministically. _chunk_from_native
        now copies the walk's views into owning arrays, making the decode
        bit-stable under allocation pressure."""
        import pyarrow.parquet as pq
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar import batch_from_arrow, batch_to_arrow
        from spark_rapids_tpu.columnar.batch import Schema
        from spark_rapids_tpu.io import parquet_device as PD
        from spark_rapids_tpu.memory.catalog import BufferCatalog

        n = 2000
        t = pa.table({
            "k": pa.array(rng.integers(0, 20, n).astype(np.int64)),
            "v": pa.array(rng.normal(0.0, 10.0, n)),
        })
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path)
        schema = Schema(("k", "v"), (T.LONG, T.DOUBLE))
        truth_k = t.column("k").to_numpy()

        def churn():
            # spill/unspill cycles recycle freshly-freed allocations, which
            # is what exposed reads of dead decode buffers
            cat = BufferCatalog(host_limit=1, spill_codec="none")
            tt = pa.table({"a": pa.array(rng.integers(0, 9, 512))})
            hh = cat.add_batch(batch_from_arrow(tt))
            cat.synchronous_spill(1)
            batch_to_arrow(cat.acquire_batch(hh))
            cat.remove(hh)

        for _ in range(3):
            pf = pq.ParquetFile(path)
            with open(path, "rb") as f:
                batch, nrows = PD.decode_row_group(pf, f, 0, schema)
            assert nrows == n
            kcol = batch.columns[0]
            assert (np.asarray(kcol.data)[:n] == truth_k).all()
            assert int(np.asarray(kcol.validity).sum()) == n
            churn()


# ---------------------------------------------------------------------------
# Wedged backend init -> DeviceStartupError within the deadline
# ---------------------------------------------------------------------------


class TestDeviceInitFaults:
    def _fresh(self):
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        DeviceManager.shutdown()
        return DeviceManager

    def test_wedged_backend_fails_fast(self):
        DeviceManager = self._fresh()
        conf = TpuConf({"spark.rapids.tpu.device.startupTimeoutSec": 0.4})
        t0 = time.monotonic()
        try:
            with inject(faults.DEVICE_INIT, "wedge", delay_s=3.0):
                with pytest.raises(DeviceStartupError) as ei:
                    DeviceManager.initialize(conf)
            assert time.monotonic() - t0 < 3.0
            assert "did not respond" in str(ei.value)
            # the failure is remembered: later queries fail fast, no re-arm
            with pytest.raises(DeviceStartupError):
                DeviceManager.initialize(conf)
        finally:
            DeviceManager.shutdown()  # clear for the rest of the suite

    def test_failing_backend_is_typed_with_diagnostics(self):
        DeviceManager = self._fresh()
        conf = TpuConf({"spark.rapids.tpu.device.startupTimeoutSec": 5.0})
        try:
            with inject(faults.DEVICE_INIT, "error",
                        error=RuntimeError("tunnel down")):
                with pytest.raises(DeviceStartupError) as ei:
                    DeviceManager.initialize(conf)
            assert "tunnel down" in str(ei.value.diagnostics.get("cause", ""))
        finally:
            DeviceManager.shutdown()


# ---------------------------------------------------------------------------
# Service admission: typed timeout + injected admission faults
# ---------------------------------------------------------------------------


class TestAdmissionFaults:
    @pytest.fixture
    def service(self, tmp_path):
        from spark_rapids_tpu.service.server import TpuDeviceService
        sock = str(tmp_path / "svc.sock")
        svc = TpuDeviceService(
            {"spark.rapids.sql.concurrentGpuTasks": 1}, sock)
        th = threading.Thread(target=svc.serve_forever, daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        import os
        while not os.path.exists(sock) and time.monotonic() < deadline:
            time.sleep(0.02)
        yield sock
        svc._stop.set()
        th.join(timeout=5)

    def test_admission_timeout_is_typed_with_diagnostics(self, service):
        from spark_rapids_tpu.service.client import TpuServiceClient
        with TpuServiceClient(service, deadline_s=10.0) as holder:
            holder.acquire()  # takes the single token
            with TpuServiceClient(service, deadline_s=10.0) as waiter:
                with pytest.raises(AdmissionTimeoutError) as ei:
                    waiter.acquire(timeout=0.1)
                err = ei.value
                assert err.held == 1 and err.waiting >= 0
                assert isinstance(err, TimeoutError)  # legacy contract
            holder.release()

    def test_injected_admission_fault_surfaces_typed(self, service):
        from spark_rapids_tpu.service.client import TpuServiceClient
        with inject(faults.ADMISSION, "error", nth=1, times=1):
            with TpuServiceClient(service, deadline_s=10.0) as cli:
                with pytest.raises(AdmissionTimeoutError):
                    cli.acquire(timeout=5.0)
                cli.acquire(timeout=5.0)  # injection budget spent: admitted
                cli.release()

    def test_wedged_admission_hits_client_deadline(self, service):
        from spark_rapids_tpu.service.client import TpuServiceClient
        t0 = time.monotonic()
        with inject(faults.ADMISSION, "wedge", delay_s=3.0):
            with TpuServiceClient(service, deadline_s=0.5) as cli:
                with pytest.raises(DeviceStartupError):
                    cli.acquire(timeout=10.0)
        assert time.monotonic() - t0 < 3.0


# ---------------------------------------------------------------------------
# persist point: durable-dir faults degrade tiers to memory-only (PR 14)
# ---------------------------------------------------------------------------


class TestPersistFaults:
    def test_stats_history_append_fault_degrades_not_raises(self, tmp_path):
        import os
        import warnings
        from spark_rapids_tpu.errors import PersistenceDegradedWarning
        from spark_rapids_tpu.stats.history import OpStats, StatsHistory
        from spark_rapids_tpu.utils import durable
        durable.reset_for_tests()
        try:
            h = StatsHistory(max_entries=16, persist_dir=str(tmp_path))
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                with inject(faults.PERSIST, "error", nth=1, times=1,
                            error=IOError) as rule:
                    h.record(OpStats(digest="d1", op="Scan", rows=10.0),
                             persistable=True)
            assert rule.fired == 1
            assert any(isinstance(w.message, PersistenceDegradedWarning)
                       for w in caught)
            # memory tier unharmed; later appends no-op instead of raising
            assert h.lookup("d1").rows == 10.0
            h.record(OpStats(digest="d2", op="Scan", rows=5.0),
                     persistable=True)
            assert h.lookup("d2").rows == 5.0
            assert not os.listdir(str(tmp_path))
        finally:
            durable.reset_for_tests()

    def test_event_log_append_fault_degrades_silently(self, tmp_path):
        import os
        import warnings
        from spark_rapids_tpu.errors import PersistenceDegradedWarning
        from spark_rapids_tpu.utils import durable, spans
        durable.reset_for_tests()
        try:
            rec = spans.client_op_record("run_plan", "t" * 32, 1000)
            log_dir = str(tmp_path / "events")
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                with inject(faults.PERSIST, "error", nth=1, times=1,
                            error=IOError) as rule:
                    spans.write_client_record(log_dir, rec)  # degrades
                spans.write_client_record(log_dir, rec)      # no-ops
            assert rule.fired == 1
            assert any(isinstance(w.message, PersistenceDegradedWarning)
                       for w in caught)
            assert not os.path.isdir(log_dir) or not os.listdir(log_dir)
        finally:
            durable.reset_for_tests()

    def test_persist_point_registered(self):
        assert faults.PERSIST in faults.ALL_POINTS
