"""Extended aggregates: variance family, collect_list/collect_set,
approx_percentile — differential CPU-vs-TPU (reference:
AggregateFunctions.scala CentralMomentAgg/Collect*, GpuApproximatePercentile)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (ApproximatePercentile, CollectList,
                                   CollectSet, Count, StddevPop, StddevSamp,
                                   Sum, VariancePop, VarianceSamp, col)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def table(rng, n=500):
    nulls = rng.random(n) < 0.15
    return pa.table({
        "k": pa.array(rng.integers(0, 12, n), type=pa.int64()),
        "v": pa.array(np.where(nulls, 0, rng.integers(-50, 50, n)),
                      type=pa.int64(), mask=nulls),
        "x": pa.array(rng.normal(0, 10, n).round(4), type=pa.float64()),
        "s": pa.array([["aa", "bb", "c", None][j]
                       for j in rng.integers(0, 4, n)]),
    })


class TestVarianceFamily:
    @pytest.mark.parametrize("fn", [VariancePop, VarianceSamp, StddevPop,
                                    StddevSamp])
    def test_variance_matches_oracle(self, session, rng, fn):
        df = session.from_arrow(table(rng))
        q = df.group_by("k").agg(r=fn(col("x")), c=Count(col("x")))
        assert_same(q, sort_by=["k"], approx_cols=("r",))

    def test_samp_single_row_group_is_null(self, session):
        t = pa.table({"k": pa.array([1, 2, 2], type=pa.int64()),
                      "x": pa.array([5.0, 1.0, 3.0], type=pa.float64())})
        df = session.from_arrow(t)
        q = df.group_by("k").agg(r=VarianceSamp(col("x")))
        out = q.collect().sort_by("k")
        assert out.column("r").to_pylist()[0] is None
        assert abs(out.column("r").to_pylist()[1] - 2.0) < 1e-9


class TestCollect:
    def test_collect_list_ints(self, session, rng):
        df = session.from_arrow(table(rng, n=300))
        q = df.group_by("k").agg(l=CollectList(col("v")), c=Count(col("v")))
        tpu = q.collect().sort_by("k")
        cpu = q.collect_cpu().sort_by("k")
        assert tpu.column("l").to_pylist() == cpu.column("l").to_pylist()
        assert tpu.column("c").to_pylist() == cpu.column("c").to_pylist()

    def test_collect_list_strings(self, session, rng):
        df = session.from_arrow(table(rng, n=200))
        q = df.group_by("k").agg(l=CollectList(col("s")))
        tpu = q.collect().sort_by("k")
        cpu = q.collect_cpu().sort_by("k")
        assert tpu.column("l").to_pylist() == cpu.column("l").to_pylist()

    def test_collect_set_dedupes(self, session, rng):
        df = session.from_arrow(table(rng, n=400))
        q = df.group_by("k").agg(s=CollectSet(col("v")))
        tpu = q.collect().sort_by("k")
        cpu = q.collect_cpu().sort_by("k")
        assert tpu.column("s").to_pylist() == cpu.column("s").to_pylist()
        for vals in tpu.column("s").to_pylist():
            assert len(vals) == len(set(vals))  # genuinely distinct

    def test_collect_global_no_keys(self, session, rng):
        df = session.from_arrow(table(rng, n=80))
        q = df.agg(l=CollectList(col("v")))
        tpu = q.collect()
        cpu = q.collect_cpu()
        assert tpu.column("l").to_pylist() == cpu.column("l").to_pylist()


class TestCollectOnDevice:
    def test_collect_runs_on_device_not_fallback(self, rng):
        # the device single-pass path must actually be reachable (the agg
        # exec rule must accept the array-typed output column)
        from spark_rapids_tpu.plan.overrides import Overrides
        from spark_rapids_tpu.exec.base import TpuExec
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE"})
        df = sess.from_arrow(table(rng, n=100))
        q = df.group_by("k").agg(l=CollectList(col("v")))
        sess.initialize_device()
        ov = Overrides(sess.conf)
        result = ov.apply(q.plan)
        assert isinstance(result, TpuExec), ov.explain_string()
        from spark_rapids_tpu.exec.transitions import TpuFromCpuExec

        def has_cpu(node):
            return isinstance(node, TpuFromCpuExec) or \
                any(has_cpu(c) for c in node.children)
        assert not has_cpu(result), ov.explain_string()

    def test_collect_negative_values_intact(self, session):
        t = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                      "v": pa.array([-5, -7, -3], type=pa.int64())})
        q = session.from_arrow(t).group_by("k").agg(l=CollectList(col("v")))
        out = q.collect().sort_by("k")
        assert out.column("l").to_pylist() == [[-7, -5], [-3]]


class TestApproxPercentile:
    def test_scalar_percentile(self, session, rng):
        df = session.from_arrow(table(rng))
        q = df.group_by("k").agg(m=ApproximatePercentile(col("x"), 0.5),
                                 c=Count(col("x")))
        assert_same(q, sort_by=["k"], approx_cols=("m",))

    def test_percentile_array(self, session, rng):
        df = session.from_arrow(table(rng, n=300))
        q = df.group_by("k").agg(
            p=ApproximatePercentile(col("x"), [0.0, 0.5, 1.0]))
        tpu = q.collect().sort_by("k")
        cpu = q.collect_cpu().sort_by("k")
        for a, b in zip(tpu.column("p").to_pylist(),
                        cpu.column("p").to_pylist()):
            assert a is not None and b is not None
            assert np.allclose(a, b, rtol=1e-9)

    def test_percentile_ints(self, session, rng):
        df = session.from_arrow(table(rng))
        q = df.group_by("k").agg(m=ApproximatePercentile(col("v"), 0.25))
        assert_same(q, sort_by=["k"], approx_cols=("m",))
