"""Peer-process entry for the TCP shuffle transport test: a REAL second
OS process that serializes columnar batches into a ShuffleBlockStore with
a tiny host budget (disk tier engaged), serves them over
TcpShuffleServer, prints its port + per-block row sums as one JSON line,
then serves until killed — the role a remote executor plays for
`RapidsShuffleServer.scala`."""

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402


def main() -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--shuffle-id", type=int, default=7)
    ap.add_argument("--maps", type=int, default=4)
    ap.add_argument("--reduces", type=int, default=2)
    ap.add_argument("--rows", type=int, default=3000)
    ap.add_argument("--host-budget", type=int, default=16 * 1024,
                    help="tiny: most blocks overflow to the disk tier")
    args = ap.parse_args()

    from spark_rapids_tpu.columnar.batch import batch_from_arrow
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.serializer import serialize_batch
    from spark_rapids_tpu.shuffle.tcp_transport import TcpShuffleServer
    from spark_rapids_tpu.shuffle.transport import BlockId, ShuffleServer

    rng = np.random.default_rng(99)
    store = ShuffleBlockStore(host_budget=args.host_budget)
    sums = {}
    for m in range(args.maps):
        for r in range(args.reduces):
            n = args.rows + 137 * m + r  # uneven block sizes
            vals = rng.integers(-10**6, 10**6, n).astype(np.int64)
            tags = np.array([f"m{m}r{r}x{i % 50}" for i in range(n)],
                            dtype=object)
            t = pa.table({"v": pa.array(vals), "s": pa.array(tags)})
            blob = serialize_batch(batch_from_arrow(t), "zstd")
            bid = BlockId(args.shuffle_id, m, r)
            store.put(bid, blob)
            sums[f"{m}:{r}"] = {"rows": n, "vsum": int(vals.sum()),
                                "ssha": hashlib.sha256(
                                    "".join(tags).encode()).hexdigest()}

    srv = ShuffleServer("peer-1", store.get, store.blocks_for_reduce)
    tcp = TcpShuffleServer(srv).start()
    print(json.dumps({"port": tcp.address[1],
                      "disk_blocks": store.disk_block_count(),
                      "sums": sums}), flush=True)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        tcp.close()
        store.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
