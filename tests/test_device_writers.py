"""Device-encoded ORC and CSV writers (orc_device_write.py /
csv_device_write.py): column streams render with device kernels, the
host writes scaffolding bytes only — closing "ORC/CSV writers are host
one-liners" (r3 verdict Weak #8; reference `GpuOrcFileFormat.scala`,
ColumnarOutputWriter). Oracle: pyarrow reads the files back."""

import datetime as dt
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import Schema, batch_from_arrow
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def mixed_table(n=1500, seed=4):
    rng = np.random.default_rng(seed)
    nulls = rng.random(n) < 0.12
    return pa.table({
        "i64": pa.array(rng.integers(-10**14, 10**14, n),
                        type=pa.int64()),
        "i32": pa.array(np.where(nulls, 0, rng.integers(-1000, 1000, n))
                        .astype(np.int32), mask=nulls),
        "s": pa.array([None if nulls[i] else f"v{i % 97}-{'y' * (i % 13)}"
                       for i in range(n)]),
        "d": pa.array(rng.normal(size=n)),
        "f": pa.array(rng.normal(size=n).astype(np.float32),
                      type=pa.float32()),
        "b": pa.array(rng.random(n) < 0.5),
        "dt": pa.array([dt.date(2020, 1, 1) + dt.timedelta(days=int(x))
                        for x in rng.integers(0, 3000, n)],
                       type=pa.date32()),
    })


class TestOrcDeviceWrite:
    def test_roundtrip_via_pyarrow(self):
        from spark_rapids_tpu.io.orc_device_write import device_encode_orc
        t = mixed_table()
        blob = device_encode_orc([batch_from_arrow(t)],
                                 Schema.from_arrow(t.schema))
        import io as _io
        from pyarrow import orc
        back = orc.read_table(_io.BytesIO(blob))
        assert back.num_rows == t.num_rows
        for c in t.schema.names:
            assert back.column(c).to_pylist() == \
                t.column(c).to_pylist(), c

    def test_multi_batch_multi_stripe(self):
        from spark_rapids_tpu.io.orc_device_write import device_encode_orc
        t1, t2 = mixed_table(400, seed=1), mixed_table(700, seed=2)
        schema = Schema.from_arrow(t1.schema)
        blob = device_encode_orc(
            [batch_from_arrow(t1), batch_from_arrow(t2)], schema)
        import io as _io
        from pyarrow import orc
        f = orc.ORCFile(_io.BytesIO(blob))
        assert f.nstripes == 2
        back = f.read()
        exp = pa.concat_tables([t1, t2])
        for c in exp.schema.names:
            assert back.column(c).to_pylist() == \
                exp.column(c).to_pylist(), c

    def test_all_null_and_empty_strings(self):
        from spark_rapids_tpu.io.orc_device_write import device_encode_orc
        t = pa.table({
            "s": pa.array(["", None, "x", None, ""]),
            "i": pa.array([None] * 5, type=pa.int64()),
        })
        blob = device_encode_orc([batch_from_arrow(t)],
                                 Schema.from_arrow(t.schema))
        import io as _io
        from pyarrow import orc
        back = orc.read_table(_io.BytesIO(blob))
        assert back.column("s").to_pylist() == ["", None, "x", None, ""]
        assert back.column("i").to_pylist() == [None] * 5

    def test_write_orc_api_takes_device_path(self, session, tmp_path):
        t = mixed_table(300, seed=7)
        df = session.from_arrow(t)
        stats = df.write_orc(str(tmp_path / "out"))
        assert stats.num_files == 1
        from pyarrow import orc
        files = os.listdir(str(tmp_path / "out"))
        assert len(files) == 1 and files[0].endswith(".orc")
        back = orc.read_table(str(tmp_path / "out" / files[0]))
        assert back.sort_by([("i64", "ascending")]).equals(
            back.sort_by([("i64", "ascending")]))
        assert back.num_rows == t.num_rows
        assert sorted(back.column("i64").to_pylist()) == \
            sorted(t.column("i64").to_pylist())

    def test_rlev2_wide_and_narrow_values(self):
        # exercise width selection across runs: tiny, 2^40-scale, and
        # negative extremes in one column (zigzag + per-512-run widths)
        from spark_rapids_tpu.io.orc_device_write import device_encode_orc
        vals = ([0, 1, -1] * 200) + [2**40, -(2**40)] * 300 + \
            [-(2**62), 2**62 - 1]
        t = pa.table({"v": pa.array(vals, type=pa.int64())})
        blob = device_encode_orc([batch_from_arrow(t)],
                                 Schema.from_arrow(t.schema))
        import io as _io
        from pyarrow import orc
        assert orc.read_table(_io.BytesIO(blob)) \
            .column("v").to_pylist() == vals


class TestCsvDeviceWrite:
    def test_blob_matches_host_semantics(self):
        from spark_rapids_tpu.io.csv_device_write import device_encode_csv
        t = pa.table({
            "i": pa.array([1, None, -5], type=pa.int64()),
            "s": pa.array(["a", "", None]),
            "b": pa.array([True, False, None]),
            "dt": pa.array([dt.date(2020, 2, 29), None,
                            dt.date(1999, 12, 31)], type=pa.date32()),
        })
        blob = device_encode_csv([batch_from_arrow(t)],
                                 Schema.from_arrow(t.schema))
        assert blob.decode() == ("i,s,b,dt\n"
                                 "1,a,true,2020-02-29\n"
                                 ",,false,\n"
                                 "-5,,,1999-12-31\n")

    def test_quoting_needed_falls_back(self):
        from spark_rapids_tpu.io.csv_device_write import device_encode_csv
        from spark_rapids_tpu.io.parquet_device import \
            DeviceDecodeUnsupported
        t = pa.table({"s": pa.array(["a,b"])})
        with pytest.raises(DeviceDecodeUnsupported):
            device_encode_csv([batch_from_arrow(t)],
                              Schema.from_arrow(t.schema))

    def test_write_csv_api_roundtrip(self, session, tmp_path):
        t = pa.table({
            "i": pa.array(range(500), type=pa.int64()),
            "s": pa.array([f"r{i}" for i in range(500)]),
            "b": pa.array([i % 2 == 0 for i in range(500)]),
        })
        df = session.from_arrow(t)
        stats = df.write_csv(str(tmp_path / "out"))
        assert stats.num_files == 1
        import pyarrow.csv as pacsv
        files = os.listdir(str(tmp_path / "out"))
        back = pacsv.read_csv(str(tmp_path / "out" / files[0]))
        assert back.sort_by([("i", "ascending")]) \
            .column("s").to_pylist() == t.column("s").to_pylist()
        assert back.column("b").to_pylist() == t.column("b").to_pylist()

    def test_float_schema_uses_host_writer(self, session, tmp_path):
        # float text needs the host's Java-compatible formatter: still a
        # correct write, just not the device path
        t = pa.table({"i": pa.array([1, 2, 3], type=pa.int64()),
                      "d": pa.array([1.5, None, -2.25])})
        df = session.from_arrow(t)
        df.write_csv(str(tmp_path / "out"))
        import pyarrow.csv as pacsv
        files = os.listdir(str(tmp_path / "out"))
        back = pacsv.read_csv(str(tmp_path / "out" / files[0]))
        assert back.column("d").to_pylist() == [1.5, None, -2.25]


class TestLongStringOverflowFallback:
    """Chunked long-string columns (head matrix + tail blob) must NOT take
    the device text writers — the byte-matrix render only sees head bytes
    and would silently write repeated-head-byte garbage tails (advisor
    r4 high findings). The host writers reassemble full values."""

    def _long_table(self):
        long = "x" * 9000 + "TAIL"
        return pa.table({"i": pa.array([1, 2], type=pa.int64()),
                         "s": pa.array(["short", long])}), long

    def test_orc_encoder_rejects_overflow(self):
        from spark_rapids_tpu.io.orc_device_write import device_encode_orc
        from spark_rapids_tpu.io.parquet_device import \
            DeviceDecodeUnsupported
        t, _ = self._long_table()
        b = batch_from_arrow(t)
        assert b.columns[1].overflow is not None  # layout sanity
        with pytest.raises(DeviceDecodeUnsupported):
            device_encode_orc([b], Schema.from_arrow(t.schema))

    def test_csv_encoder_rejects_overflow(self):
        from spark_rapids_tpu.io.csv_device_write import device_encode_csv
        from spark_rapids_tpu.io.parquet_device import \
            DeviceDecodeUnsupported
        t, _ = self._long_table()
        with pytest.raises(DeviceDecodeUnsupported):
            device_encode_csv([batch_from_arrow(t)],
                              Schema.from_arrow(t.schema))

    def test_write_orc_long_string_roundtrips(self, session, tmp_path):
        t, long = self._long_table()
        session.from_arrow(t).write_orc(str(tmp_path / "o"))
        from pyarrow import orc
        files = os.listdir(str(tmp_path / "o"))
        back = orc.read_table(str(tmp_path / "o" / files[0]))
        assert sorted(back.column("s").to_pylist()) == \
            sorted(["short", long])

    def test_write_csv_long_string_roundtrips(self, session, tmp_path):
        t, long = self._long_table()
        session.from_arrow(t).write_csv(str(tmp_path / "o"))
        import pyarrow.csv as pacsv
        files = os.listdir(str(tmp_path / "o"))
        back = pacsv.read_csv(str(tmp_path / "o" / files[0]))
        assert sorted(back.column("s").to_pylist()) == \
            sorted(["short", long])


class TestWriteFilesExecDevicePath:
    def test_write_command_exec_csv_device(self, session, tmp_path):
        # the plan-level write exec (CpuWriteFilesExec -> TpuWriteFilesExec)
        # also rides the device encoders
        from spark_rapids_tpu.frontend import DataFrame
        from spark_rapids_tpu.io.writer import CpuWriteFilesExec
        t = pa.table({"i": pa.array(range(50), type=pa.int64()),
                      "s": pa.array([f"x{i}" for i in range(50)])})
        df = session.from_arrow(t)
        node = CpuWriteFilesExec(str(tmp_path / "o"), "csv", None, "error",
                                 df.plan)
        out = DataFrame(session, node).collect()
        assert out.column("rows").to_pylist() == [50]
        import pyarrow.csv as pacsv
        files = os.listdir(str(tmp_path / "o"))
        back = pacsv.read_csv(str(tmp_path / "o" / files[0]))
        assert back.num_rows == 50

    def test_write_command_exec_orc_device(self, session, tmp_path):
        from spark_rapids_tpu.frontend import DataFrame
        from spark_rapids_tpu.io.writer import CpuWriteFilesExec
        t = mixed_table(120, seed=11)
        df = session.from_arrow(t)
        node = CpuWriteFilesExec(str(tmp_path / "o"), "orc", None, "error",
                                 df.plan)
        out = DataFrame(session, node).collect()
        assert out.column("rows").to_pylist() == [120]
        from pyarrow import orc
        files = os.listdir(str(tmp_path / "o"))
        back = orc.read_table(str(tmp_path / "o" / files[0]))
        assert back.num_rows == 120
        assert sorted(back.column("i64").to_pylist()) == \
            sorted(t.column("i64").to_pylist())
