"""Fleet gateway (ISSUE-10): health-aware routing over a TPU worker pool.

Two tiers:

  * FAST (no subprocesses, no engine queries): router/breaker/registry
    units, typed ServiceConnectionError anatomy, gateway failover and
    write-plan retry-safety against FAKE workers (thread servers that
    speak the wire protocol and die on cue), shed-at-the-door, and the
    fleet-off import gate.
  * SLOW (marker `slow`, run by scripts/fleet_matrix.sh): REAL
    TpuDeviceService worker processes behind an in-process gateway —
    kill -9 mid-run_plan failover with bit-identical rows, breaker
    half-open recovery after worker restart, cache-affinity placement
    with a worker-local rescache hit, drain/undrain, cancel-by-query-id
    through the gateway, fleet-door backpressure, and cross-process
    trace stitching (client -> gateway -> worker)."""

import json
import os
import signal
import socket as socketmod
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.errors import (QueryCancelledError,
                                     QueryRejectedError,
                                     ServiceConnectionError)
from spark_rapids_tpu.fleet import router
from spark_rapids_tpu.fleet.gateway import FleetGateway
from spark_rapids_tpu.fleet.registry import (BREAKER_CLOSED, BREAKER_OPEN,
                                             CircuitBreaker,
                                             WorkerRegistry)
from spark_rapids_tpu.service import TpuServiceClient
from spark_rapids_tpu.service.protocol import (recv_msg, send_msg,
                                               table_to_ipc)

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# plan JSON builders (the service-protocol Spark executedPlan shape)
def _attr(name, dt):
    return [{"class": "org.apache.spark.sql.catalyst.expressions."
             "AttributeReference", "num-children": 0, "name": name,
             "dataType": dt, "nullable": True, "metadata": {},
             "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]


def filter_plan(threshold: float, marker: str = "") -> str:
    """FilterExec(v > threshold) over FileSourceScanExec('t'). Distinct
    thresholds give distinct plan fingerprints (affinity spreads them
    over the pool). `marker` plants a raw-JSON write marker without
    changing translation (unknown fields are ignored) — the write-plan
    retry-safety tests ride it."""
    filt = {"class": "org.apache.spark.sql.execution.FilterExec",
            "num-children": 1,
            "condition": [{"class": "org.apache.spark.sql.catalyst."
                           "expressions.GreaterThan", "num-children": 2}]
            + _attr("v", "double")
            + [{"class": "org.apache.spark.sql.catalyst.expressions."
                "Literal", "num-children": 0, "value": str(threshold),
                "dataType": "double"}]}
    if marker:
        filt["comment"] = marker
    scan = {"class": "org.apache.spark.sql.execution.FileSourceScanExec",
            "num-children": 0, "relation": "HadoopFsRelation(parquet)",
            "output": [_attr("k", "long"), _attr("v", "double")],
            "tableIdentifier": "t"}
    return json.dumps([filt, scan])


# ---------------------------------------------------------------------------
# FAST: router / breaker units
class TestRouterUnits:
    def test_rendezvous_stable_and_minimal_remap(self):
        names = [f"w{i}" for i in range(5)]
        digests = [f"d{i}" for i in range(200)]
        first = {d: router.rendezvous_order(d, names)[0] for d in digests}
        # stable under list reordering
        for d in digests[:20]:
            assert router.rendezvous_order(d, list(reversed(names)))[0] \
                == first[d]
        # removing one worker remaps ONLY the digests that preferred it
        gone = "w2"
        rest = [n for n in names if n != gone]
        for d in digests:
            now = router.rendezvous_order(d, rest)[0]
            if first[d] != gone:
                assert now == first[d], d
            else:
                assert now in rest
        # and the load is roughly spread (no degenerate hash)
        from collections import Counter
        counts = Counter(first.values())
        assert len(counts) == 5
        assert max(counts.values()) < 200 * 0.5

    def test_rendezvous_tail_is_failover_order(self):
        order = router.rendezvous_order("digest", ["a", "b", "c"])
        assert sorted(order) == ["a", "b", "c"]
        assert len(set(order)) == 3

    def test_power_of_two_prefers_less_loaded(self):
        class W:
            def __init__(self, name, outstanding):
                self.name, self.outstanding = name, outstanding
        import random
        rng = random.Random(7)
        ws = [W("a", 5), W("b", 0), W("c", 2)]
        picks = [router.pick_two_choices(ws, rng)[0].name
                 for _ in range(100)]
        # the loaded worker is picked first only when the sample misses
        # both lighter ones — never more often than either of them
        assert picks.count("a") < picks.count("b")
        assert all(router.pick_two_choices([ws[0]], rng)[0].name == "a"
                   for _ in range(3))

    def test_write_plan_detection(self):
        assert router.plan_is_write(filter_plan(0.5, marker="InsertInto"))
        assert not router.plan_is_write(filter_plan(0.5))
        assert router.plan_is_write(
            '{"class": "...DataWritingCommandExec", "num-children": 1}')

    def test_analyze_fail_closed_routes_by_load(self):
        from spark_rapids_tpu.config import TpuConf
        conf = TpuConf({"spark.rapids.sql.enabled": True})
        # untranslatable plan: no digest, no error
        digest, is_write = router.analyze(
            '[{"class": "org.apache.spark.NoSuchExec", "num-children": 0}]',
            {}, conf)
        assert digest is None and not is_write

    def test_analyze_digest_is_stable_and_param_sensitive(self, tmp_path):
        from spark_rapids_tpu.config import TpuConf
        conf = TpuConf({"spark.rapids.sql.enabled": True})
        t = pa.table({"k": pa.array(np.arange(10)),
                      "v": pa.array(np.linspace(0, 1, 10))})
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path)
        paths = {"t": [path]}
        d1, w1 = router.analyze(filter_plan(0.25), paths, conf)
        d2, _ = router.analyze(filter_plan(0.25), paths, conf)
        d3, _ = router.analyze(filter_plan(0.75), paths, conf)
        assert d1 is not None and d1 == d2
        assert d3 is not None and d3 != d1
        assert not w1


class TestCircuitBreaker:
    def test_trip_cooldown_halfopen_recover(self):
        b = CircuitBreaker(failure_threshold=2, cooldown_s=0.2)
        assert b.allows() and b.state == BREAKER_CLOSED
        b.failure()
        assert b.state == BREAKER_CLOSED and b.allows()
        b.failure()
        assert b.state == BREAKER_OPEN and not b.allows()
        time.sleep(0.25)
        assert b.allows()                  # cooldown elapsed -> half-open
        assert b.state == "half_open"
        b.success()
        assert b.state == BREAKER_CLOSED
        assert b.consecutive_failures == 0

    def test_halfopen_failure_reopens(self):
        b = CircuitBreaker(failure_threshold=3, cooldown_s=0.1)
        for _ in range(3):
            b.failure()
        assert b.state == BREAKER_OPEN
        time.sleep(0.15)
        assert b.allows()
        b.failure()                        # trial failed
        assert b.state == BREAKER_OPEN and not b.allows()


class TestRegistryBookkeeping:
    def _registry(self):
        return WorkerRegistry([("a", "/nope/a"), ("b", "/nope/b")],
                              probe_interval_s=999, breaker_failures=3)

    def test_dispatch_placement_drain(self):
        r = self._registry()
        r.note_dispatch("a", "q1")
        assert r.placement_of("q1").name == "a"
        assert r.outstanding_of("a") == 1
        r.drain("a")
        assert [w.name for w in r.routable()] == ["b"]
        # in-flight bookkeeping survives the drain
        r.note_done("a", "q1")
        assert r.placement_of("q1") is None
        assert r.outstanding_of("a") == 0
        r.undrain("a")
        assert sorted(w.name for w in r.routable()) == ["a", "b"]

    def test_max_outstanding_cap(self):
        r = self._registry()
        r.note_dispatch("a", None)
        r.note_dispatch("a", None)
        assert [w.name for w in r.routable(max_outstanding=2)] == ["b"]
        assert len(r.routable(max_outstanding=0)) == 2

    def test_breaker_feed_and_snapshot(self):
        r = self._registry()
        for _ in range(3):
            r.note_failure("b", "boom", dispatch=True)
        assert [w.name for w in r.routable()] == ["a"]
        snap = r.snapshot()
        assert snap["workers"]["b"]["breaker"] == BREAKER_OPEN
        assert snap["workers"]["b"]["dispatch_failures"] == 3
        r.note_success("b")
        assert snap["workers"]["b"]["breaker"] == BREAKER_OPEN  # snapshot
        assert r.snapshot()["workers"]["b"]["breaker"] == BREAKER_CLOSED


# ---------------------------------------------------------------------------
# FAST: typed connection error from the direct client
class _HalfDeadServer(threading.Thread):
    """Answers the connect-time ping, then kills the connection mid-way
    through the next request — the worker-crash shape the typed
    ServiceConnectionError exists for."""

    def __init__(self, sock_path):
        super().__init__(daemon=True)
        self.sock_path = sock_path
        self.srv = socketmod.socket(socketmod.AF_UNIX,
                                    socketmod.SOCK_STREAM)
        self.srv.bind(sock_path)
        self.srv.listen(4)

    def run(self):
        try:
            conn, _ = self.srv.accept()
            header, _ = recv_msg(conn)
            assert header["op"] == "ping"
            send_msg(conn, {"ok": True, "device": "fake"})
            recv_msg(conn)       # the doomed request...
            conn.close()         # ...dies without a reply
        except Exception:
            pass

    def close(self):
        self.srv.close()


class TestServiceConnectionError:
    def test_mid_request_eof_is_typed(self, tmp_path):
        sock = str(tmp_path / "halfdead.sock")
        srv = _HalfDeadServer(sock)
        srv.start()
        try:
            cli = TpuServiceClient(sock, deadline_s=5.0).connect()
            with pytest.raises(ServiceConnectionError) as ei:
                cli.run_plan(filter_plan(0.5), {})
            e = ei.value
            assert e.endpoint == sock
            assert e.op == "run_plan"
            assert e.phase in ("send", "recv")
            assert e.maybe_executed
            assert isinstance(e, ConnectionError)  # legacy handlers
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# FAST: gateway routing against fake wire-protocol workers
class _FakeWorker(threading.Thread):
    """Thread server speaking the service wire protocol. mode:
    'ok'    — answers run_plan with a one-row Arrow body;
    'close' — reads the run_plan then drops the connection (crash);
    'stall_close' — reads the run_plan, signals `stalled`, parks until
              `release_event` (or 20s), then drops the connection — a
              worker that dies with a request provably in flight;
    'shed'  — replies the typed rejected error."""

    def __init__(self, sock_path, mode="ok"):
        super().__init__(daemon=True)
        self.sock_path = sock_path
        self.mode = mode
        self.run_plans = 0
        self.srv = socketmod.socket(socketmod.AF_UNIX,
                                    socketmod.SOCK_STREAM)
        self.srv.bind(sock_path)
        self.srv.listen(16)
        self.srv.settimeout(0.2)
        self._stop = threading.Event()
        self.stalled = threading.Event()
        self.release_event = threading.Event()
        self.fake_pid = None  # ping reply pid (reincarnation tests)
        self._table = pa.table({"x": pa.array([1])})

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.srv.accept()
            except socketmod.timeout:
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()
        self.srv.close()

    def _serve(self, conn):
        try:
            while True:
                header, _ = recv_msg(conn)
                op = header.get("op")
                if op == "ping":
                    rep = {"ok": True, "device": "fake"}
                    if self.fake_pid is not None:
                        rep["pid"] = self.fake_pid
                    send_msg(conn, rep)
                elif op == "run_plan":
                    self.run_plans += 1
                    if self.mode == "close":
                        conn.close()
                        return
                    if self.mode == "stall_close":
                        self.stalled.set()
                        self.release_event.wait(20)
                        conn.close()
                        return
                    if self.mode == "shed":
                        send_msg(conn, {"ok": False,
                                        "error_type": "rejected",
                                        "error": "overload"})
                        continue
                    send_msg(conn, {"ok": True, "num_rows": 1},
                             table_to_ipc(self._table))
                elif op == "acquire":
                    if self.mode == "acquire_timeout":
                        send_msg(conn, {"ok": False,
                                        "error_type": "admission_timeout",
                                        "error": "admission timeout",
                                        "held": 1, "waiting": 1})
                    else:
                        send_msg(conn, {"ok": True, "order": 1})
                elif op == "release":
                    send_msg(conn, {"ok": True})
                else:
                    send_msg(conn, {"ok": False, "error": "nope"})
        except Exception:
            pass
        finally:
            conn.close()

    def close(self):
        self._stop.set()


def _fake_fleet(tmp_path, modes, conf=None):
    """(gateway_socket, gateway, [fake workers], serve_thread)."""
    fakes = []
    specs = []
    for i, mode in enumerate(modes):
        sock = str(tmp_path / f"fake{i}.sock")
        fw = _FakeWorker(sock, mode)
        fw.start()
        fakes.append(fw)
        specs.append((f"f{i}", sock))
    gw_sock = str(tmp_path / "gw.sock")
    base = {"spark.rapids.tpu.fleet.probe.intervalMs": 60_000,
            "spark.rapids.tpu.fleet.probe.timeoutSec": 2.0,
            "spark.rapids.tpu.fleet.dispatch.timeoutSec": 5.0}
    base.update(conf or {})
    gw = FleetGateway(specs, base, gw_sock)
    th = threading.Thread(target=gw.serve_forever, daemon=True)
    th.start()
    cli = TpuServiceClient(gw_sock, deadline_s=10.0).connect()
    cli.close()
    return gw_sock, gw, fakes, th


def _teardown_fleet(gw_sock, gw, fakes, th):
    try:
        with TpuServiceClient(gw_sock, deadline_s=5.0) as cli:
            cli.shutdown()
    except Exception:
        gw.stop()
    th.join(timeout=10)
    for fw in fakes:
        fw.close()


class TestGatewayFakeWorkers:
    def test_read_plan_fails_over_to_next_worker(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["close", "ok"])
        try:
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                t = cli.run_plan(filter_plan(0.5), {})
            assert t.num_rows == 1
            assert sum(f.run_plans for f in fakes) == 2  # crash + retry
            stats = gw._fleet_stats()
            assert stats["route_decisions"].get("failover", 0) >= 1
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_write_plan_never_auto_retried(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["close", "ok"])
        try:
            # force the crashing worker first: it is the only one with
            # zero outstanding history, but routing samples — so drain
            # the healthy one to pin the first dispatch, then undrain
            # is not needed: one routable worker, one attempt.
            gw.registry.drain("f1")
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                with pytest.raises(ServiceConnectionError) as ei:
                    cli.run_plan(filter_plan(0.5, marker="InsertInto"), {})
            assert "not auto-retried" in str(ei.value)
            gw.registry.undrain("f1")
            assert fakes[0].run_plans == 1
            assert fakes[1].run_plans == 0  # the write never moved
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_all_workers_shed_bubbles_typed_rejection(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["shed", "shed"])
        try:
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                with pytest.raises(QueryRejectedError) as ei:
                    cli.run_plan(filter_plan(0.5), {})
            # original cause chained into the gateway's reply
            assert "shed" in str(ei.value)
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_shed_at_the_door_before_worker_sockets(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["ok", "ok"])
        try:
            gw.registry.drain("f0")
            gw.registry.drain("f1")
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                with pytest.raises(QueryRejectedError):
                    cli.run_plan(filter_plan(0.5), {})
            assert all(f.run_plans == 0 for f in fakes)
            assert gw._fleet_stats()["route_decisions"].get("shed") == 1
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_acquire_pins_connection_and_run_follows(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["ok", "ok"])
        try:
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                assert cli.acquire(timeout=5.0) == 1
                cli.run_plan(filter_plan(0.5), {})
                cli.release()
            served = [f.run_plans for f in fakes]
            assert sorted(served) == [0, 1]  # pinned, not load-balanced
            assert gw._fleet_stats()["route_decisions"].get("pinned") == 1
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_failed_acquire_does_not_pin_the_connection(self, tmp_path):
        """An acquire that granted nothing (admission timeout/shed) must
        not leave the connection pinned — later run_plans on it keep
        affinity routing and failover."""
        from spark_rapids_tpu.errors import AdmissionTimeoutError
        gw_sock, gw, fakes, th = _fake_fleet(
            tmp_path, ["acquire_timeout", "acquire_timeout"])
        try:
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                with pytest.raises(AdmissionTimeoutError):
                    cli.acquire(timeout=0.1)
                t = cli.run_plan(filter_plan(0.5), {})
            assert t.num_rows == 1
            # routed (affinity/load), NOT the pinned fast path
            decisions = gw._fleet_stats()["route_decisions"]
            assert decisions.get("pinned", 0) == 0, decisions
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_cancel_unknown_id_replies_cleanly(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(tmp_path, ["ok"])
        try:
            with TpuServiceClient(gw_sock, deadline_s=10.0) as cli:
                rep = cli.cancel("no-such-query")
            assert rep["ok"] and rep["found"] is False
            assert rep["killed"] is False
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_deadline_exhausted_reports_causes(self, tmp_path):
        gw_sock, gw, fakes, th = _fake_fleet(
            tmp_path, ["close", "close"],
            conf={"spark.rapids.tpu.fleet.failover.maxAttempts": 4})
        try:
            from spark_rapids_tpu.errors import DeadlineExceededError
            t0 = time.monotonic()
            with TpuServiceClient(gw_sock, deadline_s=30.0) as cli:
                with pytest.raises((DeadlineExceededError,
                                    ServiceConnectionError)) as ei:
                    cli.run_plan(filter_plan(0.5), {}, deadline_s=1.5)
            assert time.monotonic() - t0 < 15.0
            assert "f0" in str(ei.value) or "f1" in str(ei.value)
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)


class TestFleetOffInert:
    def test_engine_modules_do_not_import_fleet(self):
        """The off-path contract's import half: the service layer (the
        direct single-socket path) must never pull the fleet package in.
        scripts/fleet_matrix.sh runs the full zero-thread gate."""
        code = ("import sys; "
                "import spark_rapids_tpu.service.client, "
                "spark_rapids_tpu.service.server, "
                "spark_rapids_tpu.telemetry, spark_rapids_tpu.config; "
                "assert not [m for m in sys.modules "
                "if m.startswith('spark_rapids_tpu.fleet')], 'leaked'; "
                "print('inert')")
        out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             capture_output=True, text=True, timeout=120,
                             env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert out.returncode == 0, out.stderr[-2000:]
        assert "inert" in out.stdout


# ---------------------------------------------------------------------------
# SLOW: real worker processes behind an in-process gateway
def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _start_worker(sock, log_dir):
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.service.server",
         "--socket", sock, "--platform", "cpu",
         "--conf", "spark.rapids.sql.concurrentGpuTasks=1",
         "--conf", "spark.rapids.tpu.rescache.enabled=true",
         "--conf", f"spark.rapids.tpu.metrics.eventLog.dir={log_dir}"],
        cwd=REPO, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return proc


def _await_worker(sock, proc, deadline_s=90.0):
    try:
        TpuServiceClient(sock, deadline_s=deadline_s).connect().close()
    except BaseException:
        proc.kill()
        proc.wait()
        raise
    return proc


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """3 real worker processes + an in-process gateway. Yields a dict the
    tests mutate (worker restarts swap Popen handles)."""
    d = tmp_path_factory.mktemp("fleet")
    log_dir = str(d / "events")
    socks = {f"w{i}": str(d / f"w{i}.sock") for i in range(3)}
    procs = {n: _start_worker(s, log_dir) for n, s in socks.items()}
    for n, s in socks.items():
        _await_worker(s, procs[n])
    gw_sock = str(d / "gateway.sock")
    gw = FleetGateway(
        [(n, s) for n, s in socks.items()],
        {"spark.rapids.tpu.fleet.probe.intervalMs": 200,
         "spark.rapids.tpu.fleet.probe.timeoutSec": 3.0,
         "spark.rapids.tpu.fleet.breaker.failures": 2,
         "spark.rapids.tpu.fleet.breaker.cooldownMs": 1000,
         "spark.rapids.tpu.metrics.eventLog.dir": log_dir},
        gw_sock)
    th = threading.Thread(target=gw.serve_forever, daemon=True)
    th.start()
    TpuServiceClient(gw_sock, deadline_s=30.0).connect().close()
    env = {"gw": gw, "gw_sock": gw_sock, "socks": socks, "procs": procs,
           "log_dir": log_dir, "dir": d}
    yield env
    try:
        with TpuServiceClient(gw_sock, deadline_s=5.0) as cli:
            cli.shutdown()
    except Exception:
        gw.stop()
    th.join(timeout=10)
    for n, p in env["procs"].items():
        try:
            with TpuServiceClient(socks[n], deadline_s=3.0) as cli:
                cli.shutdown()
        except Exception:
            pass
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


@pytest.fixture(scope="module")
def fleet_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("fleetdata")
    rng = np.random.default_rng(11)
    n = 20_000
    t = pa.table({"k": pa.array(rng.integers(0, 64, n).astype(np.int64)),
                  "v": pa.array(rng.uniform(size=n))})
    path = str(d / "t.parquet")
    pq.write_table(t, path)
    return {"table": t, "paths": {"t": [path]}}


def _expected(t: pa.Table, threshold: float) -> pa.Table:
    mask = np.asarray(t.column("v")) > threshold
    return t.filter(pa.array(mask))


def _sorted(t: pa.Table) -> pa.Table:
    return t.sort_by([("k", "ascending"), ("v", "ascending")])


def _dispatches(gw) -> dict:
    return {n: w["dispatches"]
            for n, w in gw._fleet_stats()["workers"].items()}


@pytest.mark.slow
class TestFleetLifecycle:
    def _run(self, env, plan, paths, **kw):
        with TpuServiceClient(env["gw_sock"], deadline_s=180.0) as cli:
            return cli.run_plan(plan, paths, **kw)

    def test_route_basic_rows_identical_to_direct(self, fleet, fleet_data):
        plan = filter_plan(0.5)
        got = self._run(fleet, plan, fleet_data["paths"])
        exp = _expected(fleet_data["table"], 0.5)
        assert got.num_rows == exp.num_rows
        # bit-identical to a DIRECT single-worker run of the same plan
        any_sock = next(iter(fleet["socks"].values()))
        with TpuServiceClient(any_sock, deadline_s=180.0) as cli:
            direct = cli.run_plan(plan, fleet_data["paths"])
        assert _sorted(got).equals(_sorted(direct))
        assert _sorted(got).equals(_sorted(exp.select(["k", "v"])))

    def test_affinity_same_worker_second_run_rescache_hit(
            self, fleet, fleet_data):
        plan = filter_plan(0.31)
        before = _dispatches(fleet["gw"])
        r1 = self._run(fleet, plan, fleet_data["paths"])
        mid = _dispatches(fleet["gw"])
        target = [n for n in mid if mid[n] > before[n]]
        assert len(target) == 1, (before, mid)
        r2 = self._run(fleet, plan, fleet_data["paths"])
        after = _dispatches(fleet["gw"])
        target2 = [n for n in after if after[n] > mid[n]]
        assert target2 == target, "affinity moved between identical plans"
        assert _sorted(r1).equals(_sorted(r2))
        # the second run answered from THAT worker's result cache
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            stats = cli.cache_stats()
        s = stats[target[0]]
        assert isinstance(s, dict) and s.get("hits", {}).get("query", 0) \
            >= 1, s
        assert fleet["gw"]._fleet_stats()["route_decisions"].get(
            "affinity", 0) >= 2

    def test_kill_worker_mid_run_plan_fails_over_bit_identical(
            self, fleet, fleet_data):
        thr = 0.77
        plan = filter_plan(thr)
        qid = "kill-me-1"
        # affinity is deterministic: predict the target, FREEZE it so the
        # dispatched run_plan is provably in flight when the kill lands
        digest, _ = router.analyze(plan, fleet_data["paths"],
                                   fleet["gw"].conf)
        assert digest is not None
        target = router.rendezvous_order(digest,
                                         list(fleet["socks"]))[0]
        fleet["procs"][target].send_signal(signal.SIGSTOP)
        out = {}

        def run():
            try:
                out["table"] = self._run(fleet, plan, fleet_data["paths"],
                                         query_id=qid)
            except Exception as e:  # pragma: no cover - surfaced below
                out["error"] = e

        th = threading.Thread(target=run, daemon=True)
        th.start()
        t0 = time.time()
        placed = None
        while time.time() - t0 < 60:
            placed = fleet["gw"]._fleet_stats()["placements"].get(qid)
            if placed:
                break
            time.sleep(0.01)
        assert placed == target, f"placed on {placed}, expected {target}"
        time.sleep(0.3)  # the request is parked inside the frozen worker
        fleet["procs"][target].send_signal(signal.SIGKILL)
        fleet["procs"][target].wait(timeout=10)
        th.join(timeout=240)
        assert not th.is_alive(), "failover never completed"
        assert "error" not in out, out.get("error")
        exp = _expected(fleet_data["table"], thr).select(["k", "v"])
        assert _sorted(out["table"]).equals(_sorted(exp))
        stats = fleet["gw"]._fleet_stats()
        assert stats["route_decisions"].get("failover", 0) >= 1
        # ---- breaker half-open recovery: restart the worker in place
        t0 = time.time()
        while time.time() - t0 < 15:
            if stats["workers"][target]["breaker"] == BREAKER_OPEN:
                break
            time.sleep(0.1)
            stats = fleet["gw"]._fleet_stats()
        fleet["procs"][target] = _await_worker(
            fleet["socks"][target],
            _start_worker(fleet["socks"][target], fleet["log_dir"]))
        t0 = time.time()
        while time.time() - t0 < 30:
            w = fleet["gw"]._fleet_stats()["workers"][target]
            if w["breaker"] == BREAKER_CLOSED and w["healthy"]:
                break
            time.sleep(0.1)
        w = fleet["gw"]._fleet_stats()["workers"][target]
        assert w["breaker"] == BREAKER_CLOSED and w["healthy"], \
            "restarted worker never re-admitted through half-open probe"

    def test_drain_zero_new_placements_then_undrain(self, fleet,
                                                    fleet_data):
        gw = fleet["gw"]
        victim = "w1"
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            rep = cli.drain(victim)
        assert rep["draining"] is True
        before = _dispatches(gw)
        for i in range(5):
            self._run(fleet, filter_plan(0.40 + i * 0.01),
                      fleet_data["paths"])
        after = _dispatches(gw)
        assert after[victim] == before[victim], \
            "drained worker received new placements"
        assert sum(after.values()) - sum(before.values()) == 5
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            rep = cli.undrain(victim)
        assert rep["draining"] is False
        assert victim in [w.name for w in gw.registry.routable()]

    def test_drain_lets_in_flight_complete(self, fleet, fleet_data):
        thr = 0.88  # fresh compile window again
        qid = "drain-inflight"
        out = {}

        def run():
            out["table"] = self._run(fleet, filter_plan(thr),
                                     fleet_data["paths"], query_id=qid)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        t0 = time.time()
        target = None
        while time.time() - t0 < 60:
            target = fleet["gw"]._fleet_stats()["placements"].get(qid)
            if target:
                break
            time.sleep(0.01)
        assert target
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            cli.drain(target)
        th.join(timeout=240)
        assert "table" in out, "in-flight query did not survive drain"
        exp = _expected(fleet_data["table"], thr).select(["k", "v"])
        assert _sorted(out["table"]).equals(_sorted(exp))
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            cli.undrain(target)

    def test_cancel_through_gateway_finds_the_worker(self, fleet,
                                                     fleet_data):
        thr = 0.93
        qid = "cancel-me-1"
        out = {}

        def run():
            try:
                out["table"] = self._run(fleet, filter_plan(thr),
                                         fleet_data["paths"],
                                         query_id=qid)
            except Exception as e:
                out["error"] = e

        th = threading.Thread(target=run, daemon=True)
        th.start()
        t0 = time.time()
        while time.time() - t0 < 60:
            if fleet["gw"]._fleet_stats()["placements"].get(qid):
                break
            time.sleep(0.01)
        time.sleep(0.3)
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            rep = cli.cancel(qid, reason="test cancel")
        assert rep["ok"]
        th.join(timeout=240)
        assert not th.is_alive()
        # either the cancel landed mid-flight (typed error) or the query
        # finished first (tiny race) — both are clean outcomes; the
        # gateway must have routed the cancel without erroring
        if "error" in out:
            assert isinstance(out["error"], QueryCancelledError), \
                out["error"]
            assert rep.get("found", True)

    def test_backpressure_all_drained_sheds_at_gateway(self, fleet,
                                                       fleet_data):
        gw = fleet["gw"]
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            for n in fleet["socks"]:
                cli.drain(n)
            with pytest.raises(QueryRejectedError):
                cli.run_plan(filter_plan(0.5), fleet_data["paths"])
            for n in fleet["socks"]:
                cli.undrain(n)
        assert gw._fleet_stats()["route_decisions"].get("shed", 0) >= 1

    def test_trace_stitches_client_gateway_worker(self, fleet,
                                                  fleet_data):
        from spark_rapids_tpu.tools.profile_report import (load_records,
                                                           trace_view)
        cli = TpuServiceClient(fleet["gw_sock"], deadline_s=180.0,
                               event_log_dir=fleet["log_dir"])
        with cli:
            cli.run_plan(filter_plan(0.66), fleet_data["paths"])
        trace = cli.last_trace_id
        assert trace
        records, _ = load_records([fleet["log_dir"]])
        view = trace_view(records, trace=trace)
        assert "gateway:run_plan" in view
        assert "client:run_plan" in view
        assert "server query" in view
        assert "decision=" in view and "worker=" in view


# ---------------------------------------------------------------------------
# PR 14 satellites: drain + crash combinations, gateway-observed death
# releasing worker-side admission tokens, reincarnation reconciliation
# ---------------------------------------------------------------------------


def _affinity_order(plan):
    """Deterministic dispatch order over two fake workers: empty-path
    fake plans fail-closed to LOAD routing, and with both fakes idle the
    power-of-two pair sorts by (outstanding, name) — f0 is provably
    dispatched first (the same determinism TestGatewayFakeWorkers'
    failover tests already lean on)."""
    return ["f0", "f1"]


class TestDrainCrashCombos:
    def test_draining_worker_dies_midflight_read_fails_over(self,
                                                            tmp_path):
        """Drain lands while a READ is in flight on the worker, then the
        worker dies: the query must fail over (typed machinery, correct
        rows), and the drained corpse must receive nothing new."""
        plan = filter_plan(0.5)
        order = _affinity_order(plan)
        modes = {order[0]: "stall_close", order[1]: "ok"}
        gw_sock, gw, fakes, th = _fake_fleet(
            tmp_path, [modes["f0"], modes["f1"]])
        dying = fakes[int(order[0][1])]
        healthy = fakes[int(order[1][1])]
        try:
            out = {}

            def run():
                try:
                    with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                        out["table"] = c.run_plan(plan, {})
                except Exception as e:
                    out["error"] = e

            t = threading.Thread(target=run, daemon=True)
            t.start()
            assert dying.stalled.wait(10), "request never reached worker"
            gw.registry.drain(order[0])         # drain WHILE in flight
            dying.release_event.set()           # ... then it dies
            t.join(timeout=60)
            assert not t.is_alive()
            assert "error" not in out, out.get("error")
            assert out["table"].num_rows == 1   # failed over, rows intact
            assert healthy.run_plans == 1
            stats = gw._fleet_stats()
            assert stats["route_decisions"].get("failover", 0) >= 1
            # drained corpse gets zero NEW placements
            with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                c.run_plan(plan, {})
            assert dying.run_plans == 1
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_draining_worker_dies_midflight_write_typed_no_retry(
            self, tmp_path):
        """Same crash, but a WRITE plan: the typed connection error must
        surface with the no-retry contract intact — the surviving worker
        never sees the write."""
        plan = filter_plan(0.5, marker="InsertInto")
        order = _affinity_order(plan)
        modes = {order[0]: "stall_close", order[1]: "ok"}
        gw_sock, gw, fakes, th = _fake_fleet(
            tmp_path, [modes["f0"], modes["f1"]])
        dying = fakes[int(order[0][1])]
        healthy = fakes[int(order[1][1])]
        try:
            out = {}

            def run():
                try:
                    with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                        out["table"] = c.run_plan(plan, {})
                except Exception as e:
                    out["error"] = e

            t = threading.Thread(target=run, daemon=True)
            t.start()
            assert dying.stalled.wait(10), "request never reached worker"
            gw.registry.drain(order[0])
            dying.release_event.set()
            t.join(timeout=60)
            assert not t.is_alive()
            assert isinstance(out.get("error"), ServiceConnectionError), \
                out
            assert "not auto-retried" in str(out["error"])
            assert healthy.run_plans == 0, \
                "write plan moved to another worker after dispatch"
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)

    def test_undrain_dead_worker_not_routable_until_probe_succeeds(
            self, tmp_path):
        """undrain must not resurrect a dead worker: its breaker stays
        authoritative until a half-open PROBE actually succeeds against
        the restarted process."""
        plan = filter_plan(0.5)
        order = _affinity_order(plan)
        gw_sock, gw, fakes, th = _fake_fleet(
            tmp_path, ["ok", "ok"],
            conf={"spark.rapids.tpu.fleet.breaker.failures": 1,
                  "spark.rapids.tpu.fleet.breaker.cooldownMs": 1500,
                  "spark.rapids.tpu.fleet.probe.intervalMs": 100})
        target = order[0]
        tfake = fakes[int(target[1])]
        try:
            tfake.close()           # the affinity worker dies
            time.sleep(0.3)         # accept loop exits, socket dead
            with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                t = c.run_plan(plan, {})     # fails over
            assert t.num_rows == 1
            w = gw._fleet_stats()["workers"][target]
            assert w["breaker"] == BREAKER_OPEN
            with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                c.drain(target)
                rep = c.undrain(target)
            assert rep["draining"] is False
            # undrained but DEAD: not routable inside the cooldown ...
            assert target not in [x.name for x in gw.registry.routable()]
            # ... and over the next seconds (cooldown expiries included)
            # every query keeps landing on the survivor while the
            # half-open probe trials keep failing against the corpse
            dispatched_before = \
                gw._fleet_stats()["workers"][target]["dispatches"]
            t0 = time.time()
            while time.time() - t0 < 3.0:
                with TpuServiceClient(gw_sock, deadline_s=30.0) as c:
                    assert c.run_plan(plan, {}).num_rows == 1
                snap = gw._fleet_stats()["workers"][target]
                assert not snap["healthy"]
                time.sleep(0.2)
            # a failed half-open TRIAL dispatch is allowed; a SUCCESSFUL
            # placement on the corpse is not — nothing incremented
            # run_plans on the dead fake (its socket is gone)
            assert gw._fleet_stats()["workers"][target]["dispatches"] \
                - dispatched_before <= 3
            # restart the worker at the same address: the half-open
            # probe re-admits it without operator action
            os.unlink(tfake.sock_path)
            revived = _FakeWorker(tfake.sock_path, "ok")
            revived.start()
            fakes.append(revived)
            t0 = time.time()
            while time.time() - t0 < 15:
                w = gw._fleet_stats()["workers"][target]
                if w["breaker"] == BREAKER_CLOSED and w["healthy"]:
                    break
                time.sleep(0.1)
            w = gw._fleet_stats()["workers"][target]
            assert w["breaker"] == BREAKER_CLOSED and w["healthy"]
            assert target in [x.name for x in gw.registry.routable()]
        finally:
            _teardown_fleet(gw_sock, gw, fakes, th)


class TestReincarnationReconciliation:
    def test_pid_change_purges_placements_and_counts(self, tmp_path):
        """A worker answering probes with a NEW pid is a new process:
        the registry must count the reincarnation and purge placements
        for queries that died with the old incarnation (cancel then
        truthfully answers found:false)."""
        sock = str(tmp_path / "w.sock")
        fw = _FakeWorker(sock, "ok")
        fw.fake_pid = 1111
        fw.start()
        reg = WorkerRegistry([("w0", sock)], probe_interval_s=3600,
                             probe_timeout_s=2.0)
        try:
            reg._probe_worker(reg.workers["w0"])
            assert reg.workers["w0"].pid == 1111
            reg.note_dispatch("w0", "q-old")
            assert reg.placement_of("q-old") is not None
            fw.fake_pid = 2222          # the process "restarted"
            reg._probe_worker(reg.workers["w0"])
            w = reg.workers["w0"]
            assert w.pid == 2222
            assert w.reincarnations == 1
            assert reg.placement_of("q-old") is None, \
                "placement survived the worker's death"
            snap = reg.snapshot()["workers"]["w0"]
            assert snap["reincarnations"] == 1
        finally:
            fw.close()


@pytest.mark.slow
class TestGatewayObservedDeathTokenRelease:
    def test_wedged_worker_token_released_after_gateway_drops_pin(
            self, tmp_path, fleet_data):
        """A client holds an admission token through the gateway (pinned
        connection). The WORKER wedges; the GATEWAY observes the death
        (dispatch timeout) and drops the pin. When the worker resumes,
        the worker-side disconnect-releases-token path must fire off the
        gateway's closed upstream socket — the token may not leak."""
        sock = str(tmp_path / "w.sock")
        log_dir = str(tmp_path / "events")
        proc = _start_worker(sock, log_dir)
        _await_worker(sock, proc)
        gw_sock = str(tmp_path / "gw.sock")
        gw = FleetGateway(
            [("w0", sock)],
            {"spark.rapids.tpu.fleet.probe.intervalMs": 60_000,
             "spark.rapids.tpu.fleet.dispatch.timeoutSec": 2.0},
            gw_sock)
        th = threading.Thread(target=gw.serve_forever, daemon=True)
        th.start()
        TpuServiceClient(gw_sock, deadline_s=30.0).connect().close()
        cliA = None
        try:
            cliA = TpuServiceClient(gw_sock, deadline_s=30.0).connect()
            assert cliA.acquire(timeout=30.0) >= 1  # token held, pinned
            proc.send_signal(signal.SIGSTOP)        # worker wedges
            with pytest.raises(ServiceConnectionError):
                # gateway times out at dispatch.timeoutSec, closes the
                # pinned upstream, surfaces the typed connection error
                cliA.run_plan(filter_plan(0.41), fleet_data["paths"])
            proc.send_signal(signal.SIGCONT)        # worker resumes
            # the resumed worker finds the gateway's socket closed and
            # releases the dead connection's token; with
            # concurrentGpuTasks=1 this acquire only succeeds if it did
            with TpuServiceClient(sock, deadline_s=90.0) as cliB:
                assert cliB.acquire(timeout=60.0) >= 1
                cliB.release()
        finally:
            if cliA is not None:
                cliA.close()
            try:
                with TpuServiceClient(gw_sock, deadline_s=5.0) as c:
                    c.shutdown()
            except Exception:
                gw.stop()
            th.join(timeout=10)
            try:
                proc.send_signal(signal.SIGCONT)
            except OSError:
                pass
            try:
                with TpuServiceClient(sock, deadline_s=5.0) as c:
                    c.shutdown()
            except Exception:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


@pytest.mark.slow
class TestDrainCrashLifecycle:
    def test_draining_real_worker_killed_midflight_read_fails_over(
            self, fleet, fleet_data):
        """Real-process version of the drain+crash combo: drain lands
        while the query is in flight, SIGKILL the worker, and the read
        fails over with bit-identical rows."""
        thr = 0.83
        plan = filter_plan(thr)
        qid = "drain-die-1"
        digest, _ = router.analyze(plan, fleet_data["paths"],
                                   fleet["gw"].conf)
        target = router.rendezvous_order(digest,
                                         list(fleet["socks"]))[0]
        fleet["procs"][target].send_signal(signal.SIGSTOP)
        out = {}

        def run():
            try:
                out["table"] = TpuServiceClient(
                    fleet["gw_sock"], deadline_s=240.0
                ).connect().run_plan(plan, fleet_data["paths"],
                                     query_id=qid)
            except Exception as e:
                out["error"] = e

        th = threading.Thread(target=run, daemon=True)
        th.start()
        t0 = time.time()
        while time.time() - t0 < 60:
            if fleet["gw"]._fleet_stats()["placements"].get(qid):
                break
            time.sleep(0.01)
        assert fleet["gw"]._fleet_stats()["placements"].get(qid) == target
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            cli.drain(target)               # drain the worker mid-flight
        fleet["procs"][target].send_signal(signal.SIGKILL)
        fleet["procs"][target].wait(timeout=10)
        th.join(timeout=240)
        assert not th.is_alive(), "failover never completed"
        assert "error" not in out, out.get("error")
        exp = _expected(fleet_data["table"], thr).select(["k", "v"])
        assert _sorted(out["table"]).equals(_sorted(exp))
        # restore the fixture: restart the worker, undrain, re-admit
        fleet["procs"][target] = _await_worker(
            fleet["socks"][target],
            _start_worker(fleet["socks"][target], fleet["log_dir"]))
        with TpuServiceClient(fleet["gw_sock"], deadline_s=30.0) as cli:
            cli.undrain(target)
        t0 = time.time()
        while time.time() - t0 < 30:
            w = fleet["gw"]._fleet_stats()["workers"][target]
            if w["breaker"] == BREAKER_CLOSED and w["healthy"]:
                break
            time.sleep(0.1)
        assert fleet["gw"]._fleet_stats()["workers"][target]["breaker"] \
            == BREAKER_CLOSED
