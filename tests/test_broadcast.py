"""Broadcast exchange + broadcast join tests (reference
GpuBroadcastExchangeExec.scala:94,320, GpuBroadcastHashJoinExecBase.scala,
GpuBroadcastNestedLoopJoinExecBase.scala)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exec.broadcast import TpuBroadcastExchangeExec
from spark_rapids_tpu.expr import Sum, col, lit
from spark_rapids_tpu.plan.overrides import Overrides
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same, make_table


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def make_dim(rng, n=50):
    keys = rng.permutation(400)[:n]
    return pa.table({
        "id": pa.array(keys, type=pa.int64()),
        "w": pa.array(rng.uniform(0.5, 1.5, n), type=pa.float64()),
    })


def device_plan(session, df):
    return Overrides(session.conf).apply(df.plan).tree_string()


class TestBroadcastPlanning:
    def test_small_build_broadcasts(self, session, rng):
        fact = session.from_arrow(make_table(rng, n=500))
        dim = session.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how="inner")
        tree = device_plan(session, q)
        assert "TpuBroadcastHashJoinExec" in tree
        assert "TpuBroadcastExchangeExec" in tree
        assert_same(q, sort_by=["id", "val", "w"])

    def test_threshold_disables(self, rng):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.autoBroadcastJoinThreshold": -1})
        fact = s.from_arrow(make_table(rng, n=500))
        dim = s.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how="inner")
        tree = device_plan(s, q)
        assert "TpuBroadcastExchangeExec" not in tree
        assert "TpuShuffledHashJoinExec" in tree

    def test_tiny_threshold_disables(self, rng):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.autoBroadcastJoinThreshold": 16})
        fact = s.from_arrow(make_table(rng, n=500))
        dim = s.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how="inner")
        assert "TpuBroadcastExchangeExec" not in device_plan(s, q)

    @pytest.mark.parametrize("how", ["right", "full"])
    def test_build_tracking_joins_never_broadcast(self, session, rng, how):
        fact = session.from_arrow(make_table(rng, n=500))
        dim = session.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how=how)
        assert "TpuBroadcastExchangeExec" not in device_plan(session, q)
        assert_same(q, sort_by=["id", "val", "w"])

    @pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
    def test_broadcast_join_types_correct(self, session, rng, how):
        fact = session.from_arrow(make_table(rng, n=500))
        dim = session.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how=how)
        assert "TpuBroadcastExchangeExec" in device_plan(session, q)
        sort_cols = ["id", "val"] if how in ("semi", "anti") \
            else ["id", "val", "w"]
        assert_same(q, sort_by=sort_cols)

    def test_keyless_small_build_broadcasts(self, session, rng):
        left = session.from_arrow(make_table(rng, n=60))
        right = session.from_arrow(make_dim(rng, n=20))
        q = left.join(right, condition=col("val") > col("w"), how="inner")
        tree = device_plan(session, q)
        assert "TpuNestedLoopJoinExec" in tree
        assert "TpuBroadcastExchangeExec" in tree
        assert_same(q, sort_by=["id", "val", "w", "id"])


class _CountingChild:
    def __init__(self, batch, schema):
        self.batch = batch
        self.output = schema
        self.calls = 0
        self.children = ()

    def execute(self):
        self.calls += 1
        return iter([self.batch])


class TestBroadcastExchange:
    def test_reuse_executes_child_once(self, session, rng):
        from spark_rapids_tpu.columnar.batch import Schema, batch_from_arrow
        t = make_dim(rng)
        child = _CountingChild(batch_from_arrow(t), Schema.from_arrow(t.schema))
        ex = TpuBroadcastExchangeExec(child, session.conf)
        out1 = list(ex.do_execute())
        out2 = list(ex.do_execute())
        assert child.calls == 1  # ReusedExchange semantics
        assert len(out1) == 1 and len(out2) == 1
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        a = batch_to_arrow(out1[0]).sort_by([("id", "ascending")])
        b = batch_to_arrow(out2[0]).sort_by([("id", "ascending")])
        assert a.equals(b)
        assert a.num_rows == t.num_rows

    def test_empty_build(self, session):
        from spark_rapids_tpu.columnar.batch import Schema
        t = pa.table({"id": pa.array([], type=pa.int64())})
        child = _CountingChild(None, Schema.from_arrow(t.schema))
        child.execute = lambda: iter([])
        ex = TpuBroadcastExchangeExec(child, session.conf)
        assert list(ex.do_execute()) == []

    def test_broadcast_with_strings(self, session, rng):
        fact = session.from_arrow(make_table(rng, n=300))
        keys = rng.permutation(400)[:40]
        dim = session.from_arrow(pa.table({
            "id": pa.array(keys, type=pa.int64()),
            "tag": pa.array([None if k % 5 == 0 else f"t{k}" for k in keys]),
        }))
        q = fact.join(dim, on="id", how="left")
        assert "TpuBroadcastExchangeExec" in device_plan(session, q)
        assert_same(q, sort_by=["id", "val", "tag"])
