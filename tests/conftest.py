"""Test configuration: run everything on a virtual 8-device CPU mesh so sharding and
collective paths are exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench.py runs on the real chip)."""

import os

# Must be set before jax initializes. Forced (not setdefault): the session may point
# JAX_PLATFORMS at real TPU hardware, but tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The axon TPU plugin overrides JAX_PLATFORMS from the environment; config.update
# before first backend use is authoritative.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "faults: deterministic fault-injection matrix "
        "(scripts/fault_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "compile: compile-service suite (program cache / persistent tier / "
        "warmup / bucket tuner; scripts/compile_cache_matrix.sh runs these "
        "standalone)")
    config.addinivalue_line(
        "markers",
        "observability: query-profiler suite (span tracer / metrics "
        "wiring / event log / report tool; scripts/profile_matrix.sh runs "
        "these standalone)")
    config.addinivalue_line(
        "markers",
        "pipeline: pipelined-execution suite (bounded async prefetch / "
        "fused multi-chunk scan decode / pipeline on-off equality; "
        "scripts/pipeline_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "telemetry: live-telemetry suite (metrics registry / scrape "
        "surface / flight recorder / trace correlation; "
        "scripts/telemetry_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "sched: query-scheduler suite (priority-weighted fair admission / "
        "deadlines / cooperative cancellation / tenant quotas; "
        "scripts/sched_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "rescache: result/fragment-cache suite (plan fingerprints / "
        "cross-query reuse seams / single-flight / eviction / fault "
        "degrade; scripts/rescache_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "fleet: fleet-gateway suite (worker registry / breakers / "
        "affinity routing / failover / drain / cancel-through-gateway; "
        "scripts/fleet_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "stats: runtime-statistics suite (cardinality history / "
        "estimate-vs-actual q-error / optimizer feedback / skew "
        "histograms; scripts/stats_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "pushdown: scan-pushdown suite (compute on compressed data: "
        "golden on/off equality / planner rewrites / key+fingerprint "
        "non-aliasing / row-group pruning / aggregate-only shapes; "
        "scripts/scan_pushdown_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "live: live query-introspection suite (in-flight registry / "
        "progress+ETA from stats history / slow-query watchdog / "
        "queries surfaces / gateway fan-out / tpu_top console; "
        "scripts/liveview_matrix.sh runs these standalone)")
    config.addinivalue_line(
        "markers",
        "chaos: crash-recovery suite (durable-tier degradation / fleet "
        "supervisor / chaos campaigns over real gateway + supervised "
        "worker processes; scripts/chaos_matrix.sh runs these "
        "standalone — campaign tests are also `slow`)")
    config.addinivalue_line(
        "markers",
        "mesh: sharded-execution suite (scan sharding across mesh "
        "positions / device-resident exchange seams / partition-count "
        "mismatch degrades / per-chip HBM ledgers / one admission door / "
        "rescache ICI seam; scripts/mesh_matrix.sh runs these "
        "standalone)")
    config.addinivalue_line(
        "markers",
        "fusion: whole-stage fusion suite (planner chains / fused-stage "
        "on-off bit-identity / ANSI parity / pallas kernel exactness / "
        "dispatch accounting; scripts/fusion_matrix.sh runs these "
        "standalone)")


@pytest.fixture
def rng():
    return np.random.default_rng(42)
