"""Dynamic file/row-group pruning (GpuSubqueryBroadcastExec / DPP analog)
and top-k (TakeOrderedAndProjectExec analog)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def write_fact_files(tmp_path, nfiles=6, rows=400):
    """Each file covers a DISJOINT key range [f*1000, f*1000+rows)."""
    paths = []
    rng = np.random.default_rng(11)
    for f in range(nfiles):
        keys = np.arange(f * 1000, f * 1000 + rows, dtype=np.int64)
        t = pa.table({"k": keys,
                      "v": rng.normal(size=rows)})
        p = str(tmp_path / f"fact{f}.parquet")
        pq.write_table(t, p)
        paths.append(p)
    return paths


def find_scans(node):
    from spark_rapids_tpu.io.scanbase import TpuFileScanExec
    out = [node] if isinstance(node, TpuFileScanExec) else []
    for c in getattr(node, "children", []):
        out.extend(find_scans(c))
    return out


class TestDynamicFilePruning:
    def _joined_plan(self, session, tmp_path, join_type="inner"):
        paths = write_fact_files(tmp_path)
        fact = session.read_parquet(*paths)
        # dim keys hit ONLY files 1 and 4
        dim = session.from_arrow(pa.table({
            "k": pa.array([1005, 1010, 4100], type=pa.int64()),
            "w": pa.array([1.0, 2.0, 3.0])}))
        return fact.join(dim, on="k", how=join_type)

    def test_files_and_row_groups_pruned(self, session, tmp_path):
        df = self._joined_plan(session, tmp_path)
        session.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        ov = Overrides(session.conf)
        result = ov.apply(df.plan)
        scans = find_scans(result)
        assert scans and scans[0].dynamic_filters, "DPP filter not wired"
        batches = list(result.execute())
        total = sum(int(b.row_count()) for b in batches)
        assert total == 3
        assert scans[0].files_pruned.value >= 4  # only 2 of 6 files match

    def test_results_match_cpu(self, session, tmp_path):
        df = self._joined_plan(session, tmp_path)
        q = df.agg(n=Count(lit(1)), s=Sum(col("w")))
        out = q.collect()
        cpu = q.collect_cpu()
        assert out.column("n").to_pylist() == cpu.column("n").to_pylist() \
            == [3]
        assert out.column("s").to_pylist() == [6.0]

    def test_left_join_not_pruned(self, session, tmp_path):
        # left outer emits unmatched probe rows: pruning would be wrong
        df = self._joined_plan(session, tmp_path, join_type="left")
        session.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        ov = Overrides(session.conf)
        result = ov.apply(df.plan)
        for scan in find_scans(result):
            assert not scan.dynamic_filters
        assert df.collect().num_rows == 6 * 400

    def test_disabled_by_conf(self, tmp_path):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.dynamicFilePruning.enabled":
                            False})
        df = self._joined_plan(s, tmp_path)
        s.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        ov = Overrides(s.conf)
        result = ov.apply(df.plan)
        for scan in find_scans(result):
            assert not scan.dynamic_filters

    def test_string_keys_pruned(self, session, tmp_path):
        paths = []
        for f, names in enumerate([["alpha", "apple"], ["beta", "bird"],
                                   ["zeta", "zoo"]]):
            t = pa.table({"k": pa.array(names * 50),
                          "v": pa.array(range(100), type=pa.int64())})
            p = str(tmp_path / f"s{f}.parquet")
            pq.write_table(t, p)
            paths.append(p)
        fact = session.read_parquet(*paths)
        dim = session.from_arrow(pa.table({
            "k": pa.array(["beta"]), "w": pa.array([1],
                                                   type=pa.int64())}))
        df = fact.join(dim, on="k", how="inner")
        session.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        ov = Overrides(session.conf)
        result = ov.apply(df.plan)
        scans = find_scans(result)
        batches = list(result.execute())
        assert sum(int(b.row_count()) for b in batches) == 50
        assert scans[0].files_pruned.value == 2


class TestTopK:
    def _table(self, tmp_path, n=5000, with_nulls=True):
        rng = np.random.default_rng(3)
        vals = rng.integers(-10**6, 10**6, n)
        mask = (rng.random(n) < 0.1) if with_nulls else np.zeros(n, bool)
        t = pa.table({"v": pa.array(vals, mask=mask),
                      "tag": pa.array(rng.integers(0, 50, n)),
                      "i": pa.array(range(n), type=pa.int64())})
        p = str(tmp_path / "topk.parquet")
        pq.write_table(t, p, row_group_size=700)  # multi-batch stream
        return p, t

    def test_topk_matches_sort_limit(self, session, tmp_path):
        p, t = self._table(tmp_path)
        df = session.read_parquet(p)
        for asc in (True, False):
            q = df.sort("v", ascending=asc).limit(25)
            out = q.collect()
            cpu = q.collect_cpu()
            assert out.column("v").to_pylist() == \
                cpu.column("v").to_pylist()
            assert out.column("i").to_pylist() == \
                cpu.column("i").to_pylist()

    def test_topk_exec_actually_used(self, session, tmp_path):
        p, _ = self._table(tmp_path)
        df = session.read_parquet(p).sort("v").limit(10)
        session.initialize_device()
        from spark_rapids_tpu.exec.sort import TpuSortExec, TpuTopKExec
        from spark_rapids_tpu.plan.overrides import Overrides
        ov = Overrides(session.conf)
        result = ov.apply(df.plan)

        def find(node, cls):
            got = [node] if isinstance(node, cls) else []
            for c in getattr(node, "children", []):
                got.extend(find(c, cls))
            return got

        assert find(result, TpuTopKExec)
        assert not find(result, TpuSortExec)

    def test_topk_with_offset(self, session, tmp_path):
        p, _ = self._table(tmp_path, n=1000, with_nulls=False)
        df = session.read_parquet(p)
        q = df.sort("v").limit(7, offset=5)
        out = q.collect().column("v").to_pylist()
        cpu = q.collect_cpu().column("v").to_pylist()
        assert out == cpu and len(out) == 7

    def test_limit_larger_than_input(self, session, tmp_path):
        p, t = self._table(tmp_path, n=40, with_nulls=False)
        df = session.read_parquet(p)
        q = df.sort("v", ascending=False).limit(500)
        out = q.collect()
        assert out.num_rows == 40
        assert out.column("v").to_pylist() == \
            sorted(t.column("v").to_pylist(), reverse=True)

    def test_disabled_falls_back_to_sort(self, tmp_path):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.topK.enabled": False})
        p, _ = self._table(tmp_path, n=300)
        df = s.read_parquet(p).sort("v").limit(5)
        s.initialize_device()
        from spark_rapids_tpu.exec.sort import TpuTopKExec
        from spark_rapids_tpu.plan.overrides import Overrides
        result = Overrides(s.conf).apply(df.plan)

        def find(node):
            got = [node] if isinstance(node, TpuTopKExec) else []
            for c in getattr(node, "children", []):
                got.extend(find(c))
            return got

        assert not find(result)
        assert df.collect().num_rows == 5


class TestAdviceR3Regressions:
    def test_topk_threshold_keeps_sort_plan(self, tmp_path):
        # advisor r3: unbounded k kept an O(k) candidate batch resident and
        # lost the out-of-core sort's spill path; above the threshold the
        # planner must keep sort+limit (topKSortFallbackThreshold analog)
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.topK.threshold": 20})
        rng = np.random.default_rng(5)
        t = pa.table({"v": pa.array(rng.integers(0, 10**6, 300)),
                      "i": pa.array(range(300), type=pa.int64())})
        p = str(tmp_path / "thr.parquet")
        pq.write_table(t, p, row_group_size=64)
        s.initialize_device()
        from spark_rapids_tpu.exec.sort import TpuSortExec, TpuTopKExec
        from spark_rapids_tpu.plan.overrides import Overrides

        def find(node, cls):
            got = [node] if isinstance(node, cls) else []
            for c in getattr(node, "children", []):
                got.extend(find(c, cls))
            return got

        over = s.read_parquet(p).sort("v").limit(25)   # 25 > 20
        plan = Overrides(s.conf).apply(over.plan)
        assert not find(plan, TpuTopKExec)
        assert find(plan, TpuSortExec)
        assert over.collect().column("v").to_pylist() == \
            over.collect_cpu().column("v").to_pylist()

        under = s.read_parquet(p).sort("v").limit(15, offset=4)  # 19 <= 20
        plan = Overrides(s.conf).apply(under.plan)
        assert find(plan, TpuTopKExec)

    def test_dpp_skips_timestamp_keys(self, session, tmp_path):
        # advisor r3: footer stats for timestamp/date/decimal keys do not
        # compare reliably in the value domain — the planner must not wire
        # a filter for them (wrong pruning drops rows)
        base = np.datetime64("2023-01-01T00:00:00", "us")
        ts = base + np.arange(400).astype("timedelta64[s]")
        t = pa.table({"k": pa.array(ts), "v": pa.array(np.arange(400.0))})
        p = str(tmp_path / "ts.parquet")
        pq.write_table(t, p)
        fact = session.read_parquet(p)
        dim = session.from_arrow(pa.table({
            "k": pa.array(ts[:3]), "w": pa.array([1.0, 2.0, 3.0])}))
        df = fact.join(dim, on="k", how="inner")
        session.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        result = Overrides(session.conf).apply(df.plan)
        for scan in find_scans(result):
            assert not scan.dynamic_filters
        assert df.collect().num_rows == 3
