"""Whole-stage fusion suite (ISSUE-16): planner chains, golden fusion-
on/off bit-identity across chain shapes x types, ANSI error parity through
a fused stage, pallas kernel exactness, dispatch accounting, fused-first
warmup. `scripts/fusion_matrix.sh` runs these standalone and adds the
subprocess purity + dispatch-reduction gates."""

import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.errors import AnsiViolation
from spark_rapids_tpu.expr import Count, Divide, Sum, col, lit
from spark_rapids_tpu.plan.overrides import Overrides
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.utils.metrics import TaskMetrics

pytestmark = pytest.mark.fusion

FU = "spark.rapids.tpu.fusion.enabled"
PALLAS = "spark.rapids.tpu.fusion.pallas.mode"


@pytest.fixture(scope="module")
def sess_off():
    return TpuSession({"spark.rapids.sql.explain": "NONE"})


@pytest.fixture(scope="module")
def sess_on():
    return TpuSession({"spark.rapids.sql.explain": "NONE", FU: True})


@pytest.fixture(scope="module")
def sess_force():
    return TpuSession({"spark.rapids.sql.explain": "NONE", FU: True,
                       PALLAS: "force"})


def _mk_table(n=1500):
    import decimal
    rng = np.random.default_rng(7)
    return pa.table({
        "i64": pa.array([None if i % 13 == 0 else int(i % 700 - 350)
                         for i in range(n)], pa.int64()),
        "k": pa.array((np.arange(n) % 37).astype(np.int64)),
        "i32": pa.array(rng.integers(-100, 100, n), pa.int32()),
        "f64": pa.array(rng.normal(0, 50, n), pa.float64()),
        "s": pa.array([None if i % 11 == 0 else f"val{i % 23:02d}"
                       for i in range(n)]),
        "dec": pa.array([decimal.Decimal(int(v)).scaleb(-2) for v in
                         rng.integers(-10**6, 10**6, n)],
                        pa.decimal128(10, 2)),
    })


def _mk_dim(n=60):
    rng = np.random.default_rng(11)
    return pa.table({
        "k": pa.array(rng.permutation(80)[:n], pa.int64()),
        "w": pa.array(rng.integers(1, 9, n), pa.int64()),
    })


def _plan(sess, df):
    return Overrides(sess.conf).apply(df.plan)


def _sorted(t):
    if t.num_rows == 0:
        return t
    keys = [(n, "ascending") for n in t.schema.names
            if not pa.types.is_floating(t.schema.field(n).type)]
    return t.sort_by(keys) if keys else t


def _assert_on_off_equal(q_on, q_off, expect_fused=None):
    a, b = _sorted(q_on.collect()), _sorted(q_off.collect())
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    assert a.equals(b), f"fusion on/off mismatch:\nON:\n{a}\nOFF:\n{b}"
    if expect_fused is not None:
        assert ("TpuFusedStageExec" in expect_fused) \
            or not expect_fused, expect_fused
    return a


# --------------------------------------------------------------------------
class TestPlanner:
    def test_filter_project_fuses(self, sess_on, sess_off):
        df = sess_on.from_arrow(_mk_table())
        q = df.filter(col("i32") > 0).select(
            (col("k") * 2).alias("k2"), col("f64"))
        tree = _plan(sess_on, q).tree_string()
        assert "TpuFusedStageExec" in tree
        assert "TpuFilterExec" not in tree and "TpuProjectExec" not in tree
        # members render in the spec (kernel-key/fingerprint surface)
        assert "Filter[" in tree and "Project[" in tree

    def test_fusion_off_plans_byte_identical(self, sess_off):
        plain = TpuSession({"spark.rapids.sql.explain": "NONE"})
        for s in (sess_off, plain):
            assert not s.conf.get(FU)
        t = _mk_table()
        q = lambda s: s.from_arrow(t).filter(col("i32") > 0).select(  # noqa
            (col("k") + 1).alias("k1"))
        assert _plan(sess_off, q(sess_off)).tree_string() == \
            _plan(plain, q(plain)).tree_string()

    def test_min_ops_respected(self):
        s = TpuSession({"spark.rapids.sql.explain": "NONE", FU: True,
                        "spark.rapids.tpu.fusion.minOps": 3})
        df = s.from_arrow(_mk_table())
        q = df.filter(col("i32") > 0).select((col("k") * 2).alias("k2"))
        assert "TpuFusedStageExec" not in _plan(s, q).tree_string()
        q3 = df.filter(col("i32") > 0).filter(col("k") > 3).select(
            (col("k") * 2).alias("k2"))
        assert "TpuFusedStageExec" in _plan(s, q3).tree_string()

    def test_sort_breaks_chain(self, sess_on):
        df = sess_on.from_arrow(_mk_table())
        q = df.filter(col("i32") > 0).select(col("k"), col("f64")) \
            .sort("k").select((col("k") + 1).alias("k1"))
        tree = _plan(sess_on, q).tree_string()
        # below the sort: fused filter+project; above: a single project
        # (too short) stays unfused
        assert "TpuFusedStageExec" in tree
        assert "TpuSortExec" in tree and "TpuProjectExec" in tree

    def test_broadcast_join_chain_fuses(self, sess_on):
        fact = sess_on.from_arrow(_mk_table())
        dim = sess_on.from_arrow(_mk_dim())
        q = fact.select(col("k"), (col("i32") + 1).alias("v")) \
            .join(dim, on="k", how="inner") \
            .select((col("v") + col("w")).alias("x"))
        tree = _plan(sess_on, q).tree_string()
        assert "TpuFusedStageExec" in tree
        assert "BroadcastHashJoin[inner" in tree
        assert "TpuBroadcastExchangeExec" in tree  # build stays a child

    def test_spec_distinguishes_params(self, sess_on):
        # two chains differing only in a literal must not alias (the
        # PR-3/PR-9 repr discipline for the fused kernel key)
        df = sess_on.from_arrow(_mk_table())
        t1 = _plan(sess_on, df.filter(col("i32") > 0)
                   .select((col("k") * 2).alias("k2")))
        t2 = _plan(sess_on, df.filter(col("i32") > 1)
                   .select((col("k") * 2).alias("k2")))
        assert t1.spec != t2.spec
        assert repr(t1.spec) != repr(t2.spec)


# --------------------------------------------------------------------------
class TestGoldenEquality:
    """Bit-identical results with fusion on vs off across chain shapes
    and types (int/decimal/string/nullable)."""

    SHAPES = [
        ("filter_project_int", lambda df: df.filter(col("i32") > 0)
         .select((col("k") * 2).alias("k2"), (col("i64") + 1).alias("i"))),
        ("filter_project_decimal", lambda df: df.filter(col("i32") > 0)
         .select(col("dec"), col("k"))),
        ("filter_project_string", lambda df: df.filter(col("s") == "val07")
         .select(col("s"), col("k"))),
        ("filter_project_nullable", lambda df: df.filter(
            col("i64").is_not_null()).select(col("i64"), col("s"))),
        ("double_filter", lambda df: df.filter(col("i32") > -50)
         .filter(col("k") < 30).select(col("k"), col("i32"))),
        ("empty_result", lambda df: df.filter(col("i32") > 1000)
         .select((col("k") + 1).alias("k1"))),
    ]

    @pytest.mark.parametrize("name,build", SHAPES,
                             ids=[s[0] for s in SHAPES])
    def test_shapes(self, sess_on, sess_off, name, build):
        t = _mk_table()
        q_on = build(sess_on.from_arrow(t))
        q_off = build(sess_off.from_arrow(t))
        assert "TpuFusedStageExec" in _plan(sess_on, q_on).tree_string()
        _assert_on_off_equal(q_on, q_off)

    @pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
    def test_join_chain(self, sess_on, sess_off, how):
        t, d = _mk_table(), _mk_dim()

        def build(s):
            fact = s.from_arrow(t)
            dim = s.from_arrow(d)
            q = fact.select(col("k"), (col("i32") + 1).alias("v")) \
                .join(dim, on="k", how=how)
            if how in ("semi", "anti"):
                return q.select((col("v") * 2).alias("x"))
            return q.select((col("v") + col("w")).alias("x"))

        q_on = build(sess_on)
        assert "TpuFusedStageExec" in _plan(sess_on, q_on).tree_string()
        _assert_on_off_equal(q_on, build(sess_off))

    def test_join_chain_pallas_force(self, sess_force, sess_off):
        t, d = _mk_table(), _mk_dim()

        def build(s):
            return s.from_arrow(t) \
                .select(col("k"), (col("i32") + 1).alias("v")) \
                .join(s.from_arrow(d), on="k", how="inner") \
                .select((col("v") + col("w")).alias("x"))

        _assert_on_off_equal(build(sess_force), build(sess_off))

    def test_residual_filter_after_pushdown(self, tmp_path, sess_off):
        p = str(tmp_path / "t.parquet")
        pq.write_table(_mk_table(), p, row_group_size=500)
        pd_key = "spark.rapids.tpu.scan.pushdown.enabled"
        s_on = TpuSession({"spark.rapids.sql.explain": "NONE", FU: True,
                           pd_key: True})
        s_off = TpuSession({"spark.rapids.sql.explain": "NONE"})

        def build(s):
            # one pushable conjunct + one residual, then a projection: the
            # residual filter and the project fuse ABOVE the pushed scan
            return s.read_parquet(p).filter(
                (col("k") < 30) & (col("k") + 0 < 25)).select(
                col("k"), (col("i64") * 2).alias("i2"))

        tree = _plan(s_on, build(s_on)).tree_string()
        assert "TpuFusedStageExec" in tree
        _assert_on_off_equal(build(s_on), build(s_off))


# --------------------------------------------------------------------------
class TestPartialAggHead:
    """A stage-terminal partial aggregate fuses; partial->final results
    are identical to the unfused split (batch-level identity-partial
    extras merge away in the final)."""

    def _split_tree(self, s, t):
        from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
        df = s.from_arrow(t)
        q = df.filter(col("i32") > 0).group_by("k").agg(
            sv=Sum(col("i64")), c=Count(col("i64")))
        node = _plan(s, q)
        assert isinstance(node, TpuHashAggregateExec) \
            and node.mode == "complete"
        child = node.children[0]
        partial = TpuHashAggregateExec(node.group_exprs, node.aggs, child,
                                       s.conf, mode="partial")
        return TpuHashAggregateExec(node.group_exprs, node.aggs, partial,
                                    s.conf, mode="final",
                                    agg_bind_schema=child.output)

    def _collect(self, tree):
        from spark_rapids_tpu.columnar.batch import batch_to_arrow
        return pa.concat_tables(
            [batch_to_arrow(b) for b in tree.execute()]).sort_by(
            [("k", "ascending")])

    @pytest.mark.parametrize("pallas", ["off", "force"])
    def test_fused_partial_agg_identical(self, pallas):
        from spark_rapids_tpu.plan.fusion import apply_fusion
        t = _mk_table()
        base_s = TpuSession({"spark.rapids.sql.explain": "NONE"})
        base = self._collect(self._split_tree(base_s, t))
        s = TpuSession({"spark.rapids.sql.explain": "NONE", FU: True,
                        PALLAS: pallas})
        fused = apply_fusion(self._split_tree(s, t), s.conf)
        ts = fused.tree_string()
        assert "TpuFusedStageExec" in ts and "PartialAgg[" in ts
        out = self._collect(fused)
        assert out.equals(base), f"pallas={pallas}\n{out}\nvs\n{base}"


# --------------------------------------------------------------------------
class TestAnsiParity:
    def test_fused_error_message_matches_unfused(self):
        t = pa.table({"a": pa.array([4, 0, 7], pa.int64()),
                      "b": pa.array([2, 3, 9], pa.int64())})
        msgs = []
        for extra in ({}, {FU: True}):
            s = TpuSession(dict({"spark.rapids.sql.explain": "NONE",
                                 "spark.sql.ansi.enabled": True}, **extra))
            df = s.from_arrow(t)
            q = df.filter(col("b") > 0).select(
                Divide(lit(10), col("a")).alias("x"))
            if extra:
                assert "TpuFusedStageExec" in _plan(s, q).tree_string()
            with pytest.raises(AnsiViolation) as ei:
                q.collect()
            msgs.append(str(ei.value))
        assert msgs[0] == msgs[1], f"ANSI parity broke: {msgs}"

    def test_fused_no_error_when_clean(self):
        t = pa.table({"a": pa.array([4, 2, 7], pa.int64())})
        s = TpuSession({"spark.rapids.sql.explain": "NONE",
                        "spark.sql.ansi.enabled": True, FU: True})
        out = s.from_arrow(t).filter(col("a") > 1).select(
            Divide(lit(8), col("a")).alias("x")).collect()
        assert out.num_rows == 3


# --------------------------------------------------------------------------
class TestPallasKernels:
    """Bit-exactness of the two fused inner-loop kernels against their
    stock jnp lowerings (interpret mode on CPU)."""

    def test_hash_parity_int_long_nullable(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.exec.base import batch_vecs
        from spark_rapids_tpu.expr.hashing import hash_vecs
        from spark_rapids_tpu.ops.pallas_probe import hash_vecs_pallas
        t = pa.table({
            "i": pa.array([None if i % 7 == 0 else int(i * 31 - 4000)
                           for i in range(300)], pa.int32()),
            "l": pa.array([None if i % 5 == 0 else int(i * 10**14 - 2**50)
                           for i in range(300)], pa.int64()),
        })
        vecs = batch_vecs(batch_from_arrow(t))
        a = np.asarray(hash_vecs(jnp, vecs))
        b = np.asarray(hash_vecs_pallas(jnp, vecs))
        assert (a == b).all(), "pallas murmur3 diverged from expr.hashing"

    def test_candidate_counts_match_probe_counts(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar import batch_from_arrow
        from spark_rapids_tpu.exec.base import batch_vecs
        from spark_rapids_tpu.exec.joins import _probe_counts
        from spark_rapids_tpu.ops.pallas_probe import candidate_counts
        rng = np.random.default_rng(3)
        probe = batch_from_arrow(pa.table({
            "k": pa.array([None if i % 9 == 0 else int(v) for i, v in
                           enumerate(rng.integers(0, 50, 400))],
                          pa.int64())}))
        build = batch_from_arrow(pa.table({
            "k": pa.array([None if i % 6 == 0 else int(v) for i, v in
                           enumerate(rng.integers(0, 50, 80))],
                          pa.int64())}))
        ref = np.asarray(_probe_counts.fn(probe, build, (0,), (0,))[0])
        got = np.asarray(candidate_counts(
            jnp, batch_vecs(probe), batch_vecs(build),
            probe.row_mask(), build.row_mask()))
        assert (ref == got).all()

    def test_segment_sum_exact_and_fallback(self):
        import jax
        import jax.numpy as jnp

        from spark_rapids_tpu.ops.pallas_groupby import (MAX_SEGMENTS,
                                                         fused_segment_sum)
        rng = np.random.default_rng(4)
        n, cap = 5000, 77
        vals = jnp.asarray(rng.integers(-2**62, 2**62, n), jnp.int64)
        gid = jnp.asarray(rng.integers(0, cap, n), jnp.int32)
        ref = np.asarray(jax.ops.segment_sum(vals, gid, num_segments=cap))
        got = np.asarray(fused_segment_sum(vals, gid, cap))
        assert (ref == got).all(), "pallas segment sum diverged (wrap/exact)"
        # above MAX_SEGMENTS the wrapper must fall back, still exact
        big = MAX_SEGMENTS + 100
        ref2 = np.asarray(jax.ops.segment_sum(vals, gid, num_segments=big))
        got2 = np.asarray(fused_segment_sum(vals, gid, big))
        assert (ref2 == got2).all()


# --------------------------------------------------------------------------
class TestDispatchAccounting:
    def _run(self, extra, t, d):
        s = TpuSession(dict({"spark.rapids.sql.explain": "NONE"}, **extra))
        q = s.from_arrow(t) \
            .select(col("k"), (col("i32") + 1).alias("v")) \
            .join(s.from_arrow(d), on="k", how="inner") \
            .select((col("v") + col("w")).alias("x"))
        TaskMetrics.reset()
        out = q.collect()
        return out, TaskMetrics.get()

    def test_fusion_reduces_dispatches(self):
        t, d = _mk_table(), _mk_dim()
        out_off, tm_off = self._run({}, t, d)
        out_on, tm_on = self._run({FU: True}, t, d)
        assert _sorted(out_on).equals(_sorted(out_off))
        assert tm_off.device_dispatches > 0
        assert tm_on.device_dispatches * 2 <= tm_off.device_dispatches, (
            f"fused {tm_on.device_dispatches} vs "
            f"unfused {tm_off.device_dispatches}")
        assert tm_on.fused_stages >= 1
        assert tm_on.fused_ops >= 3
        assert tm_off.fused_stages == 0 and tm_off.fused_ops == 0
        es = tm_on.explain_string()
        assert "deviceDispatches=" in es and "fusedStages=" in es

    def test_profile_fusion_summary(self):
        from spark_rapids_tpu.tools.profile_report import fusion_summary
        model = {"queries": [
            {"task_metrics": {"device_dispatches": 4, "fused_stages": 2,
                              "fused_ops": 6}},
            {"task_metrics": {"device_dispatches": 9}},  # non-fusing query
        ]}
        fu = fusion_summary(model)
        assert fu == {"queries": 1, "fused_stages": 2, "fused_ops": 6,
                      "device_dispatches": 4, "dispatches_per_query": 4.0}
        assert fusion_summary({"queries": []}) == {}


# --------------------------------------------------------------------------
class TestWarmupFused:
    def test_fused_programs_preload_first(self, tmp_path):
        from spark_rapids_tpu.compile import (CompileService, run_warmup)
        CompileService.reset()
        try:
            s = TpuSession({"spark.rapids.sql.explain": "NONE", FU: True,
                            "spark.rapids.tpu.compile.cache.dir":
                                str(tmp_path / "xla_cache")})
            s.initialize_device()
            svc = CompileService.get()
            df = s.from_arrow(_mk_table())
            df.filter(col("i32") > 0).select(
                (col("k") * 2).alias("k2")).collect()
            metas = [svc.persisted_meta(dg) for dg in
                     svc.persisted_entries()]
            assert any(m and m.get("op") == "exec.fused_stage"
                       for m in metas), "fused stage was not persisted"
            svc.clear_memory()
            stats = run_warmup(s.conf, svc)
            assert stats["fused"] >= 1
            assert stats["preloaded"] >= stats["fused"]
        finally:
            CompileService.reset()


# --------------------------------------------------------------------------
class TestOffPurity:
    def test_fusion_off_imports_nothing(self):
        """Fusion off must never import the fusion modules (subprocess:
        this pytest process imports them for the other tests)."""
        code = (
            "import sys\n"
            "import pyarrow as pa\n"
            "from spark_rapids_tpu.plugin import TpuSession\n"
            "from spark_rapids_tpu.expr import col\n"
            "s = TpuSession({'spark.rapids.sql.explain': 'NONE'})\n"
            "t = pa.table({'a': pa.array(range(100), pa.int64())})\n"
            "out = s.from_arrow(t).filter(col('a') > 5)"
            ".select((col('a') * 2).alias('b')).collect()\n"
            "assert out.num_rows == 94\n"
            "bad = [m for m in sys.modules if m.startswith("
            "'spark_rapids_tpu') and ('fusion' in m or 'fused' in m"
            " or 'pallas_probe' in m or 'pallas_groupby' in m)]\n"
            "assert not bad, f'fusion modules leaked: {bad}'\n"
            "print('PURE')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PURE" in r.stdout
