"""Plan-driven distributed execution over the mesh.

The engine (not a demo): planned queries route their exchanges through the
compiled ICI all_to_all (exec/exchange.py _exchange_via_mesh), joins zip
co-partitioned shards, grouped aggregates run partial -> key-exchange ->
per-shard final (exec/requirements.py). Every test compares the 8-virtual-
device mesh run against the CPU engine (SparkQueryCompareTestSuite model) and
asserts the collective data plane actually executed."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import Average, Count, Max, Min, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.exec import exchange as EX

from test_queries import assert_same, make_table

NDEV = 8


@pytest.fixture(scope="module")
def session():
    # broadcast joins are disabled here ON PURPOSE: these tests pin the
    # shuffled-exchange path (small dims would otherwise broadcast and skip
    # the mesh collective); TestMeshBroadcastJoin covers the broadcast path
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE",
                       "spark.rapids.shuffle.mode": "ICI",
                       "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
                       "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}"})


@pytest.fixture(autouse=True)
def _track_mesh(session):
    before = EX.MESH_EXCHANGES
    yield
    assert EX.MESH_EXCHANGES > before, \
        "query did not execute any mesh collective"


def make_dim(rng, n=200):
    keys = rng.permutation(400)[:n]
    return pa.table({
        "id": pa.array(keys, type=pa.int64()),
        "w": pa.array(rng.uniform(0.5, 1.5, n), type=pa.float64()),
        "tag": pa.array([f"t{k % 7}" for k in keys]),
    })


class TestMeshJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                     "semi", "anti"])
    def test_join_types_on_mesh(self, session, rng, how):
        fact = session.from_arrow(make_table(rng, n=800))
        dim = session.from_arrow(make_dim(rng))
        q = fact.join(dim, on="id", how=how)
        sort_cols = ["id", "val"] if how in ("semi", "anti") else ["id", "val", "w"]
        assert_same(q, sort_by=sort_cols)

    def test_join_then_groupby_on_mesh(self, session, rng):
        """The flagship shape (BASELINE workload #1): join + grouped agg, all
        exchanges riding the mesh collective."""
        fact = session.from_arrow(make_table(rng, n=1500))
        dim = session.from_arrow(make_dim(rng))
        q = (fact.join(dim, on="id", how="inner")
             .group_by("tag")
             .agg(n=Count(col("val")), s=Sum(col("small")),
                  mx=Max(col("val")), mn=Min(col("val"))))
        assert_same(q, sort_by=["tag"], approx_cols=("s",))


class TestMeshAggregate:
    def test_groupby_on_mesh(self, session, rng):
        df = session.from_arrow(make_table(rng, n=2000))
        q = df.group_by("id").agg(
            n=Count(col("val")), total=Sum(col("small")),
            lo=Min(col("val")), hi=Max(col("val")), avg=Average(col("val")))
        assert_same(q, sort_by=["id"], approx_cols=("total", "avg"))

    def test_groupby_string_key_on_mesh(self, session, rng):
        df = session.from_arrow(make_table(rng, n=700))
        q = df.group_by("cat").agg(n=Count(col("id")), mx=Max(col("small")))
        assert_same(q, sort_by=["cat"])

    def test_filter_project_join_agg_pipeline(self, session, rng):
        fact = session.from_arrow(make_table(rng, n=1200))
        dim = session.from_arrow(make_dim(rng))
        q = (fact.filter(col("small") > -50)
             .select(col("id"), (col("val") * 2).alias("v2"), col("small"))
             .join(dim, on="id", how="inner")
             .group_by("tag")
             .agg(n=Count(col("v2")), s=Sum(col("v2"))))
        assert_same(q, sort_by=["tag"], approx_cols=("s",))


class TestMeshLongStrings:
    def test_long_string_column_rides_the_collective(self, session, rng):
        """Round-4: overflow (chunked long-string) columns no longer fall
        the whole exchange back to host — heads/lengths move with the row
        plane and tail blobs through a second byte-plane all_to_all; the
        arriving stream realigns by cumsum (exec/exchange.py
        _exchange_tail_bytes)."""
        n = 600
        ids = rng.integers(0, 40, n)
        payload = [("L%d-" % i) + "x" * int(rng.integers(300, 2500))
                   if i % 5 == 0 else f"short-{i}" for i in range(n)]
        fact = session.from_arrow(pa.table({
            "id": pa.array(ids, type=pa.int64()),
            "s": pa.array(payload),
        }))
        dim = session.from_arrow(make_dim(rng, n=40))
        q = fact.join(dim, on="id", how="inner")
        out = assert_same(q, sort_by=["id", "s"])
        # the long payloads really crossed the wire intact
        longs = [s for s in out.column("s").to_pylist() if len(s) > 256]
        assert longs and all(s.startswith("L") and s.endswith("x")
                             for s in longs)

    def test_long_string_groupby_key_exchange(self, session, rng):
        n = 400
        payload = ["k%d" % (i % 7) + "y" * int(rng.integers(400, 1200))
                   for i in range(n)]
        df = session.from_arrow(pa.table({
            "g": pa.array((np.arange(n) % 7).astype(np.int64)),
            "s": pa.array(payload),
            "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        }))
        q = df.group_by("g").agg(n=Count(col("s")), s=Sum(col("v")))
        assert_same(q, sort_by=["g"])


class TestOverflowRetry:
    def test_skewed_slot_overflow_retries_not_drops(self, rng):
        """All rows share one key -> they all land on one device. A bounded
        slot overflows; the on-device flag must trigger retry with a larger
        slot, never dropping rows (the reference can never drop shuffle
        rows)."""
        sess = TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.shuffle.mode": "ICI",
                           "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}",
                           "spark.rapids.shuffle.ici.slotRows": 16})
        n = 600
        t = pa.table({
            "id": pa.array(np.full(n, 7), type=pa.int64()),
            "val": pa.array(rng.normal(0, 1, n), type=pa.float64()),
        })
        df = sess.from_arrow(t)
        q = df.group_by("id").agg(n=Count(col("val")), s=Sum(col("val")))
        out = q.collect()
        assert out.num_rows == 1
        assert out.column("n").to_pylist() == [n]
        np.testing.assert_allclose(
            out.column("s").to_pylist()[0],
            float(np.sum(t.column("val").to_numpy())), rtol=1e-9)


class TestMeshBroadcastJoin:
    """Broadcast joins in mesh mode: the build side replicates (no mesh
    exchange needed for the join itself); a grouped agg downstream still
    rides the collective."""

    @pytest.fixture(scope="class")
    def bsession(self):
        return TpuSession({"spark.rapids.sql.enabled": True,
                           "spark.rapids.sql.explain": "NONE",
                           "spark.rapids.shuffle.mode": "ICI",
                           "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}"})

    def test_broadcast_join_groupby_on_mesh(self, bsession, rng):
        from spark_rapids_tpu.plan.overrides import Overrides
        fact = bsession.from_arrow(make_table(rng, n=800))
        dim = bsession.from_arrow(make_dim(rng))
        q = (fact.join(dim, on="id", how="inner")
                 .group_by("tag").agg(s=Sum(col("val") * col("w")),
                                      c=Count(lit(1))))
        tree = Overrides(bsession.conf).apply(q.plan).tree_string()
        assert "TpuBroadcastExchangeExec" in tree
        before = EX.MESH_EXCHANGES
        assert_same(q, sort_by=["tag"], approx_cols=("s",))
        assert EX.MESH_EXCHANGES > before  # the groupby exchange still rode ICI


class TestZippedJoinStreaming:
    def test_incremental_shard_consumption(self, session, rng):
        """The co-partitioned (zipped) join must consume shard batches
        incrementally — one probe + one build live at a time — instead of
        list()-ing both children (round-2 verdict weak item #4)."""
        from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec

        df = session.from_arrow(make_table(rng, n=800)).join(
            session.from_arrow(make_dim(rng)), on="id", how="inner")
        session.initialize_device()
        from spark_rapids_tpu.plan.overrides import Overrides
        result = Overrides(session.conf).apply(df.plan)

        def find_zip(node):
            if isinstance(node, TpuShuffledHashJoinExec) and \
                    node.zip_partitions:
                return node
            for c in getattr(node, "children", []):
                got = find_zip(c)
                if got is not None:
                    return got
            return None

        join = find_zip(result)
        assert join is not None, "mesh plan did not produce a zipped join"

        # instrument both children: track how many batches each produced
        # before the join yielded its first output
        produced = {"probe": 0, "build": 0, "first_out": None}

        def wrap(child, label):
            orig = child.execute

            def counting():
                for b in orig():
                    produced[label] += 1
                    yield b
            child.execute = counting

        wrap(join.children[0], "probe")
        wrap(join.children[1], "build")
        out_iter = join.execute()
        first = next(out_iter, None)
        assert first is not None
        # incremental: the first output must appear after at most ONE
        # build shard and ONE probe shard (plus pipeline lookahead), not
        # after the full 8-shard streams were materialized
        assert produced["build"] <= 2, produced
        assert produced["probe"] <= 2, produced
        rest = list(out_iter)
        total = int(first.row_count()) + \
            sum(int(b.row_count()) for b in rest)
        cpu_rows = df.collect_cpu().num_rows
        assert total == cpu_rows
