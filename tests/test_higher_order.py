"""Higher-order functions: lambdas over the flattened element space
(reference higherOrderFunctions.scala, GpuOverrides.scala:2629-2810).
Differential device-vs-CPU plus python oracles, incl. nested lambdas,
captured outer columns, and Spark null semantics."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import AnsiViolation
from spark_rapids_tpu.expr import (ArrayAggregate, ArrayExists, ArrayFilter,
                                   ArrayForAll, ArrayTransform, MapFilter,
                                   Size, TransformKeys, TransformValues,
                                   ZipWith, col, lit)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def arr_table(n=200, seed=3):
    rng = np.random.default_rng(seed)
    arrs = []
    for _ in range(n):
        r = rng.random()
        if r < 0.1:
            arrs.append(None)
        elif r < 0.18:
            arrs.append([])
        else:
            arrs.append([None if rng.random() < 0.12 else
                         int(rng.integers(-50, 50))
                         for _ in range(rng.integers(1, 7))])
    return pa.table({
        "a": pa.array(arrs, type=pa.list_(pa.int64())),
        "y": pa.array([int(v) for v in rng.integers(1, 10, n)],
                      type=pa.int64()),
        "i": pa.array(range(n), type=pa.int64()),
    }), arrs


class TestTransform:
    def test_basic(self, session):
        t, arrs = arr_table()
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayTransform(col("a"),
                                            lambda x: x * lit(2)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for got, a in zip(out.column("o").to_pylist(), arrs):
            want = None if a is None else [None if v is None else v * 2
                                           for v in a]
            assert got == want

    def test_with_index_and_capture(self, session):
        t, arrs = arr_table(seed=5)
        ys = t.column("y").to_pylist()
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayTransform(
            col("a"), lambda x, i: x + i * col("y")))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for got, a, y in zip(out.column("o").to_pylist(), arrs, ys):
            want = None if a is None else [
                None if v is None else v + j * y for j, v in enumerate(a)]
            assert got == want

    def test_nested_lambda(self, session):
        # transform over array<array<int>>: inner lambda inside outer
        arrs = [[[1, 2], [3]], None, [[], [4, None]]]
        t = pa.table({"a": pa.array(arrs,
                                    pa.list_(pa.list_(pa.int64()))),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayTransform(
            col("a"),
            lambda inner: ArrayTransform(inner, lambda x: x + lit(10))))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == [[11, 12], [13]]
        assert got[1] is None
        assert got[2] == [[], [14, None]]

    def test_string_result(self, session):
        from spark_rapids_tpu.expr import Concat
        arrs = [["ab", None, "c"], [], None]
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.string())),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayTransform(
            col("a"), lambda x: Concat(x, lit("!"))))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == ["ab!", None, "c!"]
        assert got[1] == [] and got[2] is None


class TestPredicates:
    def test_exists_three_valued(self, session):
        arrs = [[1, 2, 3], [None, 1], [None, 5], [], None, [None]]
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.int64())),
                      "i": pa.array(range(6), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", e=ArrayExists(col("a"), lambda x: x > lit(2)),
                      f=ArrayForAll(col("a"), lambda x: x > lit(0)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        rows = out.to_pylist()
        # exists(x>2): [T,F,..], row1: none true, has null -> NULL
        assert [r["e"] for r in rows] == [True, None, True, False, None,
                                          None]
        # forall(x>0): row0 all>0 T; row1 has 1>0 but null -> NULL;
        # row2 5>0, null -> NULL; [] -> T; null arr -> NULL; [None]->NULL
        assert [r["f"] for r in rows] == [True, None, None, True, None,
                                          None]

    def test_filter(self, session):
        t, arrs = arr_table(seed=7)
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayFilter(col("a"), lambda x: x % lit(2) ==
                                         lit(0)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for got, a in zip(out.column("o").to_pylist(), arrs):
            want = None if a is None else [v for v in a
                                           if v is not None and v % 2 == 0]
            assert got == want


class TestAggregateAndZip:
    def test_aggregate_sum(self, session):
        t, arrs = arr_table(seed=9)
        df = session.from_arrow(t)
        q = df.select("i", s=ArrayAggregate(
            col("a"), lit(0, T.LONG), lambda acc, x: acc + x))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for got, a in zip(out.column("s").to_pylist(), arrs):
            if a is None:
                assert got is None
            elif any(v is None for v in a):
                assert got is None  # null element poisons the + chain
            else:
                assert got == sum(a)

    def test_aggregate_with_finish(self, session):
        arrs = [[1, 2, 3], [], [10]]
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.int64())),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", s=ArrayAggregate(
            col("a"), lit(0, T.LONG), lambda acc, x: acc + x,
            lambda acc: acc * lit(10)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        assert out.column("s").to_pylist() == [60, 0, 100]

    def test_zip_with(self, session):
        la = [[1, 2, 3], [1], None, [5]]
        ra = [[10, 20], [7, 8], [1], None]
        t = pa.table({"l": pa.array(la, pa.list_(pa.int64())),
                      "r": pa.array(ra, pa.list_(pa.int64())),
                      "i": pa.array(range(4), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", z=ZipWith(col("l"), col("r"),
                                     lambda x, y: x + y))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("z").to_pylist()
        assert got[0] == [11, 22, None]  # zips to the longer side
        assert got[1] == [8, None]
        assert got[2] is None and got[3] is None


class TestMapHofs:
    MT = pa.map_(pa.string(), pa.int64())

    def table(self):
        maps = [{"a": 1, "b": 2}, None, {"c": None, "d": 4}, {}]
        return pa.table({"m": pa.array(maps, self.MT),
                         "i": pa.array(range(4), type=pa.int64())}), maps

    def test_transform_values(self, session):
        t, maps = self.table()
        df = session.from_arrow(t)
        q = df.select("i", o=TransformValues(col("m"),
                                             lambda k, v: v * lit(10)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == [("a", 10), ("b", 20)]
        assert got[1] is None
        assert got[2] == [("c", None), ("d", 40)]
        assert got[3] == []

    def test_transform_keys(self, session):
        from spark_rapids_tpu.expr import Concat
        t, maps = self.table()
        df = session.from_arrow(t)
        q = df.select("i", o=TransformKeys(
            col("m"), lambda k, v: Concat(k, lit("_"))))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == [("a_", 1), ("b_", 2)]

    def test_transform_keys_dup_raises(self, session):
        t, _ = self.table()
        df = session.from_arrow(t).select(
            o=TransformKeys(col("m"), lambda k, v: lit("same")))
        with pytest.raises(AnsiViolation, match="DUPLICATED_MAP_KEY"):
            df.collect()
        with pytest.raises(AnsiViolation, match="DUPLICATED_MAP_KEY"):
            df.collect_cpu()

    def test_map_filter(self, session):
        t, maps = self.table()
        df = session.from_arrow(t)
        q = df.select("i", o=MapFilter(col("m"), lambda k, v: v > lit(1)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == [("b", 2)]
        assert got[1] is None
        assert got[2] == [("d", 4)]  # null predicate drops the entry
        assert got[3] == []

    def test_chained_hof_pipeline(self, session):
        # exercise HOF composition end-to-end: filter then transform then
        # size, mixed with an ordinary filter on the result
        t, arrs = arr_table(seed=13)
        df = session.from_arrow(t)
        q = (df.select("i", o=ArrayTransform(
                ArrayFilter(col("a"), lambda x: x > lit(0)),
                lambda x: x * x))
               .select("i", "o", n=Size(col("o")))
               .filter(col("n") > lit(1)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        for r in out.to_pylist():
            a = arrs[r["i"]]
            want = [v * v for v in a if v is not None and v > 0]
            assert r["o"] == want and len(want) > 1


class TestReviewRegressions:
    def test_hof_under_untaken_ansi_branch(self):
        # a HOF inside an IF branch taken for zero rows must not raise
        # that branch's ANSI errors (row_mask inheritance through the
        # flattened element space)
        from spark_rapids_tpu.expr import If, IntegralDivide
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.sql.ansi.enabled": True})
        t = pa.table({"a": pa.array([[1, 2]], pa.list_(pa.int64()))})
        df = s.from_arrow(t).select(o=If(
            lit(False),
            Size(ArrayTransform(col("a"),
                                lambda x: IntegralDivide(x, lit(0)))),
            Size(col("a"))))
        assert df.collect().column("o").to_pylist() == [2]
        assert df.collect_cpu().column("o").to_pylist() == [2]

    def test_empty_map_concat(self, session):
        from spark_rapids_tpu.expr import MapConcat
        t = pa.table({"i": pa.array(range(2), type=pa.int64())})
        df = session.from_arrow(t)
        out = assert_same(df.select("i", m=MapConcat([])), sort_by=["i"])
        assert out.sort_by([("i", "ascending")]).column("m").to_pylist() \
            == [[], []]

    def test_create_map_nested_values_fall_back(self, session):
        # map() of nested exprs: tagged off device, host path must answer
        from spark_rapids_tpu.expr import CreateArray, CreateMap
        t = pa.table({"a": pa.array([1, 2], type=pa.int64())})
        df = session.from_arrow(t).select(
            m=CreateMap([lit("k"), CreateArray([col("a")])]))
        got = df.collect_cpu().column("m").to_pylist()
        assert got == [[("k", [1])], [("k", [2])]]

    def test_nested_lambda_outer_var_capture(self, session):
        # inner body references the OUTER lambda variable: it must
        # broadcast into the inner element space like captured columns
        from spark_rapids_tpu.expr import GetArrayItem
        arrs = [[[1, 2], [10]], [[5]], None]
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.list_(pa.int64()))),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", o=ArrayTransform(
            col("a"),
            lambda row: ArrayTransform(
                row, lambda x: x + GetArrayItem(row, lit(0, T.INT)))))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.column("o").to_pylist()
        assert got[0] == [[2, 3], [20]]   # + row[0] (1 then 10)
        assert got[1] == [[10]]
        assert got[2] is None

    def test_aggregate_unresolved_zero_column(self, session):
        # zero expr as an unresolved column: acc typing defers to binding
        arrs = [[1, 2], [3]]
        t = pa.table({"a": pa.array(arrs, pa.list_(pa.int64())),
                      "z": pa.array([100, 200], type=pa.int64()),
                      "i": pa.array(range(2), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", s=ArrayAggregate(col("a"), col("z"),
                                            lambda acc, x: acc + x))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        assert out.column("s").to_pylist() == [103, 203]
