"""Randomized expression fuzz vs a row-wise pure-python oracle.

The differential harness runs the same vectorized implementations under
two array namespaces; this fuzzer is the third, structurally independent
implementation: every generated expression tree is ALSO evaluated one
row at a time in plain python (None propagation by hand, int64 wrap via
masking) and the engine must match it exactly. Catches
wrong-but-consistent vectorized semantics the device-vs-CPU diff cannot
see (r3 verdict weak #2; the role real Spark plays for the reference's
integration tests)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (Add, And, CaseWhen, Coalesce, EqualTo,
                                   GreaterThan, If, IsNull, LessThan,
                                   Multiply, Not, Or, Subtract, col, lit)
from spark_rapids_tpu.plugin import TpuSession

N_ROWS = 200
N_TREES = 30
COLS = ("a", "b", "c")


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def _wrap64(v: int) -> int:
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


class _Node:
    """(engine expression, python row evaluator, result kind)."""

    def __init__(self, expr, fn, kind):
        self.expr, self.fn, self.kind = expr, fn, kind


def _gen_int(rng, depth):
    roll = rng.random()
    if depth <= 0 or roll < 0.25:
        if rng.random() < 0.6:
            name = COLS[rng.integers(0, len(COLS))]
            return _Node(col(name), lambda r, n=name: r[n], "int")
        v = int(rng.integers(-1000, 1000))
        return _Node(lit(v), lambda r, v=v: v, "int")
    if roll < 0.75:
        op = rng.integers(0, 3)
        x = _gen_int(rng, depth - 1)
        y = _gen_int(rng, depth - 1)
        cls, pyop = [(Add, lambda p, q: p + q),
                     (Subtract, lambda p, q: p - q),
                     (Multiply, lambda p, q: p * q)][op]

        def f(r, x=x, y=y, pyop=pyop):
            p, q = x.fn(r), y.fn(r)
            if p is None or q is None:
                return None
            return _wrap64(pyop(p, q))  # non-ANSI int64 wrap semantics

        return _Node(cls(x.expr, y.expr), f, "int")
    if roll < 0.9:
        c = _gen_bool(rng, depth - 1)
        x = _gen_int(rng, depth - 1)
        y = _gen_int(rng, depth - 1)

        def f(r, c=c, x=x, y=y):
            cv = c.fn(r)
            return x.fn(r) if cv is True else y.fn(r)

        return _Node(If(c.expr, x.expr, y.expr), f, "int")
    xs = [_gen_int(rng, depth - 1) for _ in range(3)]

    def f(r, xs=xs):
        for x in xs:
            v = x.fn(r)
            if v is not None:
                return v
        return None

    return _Node(Coalesce(*[x.expr for x in xs]), f, "int")


def _gen_bool(rng, depth):
    roll = rng.random()
    if depth <= 0 or roll < 0.4:
        x = _gen_int(rng, max(depth - 1, 0))
        y = _gen_int(rng, max(depth - 1, 0))
        op = rng.integers(0, 3)
        cls, pyop = [(LessThan, lambda p, q: p < q),
                     (GreaterThan, lambda p, q: p > q),
                     (EqualTo, lambda p, q: p == q)][op]

        def f(r, x=x, y=y, pyop=pyop):
            p, q = x.fn(r), y.fn(r)
            if p is None or q is None:
                return None
            return pyop(p, q)

        return _Node(cls(x.expr, y.expr), f, "bool")
    if roll < 0.6:
        x = _gen_int(rng, depth - 1)
        return _Node(IsNull(x.expr),
                     lambda r, x=x: x.fn(r) is None, "bool")
    if roll < 0.8:
        x = _gen_bool(rng, depth - 1)

        def f(r, x=x):
            v = x.fn(r)
            return None if v is None else not v

        return _Node(Not(x.expr), f, "bool")
    x = _gen_bool(rng, depth - 1)
    y = _gen_bool(rng, depth - 1)
    if rng.random() < 0.5:
        # SQL three-valued AND: F & anything = F; N & T = N
        def f(r, x=x, y=y):
            p, q = x.fn(r), y.fn(r)
            if p is False or q is False:
                return False
            if p is None or q is None:
                return None
            return True

        return _Node(And(x.expr, y.expr), f, "bool")

    def f(r, x=x, y=y):
        p, q = x.fn(r), y.fn(r)
        if p is True or q is True:
            return True
        if p is None or q is None:
            return None
        return False

    return _Node(Or(x.expr, y.expr), f, "bool")


def _data(rng):
    rows = []
    for _ in range(N_ROWS):
        rows.append({n: (None if rng.random() < 0.12
                         else int(rng.integers(-1000, 1000)))
                     for n in COLS})
    table = pa.table({n: pa.array([r[n] for r in rows],
                                  type=pa.int64()) for n in COLS})
    return rows, table


class TestExpressionFuzzVsPythonOracle:
    @pytest.mark.parametrize("seed", range(N_TREES))
    def test_random_tree(self, session, seed):
        rng = np.random.default_rng(1000 + seed)
        rows, table = _data(rng)
        node = _gen_int(rng, depth=4) if seed % 2 else \
            _gen_bool(rng, depth=4)
        df = session.from_arrow(table)
        got = df.select(x=node.expr).collect().column("x").to_pylist()
        want = [node.fn(r) for r in rows]
        assert got == want, f"seed {seed}: tree {node.expr!r}"

    def test_case_when_chain(self, session):
        rng = np.random.default_rng(77)
        rows, table = _data(rng)
        branches = []
        fns = []
        for i in range(3):
            c = _gen_bool(rng, 2)
            v = _gen_int(rng, 2)
            branches.append((c.expr, v.expr))
            fns.append((c.fn, v.fn))
        d = _gen_int(rng, 2)
        expr = CaseWhen(branches, d.expr)

        def oracle(r):
            for cf, vf in fns:
                if cf(r) is True:
                    return vf(r)
            return d.fn(r)

        df = session.from_arrow(table)
        got = df.select(x=expr).collect().column("x").to_pylist()
        assert got == [oracle(r) for r in rows]
