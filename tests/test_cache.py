"""Cache serializer tests (ParquetCachedBatchSerializer.scala:221 analog):
df.cache() stores results as compressed parquet blobs; re-execution decodes
them (on device where the encoding allows) instead of re-running the plan."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.datasources.cache import CpuCachedExec
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same, make_table


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


class TestCache:
    def test_cache_roundtrip_device(self, session, rng):
        df = session.from_arrow(make_table(rng, n=400))
        cached = df.filter(col("small") > 0).cache()
        first = cached.collect()
        assert cached.plan.relation is not None
        second = cached.collect()  # decodes blobs, no re-execution
        key = [("id", "ascending"), ("val", "ascending")]
        assert first.sort_by(key).equals(second.sort_by(key))

    def test_cache_differential(self, session, rng):
        df = session.from_arrow(make_table(rng, n=300))
        cached = df.select(col("id"), (col("val") * 2).alias("v2"),
                           col("cat")).cache()
        assert_same(cached, sort_by=["id", "v2"])

    def test_cache_feeds_downstream_query(self, session, rng):
        df = session.from_arrow(make_table(rng, n=500))
        cached = df.cache()
        q = cached.group_by("cat").agg(n=Count(lit(1)), s=Sum(col("small")))
        assert_same(q, sort_by=["cat"])
        # second downstream query reuses the SAME materialized relation
        rel = cached.plan.relation
        assert rel is not None
        q2 = cached.filter(col("small") > 0).agg(c=Count(lit(1)))
        assert_same(q2)
        assert cached.plan.relation is rel

    def test_cpu_materializes_device_reads(self, session, rng):
        df = session.from_arrow(make_table(rng, n=200))
        cached = df.cache()
        cpu = cached.collect_cpu()  # CPU engine materializes
        assert cached.plan.relation is not None
        dev = cached.collect()      # device engine decodes same blobs
        key = [("id", "ascending"), ("val", "ascending")]
        assert cpu.sort_by(key).equals(dev.sort_by(key))

    def test_unpersist(self, session, rng):
        df = session.from_arrow(make_table(rng, n=50))
        cached = df.cache()
        cached.collect()
        assert cached.plan.relation is not None
        cached.unpersist()
        assert cached.plan.relation is None
        cached.collect()  # re-materializes cleanly
        assert cached.plan.relation is not None

    def test_cache_idempotent(self, session, rng):
        df = session.from_arrow(make_table(rng, n=50))
        cached = df.cache()
        assert cached.cache() is cached

    def test_compressed_smaller_than_raw(self, session, rng):
        n = 5000
        t = pa.table({
            "a": pa.array(np.arange(n) % 7, type=pa.int64()),
            "b": pa.array(np.zeros(n), type=pa.float64()),
        })
        df = session.from_arrow(t).cache()
        df.collect()
        rel = df.plan.relation
        assert rel.num_rows == n
        assert rel.size_bytes < n * 16 / 4  # zstd crushes the constants

    def test_device_decode_path_used(self, session, rng):
        # plain numeric cache blob decodes on device: verify the blob is
        # PLAIN-encoded (no dictionary pages), the contract the device
        # decoder needs
        import io
        import pyarrow.parquet as pq
        t = pa.table({"x": pa.array(np.arange(100), type=pa.int64())})
        df = session.from_arrow(t).cache()
        df.collect()
        pf = pq.ParquetFile(io.BytesIO(df.plan.relation.blobs[0]))
        cm = pf.metadata.row_group(0).column(0)
        assert cm.dictionary_page_offset is None
        out = df.collect()
        assert sorted(out.column("x").to_pylist()) == list(range(100))

    def test_empty_result_cache(self, session, rng):
        df = session.from_arrow(make_table(rng, n=50))
        cached = df.filter(col("small") > lit(10**9)).cache()
        out = cached.collect()
        assert out.num_rows == 0
        out2 = cached.collect()
        assert out2.num_rows == 0
        assert out2.schema.names == out.schema.names
