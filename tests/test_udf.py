"""UDF layer tests (reference model: `udf-compiler` suites +
`integration_tests/.../udf_test.py` / `udf_cudf_test.py`)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import col, lit
from spark_rapids_tpu.expr.base import Vec
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.udf import (ColumnarUDFExpr, PandasUDF, TpuUDF,
                                  UdfCompileError, compile_udf, from_jax,
                                  pandas_udf, python_udf_to_expr, to_jax)

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def udf_table(rng, n=300):
    nulls = rng.random(n) < 0.15
    return pa.table({
        "x": pa.array(np.where(nulls, 0, rng.integers(-100, 100, n)),
                      type=pa.int64(), mask=nulls),
        "y": pa.array(rng.normal(0, 10, n), type=pa.float64()),
        "s": pa.array([["ab", "Hello World", "  pad  ", "zz"][i]
                       for i in rng.integers(0, 4, n)]),
    })


class TestUdfCompiler:
    def test_arithmetic(self, session, rng):
        @compile_udf
        def f(x, y):
            return x * 2 + y / 3 - 1

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("x"), f(col("x"), col("y")).alias("r"))
        assert_same(q, sort_by=["x", "r"], approx_cols=("r",))

    def test_conditionals(self, session, rng):
        @compile_udf
        def f(x):
            if x > 50:
                return 2 * x
            elif x < -50:
                return -x
            else:
                return 0

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("x"), f(col("x")).alias("r"))
        assert_same(q, sort_by=["x", "r"])

    def test_assignments_and_ternary(self, session, rng):
        @compile_udf
        def f(x, y):
            a = x + 1
            a += 2
            b = a * a
            return b if y > 0 else -b

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("x"), f(col("x"), col("y")).alias("r"))
        assert_same(q, sort_by=["x", "r"])

    def test_math_and_builtins(self, session, rng):
        @compile_udf
        def f(x, y):
            import math
            return math.sqrt(abs(x)) + min(x, 10) + max(y, 0.0)

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("x"), f(col("x"), col("y")).alias("r"))
        assert_same(q, sort_by=["x", "r"], approx_cols=("r",))

    def test_string_methods(self, session, rng):
        @compile_udf
        def f(s):
            return s.strip().upper()

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("s"), f(col("s")).alias("r"))
        assert_same(q, sort_by=["s", "r"])

    def test_string_predicates(self, session, rng):
        @compile_udf
        def f(s):
            return s.startswith("H") or "z" in s

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("s"), f(col("s")).alias("r"))
        assert_same(q, sort_by=["s", "r"])

    def test_lambda(self):
        e = python_udf_to_expr(lambda x: x + 1, [col("a")])
        assert "Add" in type(e).__name__

    def test_comparison_chain(self, session, rng):
        @compile_udf
        def f(x):
            return 0 < x < 50

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("x"), f(col("x")).alias("r"))
        assert_same(q, sort_by=["x", "r"])

    @pytest.mark.parametrize("bad", [
        lambda: compile_udf(lambda x: [x])(col("a")),          # list
        lambda: compile_udf(lambda x: x[0])(col("a")),         # subscript
        lambda: compile_udf(lambda x: print(x))(col("a")),     # call
    ])
    def test_uncompilable_raises(self, bad):
        with pytest.raises(UdfCompileError):
            bad()

    def test_loop_rejected(self):
        def f(x):
            total = x
            for i in range(3):
                total = total + i
            return total

        with pytest.raises(UdfCompileError, match="For"):
            python_udf_to_expr(f, [col("a")])


class TestUdfCompilerRegressions:
    def test_else_branch_with_fallthrough(self, session, rng):
        @compile_udf
        def g(x):
            if x > 0:
                return 1.0
            else:
                y = 2.0
            return y

        df = session.from_arrow(udf_table(rng, n=50))
        q = df.select(col("x"), g(col("x")).alias("r"))
        assert_same(q, sort_by=["x", "r"])

    def test_pandas_udf_in_filter_runs_eagerly(self, session, rng):
        @pandas_udf(T.DOUBLE)
        def ident(y):
            return y

        df = session.from_arrow(udf_table(rng, n=50))
        q = df.filter(ident(col("y")) > lit(0.0))
        out = q.collect()  # eager filter kernel hosts the UDF hop on device
        assert all(v > 0 for v in out.column("y").to_pylist())
        assert "only supported in projections" not in q.explain()


class TestColumnarUdfSpi:
    def test_device_columnar_udf(self, session, rng):
        class Clamp(TpuUDF):
            return_type = T.DOUBLE

            def evaluate_columnar(self, xp, v):
                return Vec(T.DOUBLE, xp.clip(v.data, -5.0, 5.0), v.validity)

        df = session.from_arrow(udf_table(rng))
        q = df.select(col("y"), Clamp()(col("y")).alias("r"))
        out = assert_same(q, sort_by=["y", "r"])
        vals = [v for v in out.column("r").to_pylist() if v is not None]
        assert all(-5.0 <= v <= 5.0 for v in vals)
        # it planned onto the device (no fallback reasons)
        assert "!" not in q.explain().split("\n")[0]


class TestPandasUdf:
    def test_pandas_udf_roundtrip(self, session, rng):
        @pandas_udf(T.DOUBLE)
        def plus_mean(x):
            return x + x.mean()

        df = session.from_arrow(udf_table(rng, n=100))
        q = df.select(col("y"), plus_mean(col("y")).alias("r"))
        tpu = q.collect()
        ys = np.array([v for v in tpu.column("y").to_pylist()])
        rs = np.array([v for v in tpu.column("r").to_pylist()])
        assert np.allclose(rs, ys + ys.mean(), rtol=1e-9)

    def test_pandas_udf_nulls(self, session):
        t = pa.table({"x": pa.array([1, None, 3], type=pa.int64())})

        @pandas_udf(T.LONG)
        def double(x):
            return x * 2

        df = session.from_arrow(t)
        out = df.select(double(col("x")).alias("r")).collect()
        assert out.column("r").to_pylist() == [2, None, 6]

    def test_pandas_udf_strings(self, session):
        t = pa.table({"s": pa.array(["a", None, "cc"])})

        @pandas_udf(T.STRING)
        def shout(s):
            return s.map(lambda v: v.upper() + "!" if v is not None else None)

        df = session.from_arrow(t)
        out = df.select(shout(col("s")).alias("r")).collect()
        assert out.column("r").to_pylist() == ["A!", None, "CC!"]


class TestJaxHandoff:
    def test_to_jax_zero_copy(self, session, rng):
        df = session.from_arrow(udf_table(rng, n=200)) \
            .select(col("x"), col("y"))
        arrays = to_jax(df)
        assert arrays["__num_rows__"] == 200
        data, validity = arrays["y"]
        import jax.numpy as jnp
        assert isinstance(data, jnp.ndarray)
        # feed straight into jax compute without leaving the device
        total = float(jnp.sum(jnp.where(validity, data, 0.0)))
        expected = sum(v for v in df.collect().column("y").to_pylist()
                       if v is not None)
        assert abs(total - expected) < 1e-6 * max(abs(expected), 1.0)

    def test_round_trip(self, session, rng):
        df = session.from_arrow(udf_table(rng, n=64)).select(col("x"))
        arrays = to_jax(df)
        df2 = from_jax(session, arrays)
        assert df2.collect().column("x").to_pylist() == \
            df.collect().column("x").to_pylist()
