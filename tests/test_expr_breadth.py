"""Differential tests for the breadth-push expressions (misc, datetime tail,
more strings, array set ops, new aggregates) — device vs CPU engine plus
hand-computed oracles for the tricky semantics."""

import datetime as dtlib

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (ArrayDistinct, ArrayExcept, ArrayIntersect,
                                   ArrayJoin, ArrayPosition, ArrayRemove,
                                   ArrayRepeat, ArraysOverlap, ArrayUnion,
                                   AssertTrue, BitAndAgg, BitOrAgg, BitXorAgg,
                                   BoolAnd, BoolOr, Conv, Count, CountIf,
                                   CreateArray, DayName, Euler, Empty2Null,
                                   Flatten, FormatNumber, Kurtosis,
                                   Levenshtein, Literal, MakeDate, MonthName,
                                   Overlay, Pi, RaiseError, Reverse, Sequence,
                                   Skewness, Slice, SoundEx, SparkPartitionID,
                                   TimestampMillis, TimestampSeconds,
                                   TruncTimestamp, UnixDate, WeekOfYear,
                                   WidthBucket, col, lit)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def arr_df(session, rows, typ=pa.int64()):
    t = pa.table({"a": pa.array(rows, type=pa.list_(typ)),
                  "i": pa.array(range(len(rows)), type=pa.int64())})
    return session.from_arrow(t)


class TestArrayOps:
    ROWS = [[1, 2, 2, 3], [], None, [5, None, 5], [7], [None, None],
            [2, 4, 6, 8]]

    def test_position_remove_distinct(self, session):
        df = arr_df(session, self.ROWS)
        q = df.select("i",
                      p=ArrayPosition(col("a"), lit(2)),
                      r=ArrayRemove(col("a"), lit(2)),
                      d=ArrayDistinct(col("a")))
        out = assert_same(q, sort_by=["i"])
        assert out.column("p").to_pylist() == [2, 0, None, 0, 0, 0, 1]
        assert out.column("r").to_pylist() == [
            [1, 3], [], None, [5, None, 5], [7], [None, None], [4, 6, 8]]
        assert out.column("d").to_pylist() == [
            [1, 2, 3], [], None, [5, None], [7], [None], [2, 4, 6, 8]]

    def test_set_ops(self, session):
        t = pa.table({
            "a": pa.array([[1, 2, 3], [1, 1], None, [None, 1]],
                          type=pa.list_(pa.int64())),
            "b": pa.array([[2, 4], [1], [1], [None]],
                          type=pa.list_(pa.int64())),
            "i": pa.array(range(4), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.select("i",
                      u=ArrayUnion(col("a"), col("b")),
                      n=ArrayIntersect(col("a"), col("b")),
                      e=ArrayExcept(col("a"), col("b")),
                      o=ArraysOverlap(col("a"), col("b")))
        out = assert_same(q, sort_by=["i"])
        assert out.column("u").to_pylist() == [
            [1, 2, 3, 4], [1], None, [None, 1]]
        assert out.column("n").to_pylist() == [[2], [1], None, [None]]
        assert out.column("e").to_pylist() == [[1, 3], [], None, [1]]
        assert out.column("o").to_pylist() == [True, True, None, None]

    def test_slice_reverse_repeat(self, session):
        df = arr_df(session, self.ROWS)
        q = df.select("i",
                      s=Slice(col("a"), lit(2), lit(2)),
                      sn=Slice(col("a"), lit(-2), lit(2)),
                      rv=Reverse(col("a")),
                      rp=ArrayRepeat(col("i"), lit(3)))
        out = assert_same(q, sort_by=["i"])
        assert out.column("s").to_pylist() == [
            [2, 2], [], None, [None, 5], [], [None], [4, 6]]
        assert out.column("rv").to_pylist() == [
            [3, 2, 2, 1], [], None, [5, None, 5], [7], [None, None],
            [8, 6, 4, 2]]
        assert out.column("rp").to_pylist()[0] == [0, 0, 0]

    def test_flatten(self, session):
        t = pa.table({
            "a": pa.array([[[1, 2], [3]], [[], [4]], [None, [5]], None],
                          type=pa.list_(pa.list_(pa.int64()))),
            "i": pa.array(range(4), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.select("i", f=Flatten(col("a")))
        out = assert_same(q, sort_by=["i"])
        assert out.column("f").to_pylist() == [[1, 2, 3], [4], None, None]

    def test_array_join(self, session):
        t = pa.table({
            "a": pa.array([["x", "y"], ["x", None, "z"], [], None],
                          type=pa.list_(pa.string())),
            "i": pa.array(range(4), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.select("i", j=ArrayJoin(col("a"), lit(",")),
                      jr=ArrayJoin(col("a"), lit("-"), lit("NUL")))
        out = assert_same(q, sort_by=["i"])
        assert out.column("j").to_pylist() == ["x,y", "x,z", "", None]
        assert out.column("jr").to_pylist() == ["x-y", "x-NUL-z", "", None]


class TestMisc:
    def test_partition_id_and_constants(self, session, rng):
        t = pa.table({"x": pa.array(rng.normal(0, 1, 20))})
        df = session.from_arrow(t)
        q = df.select(p=SparkPartitionID(), pi=Pi(), e=Euler())
        out = assert_same(q)
        assert set(out.column("p").to_pylist()) == {0}
        assert abs(out.column("pi").to_pylist()[0] - np.pi) < 1e-15

    def test_width_bucket(self, session):
        t = pa.table({"v": pa.array([-1.0, 0.0, 2.5, 9.99, 10.0, 15.0,
                                     None])})
        df = session.from_arrow(t)
        q = df.select("v", b=WidthBucket(col("v"), lit(0.0), lit(10.0),
                                         lit(5)))
        out = assert_same(q, sort_by=["v"])
        got = dict(zip(out.column("v").to_pylist(),
                       out.column("b").to_pylist()))
        assert got[-1.0] == 0 and got[0.0] == 1 and got[2.5] == 2
        assert got[9.99] == 5 and got[10.0] == 6 and got[15.0] == 6
        assert got[None] is None

    def test_sequence(self, session):
        t = pa.table({"i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", s=Sequence(lit(1), lit(5)),
                      sd=Sequence(lit(10), lit(4), lit(-3)))
        out = assert_same(q, sort_by=["i"])
        assert out.column("s").to_pylist()[0] == [1, 2, 3, 4, 5]
        assert out.column("sd").to_pylist()[0] == [10, 7, 4]

    def test_raise_error_fires(self, session):
        t = pa.table({"x": pa.array([1, 2], type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select(e=RaiseError(lit("boom")))
        with pytest.raises(Exception, match="boom"):
            q.collect()
        with pytest.raises(Exception, match="boom"):
            q.collect_cpu()

    def test_assert_true(self, session):
        t = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
        df = session.from_arrow(t)
        ok = df.select(a=AssertTrue(col("x") > lit(0)))
        assert ok.collect().column("a").to_pylist() == [None] * 3
        bad = df.select(a=AssertTrue(col("x") > lit(2), lit("too small")))
        with pytest.raises(Exception, match="too small"):
            bad.collect()


class TestDatetimeTail:
    def make_dates(self, session):
        days = [0, 1, 365, 11323, 19000, -1, None]  # epoch-day ints
        t = pa.table({"d": pa.array(
            [None if d is None else dtlib.date(1970, 1, 1)
             + dtlib.timedelta(days=d) for d in days], type=pa.date32())})
        return session.from_arrow(t)

    def test_week_names(self, session):
        df = self.make_dates(session)
        q = df.select("d", w=WeekOfYear(col("d")), dn=DayName(col("d")),
                      mn=MonthName(col("d")))
        out = assert_same(q, sort_by=["d"])
        by_d = {str(d): (w, dn, mn) for d, w, dn, mn in zip(
            out.column("d").to_pylist(), out.column("w").to_pylist(),
            out.column("dn").to_pylist(), out.column("mn").to_pylist())}
        # 1970-01-01 was a Thursday, ISO week 1
        assert by_d["1970-01-01"] == (1, "Thu", "Jan")
        assert by_d["2001-01-01"][1] == "Mon"  # epoch day 11323

    def test_iso_week_against_python(self, session, rng):
        days = rng.integers(-3000, 25000, 200)
        t = pa.table({"d": pa.array(
            [dtlib.date(1970, 1, 1) + dtlib.timedelta(days=int(x))
             for x in days], type=pa.date32())})
        df = session.from_arrow(t)
        out = assert_same(df.select("d", w=WeekOfYear(col("d"))),
                          sort_by=["d"])
        for d, w in zip(out.column("d").to_pylist(),
                        out.column("w").to_pylist()):
            assert w == d.isocalendar()[1], d

    def test_epoch_conversions(self, session):
        t = pa.table({"s": pa.array([0, 1_600_000_000, None],
                                    type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select(ts=TimestampSeconds(col("s")),
                      tm=TimestampMillis(col("s")))
        out = assert_same(q)
        vals = out.column("ts").to_pylist()
        assert vals[0] is not None

    def test_make_date_unix_date(self, session):
        t = pa.table({"y": pa.array([2020, 2021, 2020, None],
                                    type=pa.int32()),
                      "m": pa.array([2, 13, 2, 1], type=pa.int32()),
                      "d": pa.array([29, 1, 30, 1], type=pa.int32())})
        df = session.from_arrow(t)
        q = df.select(md=MakeDate(col("y"), col("m"), col("d")))
        out = assert_same(q)
        vals = out.column("md").to_pylist()
        assert dtlib.date(2020, 2, 29) in vals
        assert vals.count(None) == 3  # bad month, Feb 30, null year

    def test_trunc_timestamp(self, session):
        base = 1_700_000_000_123_456  # us
        t = pa.table({"ts": pa.array([base], type=pa.timestamp("us",
                                                               tz="UTC"))})
        df = session.from_arrow(t)
        q = df.select(h=TruncTimestamp("HOUR", col("ts")),
                      dy=TruncTimestamp("DAY", col("ts")),
                      mo=TruncTimestamp("MONTH", col("ts")))
        out = assert_same(q)
        h = out.column("h").to_pylist()[0]
        assert h.minute == 0 and h.second == 0 and h.microsecond == 0


class TestStringsMore:
    def test_overlay(self, session):
        t = pa.table({"s": pa.array(["Spark SQL", "abcdef", "", None])})
        df = session.from_arrow(t)
        q = df.select("s", o=Overlay(col("s"), lit("_"), lit(6)),
                      o2=Overlay(col("s"), lit("XX"), lit(2), lit(3)))
        out = assert_same(q, sort_by=["s"])
        got = dict(zip(out.column("s").to_pylist(),
                       out.column("o").to_pylist()))
        assert got["Spark SQL"] == "Spark_SQL"
        got2 = dict(zip(out.column("s").to_pylist(),
                        out.column("o2").to_pylist()))
        assert got2["abcdef"] == "aXXef"

    def test_levenshtein(self, session):
        pairs = [("kitten", "sitting", 3), ("", "abc", 3), ("abc", "", 3),
                 ("same", "same", 0), ("flaw", "lawn", 2), ("a", "b", 1)]
        t = pa.table({"a": pa.array([p[0] for p in pairs]),
                      "b": pa.array([p[1] for p in pairs])})
        df = session.from_arrow(t)
        out = assert_same(df.select("a", "b",
                                    d=Levenshtein(col("a"), col("b"))),
                          sort_by=["a", "b"])
        got = {(a, b): d for a, b, d in zip(out.column("a").to_pylist(),
                                            out.column("b").to_pylist(),
                                            out.column("d").to_pylist())}
        for a, b, want in pairs:
            assert got[(a, b)] == want, (a, b)

    def test_soundex(self, session):
        cases = [("Robert", "R163"), ("Rupert", "R163"),
                 ("Ashcraft", "A261"), ("Tymczak", "T522"),
                 ("Pfister", "P236"), ("Miller", "M460"), ("", ""),
                 ("123", "123")]
        t = pa.table({"s": pa.array([c[0] for c in cases])})
        df = session.from_arrow(t)
        out = assert_same(df.select("s", x=SoundEx(col("s"))),
                          sort_by=["s"])
        got = dict(zip(out.column("s").to_pylist(),
                       out.column("x").to_pylist()))
        for s, want in cases:
            assert got[s] == want, s

    def test_format_number(self, session):
        t = pa.table({"v": pa.array([1234567.891, 0.5, -4536.1, 0.0,
                                     None])})
        df = session.from_arrow(t)
        out = assert_same(df.select("v", f=FormatNumber(col("v"), lit(2))),
                          sort_by=["v"])
        got = dict(zip(out.column("v").to_pylist(),
                       out.column("f").to_pylist()))
        assert got[1234567.891] == "1,234,567.89"
        assert got[0.5] == "0.50"
        assert got[-4536.1] == "-4,536.10"
        assert got[0.0] == "0.00"
        assert got[None] is None

    def test_conv(self, session):
        t = pa.table({"s": pa.array(["100", "ff", "1010", "zz", ""])})
        df = session.from_arrow(t)
        out = assert_same(
            df.select("s", h=Conv(col("s"), lit(16), lit(10)),
                      b=Conv(col("s"), lit(2), lit(16))),
            sort_by=["s"])
        got = dict(zip(out.column("s").to_pylist(),
                       out.column("h").to_pylist()))
        assert got["ff"] == "255"
        assert got["100"] == "256"
        gb = dict(zip(out.column("s").to_pylist(),
                      out.column("b").to_pylist()))
        assert gb["1010"] == "A"

    def test_empty2null(self, session):
        t = pa.table({"s": pa.array(["x", "", None, "y"])})
        df = session.from_arrow(t)
        out = assert_same(df.select(e=Empty2Null(col("s"))))
        assert sorted(out.column("e").to_pylist(), key=str) == \
            sorted(["x", None, None, "y"], key=str)


class TestNewAggregates:
    def agg_df(self, session, rng, n=300):
        t = pa.table({
            "g": pa.array(rng.integers(0, 6, n), type=pa.int32()),
            "b": pa.array(np.where(rng.random(n) < 0.1, None,
                                   rng.random(n) < 0.5), type=pa.bool_()),
            "x": pa.array(rng.integers(0, 255, n), type=pa.int64()),
            "v": pa.array(np.where(rng.random(n) < 0.1, None,
                                   rng.normal(0, 2, n).round(3)),
                          type=pa.float64()),
        })
        return session.from_arrow(t), t

    def test_count_if_bool_aggs(self, session, rng):
        df, t = self.agg_df(session, rng)
        q = df.group_by("g").agg(ci=CountIf(col("b")),
                                 ba=BoolAnd(col("b")),
                                 bo=BoolOr(col("b")),
                                 n=Count(col("b")))
        assert_same(q, sort_by=["g"])

    def test_bit_aggs(self, session, rng):
        df, t = self.agg_df(session, rng)
        q = df.group_by("g").agg(a=BitAndAgg(col("x")),
                                 o=BitOrAgg(col("x")),
                                 x=BitXorAgg(col("x")))
        out = assert_same(q, sort_by=["g"])
        # oracle for group 0
        import numpy as _np
        g = t.column("g").to_numpy()
        x = t.column("x").to_numpy()
        vals = [int(v) for v in x[g == 0]]
        acc_a, acc_o, acc_x = vals[0], vals[0], vals[0]
        for v in vals[1:]:
            acc_a &= v
            acc_o |= v
            acc_x ^= v
        row0 = out.to_pylist()[0]
        assert (row0["a"], row0["o"], row0["x"]) == (acc_a, acc_o, acc_x)

    def test_moments(self, session, rng):
        df, t = self.agg_df(session, rng)
        q = df.group_by("g").agg(sk=Skewness(col("v")),
                                 ku=Kurtosis(col("v")))
        out = assert_same(q, sort_by=["g"], approx_cols=("sk", "ku"))
        # scipy-free oracle for one group
        g = t.column("g").to_numpy()
        v = t.column("v").to_numpy(zero_copy_only=False)
        sel = (g == 0) & ~pa.compute.is_null(t.column("v")).to_numpy(
            zero_copy_only=False)
        vals = v[sel].astype(float)
        mu = vals.mean()
        m2 = ((vals - mu) ** 2).sum()
        m3 = ((vals - mu) ** 3).sum()
        want_sk = np.sqrt(len(vals)) * m3 / m2 ** 1.5
        got_sk = out.column("sk").to_pylist()[0]
        assert abs(got_sk - want_sk) < 1e-9

    def test_moments_distributed(self, rng):
        # partial/final split must reconstitute identical moments
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.shuffle.mode": "ICI",
                        "spark.rapids.tpu.mesh.shape": "shuffle=8"})
        df, _t = self.agg_df(s, rng, n=500)
        q = df.group_by("g").agg(sk=Skewness(col("v")),
                                 ci=CountIf(col("b")),
                                 bo=BitOrAgg(col("x")))
        assert_same(q, sort_by=["g"], approx_cols=("sk",))


class TestReviewRegressions:
    def test_device_placement_of_breadth_exprs(self, session, rng):
        """The breadth expressions must actually RUN on device (sig checks
        compare the OUTPUT type; a wrong sig silently falls back)."""
        from spark_rapids_tpu.expr import WeekOfYear, Levenshtein, CountIf
        t = pa.table({
            "d": pa.array([dtlib.date(2020, 5, 9)], type=pa.date32()),
            "a": pa.array(["abc"]), "b": pa.array(["abd"]),
            "f": pa.array([True]),
        })
        df = session.from_arrow(t)
        q = df.select(w=WeekOfYear(col("d")), l=Levenshtein(col("a"),
                                                            col("b")))
        assert "not supported" not in q.explain()
        q2 = df.group_by().agg(c=CountIf(col("f")))
        assert "not supported" not in q2.explain()
        assert q2.collect().column("c").to_pylist() == [1]

    def test_monotonic_id_unique_across_batches(self, rng):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.sql.batchSizeRows": 64})
        from spark_rapids_tpu.expr import MonotonicallyIncreasingID
        n = 300  # several 64-row batches
        t = pa.table({"x": pa.array(np.arange(n), type=pa.int64())})
        df = s.from_arrow(t)
        out = df.select("x", id=MonotonicallyIncreasingID()).collect()
        ids = out.column("id").to_pylist()
        assert len(set(ids)) == n  # unique across batches
        cpu = df.select("x", id=MonotonicallyIncreasingID()).collect_cpu()
        assert sorted(ids) == sorted(cpu.column("id").to_pylist())

    def test_slice_negative_beyond_start_empty(self, session):
        t = pa.table({"a": pa.array([[1, 2, 3]], type=pa.list_(pa.int64()))})
        df = session.from_arrow(t)
        out = assert_same(df.select(s=Slice(col("a"), lit(-5), lit(2))))
        assert out.column("s").to_pylist() == [[]]

    def test_arrays_overlap_empty_side(self, session):
        t = pa.table({
            "a": pa.array([[], [1]], type=pa.list_(pa.int64())),
            "b": pa.array([[None], [None]], type=pa.list_(pa.int64())),
        })
        df = session.from_arrow(t)
        out = assert_same(df.select(o=ArraysOverlap(col("a"), col("b"))),
                          sort_by=None)
        assert out.column("o").to_pylist() == [False, None]

    def test_trunc_timestamp_dd(self, session):
        t = pa.table({"ts": pa.array([1_700_000_000_123_456],
                                     type=pa.timestamp("us", tz="UTC"))})
        df = session.from_arrow(t)
        out = assert_same(df.select(d=TruncTimestamp("DD", col("ts")),
                                    ms=TruncTimestamp("MILLISECOND",
                                                      col("ts"))))
        d = out.column("d").to_pylist()[0]
        assert d.hour == 0 and d.minute == 0
        assert out.column("ms").to_pylist()[0].microsecond % 1000 == 0


class TestStringElementArrays:
    def test_slice_reverse_string_elements(self, session):
        t = pa.table({
            "a": pa.array([["aa", "b", None, "ccc"], [], ["zz"]],
                          type=pa.list_(pa.string())),
            "i": pa.array(range(3), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.select("i", r=Reverse(col("a")),
                      s=Slice(col("a"), lit(2), lit(2)))
        out = assert_same(q, sort_by=["i"])
        assert out.column("r").to_pylist() == [
            ["ccc", None, "b", "aa"], [], ["zz"]]
        assert out.column("s").to_pylist() == [["b", None], [], []]

    def test_flatten_string_elements(self, session):
        t = pa.table({
            "a": pa.array([[["x", "yy"], ["z"]], [[]]],
                          type=pa.list_(pa.list_(pa.string()))),
            "i": pa.array(range(2), type=pa.int64()),
        })
        df = session.from_arrow(t)
        out = assert_same(df.select("i", f=Flatten(col("a"))),
                          sort_by=["i"])
        assert out.column("f").to_pylist() == [["x", "yy", "z"], []]

    def test_literal_required_raises_at_build(self, session):
        with pytest.raises(ValueError, match="literal"):
            Sequence(col("x"), lit(5))
        with pytest.raises(ValueError, match="literal"):
            FormatNumber(col("x"), col("d"))
        with pytest.raises(ValueError, match="conv"):
            Conv(col("s"), lit(40), lit(10))
        with pytest.raises(ValueError, match="literal"):
            ArrayRepeat(col("x"), col("n"))
        with pytest.raises(ValueError, match="literal"):
            ArrayJoin(col("a"), col("d"))


class TestDeepNestedArrayOps:
    def test_reverse_slice_nested_arrays(self, session):
        t = pa.table({
            "a": pa.array([[[1, 2], [3]], [[4], [], [5, 6]]],
                          type=pa.list_(pa.list_(pa.int64()))),
            "i": pa.array(range(2), type=pa.int64()),
        })
        df = session.from_arrow(t)
        out = assert_same(df.select("i", r=Reverse(col("a")),
                                    s=Slice(col("a"), lit(1), lit(2))),
                          sort_by=["i"])
        assert out.column("r").to_pylist() == [
            [[3], [1, 2]], [[5, 6], [], [4]]]
        assert out.column("s").to_pylist() == [
            [[1, 2], [3]], [[4], []]]

    def test_sequence_null_literal_raises(self, session):
        with pytest.raises(ValueError, match="literal"):
            Sequence(lit(None), lit(5))


class TestDatetimeStringBridge:
    def test_date_format_roundtrip(self, session, rng):
        from spark_rapids_tpu.expr import (DateFormat, FromUnixTime,
                                           ToUnixTimestamp)
        secs = rng.integers(0, 2_000_000_000, 100)
        t = pa.table({"s": pa.array(secs, type=pa.int64()),
                      "i": pa.array(range(100), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", "s",
                      f=FromUnixTime(col("s")),
                      back=ToUnixTimestamp(FromUnixTime(col("s"))))
        out = assert_same(q, sort_by=["i"])
        import datetime as dtl
        rows = out.sort_by([("i", "ascending")])
        for sec, fstr, back in zip(rows.column("s").to_pylist(),
                                   rows.column("f").to_pylist(),
                                   rows.column("back").to_pylist()):
            want = dtl.datetime.fromtimestamp(
                sec, dtl.timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
            assert fstr == want
            assert back == sec

    def test_date_format_patterns(self, session):
        from spark_rapids_tpu.expr import DateFormat
        import datetime as dtl
        t = pa.table({"d": pa.array([dtl.date(2024, 3, 7),
                                     dtl.date(1999, 12, 31)],
                                    type=pa.date32())})
        df = session.from_arrow(t)
        out = assert_same(df.select(a=DateFormat(col("d"), "yyyy/MM/dd"),
                                    b=DateFormat(col("d"), "dd-MM-yyyy")))
        assert sorted(out.column("a").to_pylist()) == ["1999/12/31",
                                                       "2024/03/07"]
        assert sorted(out.column("b").to_pylist()) == ["07-03-2024",
                                                       "31-12-1999"]

    def test_unix_timestamp_malformed_null(self, session):
        from spark_rapids_tpu.expr import ToUnixTimestamp
        t = pa.table({"s": pa.array(["2024-01-01 00:00:00",
                                     "2024-13-01 00:00:00",
                                     "2024-02-30 00:00:00",
                                     "not a date", None,
                                     "2024-01-01 25:00:00"])})
        df = session.from_arrow(t)
        out = assert_same(df.select("s", u=ToUnixTimestamp(col("s"))),
                          sort_by=["s"])
        got = dict(zip(out.column("s").to_pylist(),
                       out.column("u").to_pylist()))
        assert got["2024-01-01 00:00:00"] == 1704067200
        assert got["2024-13-01 00:00:00"] is None
        assert got["2024-02-30 00:00:00"] is None
        assert got["not a date"] is None
        assert got[None] is None
        assert got["2024-01-01 25:00:00"] is None

    def test_bad_pattern_raises(self):
        from spark_rapids_tpu.expr import DateFormat
        with pytest.raises(ValueError, match="pattern"):
            DateFormat(col("d"), "MMM d, yyyy")  # variable-width month name


class TestCastAndPatternEdges:
    def test_hex_float_grammar(self, session):
        from spark_rapids_tpu.expr import Cast
        from spark_rapids_tpu import types as TT
        t = pa.table({"s": pa.array(["0x1p3", "0x1f", "0x1p3d", "123d",
                                     "nand", "infinityf", "Infinity"])})
        df = session.from_arrow(t)
        out = df.select("s", d=Cast(col("s"), TT.DOUBLE)).collect_cpu()
        got = dict(zip(out.column("s").to_pylist(),
                       out.column("d").to_pylist()))
        assert got["0x1p3"] == 8.0
        assert got["0x1f"] is None      # hex needs the p exponent (Java)
        assert got["0x1p3d"] == 8.0     # suffix strips on hex too
        assert got["123d"] == 123.0
        assert got["nand"] is None      # no suffix on NaN/Infinity words
        assert got["infinityf"] is None
        assert got["Infinity"] == float("inf")

    def test_quoted_pattern_literals(self, session):
        from spark_rapids_tpu.expr import DateFormat, ToUnixTimestamp
        import datetime as dtl
        t = pa.table({"d": pa.array([dtl.date(2024, 3, 7)],
                                    type=pa.date32())})
        df = session.from_arrow(t)
        out = assert_same(df.select(
            a=DateFormat(col("d"), "yyyy'T'MM"),
            b=DateFormat(col("d"), "yyyy''MM")))
        assert out.column("a").to_pylist() == ["2024T03"]
        assert out.column("b").to_pylist() == ["2024'03"]
        with pytest.raises(ValueError, match="unterminated"):
            DateFormat(col("d"), "yyyy'oops")

    def test_escaped_quote_inside_quoted_run(self, session):
        from spark_rapids_tpu.expr import DateFormat
        from spark_rapids_tpu.expr.datetime_ import compile_dt_pattern
        parts, width = compile_dt_pattern("yyyy' o''clock'")
        lits = "".join(t for k, _, t in parts if k == "lit")
        assert lits == " o'clock" and width == 4 + len(" o'clock")
        import datetime as dtl
        t = pa.table({"d": pa.array([dtl.date(2024, 1, 1)],
                                    type=pa.date32())})
        df = session.from_arrow(t)
        out = assert_same(df.select(a=DateFormat(col("d"),
                                                 "yyyy' o''clock'")))
        assert out.column("a").to_pylist() == ["2024 o'clock"]
