"""Decimal128 (precision > 18) tests — two-limb device representation vs
the CPU engine and python-Decimal hand oracles (reference:
decimalExpressions.scala + spark-rapids-jni decimal128 kernels)."""

import decimal
import random

import numpy as np
import pyarrow as pa
import pytest

decimal.getcontext().prec = 60

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (Cast, Count, First, Last, Max, Min, Sum,
                                   col, lit)
from spark_rapids_tpu.plugin import TpuSession

from test_queries import assert_same

D = decimal.Decimal


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def dec_table(seed=3, n=400, digits=30, scale=3, null_frac=0.1):
    rnd = random.Random(seed)
    vals = [None if rnd.random() < null_frac else
            D(rnd.randint(-(10 ** digits), 10 ** digits)).scaleb(-scale)
            for _ in range(n)]
    return pa.table({
        "d": pa.array(vals, type=pa.decimal128(digits + scale, scale)),
        "g": pa.array([i % 7 for i in range(n)], type=pa.int32()),
        "i": pa.array(range(n), type=pa.int64()),
    }), vals


class TestDecimal128:
    def test_roundtrip_and_placement(self, session):
        t, _ = dec_table()
        df = session.from_arrow(t)
        q = df.select("i", "d")
        assert "not supported" not in q.explain()  # runs ON device
        out = assert_same(q, sort_by=["i"])
        assert out.column("d").to_pylist() == t.column("d").to_pylist()

    def test_group_aggregates_vs_python(self, session):
        t, vals = dec_table()
        df = session.from_arrow(t)
        q = df.group_by("g").agg(s=Sum(col("d")), mn=Min(col("d")),
                                 mx=Max(col("d")), c=Count(col("d")))
        out = assert_same(q, sort_by=["g"])
        rows = out.sort_by([("g", "ascending")]).to_pylist()
        for g in range(7):
            sel = [v for i, v in enumerate(vals) if i % 7 == g
                   and v is not None]
            assert rows[g]["s"] == sum(sel)
            assert rows[g]["mn"] == min(sel)
            assert rows[g]["mx"] == max(sel)
            assert rows[g]["c"] == len(sel)

    def test_add_subtract_overflow_null(self, session):
        big = D(10 ** 37)
        t = pa.table({"a": pa.array([big, -big, D(1)],
                                    type=pa.decimal128(38, 0)),
                      "b": pa.array([big, -big, D(2)],
                                    type=pa.decimal128(38, 0))})
        df = session.from_arrow(t)
        q = df.select(s=col("a") + col("b"), d=col("a") - col("b"))
        out = assert_same(q)
        got = sorted(out.column("s").to_pylist(), key=str)
        # 2e37 fits in precision 38; 1+2=3 fits
        assert D(2 * 10 ** 37) in got and D(-2 * 10 ** 37) in got
        assert D(3) in got

    def test_mixed_scale_add(self, session):
        t = pa.table({
            "a": pa.array([D("1.50"), D("-2.25")],
                          type=pa.decimal128(25, 2)),
            "b": pa.array([D("0.125"), D("10.000")],
                          type=pa.decimal128(30, 3)),
        })
        df = session.from_arrow(t)
        out = assert_same(df.select(s=col("a") + col("b")))
        assert sorted(out.column("s").to_pylist()) == [D("1.625"),
                                                      D("7.750")]

    def test_comparisons_and_filter(self, session):
        t, vals = dec_table(seed=9)
        df = session.from_arrow(t)
        zero = lit(D(0), T.DecimalType(33, 3))
        q = df.filter(col("d") > zero)
        want = sum(1 for v in vals if v is not None and v > 0)
        assert q.collect().num_rows == q.collect_cpu().num_rows == want

    def test_sort_order(self, session):
        t, vals = dec_table(seed=5, n=200)
        df = session.from_arrow(t)
        out = df.select("d", "i").sort("d").collect()
        got = [v for v in out.column("d").to_pylist() if v is not None]
        assert got == sorted(got)

    def test_rescale_casts_half_up(self, session):
        vals = [D("1.235"), D("-1.235"), D("99999999999999999999999.995"),
                D("0.004"), None]
        t = pa.table({"d": pa.array(vals, type=pa.decimal128(26, 3))})
        df = session.from_arrow(t)
        q = df.select(up=Cast(col("d"), T.DecimalType(30, 5)),
                      down=Cast(col("d"), T.DecimalType(26, 2)))
        out = assert_same(q)
        ups = out.column("up").to_pylist()
        downs = out.column("down").to_pylist()
        for v, u, dn in zip(vals, ups, downs):
            if v is None:
                assert u is None and dn is None
                continue
            assert u == v.quantize(D("0.00001"))
            assert dn == v.quantize(D("0.01"),
                                    rounding=decimal.ROUND_HALF_UP)

    def test_cast_overflow_to_narrow_null(self, session):
        t = pa.table({"d": pa.array([D(10 ** 25), D(5)],
                                    type=pa.decimal128(30, 0))})
        df = session.from_arrow(t)
        out = assert_same(df.select(x=Cast(col("d"), T.DecimalType(20, 1))))
        got = out.column("x").to_pylist()
        assert None in got and D("5.0") in got

    def test_sum_widens_to_128(self, session):
        # dec64 input whose SUM type is decimal(28) -> limb accumulation
        rnd = random.Random(11)
        vals = [D(rnd.randint(-(10 ** 17), 10 ** 17)) for _ in range(500)]
        t = pa.table({"d": pa.array(vals, type=pa.decimal128(18, 0)),
                      "g": pa.array([0] * 500, type=pa.int32())})
        df = session.from_arrow(t)
        out = assert_same(df.group_by("g").agg(s=Sum(col("d"))))
        assert out.column("s").to_pylist() == [sum(vals)]

    def test_first_last_if_coalesce(self, session):
        from spark_rapids_tpu.expr import Coalesce, If
        t, vals = dec_table(seed=7, n=100)
        df = session.from_arrow(t)
        zero = lit(D(0), T.DecimalType(33, 3))
        q = df.select("i", c=Coalesce(col("d"), zero),
                      f=If(col("d") > zero, col("d"), zero))
        assert_same(q, sort_by=["i"])

    def test_distributed_dec128_agg(self):
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.shuffle.mode": "ICI",
                        "spark.rapids.tpu.mesh.shape": "shuffle=8",
                        "spark.rapids.sql.autoBroadcastJoinThreshold": -1})
        t, vals = dec_table(seed=13, n=600)
        df = s.from_arrow(t)
        q = df.group_by("g").agg(sm=Sum(col("d")), mn=Min(col("d")))
        out = assert_same(q, sort_by=["g"])
        rows = out.sort_by([("g", "ascending")]).to_pylist()
        for g in range(7):
            sel = [v for i, v in enumerate(vals) if i % 7 == g
                   and v is not None]
            assert rows[g]["sm"] == sum(sel)


class TestWideExactness:
    """Round-3 advisor regressions: 128-bit rescale wrap aliasing, Spark's
    allowPrecisionLoss result type, exact wide compares, -2^127 bound."""

    def test_addsub_rescale_no_wrap_alias(self, session):
        # dec(38,0) + dec(38,10): types as (38,6) under adjustPrecisionScale
        # and values up to 10^31 stay EXACT (the old 128-bit rescale wrapped
        # 34028236692093846346337460743 into ~-0.177 with validity=true)
        big = [D(34028236692093846346337460743), D(10) ** 30,
               D(-(10 ** 28)), D(7)]
        t = pa.table({
            "a": pa.array(big, type=pa.decimal128(38, 0)),
            "b": pa.array([D("0.5"), D(0), D("0.0000000001"), D("-7")],
                          type=pa.decimal128(38, 10)),
            "i": pa.array(range(4), type=pa.int64()),
        })
        df = session.from_arrow(t)
        q = df.select("i", s=col("a") + col("b"), d=col("a") - col("b"))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        st = out.schema.field("s").type
        assert (st.precision, st.scale) == (38, 6)
        got = out.to_pylist()
        assert got[0]["s"] == D("34028236692093846346337460743.5")
        assert got[1]["s"] == D(10) ** 30
        assert got[2]["s"] == D(-(10 ** 28))  # 1e-10 rounds away at scale 6
        assert got[3]["s"] == D(0) and got[3]["d"] == D(14)

    def test_addsub_true_overflow_still_nulls(self, session):
        mx = D(10) ** 37 * 9  # 9e37, near the 38-digit cap
        t = pa.table({"a": pa.array([mx, mx], type=pa.decimal128(38, 0)),
                      "b": pa.array([mx, -mx], type=pa.decimal128(38, 0)),
                      "i": pa.array([0, 1], type=pa.int64())})
        df = session.from_arrow(t)
        out = assert_same(df.select("i", s=col("a") + col("b")),
                          sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.to_pylist()
        assert got[0]["s"] is None      # 1.8e38 overflows (38,0)
        assert got[1]["s"] == D(0)

    def test_compare_wide_scale_gap_exact(self, session):
        # comparing dec(38,0) vs dec(38,10) forces a 10-digit rescale that
        # wrapped in 128 bits and misordered huge values
        a = [D(10) ** 30, D(34028236692093846346337460743), D(-(10 ** 29))]
        b = [D("0.5"), D("1.5"), D("0.5")]
        t = pa.table({"a": pa.array(a, type=pa.decimal128(38, 0)),
                      "b": pa.array(b, type=pa.decimal128(38, 10)),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        out = assert_same(df.select("i", gt=col("a") > col("b"),
                                    lt=col("a") < col("b")),
                          sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.to_pylist()
        assert [g["gt"] for g in got] == [True, True, False]
        assert [g["lt"] for g in got] == [False, False, True]

    def test_cast_upscale_no_wrap_alias(self, session):
        # dec(38,0) -> dec(38,10): values >= 10^28 must null (true overflow),
        # never alias back into bounds through a wrapped multiply
        vals = [D(34028236692093846346337460743), D(10) ** 27, D(5)]
        t = pa.table({"d": pa.array(vals, type=pa.decimal128(38, 0)),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", c=Cast(col("d"), T.DecimalType(38, 10)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.to_pylist()
        assert got[0]["c"] is None
        assert got[1]["c"] == D(10) ** 27
        assert got[2]["c"] == D(5)

    def test_adjust_precision_scale_unit(self):
        from spark_rapids_tpu.expr.decimal128 import (add_result_type,
                                                      adjust_precision_scale)
        r = add_result_type(T.DecimalType(38, 0), T.DecimalType(38, 10))
        assert (r.precision, r.scale) == (38, 6)
        r = add_result_type(T.DecimalType(10, 2), T.DecimalType(12, 4))
        assert (r.precision, r.scale) == (13, 4)  # no adjustment needed
        r = adjust_precision_scale(77, 38)
        assert (r.precision, r.scale) == (38, 6)
        r = adjust_precision_scale(40, 3)
        assert (r.precision, r.scale) == (38, 3)  # min_scale=3 floor holds

    def test_in_bounds_int128_min(self):
        from spark_rapids_tpu.expr.decimal128 import in_bounds, split_int
        hi, lo = split_int(-(2 ** 127))
        ok = in_bounds(np, np.array([hi], np.int64),
                       np.array([lo], np.int64), 38)
        assert not bool(ok[0])

    def test_integral_to_dec64_cast_no_wrap(self, session):
        # CAST(1844674408L AS DECIMAL(18,10)): 1844674408 * 10^10 wraps
        # int64 to 6290448384 which passed the old post-hoc bound check
        t = pa.table({"v": pa.array([1844674408, 12345678, -(2 ** 63)],
                                    type=pa.int64()),
                      "i": pa.array(range(3), type=pa.int64())})
        df = session.from_arrow(t)
        q = df.select("i", c=Cast(col("v"), T.DecimalType(18, 10)))
        out = assert_same(q, sort_by=["i"]).sort_by([("i", "ascending")])
        got = out.to_pylist()
        assert got[0]["c"] is None          # 1.8e9 needs 10 int digits > 8
        assert got[1]["c"] == D(12345678)
        assert got[2]["c"] is None          # int64-min: abs() wraps
