"""Sharded-execution suite (spark_rapids_tpu/mesh/, marker `mesh`).

Every query-level test compares the 8-virtual-device mesh run against the
CPU engine and asserts the specific mesh mechanism under test actually
engaged (collectives executed, shards produced, residency held, or —
for the mismatch cases — that the host path took over CLEANLY). The
off-path tests pin the established contract: mesh disabled means
byte-identical plans, zero new threads, zero mesh plan activity.
"""

import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exec import exchange as EX
from spark_rapids_tpu.expr import Count, Max, Min, Sum, col
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.utils.metrics import TaskMetrics

from test_queries import assert_same, make_table

pytestmark = pytest.mark.mesh

NDEV = 8

MESH_CONF = {
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.explain": "NONE",
    "spark.rapids.shuffle.mode": "ICI",
    # pin the shuffled-exchange path — a small dim would otherwise
    # broadcast and skip the collective under test
    "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
    "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}",
    "spark.rapids.tpu.mesh.enabled": True,
}


@pytest.fixture(scope="module")
def session():
    return TpuSession(dict(MESH_CONF))


def make_dim(rng, n=120, key_space=300):
    keys = rng.permutation(key_space)[:n]
    return pa.table({
        "id": pa.array(keys, type=pa.int64()),
        "w": pa.array(rng.uniform(0.5, 1.5, n), type=pa.float64()),
        "tag": pa.array([f"t{k % 7}" for k in keys]),
    })


def make_fact(rng, n=2500, key_space=300):
    return pa.table({
        "id": pa.array(rng.integers(0, key_space, n), type=pa.int64()),
        "val": pa.array(rng.uniform(-1, 1, n), type=pa.float64()),
        "small": pa.array(rng.integers(-100, 100, n), type=pa.int32()),
    })


def find_exec(node, cls):
    if isinstance(node, cls):
        return node
    for c in node.children:
        r = find_exec(c, cls)
        if r is not None:
            return r
    return None


class TestShardedScan:
    def test_parquet_rowgroup_shards_end_to_end(self, session, rng,
                                                tmp_path):
        """The acceptance shape: planned scan->filter->exchange->join->agg
        with mesh.shape=8 executes its exchanges as mesh collectives —
        MESH_EXCHANGES > 0, zero host-shuffle bytes — bit-identical to
        the CPU engine, with the parquet scan sharded at row-group
        granularity across the chips."""
        import pyarrow.parquet as pq
        path = str(tmp_path / "fact.parquet")
        pq.write_table(make_fact(rng, n=3000), path, row_group_size=256)
        dim = session.from_arrow(make_dim(rng))
        q = (session.read_parquet(path).filter(col("val") > -0.5)
             .join(dim, on="id", how="inner")
             .group_by("tag").agg(n=Count(col("val")), s=Sum(col("small")),
                                  mx=Max(col("id")), mn=Min(col("small"))))
        before = EX.MESH_EXCHANGES
        TaskMetrics.reset()
        assert_same(q, sort_by=["tag"])
        tm = TaskMetrics.get()
        assert EX.MESH_EXCHANGES > before, "no mesh collective executed"
        assert tm.mesh_exchanges > 0
        assert tm.mesh_shards >= NDEV, "scan was not sharded"
        assert tm.mesh_ici_bytes > 0
        assert tm.shuffle_bytes_written == 0, \
            "mesh run moved bytes over the host shuffle data plane"
        assert "meshExchanges=" in tm.explain_string()

    def test_scan_shards_are_per_device_and_complete(self, session, rng):
        """MeshShardedScanExec yields exactly ndev batches, one committed
        to each mesh device, whose union is the input table."""
        import jax
        from spark_rapids_tpu.mesh.shard import MeshShardedScanExec
        from spark_rapids_tpu.plan.overrides import Overrides
        t = make_fact(rng, n=2000)
        session.initialize_device()
        q = (session.from_arrow(t)
             .join(session.from_arrow(make_dim(rng)), on="id", how="inner"))
        plan = Overrides(session.conf).apply(q.plan)
        scan = find_exec(plan, MeshShardedScanExec)
        assert scan is not None, "plan pass did not shard the scan"
        batches = list(scan.execute())
        assert len(batches) == NDEV
        devs = set()
        total = 0
        for b in batches:
            d = b.columns[0].data.devices()
            assert len(d) == 1 and b.columns[0].data.committed
            devs.add(next(iter(d)))
            total += int(b.row_count())
        assert len(devs) == NDEV, "shards not spread across the mesh"
        assert total == t.num_rows

    def test_resident_exchange_output_devices(self, session, rng):
        """The exchange feeding a zipped join is marked device-resident
        and hands out one committed single-device batch per chip — the
        'partitions stay on-device between exchange and join' contract
        (no gather to a replicated layout, no host concat)."""
        from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
        from spark_rapids_tpu.plan.overrides import Overrides
        session.initialize_device()
        q = (session.from_arrow(make_fact(rng, n=1500))
             .join(session.from_arrow(make_dim(rng)), on="id", how="inner"))
        plan = Overrides(session.conf).apply(q.plan)
        ex = find_exec(plan, TpuShuffleExchangeExec)
        assert ex is not None and ex.mesh_resident_out
        outs = list(ex.execute())
        assert len(outs) == NDEV
        devs = set()
        for b in outs:
            d = b.columns[0].data.devices()
            assert len(d) == 1 and b.columns[0].data.committed
            devs.add(next(iter(d)))
        assert len(devs) == NDEV

    def test_host_fallback_honors_shard_ranges(self, session, rng,
                                               tmp_path):
        """deviceDecode flipped off AFTER planning: shard clones fall to
        the host decode, which must still honor the row-group
        restriction — 8 shards re-reading the whole file would be a
        duplicated (wrong) split, not a slow one."""
        import pyarrow.parquet as pq
        from spark_rapids_tpu.mesh.shard import MeshShardedScanExec
        from spark_rapids_tpu.plan.overrides import Overrides
        n = 2000
        path = str(tmp_path / "fact.parquet")
        pq.write_table(make_fact(rng, n=n), path, row_group_size=128)
        session.initialize_device()
        q = session.read_parquet(path).repartition(NDEV, "id")
        plan = Overrides(session.conf).apply(q.plan)
        scan = find_exec(plan, MeshShardedScanExec)
        assert scan is not None
        key = "spark.rapids.sql.format.parquet.deviceDecode.enabled"
        session.conf.set(key, False)
        try:
            total = sum(int(b.row_count()) for b in scan.execute())
        finally:
            session.conf.set(key, True)
        assert total == n, \
            f"host fallback duplicated the shard split: {total} != {n}"

    @pytest.mark.slow
    def test_string_keys_ride_the_mesh(self, session, rng):
        """String group keys (lengths plane, no overflow) flow through
        the collective and the aligned per-shard assembly."""
        df = session.from_arrow(make_table(rng, n=1200))
        q = df.group_by("cat").agg(n=Count(col("id")),
                                   mx=Max(col("small")))
        before = EX.MESH_EXCHANGES
        assert_same(q, sort_by=["cat"])
        assert EX.MESH_EXCHANGES > before

    @pytest.mark.slow
    def test_parallel_shard_decode_one_admission_door(self, rng, tmp_path):
        """8 concurrent shard decode workers, ONE admission: workers
        adopt the query's hold (mesh/admission.py) — sched_admissions
        stays 1 and every worker thread is joined before the query
        returns."""
        import pyarrow.parquet as pq
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        path = str(tmp_path / "fact.parquet")
        pq.write_table(make_fact(rng, n=2400), path, row_group_size=256)
        conf = dict(MESH_CONF)
        conf["spark.rapids.tpu.mesh.scan.parallel"] = True
        conf["spark.rapids.tpu.sched.enabled"] = True
        sess = TpuSession(conf)
        sess.initialize_device()
        TpuSemaphore.initialize(sess.conf.concurrent_tpu_tasks, sess.conf)
        try:
            threads0 = threading.active_count()
            q = (sess.read_parquet(path).filter(col("val") > 0)
                 .group_by("id").agg(s=Sum(col("small"))))
            TaskMetrics.reset()
            tpu = q.collect().sort_by("id")
            tm = TaskMetrics.get()
            cpu = q.collect_cpu().sort_by("id")
            assert tpu.equals(cpu)
            assert tm.sched_admissions == 1, \
                f"per-shard token storm: {tm.sched_admissions} admissions"
            assert threading.active_count() <= threads0, \
                "mesh shard worker threads leaked"
        finally:
            TpuSemaphore._instance = None


class TestPartitionCountMismatch:
    def test_hash_repartition_resized_to_mesh(self, session, rng):
        """repartition(5, key) under the mesh: the plan pass resizes the
        hash exchange to mesh.size so it rides the collective."""
        df = session.from_arrow(make_fact(rng, n=1500))
        q = df.repartition(5, "id").group_by("id").agg(
            s=Sum(col("small")), n=Count(col("val")))
        before = EX.MESH_EXCHANGES
        TaskMetrics.reset()
        assert_same(q, sort_by=["id"])
        assert EX.MESH_EXCHANGES > before
        assert TaskMetrics.get().mesh_degraded == 0

    def test_roundrobin_mismatch_degrades_cleanly(self, session, rng):
        """repartition(5) (round-robin — partition membership is
        positional, never resized) must degrade to the host data plane:
        correct results, degrade counted, no wrong split."""
        df = session.from_arrow(make_fact(rng, n=1200))
        q = df.repartition(5).group_by("id").agg(s=Sum(col("small")))
        TaskMetrics.reset()
        assert_same(q, sort_by=["id"])
        assert TaskMetrics.get().mesh_degraded >= 1

    def test_range_mismatch_degrades_cleanly(self, session, rng):
        df = session.from_arrow(make_fact(rng, n=1200))
        q = df.repartition_by_range(5, "id")
        TaskMetrics.reset()
        assert_same(q, sort_by=["id", "val"])
        assert TaskMetrics.get().mesh_degraded >= 1

    @pytest.mark.slow
    def test_resize_off_degrades_cleanly(self, rng):
        """With resizeExchanges off a mismatched hash exchange keeps its
        partition count and takes the host path — never a wrong split."""
        conf = dict(MESH_CONF)
        conf["spark.rapids.tpu.mesh.resizeExchanges"] = False
        sess = TpuSession(conf)
        df = sess.from_arrow(make_fact(rng, n=1200))
        q = df.repartition(5, "id").group_by("id").agg(
            s=Sum(col("small")))
        TaskMetrics.reset()
        assert_same(q, sort_by=["id"])
        assert TaskMetrics.get().mesh_degraded >= 1


class TestPerChipMemory:
    def _conf(self, per_chip):
        conf = TpuSession(dict(MESH_CONF)).conf
        conf.set("spark.rapids.tpu.mesh.hbmPerChip", per_chip)
        return conf

    def test_chip_ledger_spills_one_chip_only(self, rng):
        """Chip-tagged parked buffers charge their OWN chip; overflowing
        chip 3's sub-budget spills only chip-3 buffers — chip 0's stay
        device-resident (the per-chip half of the PR-6 quota model)."""
        from spark_rapids_tpu.columnar.batch import batch_from_dict
        from spark_rapids_tpu.memory.budget import MemoryBudget
        from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                                     StorageTier)
        from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch

        def mk_batch():
            return batch_from_dict(
                {"v": rng.normal(size=4096)})

        probe = mk_batch().device_memory_size()
        old_budget = MemoryBudget._instance
        old_catalog = BufferCatalog._instance
        try:
            BufferCatalog._instance = BufferCatalog()
            MemoryBudget.initialize(1 << 40, self._conf(int(probe * 2.5)))
            assert MemoryBudget.get().chip_budgets, \
                "per-chip budgets not configured"
            chip0 = SpillableColumnarBatch(mk_batch(), chip=0)
            chip3 = [SpillableColumnarBatch(mk_batch(), chip=3)
                     for _ in range(4)]  # ~4x a 2.5x budget => must spill
            cat = BufferCatalog.get()
            assert cat.tier_of(chip0._handle) == StorageTier.DEVICE, \
                "chip-0 buffer evicted by chip-3 pressure"
            spilled3 = sum(cat.tier_of(sp._handle) != StorageTier.DEVICE
                           for sp in chip3)
            assert spilled3 >= 1, "chip-3 overflow did not spill"
            b = MemoryBudget.get()
            assert b.chip_used.get(3, 0) <= b.chip_budgets[3]
            assert b.chip_used.get(0, 0) == probe
            for sp in [chip0] + chip3:
                sp.close()
            assert b.chip_used.get(0, 0) == 0
            assert b.chip_used.get(3, 0) == 0
        finally:
            MemoryBudget._instance = old_budget
            BufferCatalog._instance = old_catalog

    @pytest.mark.slow
    def test_mesh_query_under_tenant_quota(self, rng):
        """A mesh-active query under a PR-6 tenant sub-quota completes
        bit-identically (over-quota steps split, never evict neighbours)
        and drains its tenant ledger."""
        from spark_rapids_tpu.memory.budget import MemoryBudget
        conf = dict(MESH_CONF)
        conf["spark.rapids.tpu.sched.tenant"] = "t1"
        conf["spark.rapids.tpu.sched.tenant.quotas"] = "t1=0.5"
        sess = TpuSession(conf)
        old_budget = MemoryBudget._instance
        try:
            sess.initialize_device()
            MemoryBudget.initialize(1 << 30, sess.conf)
            q = (sess.from_arrow(make_fact(rng, n=1500))
                 .join(sess.from_arrow(make_dim(rng)), on="id",
                       how="inner")
                 .group_by("tag").agg(n=Count(col("val"))))
            tpu = q.collect().sort_by("tag")
            cpu = q.collect_cpu().sort_by("tag")
            assert tpu.equals(cpu)
            b = MemoryBudget.get()
            assert b.tenant_used.get("t1", 0) == 0, \
                "tenant ledger not drained after the mesh query"
        finally:
            MemoryBudget._instance = old_budget


class TestRescacheIciSeam:
    @pytest.mark.slow
    def test_exchange_fragments_replay_on_mesh(self, rng):
        """The rescache exchange seam is un-gated for ICI under mesh
        execution: a repeated subplan replays its mesh-exchanged
        partitions from chip-tagged spillables — second run answers with
        cache hits, zero new collectives, identical bytes."""
        from spark_rapids_tpu import rescache
        conf = dict(MESH_CONF)
        conf["spark.rapids.tpu.rescache.enabled"] = True
        conf["spark.rapids.tpu.rescache.exchange.enabled"] = True
        conf["spark.rapids.tpu.rescache.query.enabled"] = False
        conf["spark.rapids.tpu.rescache.scan.enabled"] = False
        sess = TpuSession(conf)
        try:
            fact = make_fact(rng, n=1500)
            dim = make_dim(rng)

            def q():
                return (sess.from_arrow(fact)
                        .join(sess.from_arrow(dim), on="id", how="inner")
                        .group_by("tag").agg(n=Count(col("val")),
                                             s=Sum(col("small"))))
            cold = q().collect().sort_by("tag")
            before = EX.MESH_EXCHANGES
            TaskMetrics.reset()
            warm = q().collect().sort_by("tag")
            tm = TaskMetrics.get()
            assert warm.equals(cold)
            assert tm.rescache_hits > 0, "exchange seam did not replay"
            assert EX.MESH_EXCHANGES == before, \
                "warm run re-executed the collective"
        finally:
            rescache.shutdown()


class TestMeshOffPath:
    def test_off_plans_and_results_byte_identical(self, rng):
        """mesh.enabled=false (even with a mesh shape configured) is the
        established off contract: plans byte-identical to a no-mesh
        session, zero new threads, zero mesh plan activity."""
        import spark_rapids_tpu.mesh as mesh
        from spark_rapids_tpu.plan.overrides import Overrides
        fact = make_fact(rng, n=1000)
        dim = make_dim(rng)

        def tree(s):
            q = (s.from_arrow(fact).join(s.from_arrow(dim), on="id",
                                         how="inner")
                 .group_by("tag").agg(n=Count(col("val"))))
            return Overrides(s.conf).apply(q.plan).tree_string(), q
        plans_before = mesh.MESH_PLANS
        threads0 = threading.active_count()
        s_plain = TpuSession({"spark.rapids.sql.enabled": True,
                              "spark.rapids.sql.explain": "NONE"})
        off_conf = {"spark.rapids.sql.enabled": True,
                    "spark.rapids.sql.explain": "NONE",
                    "spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}",
                    "spark.rapids.tpu.mesh.enabled": False}
        s_off = TpuSession(off_conf)
        t_plain, _ = tree(s_plain)
        t_off, q_off = tree(s_off)
        assert t_plain == t_off, "mesh-off plan differs from no-mesh plan"
        assert "MeshShardedScanExec" not in t_off
        assert mesh.MESH_PLANS == plans_before, \
            "mesh plan pass engaged while disabled"
        assert threading.active_count() <= threads0
        assert_same(q_off, sort_by=["tag"])

    def test_mesh_needs_ici_mode(self, rng):
        """mesh.enabled with a non-ICI shuffle mode never engages the
        pass (the data plane IS the point)."""
        import spark_rapids_tpu.mesh as mesh
        conf = dict(MESH_CONF)
        conf["spark.rapids.shuffle.mode"] = "MULTITHREADED"
        sess = TpuSession(conf)
        before = mesh.MESH_PLANS
        q = (sess.from_arrow(make_fact(rng, n=800))
             .group_by("id").agg(s=Sum(col("small"))))
        assert_same(q, sort_by=["id"])
        assert mesh.MESH_PLANS == before


class TestConfMeshCache:
    def test_mesh_from_conf_invalidates_on_set(self):
        """The `_CONF_MESH` memo drops whenever a mesh conf key changes
        via TpuConf.set — the same conf-generation invalidation the
        padding memo got in PR 3 (no stale mesh mid-session)."""
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.parallel import mesh as pmesh
        conf = TpuConf({"spark.rapids.tpu.mesh.shape": f"shuffle={NDEV}"})
        m1 = pmesh.mesh_from_conf(conf)
        assert m1 is not None and pmesh._CONF_MESH
        conf.set("spark.rapids.tpu.mesh.shape", "shuffle=4")
        assert not pmesh._CONF_MESH, \
            "conf.set on a mesh key did not invalidate the mesh cache"
        m2 = pmesh.mesh_from_conf(conf)
        assert m2 is not None and m2.size == 4
        conf.set("spark.rapids.tpu.mesh.enabled", True)
        assert not pmesh._CONF_MESH


class TestSurfacing:
    @pytest.mark.slow
    def test_telemetry_counters_and_chip_gauge(self, rng):
        """tpu_mesh_exchanges_total / tpu_mesh_ici_bytes_total move on
        the scrape surface for a mesh query; the per-chip HBM gauge
        renders from the budget singleton."""
        from spark_rapids_tpu import telemetry
        conf = dict(MESH_CONF)
        conf["spark.rapids.tpu.telemetry.enabled"] = True
        conf["spark.rapids.tpu.telemetry.http.port"] = -1
        sess = TpuSession(conf)
        try:
            telemetry.configure(sess.conf)
            q = (sess.from_arrow(make_fact(rng, n=1200))
                 .group_by("id").agg(s=Sum(col("small"))))
            q.collect()
            text = telemetry.render_prometheus()
            assert "tpu_mesh_exchanges_total" in text
            ln = [l for l in text.splitlines()
                  if l.startswith("tpu_mesh_exchanges_total")]
            assert ln and float(ln[0].rsplit(" ", 1)[1]) >= 1
            assert "tpu_mesh_ici_bytes_total" in text
        finally:
            telemetry.shutdown()

    def test_report_mesh_summary(self):
        from spark_rapids_tpu.tools.profile_report import mesh_summary
        model = {"queries": [
            {"task_metrics": {"mesh_exchanges": 3, "mesh_ici_bytes": 1024,
                              "mesh_shards": 16, "mesh_degraded": 1}},
            {"task_metrics": {}},
        ]}
        s = mesh_summary(model)
        assert s == {"queries": 1, "exchanges": 3, "ici_bytes": 1024,
                     "shards": 16, "degraded": 1}
        assert mesh_summary({"queries": [{"task_metrics": {}}]}) == {}
