"""Compile service matrix (spark_rapids_tpu/compile/): keyed program cache,
persistent tier, single-flight, fault degradation, warmup, bucket tuner,
and the padding-conf memoization satellite.

Acceptance contract (ISSUE 3):
  * the same query run twice in one session shows cache hits and ZERO new
    compiles on the second run (asserted via service stats + TaskMetrics);
  * clearing the in-memory tier (simulated process restart) reloads
    executables from the persistent tier without recompiling;
  * injected `compile` faults degrade to direct jax.jit with a typed
    warning (CompileServiceWarning) — never a wrong result;
  * a poisoned persistent entry is a miss + delete, never a wrong program.
"""

import os
import threading
import warnings

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import padding
from spark_rapids_tpu.compile import BucketTuner, CompileService, run_warmup
from spark_rapids_tpu.config import get_default_conf
from spark_rapids_tpu.errors import CompileServiceWarning
from spark_rapids_tpu.expr import Count, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession
from spark_rapids_tpu.utils.metrics import TaskMetrics

pytestmark = pytest.mark.compile


@pytest.fixture
def service():
    """A fresh CompileService singleton per test (and restore after)."""
    CompileService.reset()
    svc = CompileService.get()
    yield svc
    CompileService.reset()
    BucketTuner.reset()


@pytest.fixture
def session(service, tmp_path):
    s = TpuSession({"spark.rapids.sql.enabled": True,
                    "spark.rapids.sql.explain": "NONE",
                    "spark.rapids.tpu.compile.cache.dir":
                        str(tmp_path / "xla_cache")})
    s.initialize_device()
    return s


def _table(rows=800, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array((np.arange(rows) % 11).astype(np.int64)),
        "v": pa.array(rng.uniform(0.0, 10.0, rows)),
    })


def _query(session, t):
    df = session.from_arrow(t)
    return (df.filter(col("k") > 2)
              .group_by("k")
              .agg(total=Sum(col("v")), n=Count(col("v")))
              .collect()
              .sort_by([("k", "ascending")]))


class TestWarmVsCold:
    def test_second_identical_query_zero_new_compiles(self, session,
                                                      service):
        t = _table()
        r1 = _query(session, t)
        after_cold = service.stats.totals()
        assert after_cold["compiles"] > 0
        assert TaskMetrics.get().compile_count > 0  # per-query counter

        r2 = _query(session, t)
        after_warm = service.stats.totals()
        assert r1.equals(r2)
        assert after_warm["compiles"] == after_cold["compiles"], \
            "second identical query must not compile anything new"
        assert after_warm["hits"] > after_cold["hits"]
        # TaskMetrics resets per query: the warm query saw hits, no compiles
        tm = TaskMetrics.get()
        assert tm.compile_count == 0
        assert tm.compile_cache_hits > 0
        assert "compileCacheHits" in tm.explain_string()

    def test_restart_reloads_from_persistent_tier(self, session, service):
        t = _table()
        r1 = _query(session, t)
        warm = service.stats.totals()
        assert warm["persist_stores"] > 0
        assert len(os.listdir(service.persistent_dir)) == \
            warm["persist_stores"]

        service.clear_memory()  # simulated process restart
        r2 = _query(session, t)
        cold = service.stats.totals()
        assert r1.equals(r2)
        assert cold["compiles"] == warm["compiles"], \
            "restart must reload persisted executables, not recompile"
        assert cold["persist_hits"] > 0
        assert TaskMetrics.get().compile_persist_hits > 0

    def test_stats_tracked_per_op(self, session, service):
        _query(session, _table())
        per_op = service.stats.per_op()
        assert any(op.startswith("exec.filter") for op in per_op)
        assert any(op.startswith("exec.aggregate") for op in per_op)
        for d in per_op.values():
            assert d["compile_ns"] >= 0


@pytest.mark.faults
class TestCompileFaults:
    def test_compile_fault_degrades_to_direct_jit(self, session, service):
        t = _table(seed=3)
        with faults.inject(faults.COMPILE, "error", nth=1) as rule:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                r_fault = _query(session, t)
        assert rule.fired == 1
        assert any(isinstance(w.message, CompileServiceWarning)
                   for w in caught), \
            "degradation must surface a typed warning"
        assert service.stats.totals()["fallbacks"] >= 1
        # the direct-jit path computes the identical program
        assert r_fault.equals(_query(session, t))

    def test_compile_delay_fault_still_succeeds(self, session, service):
        t = _table(seed=4)
        with faults.inject(faults.COMPILE, "delay", nth=1,
                           delay_s=0.05) as rule:
            r = _query(session, t)
        assert rule.fired == 1
        assert r.equals(_query(session, t))

    def test_injected_corruption_is_miss_plus_delete(self, session,
                                                     service):
        t = _table(seed=5)
        r1 = _query(session, t)
        baseline = service.stats.totals()
        service.clear_memory()
        # every persisted read returns flipped bytes -> CRC mismatch
        with faults.inject(faults.COMPILE, "corrupt", nth=0, times=0):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                r2 = _query(session, t)
        assert r2.equals(r1), "corruption must never produce wrong rows"
        tot = service.stats.totals()
        assert tot["poisoned"] >= 1
        assert tot["compiles"] > baseline["compiles"], \
            "poisoned entries recompile"
        # deleted-then-repersisted: the tier stays usable
        service.clear_memory()
        r3 = _query(session, t)
        assert r3.equals(r1)
        assert service.stats.totals()["persist_hits"] > \
            tot["persist_hits"]

    def test_on_disk_garbage_is_rejected(self, session, service):
        t = _table(seed=6)
        r1 = _query(session, t)
        # scribble over every persisted entry directly (torn write /
        # truncation / foreign bytes)
        for f in os.listdir(service.persistent_dir):
            with open(os.path.join(service.persistent_dir, f), "wb") as fh:
                fh.write(b"not a program")
        service.clear_memory()
        r2 = _query(session, t)
        assert r2.equals(r1)
        assert service.stats.totals()["poisoned"] >= 1


class TestServiceMechanics:
    def test_single_flight_dedups_concurrent_compiles(self, service):
        import jax.numpy as jnp

        from spark_rapids_tpu.compile import sjit

        @sjit(op="test.single_flight")
        def kernel(x):
            return (x * 3 + 1).sum()

        x = jnp.arange(4096, dtype=jnp.float64)
        barrier = threading.Barrier(4)
        results = []

        def worker():
            barrier.wait()
            results.append(float(kernel(x)))

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(set(results)) == 1
        st = service.stats.per_op()["test.single_flight"]
        assert st["compiles"] == 1, \
            f"concurrent callers must share one compile, saw {st}"

    def test_distinct_shapes_get_distinct_programs(self, service):
        import jax.numpy as jnp

        from spark_rapids_tpu.compile import sjit

        @sjit(op="test.shapes")
        def kernel(x):
            return x + 1

        kernel(jnp.zeros(128))
        kernel(jnp.zeros(256))
        kernel(jnp.zeros(128))  # hit
        st = service.stats.per_op()["test.shapes"]
        assert st["compiles"] == 2
        assert st["hits"] == 1

    def test_static_args_key_the_program(self, service):
        import jax.numpy as jnp

        from spark_rapids_tpu.compile import sjit

        @sjit(op="test.statics", static_argnums=(1,))
        def kernel(x, k: int):
            return x[:k].sum()

        x = jnp.arange(512, dtype=jnp.float64)
        assert float(kernel(x, 4)) == 6.0
        assert float(kernel(x, 8)) == 28.0
        assert float(kernel(x, 4)) == 6.0
        st = service.stats.per_op()["test.statics"]
        assert st["compiles"] == 2 and st["hits"] == 1

    def test_disabled_service_is_direct_passthrough(self, tmp_path):
        CompileService.reset()
        svc = CompileService.get()
        s = TpuSession({"spark.rapids.sql.enabled": True,
                        "spark.rapids.sql.explain": "NONE",
                        "spark.rapids.tpu.compile.enabled": False})
        s.initialize_device()
        t = _table(seed=7)
        r = _query(s, t)
        assert r.num_rows > 0
        assert svc.stats.totals()["compiles"] == 0, \
            "disabled service must not account compiles"
        CompileService.reset()

    def test_lru_bounds_memory_tier(self, service):
        import jax.numpy as jnp

        from spark_rapids_tpu.compile import sjit
        service._max_programs = 2

        @sjit(op="test.lru")
        def kernel(x):
            return x * 2

        for n in (128, 256, 384):
            kernel(jnp.zeros(n))
        assert service.cached_programs() <= 2


class TestWarmup:
    def test_warmup_precompiles_generic_kernels(self, service, tmp_path):
        from spark_rapids_tpu.config import TpuConf
        conf = TpuConf({
            "spark.rapids.tpu.compile.cache.dir": str(tmp_path / "wc"),
            "spark.rapids.tpu.compile.warmup.maxRows": 1024,
            "spark.rapids.tpu.compile.warmup.schema": "long,double",
        })
        service.configure(conf)  # warmup.enabled stays False: run inline
        stats = run_warmup(conf, service)
        assert stats["synthetic"] > 0
        warm = service.stats.totals()["compiles"]
        assert warm > 0
        # a real concat at a warmed shape is now a pure cache hit
        from spark_rapids_tpu.compile.warmup import make_warmup_batch
        from spark_rapids_tpu.exec.coalesce import concat_batches
        b = make_warmup_batch(["long", "double"], 128, 64)
        concat_batches([b, b])
        assert service.stats.totals()["compiles"] == warm, \
            "warmed shape must not recompile"

    def test_warmup_preloads_persistent_tier(self, session, service):
        t = _table(seed=8)
        _query(session, t)
        service.clear_memory()
        assert service.cached_programs() == 0
        stats = run_warmup(session.conf, service)
        assert stats["preloaded"] > 0
        assert service.cached_programs() >= stats["preloaded"]
        before = service.stats.totals()["compiles"]
        _query(session, t)
        assert service.stats.totals()["compiles"] == before

    def test_background_warmup_thread_starts(self, tmp_path):
        CompileService.reset()
        svc = CompileService.get()
        s = TpuSession({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.tpu.compile.warmup.enabled": True,
            "spark.rapids.tpu.compile.warmup.maxRows": 256,
            "spark.rapids.tpu.compile.cache.dir": str(tmp_path / "bg"),
        })
        s.initialize_device()
        assert svc.warmup_thread is not None
        svc.warmup_thread.join(timeout=120)
        assert not svc.warmup_thread.is_alive()
        assert svc.stats.totals()["compiles"] > 0
        CompileService.reset()


class TestBucketTuner:
    def test_observations_are_attributed(self, session, service):
        tuner = BucketTuner.get()
        tuner.clear()
        _query(session, _table())
        obs = tuner.observations()
        assert sum(sum(h.values()) for h in obs.values()) > 0

    def test_retune_installs_learned_ladder(self, service):
        tuner = BucketTuner.get()
        tuner.clear()
        try:
            # workload clustered at ~3000 and ~50000 rows
            for _ in range(40):
                tuner.record("scan", 3000)
            for _ in range(10):
                tuner.record("scan", 50_000)
            ladder = tuner.retune()
            assert ladder, "clustered observations must yield a ladder"
            assert padding.tuned_buckets() == ladder
            # observed sizes land exactly on a rung (no geometric slack)
            assert padding.row_bucket(3000) == 3072
            assert padding.row_bucket(50_000) == 50_048
            # sizes beyond the ladder still grow geometrically
            assert padding.row_bucket(200_000) >= 200_000
        finally:
            tuner.clear()

    def test_retuned_buckets_cut_waste_vs_geometric(self, service):
        tuner = BucketTuner.get()
        tuner.clear()
        try:
            n = 33_000  # just past the 32768 geometric rung -> 2x waste
            geometric_cap = padding.row_bucket(n)
            for _ in range(32):
                tuner.record("scan", n)
            tuner.retune()
            tuned_cap = padding.row_bucket(n)
            assert tuned_cap < geometric_cap
            assert (tuned_cap - n) / n < 0.01
        finally:
            tuner.clear()

    def test_ladder_clears_back_to_geometric(self, service):
        tuner = BucketTuner.get()
        tuner.record("x", 5000)
        tuner.retune()
        tuner.clear()
        assert padding.tuned_buckets() == ()
        assert padding.row_bucket(129) == 256


class TestPaddingMemoization:
    def test_conf_change_invalidates_memo(self):
        conf = get_default_conf()
        orig = conf._settings.get("spark.rapids.tpu.padding.minRows")
        try:
            assert padding.row_bucket(1) == 128
            conf.set("spark.rapids.tpu.padding.minRows", 512)
            # TpuConf.set on a padding key must drop the memo immediately
            assert padding.row_bucket(1) == 512
        finally:
            if orig is None:
                conf._settings.pop("spark.rapids.tpu.padding.minRows",
                                   None)
            else:
                conf._settings["spark.rapids.tpu.padding.minRows"] = orig
            padding.invalidate_cache()
            assert padding.row_bucket(1) == 128

    def test_hot_path_skips_conf_registry(self, monkeypatch):
        """row_bucket must not consult TpuConf.get per call once memoized."""
        import spark_rapids_tpu.columnar.padding as pad
        pad.row_bucket(100)  # prime the memo
        calls = {"n": 0}
        conf = get_default_conf()
        real_get = conf.get

        def counting_get(key):
            calls["n"] += 1
            return real_get(key)

        monkeypatch.setattr(conf, "get", counting_get)
        for _ in range(50):
            pad.row_bucket(1000)
        assert calls["n"] == 0, "memoized params must bypass conf.get"
