"""Extended expression surface: string breadth, math, datetime, array ops —
CPU-vs-TPU differential plus handwritten Spark-semantic expectations
(values cross-checked against Spark 3.x behavior)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr import (
    AddMonths, ArrayMax, ArrayMin, Ascii, Atan2, BitLength, BRound, Chr,
    ConcatWs, Cot, Expm1, FindInSet, Hypot, InitCap, LastDay, Left, Log1p,
    Logarithm, MonthsBetween, NextDay, OctetLength, Right, Rint, SortArray,
    StringInstr, StringLocate, StringLPad, StringRepeat, StringReplace,
    StringReverse, StringRPad, StringSpace, SubstringIndex, StringTranslate,
    TruncDate, col, lit)

from harness import assert_cpu_tpu_equal

S = lambda *v: pa.array(v, type=pa.string())
I = lambda *v: pa.array(v, type=pa.int32())
D = lambda *v: pa.array(v, type=pa.float64())


def t(**cols):
    return pa.table(dict(cols))


def dates(*v):
    return pa.array(v, type=pa.date32())


class TestStringBreadth:
    def test_repeat(self):
        out = assert_cpu_tpu_equal(
            lambda: StringRepeat(col("s"), lit(3)),
            t(s=S("ab", "", None, "xyz")))
        assert out.to_pylist() == ["ababab", "", None, "xyzxyzxyz"]

    def test_lpad_rpad(self):
        out = assert_cpu_tpu_equal(
            lambda: StringLPad(col("s"), lit(5), lit("*-")),
            t(s=S("ab", "abcdef", "", None)))
        assert out.to_pylist() == ["*-*ab", "abcde", "*-*-*", None]
        out = assert_cpu_tpu_equal(
            lambda: StringRPad(col("s"), lit(5), lit("xy")),
            t(s=S("ab", "abcdef", "")))
        assert out.to_pylist() == ["abxyx", "abcde", "xyxyx"]

    def test_lpad_utf8_truncation(self):
        out = assert_cpu_tpu_equal(
            lambda: StringLPad(col("s"), lit(3), lit(".")),
            t(s=S("héllo", "é")))
        assert out.to_pylist() == ["hél", "..é"]

    def test_locate_instr(self):
        out = assert_cpu_tpu_equal(
            lambda: StringLocate(lit("bar"), col("s"), lit(1)),
            t(s=S("foobarbar", "foo", None, "barbar")))
        assert out.to_pylist() == [4, 0, None, 1]
        out = assert_cpu_tpu_equal(
            lambda: StringLocate(lit("bar"), col("s"), lit(5)),
            t(s=S("foobarbar", "barbar")))
        assert out.to_pylist() == [7, 0]
        out = assert_cpu_tpu_equal(
            lambda: StringInstr(col("s"), lit("ar")),
            t(s=S("foobar", "xx")))
        assert out.to_pylist() == [5, 0]

    def test_locate_utf8_positions(self):
        out = assert_cpu_tpu_equal(
            lambda: StringLocate(lit("ll"), col("s"), lit(1)),
            t(s=S("héllo")))
        assert out.to_pylist() == [3]  # char positions, not bytes

    def test_replace(self):
        out = assert_cpu_tpu_equal(
            lambda: StringReplace(col("s"), lit("ab"), lit("XYZ")),
            t(s=S("ababab", "xabx", "", None, "aab")))
        assert out.to_pylist() == ["XYZXYZXYZ", "xXYZx", "", None, "aXYZ"]

    def test_replace_delete(self):
        out = assert_cpu_tpu_equal(
            lambda: StringReplace(col("s"), lit("aa"), lit("")),
            t(s=S("aaaa", "baaab", "aaa")))
        assert out.to_pylist() == ["", "bab", "a"]  # java semantics: scan resumes AFTER each match

    def test_translate(self):
        out = assert_cpu_tpu_equal(
            lambda: StringTranslate(col("s"), lit("abc"), lit("xy")),
            t(s=S("aabbcc", "cab", None)))
        assert out.to_pylist() == ["xxyy", "xy", None]

    def test_reverse(self):
        out = assert_cpu_tpu_equal(
            lambda: StringReverse(col("s")),
            t(s=S("abc", "", None, "héllo")))
        assert out.to_pylist() == ["cba", "", None, "olléh"]

    def test_concat_ws_skips_nulls(self):
        out = assert_cpu_tpu_equal(
            lambda: ConcatWs(lit(","), col("a"), col("b"), col("c")),
            t(a=S("x", None, None), b=S("y", "q", None), c=S(None, "r", None)))
        assert out.to_pylist() == ["x,y", "q,r", ""]

    def test_substring_index(self):
        out = assert_cpu_tpu_equal(
            lambda: SubstringIndex(col("s"), lit("."), lit(2)),
            t(s=S("a.b.c.d", "abc", "", None)))
        assert out.to_pylist() == ["a.b", "abc", "", None]
        out = assert_cpu_tpu_equal(
            lambda: SubstringIndex(col("s"), lit("."), lit(-2)),
            t(s=S("a.b.c.d", "abc")))
        assert out.to_pylist() == ["c.d", "abc"]

    def test_initcap(self):
        out = assert_cpu_tpu_equal(
            lambda: InitCap(col("s")),
            t(s=S("spark sql", "SPARK  SQL", "x", None)))
        assert out.to_pylist() == ["Spark Sql", "Spark  Sql", "X", None]

    def test_ascii_chr(self):
        out = assert_cpu_tpu_equal(
            lambda: Ascii(col("s")), t(s=S("A", "", "abc", "é", None)))
        assert out.to_pylist() == [65, 0, 97, 233, None]
        out = assert_cpu_tpu_equal(
            lambda: Chr(col("n")), t(n=pa.array([65, 97, 0, 256 + 66, 233],
                                                type=pa.int64())))
        assert out.to_pylist() == ["A", "a", "", "B", "é"]

    def test_left_right(self):
        out = assert_cpu_tpu_equal(
            lambda: Left(col("s"), lit(3)), t(s=S("abcdef", "ab", None, "")))
        assert out.to_pylist() == ["abc", "ab", None, ""]
        out = assert_cpu_tpu_equal(
            lambda: Right(col("s"), lit(3)), t(s=S("abcdef", "ab", None, "")))
        assert out.to_pylist() == ["def", "ab", None, ""]

    def test_space_bit_octet(self):
        out = assert_cpu_tpu_equal(lambda: StringSpace(lit(4)),
                                   t(s=S("x", "y")))
        assert out.to_pylist() == ["    ", "    "]
        out = assert_cpu_tpu_equal(lambda: BitLength(col("s")),
                                   t(s=S("abc", "", "é", None)))
        assert out.to_pylist() == [24, 0, 16, None]
        out = assert_cpu_tpu_equal(lambda: OctetLength(col("s")),
                                   t(s=S("abc", "é", None)))
        assert out.to_pylist() == [3, 2, None]

    def test_find_in_set(self):
        out = assert_cpu_tpu_equal(
            lambda: FindInSet(col("s"), lit("ab,cd,ef")),
            t(s=S("cd", "ab", "ef", "x", "", "a,b", None)))
        assert out.to_pylist() == [2, 1, 3, 0, 0, 0, None]

    def test_find_in_set_empty_element(self):
        out = assert_cpu_tpu_equal(
            lambda: FindInSet(col("s"), lit("ab,,cd")),
            t(s=S("", "cd")))
        assert out.to_pylist() == [2, 3]


class TestMathBreadth:
    def test_atan2_hypot(self):
        out = assert_cpu_tpu_equal(lambda: Atan2(col("a"), col("b")),
                                   t(a=D(1.0, 0.0, None), b=D(1.0, -1.0, 2.0)))
        exp = [np.arctan2(1.0, 1.0), np.arctan2(0.0, -1.0), None]
        got = out.to_pylist()
        assert got[2] is None and \
            np.allclose(got[:2], exp[:2], rtol=1e-12)
        out = assert_cpu_tpu_equal(lambda: Hypot(col("a"), col("b")),
                                   t(a=D(3.0, 5.0), b=D(4.0, 12.0)))
        assert np.allclose(out.to_pylist(), [5.0, 13.0], rtol=1e-12)

    def test_logarithm_domain(self):
        out = assert_cpu_tpu_equal(lambda: Logarithm(lit(2.0), col("x")),
                                   t(x=D(8.0, 0.0, -1.0, None)))
        got = out.to_pylist()
        assert abs(got[0] - 3.0) < 1e-12
        assert got[1] is None and got[2] is None and got[3] is None

    def test_expm1_log1p_rint_cot(self):
        out = assert_cpu_tpu_equal(lambda: Expm1(col("x")), t(x=D(0.0, 1.0)),
                                   approx=True)
        assert np.allclose(out.to_pylist(), [0.0, np.expm1(1.0)], rtol=1e-12)
        out = assert_cpu_tpu_equal(lambda: Log1p(col("x")),
                                   t(x=D(0.0, -2.0)))
        assert out.to_pylist()[1] is None
        out = assert_cpu_tpu_equal(lambda: Rint(col("x")),
                                   t(x=D(2.5, 3.5, -2.5)))
        assert out.to_pylist() == [2.0, 4.0, -2.0]  # half-even
        out = assert_cpu_tpu_equal(lambda: Cot(col("x")), t(x=D(1.0)),
                                   approx=True)
        assert np.allclose(out.to_pylist(), [1 / np.tan(1.0)], rtol=1e-12)

    def test_bround_half_even(self):
        out = assert_cpu_tpu_equal(lambda: BRound(col("x"), 0),
                                   t(x=D(2.5, 3.5, -2.5, 1.25)))
        assert out.to_pylist() == [2.0, 4.0, -2.0, 1.0]
        out = assert_cpu_tpu_equal(lambda: BRound(col("x"), 1),
                                   t(x=D(1.25, 1.35)), approx=True)
        got = out.to_pylist()
        assert abs(got[0] - 1.2) < 1e-9 and abs(got[1] - 1.4) < 1e-9


class TestDatetimeBreadth:
    def test_last_day(self):
        import datetime as dt
        out = assert_cpu_tpu_equal(
            lambda: LastDay(col("d")),
            t(d=dates(dt.date(2020, 2, 10), dt.date(2021, 2, 1),
                      dt.date(2020, 12, 31), None)))
        assert out.to_pylist() == [dt.date(2020, 2, 29), dt.date(2021, 2, 28),
                                   dt.date(2020, 12, 31), None]

    def test_add_months_clamps(self):
        import datetime as dt
        out = assert_cpu_tpu_equal(
            lambda: AddMonths(col("d"), lit(1)),
            t(d=dates(dt.date(2020, 1, 31), dt.date(2020, 2, 29), None)))
        assert out.to_pylist() == [dt.date(2020, 2, 29),
                                   dt.date(2020, 3, 29), None]

    def test_months_between(self):
        import datetime as dt
        out = assert_cpu_tpu_equal(
            lambda: MonthsBetween(col("a"), col("b")),
            t(a=dates(dt.date(2020, 3, 31), dt.date(2020, 3, 15)),
              b=dates(dt.date(2020, 1, 31), dt.date(2020, 1, 15))))
        assert out.to_pylist() == [2.0, 2.0]
        out = assert_cpu_tpu_equal(
            lambda: MonthsBetween(col("a"), col("b")),
            t(a=dates(dt.date(2020, 2, 1)), b=dates(dt.date(2020, 1, 10))))
        assert abs(out.to_pylist()[0] - (1 + (1 - 10) / 31.0)) < 1e-8

    def test_trunc_date(self):
        import datetime as dt
        d = dates(dt.date(2020, 5, 15), dt.date(2020, 11, 3), None)
        for fmt, exp in [("YEAR", [dt.date(2020, 1, 1), dt.date(2020, 1, 1),
                                   None]),
                         ("MM", [dt.date(2020, 5, 1), dt.date(2020, 11, 1),
                                 None]),
                         ("QUARTER", [dt.date(2020, 4, 1),
                                      dt.date(2020, 10, 1), None]),
                         ("WEEK", [dt.date(2020, 5, 11),
                                   dt.date(2020, 11, 2), None])]:
            out = assert_cpu_tpu_equal(lambda: TruncDate(col("d"), fmt),
                                       t(d=d))
            assert out.to_pylist() == exp, fmt

    def test_next_day(self):
        import datetime as dt
        out = assert_cpu_tpu_equal(
            lambda: NextDay(col("d"), "MON"),
            # 2020-05-15 is a Friday; next Monday = 05-18
            t(d=dates(dt.date(2020, 5, 15), dt.date(2020, 5, 18))))
        assert out.to_pylist() == [dt.date(2020, 5, 18),
                                   dt.date(2020, 5, 25)]


class TestArrayOps:
    def arr(self, *v):
        return pa.array(v, type=pa.list_(pa.int64()))

    def test_array_min_max(self):
        data = self.arr([3, 1, 2], [], None, [5, None, -7])
        out = assert_cpu_tpu_equal(lambda: ArrayMin(col("a")), t(a=data))
        assert out.to_pylist() == [1, None, None, -7]
        out = assert_cpu_tpu_equal(lambda: ArrayMax(col("a")), t(a=data))
        assert out.to_pylist() == [3, None, None, 5]

    def test_sort_array(self):
        data = self.arr([3, 1, None, 2], [], None)
        out = assert_cpu_tpu_equal(lambda: SortArray(col("a")), t(a=data))
        assert out.to_pylist() == [[None, 1, 2, 3], [], None]
        out = assert_cpu_tpu_equal(lambda: SortArray(col("a"), False),
                                   t(a=data))
        assert out.to_pylist() == [[3, 2, 1, None], [], None]

    def test_sort_array_floats_nan(self):
        data = pa.array([[2.5, float("nan"), -1.0, float("inf")]],
                        type=pa.list_(pa.float64()))
        out = assert_cpu_tpu_equal(lambda: SortArray(col("a")), t(a=data))
        got = out.to_pylist()[0]
        assert got[0] == -1.0 and got[1] == 2.5 and got[2] == float("inf") \
            and got[3] != got[3]  # NaN sorts largest
