"""Driver-contract tests: entry() jits single-chip; dryrun_multichip compiles the
full distributed step on the virtual 8-device mesh and matches the numpy oracle."""

import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from __graft_entry__ import dryrun_multichip, entry  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = entry()
    sums, counts = jax.jit(fn)(*args)
    assert np.asarray(sums).shape == (64,)
    assert int(np.asarray(counts).sum()) > 0
    # agg total == sum over kept rows (oracle)
    b = args[0]
    key = np.asarray(b.columns[0].data)[:int(b.row_count())]
    qty = np.asarray(b.columns[1].data)[:int(b.row_count())]
    price = np.asarray(b.columns[2].data)[:int(b.row_count())]
    keep = qty > 2
    np.testing.assert_allclose(
        float(np.asarray(sums).sum()),
        float((qty[keep].astype(np.float64) * price[keep]).sum()), rtol=1e-9)


def test_dryrun_multichip_8():
    dryrun_multichip(8)


def test_dryrun_multichip_2():
    dryrun_multichip(2)
