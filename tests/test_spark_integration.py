"""Spark physical-plan adapter (integration/spark_plan.py): a
TreeNode.toJSON executed plan — q5 shape: scan + filter + join + agg —
translates into engine plan nodes and answers identically on the device
and CPU engines vs a pyarrow oracle. The fixture follows the toJSON
contract (pre-order array, num-children links, nested expression
arrays); see the module docstring for the honest no-JVM gap."""

import json

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.integration import translate_spark_plan
from spark_rapids_tpu.integration.spark_plan import UnsupportedSparkPlan
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def attr(name, dtype):
    return [{"class": "org.apache.spark.sql.catalyst.expressions."
             "AttributeReference", "num-children": 0, "name": name,
             "dataType": dtype, "nullable": True, "metadata": {},
             "exprId": {"id": 1, "jvmId": "x"}, "qualifier": []}]


def lit(value, dtype):
    return [{"class": "org.apache.spark.sql.catalyst.expressions.Literal",
             "num-children": 0, "value": str(value), "dataType": dtype}]


def binop(cls, left, right):
    return [{"class": f"org.apache.spark.sql.catalyst.expressions.{cls}",
             "num-children": 2}] + left + right


def q5_fixture(fact_ident, dim_ident):
    """scan(fact) -> filter(v > 0) -> join(dim on k) -> agg by tag."""
    scan_fact = {
        "class": "org.apache.spark.sql.execution.FileSourceScanExec",
        "num-children": 0, "relation": "HadoopFsRelation(parquet)",
        "output": [attr("k", "long"), attr("v", "double")],
        "tableIdentifier": fact_ident}
    filt = {
        "class": "org.apache.spark.sql.execution.FilterExec",
        "num-children": 1,
        "condition": binop("GreaterThan", attr("v", "double"),
                           lit(0.0, "double"))}
    scan_dim = {
        "class": "org.apache.spark.sql.execution.FileSourceScanExec",
        "num-children": 0, "relation": "HadoopFsRelation(parquet)",
        "output": [attr("k", "long"), attr("tag", "string"),
                   attr("w", "double")],
        "tableIdentifier": dim_ident}
    join = {
        "class": "org.apache.spark.sql.execution.joins."
                 "BroadcastHashJoinExec",
        "num-children": 2, "joinType": "Inner",
        "leftKeys": [attr("k", "long")],
        "rightKeys": [attr("k", "long")]}
    agg = {
        "class": "org.apache.spark.sql.execution.aggregate."
                 "HashAggregateExec",
        "num-children": 1,
        "groupingExpressions": [attr("tag", "string")],
        "aggregateExpressions": [
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.AggregateExpression", "num-children": 1,
              "mode": "Complete", "isDistinct": False}] +
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.Sum", "num-children": 1}] + attr("v", "double"),
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.AggregateExpression", "num-children": 1,
              "mode": "Complete", "isDistinct": False}] +
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.Count", "num-children": 1}] + lit(1, "integer"),
        ],
        "resultExpressions": []}
    ws = {"class": "org.apache.spark.sql.execution."
          "WholeStageCodegenExec", "num-children": 1}
    # pre-order: agg -> ws -> join -> filter -> scan_fact, scan_dim
    return json.dumps([agg, ws, join, filt, scan_fact, scan_dim])


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("sparkplan")
    rng = np.random.default_rng(17)
    n = 4000
    fact = pa.table({
        "k": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        "v": pa.array(rng.normal(0.2, 1.0, n))})
    dim = pa.table({
        "k": pa.array(np.arange(100, dtype=np.int64)),
        "tag": pa.array([f"t{i % 5}" for i in range(100)]),
        "w": pa.array(rng.uniform(size=100))})
    fp = str(d / "fact.parquet")
    dp = str(d / "dim.parquet")
    pq.write_table(fact, fp)
    pq.write_table(dim, dp)
    return fp, dp, fact, dim


class TestSparkPlanTranslation:
    def test_q5_shape_end_to_end(self, session, data):
        fp, dp, fact, dim = data
        plan = translate_spark_plan(
            q5_fixture("fact", "dim"), session.conf,
            {"fact": [fp], "dim": [dp]})
        dev = session.execute_plan(plan)
        cpu = session.execute_plan(plan, use_device=False)
        ks = [(dev.schema.names[0], "ascending")]
        assert dev.sort_by(ks).equals(cpu.sort_by(ks))
        # pyarrow oracle
        import collections
        tagof = dict(zip(dim.column("k").to_pylist(),
                         dim.column("tag").to_pylist()))
        sums = collections.defaultdict(float)
        counts = collections.defaultdict(int)
        for k, v in zip(fact.column("k").to_pylist(),
                        fact.column("v").to_pylist()):
            if v > 0:
                sums[tagof[k]] += v
                counts[tagof[k]] += 1
        got = {r[dev.schema.names[0]]: (r["agg0"], r["agg1"])
               for r in dev.to_pylist()}
        assert set(got) == set(sums)
        for tag in sums:
            assert abs(got[tag][0] - sums[tag]) < 1e-9 * max(
                1.0, abs(sums[tag]))
            assert got[tag][1] == counts[tag]

    def test_partial_final_pair_collapses(self, session, data):
        fp, dp, fact, dim = data
        # Partial HashAgg under Final HashAgg with an exchange between,
        # the shape real Spark emits
        partial = {
            "class": "org.apache.spark.sql.execution.aggregate."
                     "HashAggregateExec",
            "num-children": 1,
            "groupingExpressions": [attr("k", "long")],
            "aggregateExpressions": [
                [{"class": "org.apache.spark.sql.catalyst.expressions."
                  "aggregate.AggregateExpression", "num-children": 1,
                  "mode": "Partial", "isDistinct": False}] +
                [{"class": "org.apache.spark.sql.catalyst.expressions."
                  "aggregate.Sum", "num-children": 1}] +
                attr("v", "double")],
            "resultExpressions": []}
        final = dict(partial)
        final["aggregateExpressions"] = [
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.AggregateExpression", "num-children": 1,
              "mode": "Final", "isDistinct": False}] +
            [{"class": "org.apache.spark.sql.catalyst.expressions."
              "aggregate.Sum", "num-children": 1}] + attr("v", "double")]
        exchange = {"class": "org.apache.spark.sql.execution.exchange."
                    "ShuffleExchangeExec", "num-children": 1}
        scan = {"class": "org.apache.spark.sql.execution."
                "FileSourceScanExec", "num-children": 0,
                "relation": "HadoopFsRelation(parquet)",
                "output": [attr("k", "long"), attr("v", "double")],
                "tableIdentifier": "fact"}
        pj = json.dumps([final, exchange, partial, scan])
        plan = translate_spark_plan(pj, session.conf, {"fact": [fp]})
        out = session.execute_plan(plan)
        assert out.num_rows == 100  # one row per key, not double-agged
        import collections
        sums = collections.defaultdict(float)
        for k, v in zip(fact.column("k").to_pylist(),
                        fact.column("v").to_pylist()):
            sums[k] += v
        got = {r[out.schema.names[0]]: r["agg0"] for r in out.to_pylist()}
        for k in sums:
            assert abs(got[k] - sums[k]) < 1e-9 * max(1.0, abs(sums[k]))

    def test_sort_and_take_ordered(self, session, data):
        fp, dp, fact, dim = data
        top = {
            "class": "org.apache.spark.sql.execution."
                     "TakeOrderedAndProjectExec",
            "num-children": 1, "limit": 5,
            "sortOrder": [
                [{"class": "org.apache.spark.sql.catalyst.expressions."
                  "SortOrder", "num-children": 1,
                  "direction": "Descending", "nullOrdering": "NullsLast"}]
                + attr("v", "double")],
            "projectList": []}
        scan = {"class": "org.apache.spark.sql.execution."
                "FileSourceScanExec", "num-children": 0,
                "relation": "HadoopFsRelation(parquet)",
                "output": [attr("k", "long"), attr("v", "double")],
                "tableIdentifier": "fact"}
        plan = translate_spark_plan(json.dumps([top, scan]), session.conf,
                                    {"fact": [fp]})
        out = session.execute_plan(plan)
        want = sorted(fact.column("v").to_pylist(), reverse=True)[:5]
        assert out.column("v").to_pylist() == want

    def test_unknown_node_raises_with_name(self, session):
        # WindowExec graduated to supported in round 4; use a node class
        # that genuinely doesn't exist to probe the honesty contract
        bad = [{"class": "org.apache.spark.sql.execution.exotic."
                "FlumeCapacitorExec", "num-children": 0}]
        with pytest.raises(UnsupportedSparkPlan,
                           match="FlumeCapacitorExec"):
            translate_spark_plan(json.dumps(bad), session.conf, {})

    def test_missing_path_mapping_raises(self, session):
        scan = [{"class": "org.apache.spark.sql.execution."
                 "FileSourceScanExec", "num-children": 0,
                 "relation": "HadoopFsRelation(parquet)",
                 "output": [attr("k", "long")],
                 "tableIdentifier": "nowhere"}]
        with pytest.raises(UnsupportedSparkPlan, match="nowhere"):
            translate_spark_plan(json.dumps(scan), session.conf, {})
