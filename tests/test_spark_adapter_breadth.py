"""Spark physical-plan adapter breadth (round-3 verdict #8): Window,
Expand, Generate, Union, Range, BroadcastNestedLoopJoin and
InsertIntoHadoopFsRelation toJSON fixtures translate into the engine and
answer identically on the device and CPU engines, checked against
independent pyarrow/pandas oracles. Fixtures follow the TreeNode.toJSON
contract (pre-order plan array, num-children links, expression fields as
nested arrays) — see `integration/spark_plan.py` for the honest no-JVM
gap; the service test covers the live socket transport for these same
payloads."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.integration import translate_spark_plan
from spark_rapids_tpu.integration.spark_plan import UnsupportedSparkPlan
from spark_rapids_tpu.plugin import TpuSession

EXPR = "org.apache.spark.sql.catalyst.expressions."
EXEC = "org.apache.spark.sql.execution."


@pytest.fixture(scope="module")
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


def attr(name, dtype):
    return [{"class": EXPR + "AttributeReference", "num-children": 0,
             "name": name, "dataType": dtype, "nullable": True,
             "metadata": {}, "exprId": {"id": 1, "jvmId": "x"},
             "qualifier": []}]


def lit(value, dtype):
    return [{"class": EXPR + "Literal", "num-children": 0,
             "value": str(value), "dataType": dtype}]


def scan(ident, cols):
    return {"class": EXEC + "FileSourceScanExec", "num-children": 0,
            "relation": "HadoopFsRelation(parquet)",
            "output": [attr(n, t) for n, t in cols],
            "tableIdentifier": ident}


def sort_order(name, dtype, asc=True):
    return [{"class": EXPR + "SortOrder", "num-children": 1,
             "direction": "Ascending" if asc else "Descending",
             "nullOrdering": "NullsFirst" if asc else "NullsLast"}] + \
        attr(name, dtype)


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("adapter")
    rng = np.random.default_rng(23)
    n = 2000
    t = pa.table({
        "k": pa.array(rng.integers(0, 20, n).astype(np.int64)),
        "v": pa.array(rng.normal(0.0, 10.0, n)),
    })
    p = str(d / "t.parquet")
    pq.write_table(t, p)
    small = pa.table({
        "g": pa.array(np.arange(5, dtype=np.int64)),
        "w": pa.array(rng.uniform(size=5))})
    sp = str(d / "small.parquet")
    pq.write_table(small, sp)
    return p, t, sp, small


def run_both(session, plan, sort_cols):
    dev = session.execute_plan(plan)
    cpu = session.execute_plan(plan, use_device=False)
    keys = [(c, "ascending") for c in sort_cols]
    dev, cpu = dev.sort_by(keys), cpu.sort_by(keys)
    assert dev.schema.names == cpu.schema.names
    assert dev.num_rows == cpu.num_rows
    for name in dev.schema.names:
        a, b = dev.column(name).to_pylist(), cpu.column(name).to_pylist()
        for x, y in zip(a, b):
            if isinstance(x, float) and x is not None and y is not None:
                assert x == y or abs(x - y) <= 1e-9 * max(
                    abs(x), abs(y), 1.0), (name, x, y)
            else:
                assert x == y, (name, x, y)
    return dev


class TestAdapterBreadth:
    def test_union(self, session, data):
        p, t, *_ = data
        u = {"class": EXEC + "UnionExec", "num-children": 2}
        cols = [("k", "long"), ("v", "double")]
        plan = translate_spark_plan(
            json.dumps([u, scan("t", cols), scan("t", cols)]),
            session.conf, {"t": [p]})
        dev = run_both(session, plan, ["k", "v"])
        assert dev.num_rows == 2 * t.num_rows

    def test_range(self, session):
        r = {"class": EXEC + "RangeExec", "num-children": 0,
             "start": 5, "end": 50, "step": 3}
        plan = translate_spark_plan(json.dumps([r]), None, {})
        # independent oracle
        got = run_both(TpuSession({"spark.rapids.sql.enabled": True,
                                   "spark.rapids.sql.explain": "NONE"}),
                       plan, ["id"])
        assert got.column("id").to_pylist() == list(range(5, 50, 3))

    def test_broadcast_nested_loop_join(self, session, data):
        p, t, sp, small = data
        j = {"class": EXEC + "joins.BroadcastNestedLoopJoinExec",
             "num-children": 2, "joinType": "Inner",
             "condition": [{"class": EXPR + "LessThan",
                            "num-children": 2}] + attr("w", "double")
             + attr("v", "double")}
        plan = translate_spark_plan(
            json.dumps([j, scan("small", [("g", "long"), ("w", "double")]),
                        scan("t", [("k", "long"), ("v", "double")])]),
            session.conf, {"t": [p], "small": [sp]})
        dev = run_both(session, plan, ["g", "k", "v"])
        # independent oracle: nested loop count
        w = small.column("w").to_numpy()
        v = t.column("v").to_numpy()
        assert dev.num_rows == int((w[:, None] < v[None, :]).sum())

    def test_cartesian_product(self, session, data):
        p, t, sp, small = data
        j = {"class": EXEC + "joins.CartesianProductExec",
             "num-children": 2}
        plan = translate_spark_plan(
            json.dumps([j, scan("small", [("g", "long"), ("w", "double")]),
                        scan("small2", [("g", "long")])]),
            session.conf, {"small": [sp], "small2": [sp]})
        # small x small: 25 rows (second scan pruned to one column)
        dev = session.execute_plan(plan)
        assert dev.num_rows == 25

    def test_expand(self, session, data):
        """Two projections per row: (k, v) and (null-tagged total, v) —
        the rollup lowering shape."""
        p, t, *_ = data
        e = {"class": EXEC + "ExpandExec", "num-children": 1,
             "projections": [
                 [attr("k", "long"), attr("v", "double")],
                 [lit(-1, "long"), attr("v", "double")],
             ],
             "output": [attr("k", "long"), attr("v", "double")]}
        plan = translate_spark_plan(
            json.dumps([e, scan("t", [("k", "long"), ("v", "double")])]),
            session.conf, {"t": [p]})
        dev = run_both(session, plan, ["k", "v"])
        assert dev.num_rows == 2 * t.num_rows
        assert sum(1 for x in dev.column("k").to_pylist() if x == -1) \
            == t.num_rows

    def test_window_rank_and_framed_sum(self, session, data):
        p, t, *_ = data
        we_rank = [{"class": EXPR + "Alias", "num-children": 1,
                    "name": "rnk"},
                   {"class": EXPR + "WindowExpression", "num-children": 2},
                   {"class": EXPR + "Rank", "num-children": 0},
                   {"class": EXPR + "WindowSpecDefinition",
                    "num-children": 1},
                   {"class": EXPR + "SpecifiedWindowFrame",
                    "num-children": 2, "frameType": "RowFrame"},
                   {"class": EXPR + "UnboundedPreceding$",
                    "num-children": 0},
                   {"class": EXPR + "CurrentRow$", "num-children": 0}]
        we_sum = [{"class": EXPR + "Alias", "num-children": 1,
                   "name": "running"},
                  {"class": EXPR + "WindowExpression", "num-children": 2},
                  {"class": EXPR + "aggregate.AggregateExpression",
                   "num-children": 1, "mode": "Complete",
                   "isDistinct": False},
                  {"class": EXPR + "aggregate.Sum", "num-children": 1}]
        we_sum += attr("v", "double")
        we_sum += [{"class": EXPR + "WindowSpecDefinition",
                    "num-children": 1},
                   {"class": EXPR + "SpecifiedWindowFrame",
                    "num-children": 2, "frameType": "RowFrame"},
                   {"class": EXPR + "UnboundedPreceding$",
                    "num-children": 0},
                   {"class": EXPR + "CurrentRow$", "num-children": 0}]
        w = {"class": EXEC + "window.WindowExec", "num-children": 1,
             "windowExpression": [we_rank, we_sum],
             "partitionSpec": [attr("k", "long")],
             "orderSpec": [sort_order("v", "double")]}
        plan = translate_spark_plan(
            json.dumps([w, scan("t", [("k", "long"), ("v", "double")])]),
            session.conf, {"t": [p]})
        dev = run_both(session, plan, ["k", "v"])
        # independent oracle on one partition: rank over ascending v is
        # 1..m (v is continuous, no ties), running sum is the prefix sum
        pdf = dev.to_pandas()
        g = pdf[pdf["k"] == 3].sort_values("v")
        assert list(g["rnk"]) == list(range(1, len(g) + 1))
        assert np.allclose(g["running"].to_numpy(),
                           np.cumsum(g["v"].to_numpy()))

    def test_generate_explode(self, session, tmp_path):
        """GenerateExec over an array column: posexplode with outer."""
        t = pa.table({
            "id": pa.array([1, 2, 3], pa.int64()),
            "xs": pa.array([[10, 20], [], [30]],
                           pa.list_(pa.int64()))})
        p = str(tmp_path / "arr.parquet")
        pq.write_table(t, p)
        arr_type = {"type": "array", "elementType": "long",
                    "containsNull": True}
        g = {"class": EXEC + "GenerateExec", "num-children": 1,
             "generator": [{"class": EXPR + "Explode",
                            "num-children": 1}] + attr("xs", arr_type),
             "outer": False,
             "requiredChildOutput": [attr("id", "long")],
             "generatorOutput": [attr("el", "long")]}
        plan = translate_spark_plan(
            json.dumps([g, scan("arr", [("id", "long"),
                                        ("xs", arr_type)])]),
            session.conf, {"arr": [p]})
        dev = run_both(session, plan, ["id", "el"])
        rows = [(r["id"], r["el"]) for r in dev.to_pylist()]
        assert sorted(rows) == [(1, 10), (1, 20), (3, 30)]
        assert dev.schema.names == ["id", "el"]

    def test_insert_into_hadoop_fs_relation(self, session, data,
                                            tmp_path):
        """DataWritingCommandExec -> write exec: rows land as parquet and
        the command reports the written row count."""
        p, t, *_ = data
        out_dir = str(tmp_path / "out")
        w = {"class": EXEC + "command.DataWritingCommandExec",
             "num-children": 1,
             "cmd": [{"class": EXEC + "datasources."
                      "InsertIntoHadoopFsRelationCommand",
                      "num-children": 0, "outputPath": out_dir,
                      "fileFormat": "Parquet", "mode": "Overwrite"}]}
        filt = {"class": EXEC + "FilterExec", "num-children": 1,
                "condition": [{"class": EXPR + "GreaterThan",
                               "num-children": 2}] + attr("v", "double")
                + lit(0.0, "double")}
        plan = translate_spark_plan(
            json.dumps([w, filt,
                        scan("t", [("k", "long"), ("v", "double")])]),
            session.conf, {"t": [p]})
        summary = session.execute_plan(plan)
        expected = int((t.column("v").to_numpy() > 0.0).sum())
        assert summary.to_pylist() == [{"path": out_dir,
                                        "rows": expected}]
        written = pq.read_table(out_dir)
        assert written.num_rows == expected
        assert set(written.schema.names) == {"k", "v"}

    def test_unknown_node_still_raises(self, session):
        bogus = {"class": EXEC + "SomeFancyNewExec", "num-children": 0}
        with pytest.raises(UnsupportedSparkPlan, match="SomeFancyNewExec"):
            translate_spark_plan(json.dumps([bogus]), session.conf, {})


class TestAdapterOverServiceTransport:
    def test_window_plan_over_live_socket(self, tmp_path, data):
        """The live transport seam: a WindowExec toJSON payload submitted
        by a REAL worker process over the service socket comes back as
        Arrow (verdict #8's 'any external Spark can attach' contract)."""
        import subprocess
        import sys
        from test_service import (_env, _start_server, _stop_server,
                                  _worker, _result)
        p, t, *_ = data
        we = [{"class": EXPR + "Alias", "num-children": 1, "name": "rn"},
              {"class": EXPR + "WindowExpression", "num-children": 2},
              {"class": EXPR + "RowNumber", "num-children": 0},
              {"class": EXPR + "WindowSpecDefinition", "num-children": 0}]
        w = {"class": EXEC + "window.WindowExec", "num-children": 1,
             "windowExpression": [we],
             "partitionSpec": [attr("k", "long")],
             "orderSpec": [sort_order("v", "double")]}
        plan_path = str(tmp_path / "wplan.json")
        with open(plan_path, "w") as f:
            f.write(json.dumps(
                [w, scan("t", [("k", "long"), ("v", "double")])]))
        sock = str(tmp_path / "svc.sock")
        proc = _start_server(sock)
        try:
            wk = _worker(sock, "W", "--plan", plan_path, "--paths",
                         json.dumps({"t": [str(data[0])]}))
            r = _result(wk, timeout=120)
            assert r["num_rows"] == t.num_rows
            assert r["columns"] == ["k", "v", "rn"]
        finally:
            _stop_server(proc, sock)
