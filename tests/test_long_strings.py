"""Chunked long-string device layout (columnar/strings.py): head byte-matrix
+ shared tail blob + row-aligned spans. The round-3 verdict's acceptance: a
1MB string traverses scan -> filter -> join -> collect WITHOUT the cap x
width blow-up or StringWidthExceeded, with a peak-bytes assertion
(reference: libcudf offset+data strings, `stringFunctions.scala:1`)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import from_arrow, to_arrow
from spark_rapids_tpu.expr import Count, Length, Sum, col, lit
from spark_rapids_tpu.plugin import TpuSession


@pytest.fixture()
def session():
    return TpuSession({"spark.rapids.sql.enabled": True,
                       "spark.rapids.sql.explain": "NONE"})


BIG = "x" * (1 << 20) + "END"          # ~1MB
MED = "m" * 5000                       # > head width, < 8KB


def mixed_strings(n=40, big_at=(3, 17)):
    vals = [f"short-{i}" for i in range(n)]
    for i in big_at:
        vals[i] = BIG
    vals[7] = MED
    vals[11] = None
    return vals


class TestLayout:
    def test_roundtrip_exact(self):
        arr = pa.array(mixed_strings())
        col_, n = from_arrow(arr)
        assert col_.overflow is not None
        # head stays at the configured bucket, not the 1MB width
        assert col_.data.shape[1] <= 256
        back = to_arrow(col_, n)
        assert back.to_pylist() == arr.to_pylist()

    def test_short_columns_unchanged(self):
        arr = pa.array(["a", "bb", None, "ccc"])
        col_, n = from_arrow(arr)
        assert col_.overflow is None  # plain flat layout, zero overhead
        assert to_arrow(col_, n).to_pylist() == arr.to_pylist()

    def test_peak_bytes_bounded(self):
        vals = mixed_strings(n=1000)
        raw = sum(len(v.encode()) for v in vals if v is not None)
        col_, n = from_arrow(pa.array(vals))
        # the flat layout would hold cap x 1MB-bucket ~ 1GB; the chunked
        # layout stays within a small factor of the raw bytes
        assert col_.device_memory_size() < 4 * raw
        assert col_.device_memory_size() < 16 * (1 << 20)


class TestEngineTraversal:
    def _fact(self, tmp_path, n=64):
        vals = mixed_strings(n)
        t = pa.table({
            "k": pa.array(np.arange(n) % 8, type=pa.int64()),
            "v": pa.array(np.arange(n, dtype=np.float64)),
            "s": pa.array(vals),
        })
        p = str(tmp_path / "long.parquet")
        pq.write_table(t, p)
        return p, t

    def test_scan_filter_join_collect(self, session, tmp_path):
        """The acceptance query: the 1MB string is carried (gathered,
        joined, collected) but never byte-inspected on device."""
        p, t = self._fact(tmp_path)
        fact = session.read_parquet(p)
        dim = session.from_arrow(pa.table({
            "k": pa.array([1, 3, 5], type=pa.int64()),
            "w": pa.array([1.0, 2.0, 3.0])}))
        q = fact.filter(col("v") < 40).join(dim, on="k", how="inner")
        out = q.collect().sort_by([("v", "ascending")])
        cpu = q.collect_cpu().sort_by([("v", "ascending")])
        assert out.column("s").to_pylist() == cpu.column("s").to_pylist()
        # the big strings actually survived the traversal
        joined = out.column("s").to_pylist()
        src = t.column("s").to_pylist()
        assert any(s == BIG for s in joined) or not any(
            src[i] == BIG and (i % 8) in (1, 3, 5) and i < 40
            for i in range(len(src)))

    def test_peak_device_bytes_during_query(self, session, tmp_path):
        p, _ = self._fact(tmp_path, n=256)
        fact = session.read_parquet(p)
        q = fact.filter(col("v") < 100)
        from spark_rapids_tpu.plan.overrides import Overrides
        session.initialize_device()
        result = Overrides(session.conf).apply(q.plan)
        peak = 0
        for b in result.execute():
            peak = max(peak, b.device_memory_size())
        # flat layout would be >= cap x 1MB-bucket per batch (>256MB)
        assert 0 < peak < 16 * (1 << 20)

    def test_byte_op_falls_back_but_answers(self, session, tmp_path):
        """A byte-inspecting op (substring-ish Length) over the long
        column must still ANSWER via the per-op fallback path."""
        p, t = self._fact(tmp_path)
        fact = session.read_parquet(p)
        q = fact.select("v", ln=Length(col("s")))
        out = q.collect().sort_by([("v", "ascending")])
        cpu = q.collect_cpu().sort_by([("v", "ascending")])
        assert out.column("ln").to_pylist() == cpu.column("ln").to_pylist()

    def test_groupby_on_other_key_carries_sum(self, session, tmp_path):
        p, _ = self._fact(tmp_path)
        fact = session.read_parquet(p)
        q = (fact.filter(col("v") >= 0).group_by("k")
             .agg(n=Count(col("s")), sv=Sum(col("v"))))
        out = q.collect().sort_by([("k", "ascending")])
        cpu = q.collect_cpu().sort_by([("k", "ascending")])
        assert out.column("n").to_pylist() == cpu.column("n").to_pylist()

    def test_sort_on_long_string_falls_back(self, session, tmp_path):
        p, _ = self._fact(tmp_path, n=32)
        q = session.read_parquet(p).sort("s")
        out = q.collect()
        cpu = q.collect_cpu()
        assert out.column("s").to_pylist() == cpu.column("s").to_pylist()


class TestCoalesceHealing:
    def test_filter_drops_long_rows_then_heals(self, session):
        """After the filter removes every long row, the coalesce healing
        drops the overflow and the column returns to the flat layout."""
        n = 200
        vals = [BIG if i < 3 else f"s{i}" for i in range(n)]
        t = pa.table({"i": pa.array(range(n), type=pa.int64()),
                      "s": pa.array(vals)})
        df = session.from_arrow(t).filter(col("i") >= 3)
        from spark_rapids_tpu.plan.overrides import Overrides
        session.initialize_device()
        result = Overrides(session.conf).apply(df.plan)
        from spark_rapids_tpu.exec.coalesce import rebucket_string_widths
        for b in result.execute():
            healed = rebucket_string_widths(b)
            si = b.schema.names.index("s")
            assert healed.columns[si].overflow is None
            assert healed.columns[si].data.shape[1] <= 8

    def test_blob_gc_compacts(self):
        from spark_rapids_tpu.columnar.strings import compact_tails
        lens = np.array([300, 10, 500], np.int32)
        blob = np.zeros(4096, np.uint8)
        blob[0:44] = 1    # row0 tail (300-256)
        blob[44:288] = 2  # row2 tail (500-256)
        ts = np.array([0, 0, 44], np.int32)
        live = np.array([False, True, True])
        blob2, ts2 = compact_tails(lens, (blob, ts), live, 256)
        assert blob2.shape[0] < blob.shape[0] or blob2.shape[0] == 1024
        # row2's tail preserved at its new offset
        got = blob2[ts2[2]:ts2[2] + 244]
        assert (got == 2).all()


class TestShuffleWire:
    def test_serialize_roundtrip_varlen(self, session):
        from spark_rapids_tpu.shuffle.serializer import (
            concat_host_tables, deserialize_table, serialize_batch)
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        vals = mixed_strings(24)
        t = pa.table({"s": pa.array(vals),
                      "i": pa.array(range(24), type=pa.int64())})
        session.initialize_device()
        b = batch_from_arrow(t)
        blob = serialize_batch(b, "zstd")
        # wire size must be near the raw bytes, not cap x width
        raw = sum(len(v.encode()) for v in vals if v is not None)
        assert len(blob) < 2 * raw + 65536
        ht, consumed = deserialize_table(blob)
        assert consumed == len(blob)
        out = concat_host_tables([ht, ht])
        got = to_arrow(out.columns[0], int(out.row_count())).to_pylist()
        assert got == vals + vals


class TestReviewRegressions:
    def test_conditional_over_long_string_answers(self, session):
        # If/CaseWhen override Expression.eval and skip its gate; the
        # pad_common_width choke point must still stop silent truncation
        from spark_rapids_tpu.expr import If
        n = 20
        vals = [BIG if i == 2 else f"s{i}" for i in range(n)]
        t = pa.table({"v": pa.array(np.arange(n, dtype=np.float64)),
                      "s": pa.array(vals)})
        df = session.from_arrow(t)
        q = df.select("v", out=If(col("v") > 1.0, col("s"), lit("tiny")))
        out = q.collect().sort_by([("v", "ascending")])
        cpu = q.collect_cpu().sort_by([("v", "ascending")])
        got = out.column("out").to_pylist()
        assert got == cpu.column("out").to_pylist()
        assert got[2] == BIG  # not truncated at the head width

    def test_empty_varlen_chunk_concat(self, session):
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        from spark_rapids_tpu.shuffle.serializer import (
            concat_host_tables, deserialize_table, serialize_batch)
        session.initialize_device()
        vals8 = [BIG if i == 2 else f"s{i}" for i in range(8)]
        full = batch_from_arrow(pa.table({"s": pa.array(vals8)}))
        # zero-row batch whose column still carries the blob
        import jax.numpy as jnp
        import dataclasses
        empty = dataclasses.replace(full, num_rows=jnp.asarray(0, jnp.int32))
        ht_e, _ = deserialize_table(serialize_batch(empty))
        ht_f, _ = deserialize_table(serialize_batch(full))
        out = concat_host_tables([ht_e, ht_f])
        got = to_arrow(out.columns[0], int(out.row_count())).to_pylist()
        assert got == vals8
